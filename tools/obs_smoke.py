#!/usr/bin/env python
"""Live-observability smoke: a real 20-step CLI run with --status_port,
scraped over HTTP while it trains.

tools/verify.sh runs this before the tier-1 gate.  It exercises the
exact production path — ``run_tffm.py train <cfg> --status_port`` in a
SUBPROCESS (pinned to CPU), not an in-process Trainer — and asserts:

1. ``/status`` answers mid-run with well-formed JSON carrying the
   heartbeat-record shape (``record``, ``step``, ``stages``) plus the
   resource block;
2. ``/metrics`` answers non-empty, every line Prometheus-parseable
   (``# HELP``/``# TYPE`` comments or ``name{labels} value``), and
   includes the core series + the ``tffm_build_info`` identity gauge;
3. ``/debug/threadz`` serves an all-thread stack dump naming the
   pipeline's threads;
4. ``/profile?secs=N`` captures one profiler window mid-run, and its
   busy-guard rejects a CONCURRENT second request with 409;
5. the run itself exits 0, and its final record carries a non-empty
   ``quality`` block (windowed online eval + drift sketches ran).

Then the SERVE smoke (the online scoring path, SERVING.md) against the
checkpoint that run just wrote — ``run_tffm.py serve`` in a subprocess:

6. ``POST /score`` answers with one parseable score per input line;
7. ``/metrics`` serves the ``tffm_serve_*`` series (Prometheus-valid);
8. a second short training run into the same model dir republishes the
   checkpoint manifest, and the server HOT-SWAPS exactly as designed
   (``tffm_counter_serve_swaps_total`` reaches 1) while still scoring;
8b. training→serving skew END TO END: identity traffic (lines from
   the training file) reads stable against the manifest's training
   sketches, and a shifted request population (foreign ids, 100x
   values) breaches ``tffm_serve_skew_psi_max`` > 0.25 on /metrics;
8c. request hot path (ISSUE 16): the pooled-accept + vectorized-parse
   defaults answer BYTE-IDENTICALLY to a second server mounted with
   ``--serve_http_threads 0 --serve_parse_mode legacy``, and an
   in-process ``PooledHTTPServer`` start/score/close cycle leaks no
   worker or acceptor threads.

Then the ROUTER smoke (scale-out serving, SERVING.md "Scale-out") —
``run_tffm.py serve --replicas 2`` in a subprocess, with per-request
tracing sampled at 1.0 (``--trace`` + ``--serve_trace_sample 1``):

9.  the router answers ``/score`` AND the binary ``/score_bin`` (a
    hand-rolled frame pinning the documented wire layout) with
    IDENTICAL scores for the same examples, every response echoing an
    ``X-Request-Id``;
10. the router's ``/metrics`` exposes the FLEET: aggregated
    ``tffm_serve_fleet_*`` series and per-replica labeled series
    scraped from each replica's ``/status`` — one scrape sees the
    whole fleet;
11. SIGKILLing one replica MID-TRACE loses no requests (transparent
    retry) and the router's ``/metrics`` shows the eviction
    (``tffm_counter_serve_evictions_total`` >= 1, the replica's
    ``tffm_serve_replica_healthy`` series at 0);
12. the RESPAWN policy relaunches the killed managed replica
    (``tffm_counter_serve_respawns_total`` >= 1) and the health loop
    readmits it (``tffm_serve_replica_healthy{replica="0"} 1``);
13. terminating the router tears down every replica subprocess — no
    orphaned jax processes — and dumps the trace family;
14. ``tools/report.py --serve-trace`` re-joins the router + surviving
    replica traces into COMPLETE per-request chains (admit -> proxy ->
    queue -> coalesce -> dispatch -> respond), the SIGKILLed
    replica's lost spans notwithstanding.

Then the INCIDENT smoke (flight recorder + capture/replay, ISSUE 20) —
``run_tffm.py serve`` with an always-breaching alert rule, full-sample
traffic capture, and an explicit ``--incident_dir``:

15. the breach dumps a VALID forensic bundle (manifest naming the
    rule, heartbeat ring with the ``alerts`` block, threadz dump, a
    /metrics snapshot carrying ``tffm_alert_active{rule=...}`` — also
    asserted on the LIVE endpoint), its dir name pid-suffixed;
    ``POST /incident?reason=...`` dumps a second, manually-named
    bundle and answers its dir as JSON;
16. ``tools/report.py --incident <bundle>`` renders the summary
    (rule fired, signal trajectory) and exits 0;
17. the TFC1 capture file replays BITWISE against a fresh serve
    subprocess on the same checkpoint (``tools/replay.py`` exit 0) —
    the capture/replay loop closes end to end.

The training stage also asserts the ``record: profile`` entry the
``/profile`` capture writes, and the resource block's
``uptime_s``/``open_fds`` vitals.

Exit 0 = all held; any other exit fails the audit.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One sample line per Prometheus text-format metric: bare name or
# name{labels}, then a number (int/float/scientific/inf/nan).
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
    r"[-+]?(\d+\.?\d*([eE][-+]?\d+)?|\.\d+|[Ii]nf|[Nn]a[Nn])$"
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _gen_data(path: str, n_lines: int = 6400, vocab: int = 50) -> None:
    import random

    rng = random.Random(0)
    with open(path, "w") as f:
        for _ in range(n_lines):
            feats = rng.sample(range(vocab), 3)
            toks = " ".join(
                f"{i}:{rng.uniform(0.1, 1.0):.3f}" for i in feats
            )
            f.write(f"{rng.randint(0, 1)} {toks}\n")


def _scrape_both(port: int, deadline: float, proc) -> tuple:
    """(status_bytes, metrics_bytes) fetched back-to-back mid-run.

    The server is up for the whole of train() (it outlives jit compile
    and every dispatch), so one retry loop covers both routes; a child
    that dies before answering fails fast instead of burning the
    deadline.
    """
    base = f"http://127.0.0.1:{port}"
    last_err = None
    while time.time() < deadline:
        try:
            status = urllib.request.urlopen(
                f"{base}/status", timeout=2).read()
            metrics = urllib.request.urlopen(
                f"{base}/metrics", timeout=2).read()
            return status, metrics
        except (urllib.error.URLError, OSError) as e:
            last_err = e
            if proc.poll() is not None:
                out, _ = proc.communicate()
                sys.stderr.write(out.decode(errors="replace")[-2000:])
                raise SystemExit(
                    f"FAIL: run exited {proc.returncode} before the "
                    f"status endpoint answered ({e})"
                )
            time.sleep(0.1)
    raise SystemExit(f"FAIL: {base} unreachable before deadline "
                     f"({last_err})")


def check_prometheus(text: str) -> int:
    """Validate Prometheus exposition text; returns the sample count."""
    samples = 0
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        if not _SAMPLE.match(line):
            raise SystemExit(
                f"FAIL: /metrics line {lineno} is not Prometheus-"
                f"parseable: {line!r}"
            )
        samples += 1
    if samples == 0:
        raise SystemExit("FAIL: /metrics served zero samples")
    return samples


def _get(port: int, route: str, timeout: float = 30.0) -> tuple:
    """(http_code, body bytes) — HTTPError codes return, not raise."""
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}{route}", timeout=timeout
        )
        return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def check_capture_routes(port: int) -> None:
    """/debug/threadz + the /profile busy-guard, mid-run.

    The guard contract: while one capture window is open, a second
    request gets 409 — so request A (a 0.5 s window; the process's
    FIRST capture also pays jax's one-time ~5 s profiler init, which
    the guard covers too) runs on a thread, request B fires into the
    middle of it, and both responses are asserted.  Runs right after
    the first successful scrape — early in the run, so the sized-up
    smoke run (see _run) cannot end under the open capture.
    """
    code, body = _get(port, "/debug/threadz")
    if code != 200:
        raise SystemExit(f"FAIL: /debug/threadz answered {code}")
    text = body.decode(errors="replace")
    if "--- thread" not in text or "MainThread" not in text:
        raise SystemExit(
            f"FAIL: /debug/threadz is not a thread dump: {text[:200]!r}"
        )
    results: dict = {}

    def slow_profile():
        # Store failures too: a connection reset (training subprocess
        # dying mid-capture) must surface as a FAIL diagnostic below,
        # not a KeyError in the main thread.
        try:
            results["a"] = _get(port, "/profile?secs=0.5", timeout=60)
        except Exception as exc:
            results["error"] = exc

    t = threading.Thread(target=slow_profile)
    t.start()
    time.sleep(0.5)  # give A a head start toward the capture lock
    code_b, body_b = _get(port, "/profile?secs=0.5")
    t.join()
    if "a" not in results:
        raise SystemExit(
            f"FAIL: /profile capture got no HTTP response "
            f"(run died mid-capture?): {results.get('error')!r}"
        )
    # The guard contract is about the PAIR, not the order: on a loaded
    # box request B can reach the lock first, so accept either winner —
    # exactly one 200 (with a capture dir) and one 409.
    pair = {"a": results["a"], "b": (code_b, body_b)}
    codes = sorted(code for code, _ in pair.values())
    if codes != [200, 409]:
        raise SystemExit(
            f"FAIL: concurrent /profile pair answered {codes}, wanted "
            f"exactly one 200 and one busy-guard 409"
        )
    winner = next(body for code, body in pair.values() if code == 200)
    doc = json.loads(winner)
    if not doc.get("profile_dir"):
        raise SystemExit(f"FAIL: /profile response names no dir: {doc}")
    print(f"capture routes ok: threadz dumped "
          f"{text.count('--- thread')} thread(s), /profile wrote "
          f"{doc['profile_dir']}, concurrent request got 409")


def check_serve(cfg_path: str, data: str) -> None:
    """Serve smoke: score over the socket, scrape tffm_serve_*, and
    assert one warm hot-swap when the trainer republishes the
    checkpoint.  Runs against the model dir the training smoke wrote."""
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "run_tffm.py"), "serve",
         cfg_path, "--serve_port", str(port),
         "--serve_poll_secs", "0.2"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        base = f"http://127.0.0.1:{port}"
        deadline = time.time() + 120
        while True:
            try:
                urllib.request.urlopen(f"{base}/healthz", timeout=2)
                break
            except (urllib.error.URLError, OSError) as e:
                if proc.poll() is not None:
                    out, _ = proc.communicate()
                    sys.stderr.write(
                        out.decode(errors="replace")[-2000:]
                    )
                    raise SystemExit(
                        f"FAIL: serve exited {proc.returncode} before "
                        f"answering ({e})"
                    )
                if time.time() > deadline:
                    raise SystemExit(
                        f"FAIL: serve endpoint unreachable ({e})"
                    )
                time.sleep(0.2)
        with open(data) as f:
            lines = "".join(f.readline() for _ in range(10))
        req = urllib.request.Request(
            f"{base}/score", data=lines.encode(), method="POST"
        )
        body = urllib.request.urlopen(req, timeout=30).read().decode()
        scores = body.strip().splitlines()
        if len(scores) != 10 or not all(
            0.0 <= float(s) <= 1.0 for s in scores
        ):
            raise SystemExit(
                f"FAIL: /score answered {len(scores)} line(s) for 10 "
                f"examples: {body[:200]!r}"
            )
        metrics = urllib.request.urlopen(
            f"{base}/metrics", timeout=10).read().decode()
        check_prometheus(metrics)
        for series in ("tffm_counter_serve_requests_total",
                       "tffm_counter_serve_examples_total",
                       "tffm_timer_serve_latency_p99_ms",
                       "tffm_gauge_serve_batch_fill"):
            if series not in metrics:
                raise SystemExit(
                    f"FAIL: /metrics missing serve series {series}"
                )
        # Hot swap: a short warm-start training run into the same model
        # dir republishes the manifest; the server must swap without
        # dropping its socket.
        swap_cfg = cfg_path + ".swap"
        with open(cfg_path) as f:
            content = f.read().replace("epoch_num = 20", "epoch_num = 1")
        with open(swap_cfg, "w") as f:
            f.write(content)
        train = subprocess.run(
            [sys.executable, os.path.join(REPO, "run_tffm.py"), "train",
             swap_cfg],
            cwd=REPO, env=env, capture_output=True, timeout=180,
        )
        if train.returncode != 0:
            sys.stderr.write(
                train.stdout.decode(errors="replace")[-2000:]
            )
            raise SystemExit(
                f"FAIL: hot-swap training run exited {train.returncode}"
            )
        deadline = time.time() + 60
        swaps = 0
        while time.time() < deadline:
            metrics = urllib.request.urlopen(
                f"{base}/metrics", timeout=10).read().decode()
            m = re.search(
                r"^tffm_counter_serve_swaps_total (\d+)", metrics,
                re.MULTILINE,
            )
            swaps = int(m.group(1)) if m else 0
            if swaps >= 1:
                break
            time.sleep(0.3)
        if swaps < 1:
            raise SystemExit(
                "FAIL: server never hot-swapped after the checkpoint "
                "manifest was republished"
            )
        body2 = urllib.request.urlopen(
            urllib.request.Request(
                f"{base}/score", data=lines.encode(), method="POST"
            ), timeout=30,
        ).read().decode()
        if len(body2.strip().splitlines()) != 10:
            raise SystemExit("FAIL: /score broken after hot-swap")
        # Training→serving skew, end to end over the socket: identity
        # traffic (lines from the training file itself) must read
        # stable against the manifest's training sketches; a shifted
        # request population (foreign ids, 100x values) must breach
        # tffm_serve_skew_* — the ISSUE 15 acceptance path.
        with open(data) as f:
            identity = "".join(f.readline() for _ in range(200))
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/score", data=identity.encode(), method="POST"
        ), timeout=30).read()
        status = json.loads(urllib.request.urlopen(
            f"{base}/status", timeout=10).read())
        serve_block = status.get("serve") or {}
        if serve_block.get("skew_ref_step", -1) < 0:
            raise SystemExit(
                "FAIL: serve has no skew reference — the training "
                f"smoke's manifest carried no sketches: {serve_block}"
            )
        if serve_block.get("skew_psi_max", 1.0) > 0.25:
            raise SystemExit(
                "FAIL: identity traffic reads as skewed "
                f"(skew_psi_max {serve_block.get('skew_psi_max')})"
            )
        shifted = "".join(
            "0 " + " ".join(
                f"{45 + (i + j) % 5}:{(1 + j) * 100}" for j in range(4)
            ) + "\n"
            for i in range(300)
        )
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/score", data=shifted.encode(), method="POST"
        ), timeout=30).read()
        time.sleep(0.6)  # skew block memo window
        metrics = urllib.request.urlopen(
            f"{base}/metrics", timeout=10).read().decode()
        m = re.search(
            r"^tffm_serve_skew_psi_max ([0-9.eE+-]+)", metrics,
            re.MULTILINE,
        )
        if m is None or float(m.group(1)) <= 0.25:
            raise SystemExit(
                "FAIL: shifted traffic did not breach "
                f"tffm_serve_skew_psi_max (got "
                f"{m.group(1) if m else 'no series'})"
            )
        # Request hot path (ISSUE 16): the pooled-accept + vectorized
        # parser stack (the defaults above) must be byte-identical on
        # the wire to the legacy thread-per-connection +
        # per-line-parser stack.  Second serve subprocess on the same
        # model dir with both knobs flipped, same request body,
        # compare responses byte for byte.
        l_port = _free_port()
        l_proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "run_tffm.py"),
             "serve", cfg_path, "--serve_port", str(l_port),
             "--serve_poll_secs", "0.2",
             "--serve_http_threads", "0",
             "--serve_parse_mode", "legacy"],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        try:
            l_base = f"http://127.0.0.1:{l_port}"
            deadline = time.time() + 120
            while True:
                try:
                    urllib.request.urlopen(
                        f"{l_base}/healthz", timeout=2)
                    break
                except (urllib.error.URLError, OSError) as e:
                    if l_proc.poll() is not None:
                        out, _ = l_proc.communicate()
                        sys.stderr.write(
                            out.decode(errors="replace")[-2000:]
                        )
                        raise SystemExit(
                            f"FAIL: legacy-mode serve exited "
                            f"{l_proc.returncode} before answering "
                            f"({e})"
                        )
                    if time.time() > deadline:
                        raise SystemExit(
                            f"FAIL: legacy-mode serve unreachable ({e})"
                        )
                    time.sleep(0.2)
            pooled_body = urllib.request.urlopen(
                urllib.request.Request(
                    f"{base}/score", data=lines.encode(), method="POST"
                ), timeout=30).read()
            legacy_body = urllib.request.urlopen(
                urllib.request.Request(
                    f"{l_base}/score", data=lines.encode(),
                    method="POST"
                ), timeout=30).read()
            if pooled_body != legacy_body:
                raise SystemExit(
                    "FAIL: pooled/vec serve stack is not "
                    "byte-identical to the legacy accept+parser: "
                    f"{pooled_body[:100]!r} vs {legacy_body[:100]!r}"
                )
        finally:
            if l_proc.poll() is None:
                l_proc.terminate()
                try:
                    l_proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    l_proc.kill()
                    l_proc.wait()
        # ISSUE 16: pooled server teardown must leak no worker or
        # acceptor thread — in-process so the thread set is ours to
        # enumerate.
        from http.server import BaseHTTPRequestHandler

        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        from fast_tffm_tpu.obs.status import PooledHTTPServer

        class _NoopHandler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API name
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")

            def log_message(self, *args):
                pass

        hs = PooledHTTPServer(("127.0.0.1", 0), _NoopHandler,
                              pool_size=4, acceptors=2)
        st = threading.Thread(target=hs.serve_forever, daemon=True)
        st.start()
        urllib.request.urlopen(
            f"http://127.0.0.1:{hs.server_address[1]}/", timeout=10
        ).read()
        hs.shutdown()
        st.join(timeout=10)
        hs.server_close()
        leaked = [
            t.name for t in threading.enumerate()
            if t.is_alive() and t.name.startswith("tffm-http-")
        ]
        if leaked:
            raise SystemExit(
                f"FAIL: PooledHTTPServer teardown leaked threads: "
                f"{leaked}"
            )
        print(f"serve smoke ok: scored 10/10 over the socket, "
              f"tffm_serve_* series present, {swaps} hot-swap(s) "
              f"mid-traffic, skew breach visible "
              f"(tffm_serve_skew_psi_max {float(m.group(1)):.2f} "
              f"after shifted traffic), pooled==legacy byte-identical, "
              f"pooled teardown leaked 0 threads")
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


def _wait_healthz(base: str, proc, what: str,
                  timeout_s: float = 120.0) -> None:
    deadline = time.time() + timeout_s
    while True:
        try:
            urllib.request.urlopen(f"{base}/healthz", timeout=2)
            return
        except (urllib.error.URLError, OSError) as e:
            if proc.poll() is not None:
                out, _ = proc.communicate()
                sys.stderr.write(out.decode(errors="replace")[-2000:])
                raise SystemExit(
                    f"FAIL: {what} exited {proc.returncode} before "
                    f"answering ({e})"
                )
            if time.time() > deadline:
                raise SystemExit(f"FAIL: {what} unreachable ({e})")
            time.sleep(0.2)


def check_incident(cfg_path: str, data: str) -> None:
    """Incident flight recorder + traffic capture, end to end (ISSUE
    20): a real serve subprocess with an always-breaching alert rule
    and full-sample capture; asserts

    a. the breach dumps a VALID forensic bundle (manifest + rings +
       threadz + metrics snapshot), its dir name carrying the pid
       suffix and an ``alert_`` reason;
    b. ``POST /incident?reason=...`` dumps a second, manually-named
       bundle and answers its dir as JSON;
    c. ``tools/report.py --incident`` renders the bundle (rule fired,
       signal trajectory) and exits 0;
    d. the capture file replays against a FRESH server on the same
       checkpoint with bitwise score parity (``tools/replay.py``
       exit 0).
    """
    tmpdir = os.path.dirname(cfg_path)
    incident_dir = os.path.join(tmpdir, "incidents")
    capture_file = os.path.join(tmpdir, "requests.capture")
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "run_tffm.py"), "serve",
         cfg_path, "--serve_port", str(port),
         "--serve_poll_secs", "0",
         # uptime_s is alive from the first heartbeat, so this rule
         # breaches ~0.2 s in — the injected incident.
         "--alert_rules", "uptime_s > 0 : warn",
         "--incident_dir", incident_dir,
         "--serve_capture_sample", "1",
         "--serve_capture_file", capture_file],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        base = f"http://127.0.0.1:{port}"
        _wait_healthz(base, proc, "incident-smoke serve")
        # Traffic for the capture file (sample 1.0 records every one).
        with open(data) as f:
            lines = "".join(f.readline() for _ in range(10))
        for _ in range(3):
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/score", data=lines.encode(), method="POST"
            ), timeout=30).read()
        # (a) the breach-triggered bundle.
        deadline = time.time() + 60
        bundle = None
        while time.time() < deadline:
            if os.path.isdir(incident_dir):
                for name in sorted(os.listdir(incident_dir)):
                    man = os.path.join(
                        incident_dir, name, "manifest.json"
                    )
                    if "alert_" in name and os.path.exists(man):
                        bundle = os.path.join(incident_dir, name)
                        break
            if bundle:
                break
            if proc.poll() is not None:
                out, _ = proc.communicate()
                sys.stderr.write(out.decode(errors="replace")[-2000:])
                raise SystemExit(
                    f"FAIL: serve exited {proc.returncode} before "
                    f"dumping the alert bundle"
                )
            time.sleep(0.1)
        if bundle is None:
            raise SystemExit(
                f"FAIL: alert breach dumped no incident bundle under "
                f"{incident_dir}"
            )
        if "_pid" not in os.path.basename(bundle):
            raise SystemExit(
                f"FAIL: bundle dir carries no pid suffix: {bundle}"
            )
        with open(os.path.join(bundle, "manifest.json")) as f:
            manifest = json.load(f)
        if not manifest.get("reason", "").startswith("alert_"):
            raise SystemExit(
                f"FAIL: manifest reason {manifest.get('reason')!r} "
                f"does not name the breached rule"
            )
        records = [
            json.loads(line)
            for line in open(os.path.join(bundle, "records.jsonl"))
        ]
        if not records or records[-1].get("record") != "heartbeat":
            raise SystemExit(
                f"FAIL: bundle records ring empty or malformed "
                f"({len(records)} records)"
            )
        if (records[-1].get("alerts") or {}).get("armed") != 1:
            raise SystemExit(
                "FAIL: ringed record carries no alerts block: "
                f"{records[-1].get('alerts')}"
            )
        with open(os.path.join(bundle, "threadz.txt")) as f:
            threadz = f.read()
        if "--- thread" not in threadz:
            raise SystemExit("FAIL: bundle threadz.txt is not a dump")
        with open(os.path.join(bundle, "metrics.prom")) as f:
            prom = f.read()
        if "tffm_alert_active" not in prom:
            raise SystemExit(
                "FAIL: bundle metrics snapshot lacks the per-rule "
                "tffm_alert_active gauge"
            )
        # Live /metrics must carry the armed-rule gauge too.
        live = urllib.request.urlopen(
            f"{base}/metrics", timeout=10).read().decode()
        if 'tffm_alert_active{rule="' not in live:
            raise SystemExit(
                "FAIL: live /metrics lacks tffm_alert_active{rule=...}"
            )
        # (b) the manual POST /incident route.
        resp = urllib.request.urlopen(urllib.request.Request(
            f"{base}/incident?reason=smoke", data=b"", method="POST"
        ), timeout=30)
        doc = json.loads(resp.read())
        manual = doc.get("incident_dir")
        if not manual or not os.path.exists(
            os.path.join(manual, "manifest.json")
        ):
            raise SystemExit(
                f"FAIL: POST /incident answered no valid bundle: {doc}"
            )
        if "smoke" not in os.path.basename(manual):
            raise SystemExit(
                f"FAIL: manual bundle ignores ?reason=smoke: {manual}"
            )
        # (c) report.py renders the alert bundle.
        rep = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "report.py"),
             "--incident", bundle],
            cwd=REPO, capture_output=True, timeout=60,
        )
        rep_out = rep.stdout.decode(errors="replace")
        if rep.returncode != 0 or "incident:" not in rep_out \
                or "uptime_s" not in rep_out:
            sys.stderr.write(rep_out[-2000:])
            raise SystemExit(
                f"FAIL: report.py --incident exited {rep.returncode} "
                f"or named no rule"
            )
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
    # (d) capture -> replay, bitwise, against a fresh server on the
    # same checkpoint (capture off — the replay target must not
    # append to the file it is being judged against).
    if not os.path.exists(capture_file):
        raise SystemExit(f"FAIL: no capture file at {capture_file}")
    r_port = _free_port()
    r_proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "run_tffm.py"), "serve",
         cfg_path, "--serve_port", str(r_port),
         "--serve_poll_secs", "0"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        _wait_healthz(f"http://127.0.0.1:{r_port}", r_proc,
                      "replay-target serve")
        rep = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "replay.py"),
             capture_file, "--endpoint",
             f"http://127.0.0.1:{r_port}"],
            cwd=REPO, capture_output=True, timeout=120,
        )
        rep_out = rep.stdout.decode(errors="replace")
        if rep.returncode != 0:
            sys.stderr.write(rep_out[-2000:])
            sys.stderr.write(rep.stderr.decode(errors="replace")[-500:])
            raise SystemExit(
                f"FAIL: tools/replay.py exited {rep.returncode} — "
                f"captured traffic did not re-score bitwise"
            )
        n_match = rep_out.split("/")[0].rsplit(" ", 1)[-1]
        print(
            f"incident smoke ok: alert bundle {os.path.basename(bundle)}"
            f" valid, POST /incident dumped "
            f"{os.path.basename(manual)}, report.py rendered it, "
            f"replay re-scored {n_match} captured request(s) bitwise"
        )
    finally:
        if r_proc.poll() is None:
            r_proc.terminate()
            try:
                r_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                r_proc.kill()
                r_proc.wait()


def check_router(cfg_path: str, data: str) -> None:
    """Router smoke: 2 replicas behind the P2C router, text/binary
    parity over the socket, fleet-aggregated /metrics, a SIGKILL
    mid-trace with transparent retry + respawn, teardown with no
    orphaned replica processes, and a complete merged request trace."""
    import signal
    import struct

    port = _free_port()
    tmpdir = os.path.dirname(cfg_path)
    trace_path = os.path.join(tmpdir, "serve_trace.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "run_tffm.py"), "serve",
         cfg_path, "--replicas", "2", "--serve_port", str(port),
         "--serve_poll_secs", "0.2",
         "--trace", trace_path, "--serve_trace_sample", "1.0"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    pids = []
    try:
        base = f"http://127.0.0.1:{port}"
        deadline = time.time() + 240
        while True:
            try:
                urllib.request.urlopen(f"{base}/healthz", timeout=2)
                break
            except (urllib.error.URLError, OSError) as e:
                if proc.poll() is not None:
                    out, _ = proc.communicate()
                    sys.stderr.write(out.decode(errors="replace")[-2000:])
                    raise SystemExit(
                        f"FAIL: router exited {proc.returncode} before "
                        f"answering ({e})"
                    )
                if time.time() > deadline:
                    raise SystemExit(
                        f"FAIL: router endpoint unreachable ({e})"
                    )
                time.sleep(0.3)
        status = json.loads(urllib.request.urlopen(
            f"{base}/status", timeout=10).read())
        per = status["serve"]["per_replica"]
        if len(per) != 2 or any(p["pid"] is None for p in per):
            raise SystemExit(
                f"FAIL: /status per_replica malformed: {per}"
            )
        pids = [p["pid"] for p in per]
        # Text/binary parity through the router, on a hand-rolled
        # frame so the DOCUMENTED wire layout is what's pinned (not
        # the package's own encoder): 2 examples x 3 features.
        examples = [[(5, 0.5), (9, 0.25), (3, 1.0)],
                    [(7, 0.125), (2, 0.75), (11, 1.0)]]
        text = "".join(
            "1 " + " ".join(f"{i}:{v}" for i, v in ex) + "\n"
            for ex in examples
        ).encode()
        resp = urllib.request.urlopen(urllib.request.Request(
            f"{base}/score", data=text, method="POST",
        ), timeout=30)
        text_scores = resp.read().decode().split()
        if not resp.headers.get("X-Request-Id"):
            raise SystemExit(
                "FAIL: sampled /score response carries no "
                "X-Request-Id echo"
            )
        frame = struct.pack("<4sIIB", b"TFB1", 2, 3, 0)
        frame += b"".join(
            struct.pack("<i", i) for ex in examples for i, _ in ex
        )
        frame += b"".join(
            struct.pack("<f", v) for ex in examples for _, v in ex
        )
        raw = urllib.request.urlopen(urllib.request.Request(
            f"{base}/score_bin", data=frame, method="POST",
        ), timeout=30).read()
        magic, n = struct.unpack_from("<4sI", raw)
        if magic != b"TFB1" or n != 2:
            raise SystemExit(
                f"FAIL: /score_bin response frame malformed "
                f"({magic!r}, n={n})"
            )
        bin_scores = [
            f"{s:.6f}" for s in struct.unpack_from("<2f", raw, 8)
        ]
        if bin_scores != text_scores:
            raise SystemExit(
                f"FAIL: binary scores {bin_scores} != text scores "
                f"{text_scores} for the same examples"
            )
        # Fleet metrics aggregation: the health loop scrapes every
        # replica's /status, and ONE router scrape must expose the
        # aggregated tffm_serve_fleet_* series plus per-replica
        # labeled series.
        deadline = time.time() + 60
        while True:
            metrics = urllib.request.urlopen(
                f"{base}/metrics", timeout=10).read().decode()
            if (
                "tffm_serve_fleet_requests" in metrics
                and 'tffm_serve_replica_qps{replica="0"}' in metrics
                and 'tffm_serve_replica_qps{replica="1"}' in metrics
            ):
                break
            if time.time() > deadline:
                raise SystemExit(
                    "FAIL: router /metrics never exposed the fleet "
                    "aggregates / per-replica scraped series"
                )
            time.sleep(0.3)
        check_prometheus(metrics)
        # Fleet-wide skew visibility: the scrape max-merges each
        # replica's skew_* keys under the same names, so the ROUTER's
        # /metrics carries tffm_serve_skew_examples (and the psi
        # series once enough traffic flows) — one scrape sees
        # fleet-wide training→serving skew.
        if "tffm_serve_skew_examples" not in metrics:
            raise SystemExit(
                "FAIL: router /metrics carries no fleet-merged "
                "tffm_serve_skew_* series"
            )
        # Kill one replica mid-traffic: every request must keep
        # succeeding (the router retries in-flight requests on the
        # survivor) and the eviction must show on /metrics.
        os.kill(pids[0], signal.SIGKILL)
        for i in range(20):
            body = urllib.request.urlopen(urllib.request.Request(
                f"{base}/score", data=text, method="POST",
            ), timeout=30).read().decode()
            if len(body.split()) != 2:
                raise SystemExit(
                    f"FAIL: request {i} after the SIGKILL answered "
                    f"{body[:100]!r}"
                )
        deadline = time.time() + 30
        while True:
            metrics = urllib.request.urlopen(
                f"{base}/metrics", timeout=10).read().decode()
            m = re.search(
                r"^tffm_counter_serve_evictions_total (\d+)", metrics,
                re.MULTILINE,
            )
            if m and int(m.group(1)) >= 1:
                break
            if time.time() > deadline:
                raise SystemExit(
                    "FAIL: router /metrics never showed the eviction"
                )
            time.sleep(0.3)
        check_prometheus(metrics)
        if not re.search(
            r'^tffm_serve_replica_healthy\{replica="0"[^}]*\} 0',
            metrics, re.MULTILINE,
        ):
            raise SystemExit(
                "FAIL: killed replica not marked unhealthy in the "
                "per-replica /metrics series"
            )
        # Respawn policy: the manager relaunches the killed MANAGED
        # replica (capped backoff) and the health loop readmits it
        # once its ladder is warm — the deadline is generous because
        # the fresh process pays a full jax startup + warmup on a
        # box already running two replicas.
        deadline = time.time() + 300
        while True:
            metrics = urllib.request.urlopen(
                f"{base}/metrics", timeout=10).read().decode()
            m = re.search(
                r"^tffm_counter_serve_respawns_total (\d+)", metrics,
                re.MULTILINE,
            )
            respawns = int(m.group(1)) if m else 0
            healthy0 = re.search(
                r'^tffm_serve_replica_healthy\{replica="0"[^}]*\} 1',
                metrics, re.MULTILINE,
            )
            if respawns >= 1 and healthy0:
                break
            if time.time() > deadline:
                raise SystemExit(
                    f"FAIL: killed replica never respawned+readmitted "
                    f"(respawns={respawns}, healthy0={bool(healthy0)})"
                )
            time.sleep(1.0)
        # The respawned replica is a NEW pid: the teardown check below
        # must track the live fleet, not the original pids.
        status = json.loads(urllib.request.urlopen(
            f"{base}/status", timeout=10).read())
        pids = [p["pid"] for p in status["serve"]["per_replica"]
                if p["pid"] is not None]
        # Scoring still flows through the recovered fleet.
        body = urllib.request.urlopen(urllib.request.Request(
            f"{base}/score", data=text, method="POST",
        ), timeout=30).read().decode()
        if len(body.split()) != 2:
            raise SystemExit("FAIL: scoring broken after the respawn")
        print(
            f"router smoke ok: 2 replicas, text==binary scores, "
            f"fleet aggregates on /metrics, 20/20 requests after "
            f"SIGKILL, eviction visible, {respawns} respawn(s) + "
            f"readmission"
        )
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
    # The manager's teardown contract: no replica outlives its router
    # — including the RESPAWNED one (pids was refreshed post-respawn).
    deadline = time.time() + 10
    for pid in pids:
        while time.time() < deadline:
            try:
                os.kill(pid, 0)
                time.sleep(0.2)
            except ProcessLookupError:
                break
        else:
            os.kill(pid, signal.SIGKILL)
            raise SystemExit(
                f"FAIL: replica pid {pid} outlived the router "
                "(manager teardown leak)"
            )
    print("router teardown ok: no orphaned replica processes")
    # Distributed-trace merge: the router trace + whatever replica
    # traces survived (the SIGKILLed replica's die with it — that is
    # the point of the mid-trace kill) must re-join into COMPLETE
    # per-request chains under tools/report.py --serve-trace.
    trace_files = [
        p for p in (
            trace_path,
            trace_path + ".replica0",
            trace_path + ".replica1",
        ) if os.path.exists(p)
    ]
    if trace_path not in trace_files or len(trace_files) < 2:
        raise SystemExit(
            f"FAIL: trace family incomplete on disk: {trace_files} "
            "(need the router trace + >= 1 replica trace)"
        )
    rep = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "report.py"),
         "--serve-trace"] + trace_files,
        capture_output=True, timeout=120,
    )
    out = rep.stdout.decode(errors="replace")
    if rep.returncode != 0:
        sys.stderr.write(out[-2000:])
        raise SystemExit(
            f"FAIL: report.py --serve-trace exited {rep.returncode}"
        )
    m = re.search(
        r"sampled requests: (\d+) traced, (\d+) with a complete chain",
        out,
    )
    if not m or int(m.group(2)) < 1:
        sys.stderr.write(out[-2000:])
        raise SystemExit(
            "FAIL: merged serve trace reconstructed no complete "
            "request chain"
        )
    print(
        f"serve-trace merge ok: {m.group(1)} request(s) traced, "
        f"{m.group(2)} complete chain(s) across "
        f"{len(trace_files)} file(s)"
    )


# Two-rank fleet-training worker (ISSUE 18): the ranks join a
# loopback jax.distributed cluster for IDENTITY (process_index,
# rank-suffixed streams) but each trains on its own LOCAL 2x1 mesh —
# lock-step SPMD would synchronize every dispatch through the
# all-reduce and smear the injected straggler's latency across BOTH
# ranks' dispatch timers (ratio ~= 1.0 however slow the straggler),
# which is exactly the single-host drive mode the explicit
# train_fleet_scrape target list exists for.  Rank 1 sleeps 80 ms per
# dispatch (the injected straggler); rank 0 runs the TrainFleet
# aggregator over both ranks with a live straggler_ratio rule.
_FLEET_WORKER = r"""
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
# CPU cross-process collectives need the gloo transport; without it
# any multi-process computation fails with "Multiprocess computations
# aren't implemented on the CPU backend".  Training here is local per
# rank, but checkpoint-save barriers still cross processes.
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
tmpdir, port0, port1 = sys.argv[3], int(sys.argv[4]), int(sys.argv[5])
rank = jax.process_index()

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.parallel import mesh as mesh_lib
from fast_tffm_tpu.train.loop import Trainer

cfg = FmConfig(
    vocabulary_size=64, factor_num=4, max_features=4, batch_size=64,
    mesh_data=2, mesh_model=1,
    train_files=[tmpdir + "/fleet.libsvm"],
    model_file=tmpdir + "/fleet_model%d" % rank,
    epoch_num=24, log_steps=0, thread_num=1, seed=5,
    heartbeat_secs=0.2,
    metrics_file=tmpdir + "/fleet_metrics.jsonl",
    status_port=port0 if rank == 0 else port1,
    train_fleet_scrape="127.0.0.1:%d,127.0.0.1:%d" % (port0, port1),
    alert_rules="straggler_ratio > 1.4 for 2 : warn",
)
trainer = Trainer(
    cfg, mesh=mesh_lib.make_mesh(cfg, jax.local_devices())
)
# Orbax refuses host-local arrays when process_count > 1, and this
# smoke exercises the fleet plane, not checkpointing.
trainer.save = lambda stepno: None
if rank == 1:
    real = trainer._scan_train_step
    def slow(state, batches):
        time.sleep(0.08)
        return real(state, batches)
    trainer._scan_train_step = slow
trainer.train()
print("FLEET_RANK_DONE", rank)
"""


def check_fleet(tmpdir: str) -> None:
    """2-rank fleet-training smoke: rank 0 aggregates the fleet LIVE
    (per-rank ``tffm_train_rank_*`` series on its /metrics, merged
    ``fleet`` block on /status), the injected 60 ms straggler on rank 1
    trips the ``straggler_ratio`` alert while training runs, and the
    per-rank JSONL writers never double-count into one stream."""
    import numpy as np

    rng = np.random.default_rng(11)
    data = os.path.join(tmpdir, "fleet.libsvm")
    with open(data, "w") as f:
        for _ in range(512):
            toks = [str(rng.integers(0, 2))]
            toks += [f"{rng.integers(0, 64)}:{rng.uniform(0.1, 1):.4f}"
                     for _ in range(3)]
            f.write(" ".join(toks) + "\n")
    coord_port, port0, port1 = _free_port(), _free_port(), _free_port()
    script = os.path.join(tmpdir, "fleet_worker.py")
    with open(script, "w") as f:
        f.write(_FLEET_WORKER)
    env = dict(
        os.environ,
        PALLAS_AXON_POOL_IPS="",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        PYTHONPATH=REPO + os.pathsep + os.environ.get(
            "PYTHONPATH", ""
        ),
    )
    procs = [
        subprocess.Popen(
            [sys.executable, script,
             f"127.0.0.1:{coord_port}", str(i), tmpdir,
             str(port0), str(port1)],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for i in range(2)
    ]
    try:
        # Live assertion window: rank 0's /metrics must grow BOTH
        # ranks' labeled series plus the merged fleet aggregates while
        # the ranks are still training.
        deadline = time.time() + 240
        fleet_metrics = None
        while time.time() < deadline:
            if any(p.poll() is not None for p in procs):
                break  # a fast box may finish before we catch it live
            try:
                text = urllib.request.urlopen(
                    f"http://127.0.0.1:{port0}/metrics", timeout=2
                ).read().decode()
            except (urllib.error.URLError, OSError):
                time.sleep(0.2)
                continue
            if ('tffm_train_rank_dispatch_mean_ms{rank="1"}' in text
                    and "tffm_fleet_straggler_ratio" in text):
                fleet_metrics = text
                break
            time.sleep(0.2)
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode(errors="replace"))
            if p.returncode != 0:
                sys.stderr.write(outs[-1][-3000:])
                raise SystemExit(
                    f"FAIL: fleet worker exited {p.returncode}"
                )
        if fleet_metrics is None:
            raise SystemExit(
                "FAIL: rank 0 /metrics never served the per-rank "
                "fleet series mid-run"
            )
        check_prometheus(fleet_metrics)
        for series in ('tffm_train_rank_step{rank="0"}',
                       'tffm_train_rank_step{rank="1"}',
                       "tffm_fleet_ranks_scraped 2",
                       "tffm_fleet_straggler_ratio"):
            if series not in fleet_metrics:
                raise SystemExit(
                    f"FAIL: fleet /metrics missing {series!r}"
                )
        # Rank files: rank 0 owns metrics.jsonl, rank 1 the .rank1
        # suffix — merged streams must never double-count.
        rank0_path = os.path.join(tmpdir, "fleet_metrics.jsonl")
        rank1_path = rank0_path + ".rank1"
        for path in (rank0_path, rank1_path):
            if not os.path.exists(path):
                raise SystemExit(f"FAIL: missing rank stream {path}")
        recs0 = [json.loads(line) for line in open(rank0_path)]
        ranks0 = {r.get("rank") for r in recs0 if "rank" in r}
        if ranks0 - {0}:
            raise SystemExit(
                f"FAIL: rank-0 stream carries foreign ranks {ranks0}"
            )
        recs1 = [json.loads(line) for line in open(rank1_path)]
        if not any(r.get("rank") == 1 for r in recs1):
            raise SystemExit(
                "FAIL: rank-1 stream has no rank-1 records"
            )
        # The LIVE alert: the injected straggler must have fired the
        # straggler_ratio rule into rank 0's stream during the run.
        alerts = [r for r in recs0 if r.get("record") == "alert"]
        stragglers = [
            a for a in alerts if a.get("signal") == "straggler_ratio"
        ]
        if not stragglers:
            raise SystemExit(
                f"FAIL: no straggler_ratio alert fired "
                f"(alerts: {alerts})"
            )
        if stragglers[0]["value"] <= 1.4:
            raise SystemExit(
                f"FAIL: straggler alert fired below threshold: "
                f"{stragglers[0]}"
            )
        # The final record carries the merged fleet view.
        final = [r for r in recs0 if r.get("record") == "final"][-1]
        fl = final.get("fleet") or {}
        if fl.get("ranks_scraped") != 2:
            raise SystemExit(
                f"FAIL: final fleet block incomplete: {fl}"
            )
        if fl.get("slowest_rank") != 1:
            raise SystemExit(
                f"FAIL: straggler attribution blamed rank "
                f"{fl.get('slowest_rank')}, expected 1: {fl}"
            )
        print(
            f"fleet smoke ok: 2 ranks aggregated live, "
            f"straggler_ratio={stragglers[0]['value']} alert fired "
            f"(slowest_rank={fl['slowest_rank']}), "
            f"{len(recs1)} rank-1 records in .rank1"
        )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()


def main() -> int:
    port = _free_port()
    tmpdir = tempfile.mkdtemp(prefix="tffm_obs_smoke_")
    try:
        return _run(port, tmpdir)
    finally:
        # verify.sh runs this on every invocation; leaked data/model
        # dirs would accumulate on CI boxes.
        shutil.rmtree(tmpdir, ignore_errors=True)


def _run(port: int, tmpdir: str) -> int:
    data = os.path.join(tmpdir, "train.libsvm")
    # 6400 lines x 20 epochs / batch 32 = 4000 steps (~20 s on a CPU
    # box): long enough that the /profile capture — jax's one-time
    # ~5 s profiler init plus the 0.5 s window — finishes well before
    # the run does.  A 20-step run used to end UNDER the open capture
    # and reset the connection.
    _gen_data(data)
    cfg_path = os.path.join(tmpdir, "smoke.cfg")
    with open(cfg_path, "w") as f:
        f.write(f"""[General]
vocabulary_size = 50
factor_num = 4
model_file = {tmpdir}/model
[Train]
train_files = {data}
epoch_num = 20
batch_size = 32
log_steps = 0
thread_num = 2
heartbeat_secs = 0.2
metrics_file = {tmpdir}/metrics.jsonl
[Tpu]
max_features = 4
""")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "run_tffm.py"), "train",
         cfg_path, "--status_port", str(port)],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.time() + 180
        status_raw, metrics_raw = _scrape_both(port, deadline, proc)
        # Capture routes first: the scrape above succeeded inside the
        # startup/compile window, so the 2 s profile capture cannot
        # outlive the run.
        check_capture_routes(port)
        status = json.loads(status_raw)
        for key in ("record", "step", "stages", "resource"):
            if key not in status:
                raise SystemExit(
                    f"FAIL: /status record missing {key!r}: {status}"
                )
        if status["record"] != "status":
            raise SystemExit(
                f"FAIL: /status record type {status['record']!r}"
            )
        if "rss_mb" not in status["resource"]:
            raise SystemExit(
                f"FAIL: resource block has no rss_mb: "
                f"{status['resource']}"
            )
        metrics = metrics_raw.decode()
        n = check_prometheus(metrics)
        for series in ("tffm_step", "tffm_counter_ingest_examples_total",
                       "tffm_timer_train_dispatch_count",
                       "tffm_resource_rss_mb", "tffm_build_info"):
            if series not in metrics:
                raise SystemExit(
                    f"FAIL: /metrics missing core series {series}"
                )
        out, _ = proc.communicate(timeout=180)
        if proc.returncode != 0:
            sys.stderr.write(out.decode(errors="replace")[-2000:])
            raise SystemExit(
                f"FAIL: training run exited {proc.returncode}"
            )
        # Model-quality plane: the final record must carry the quality
        # block (windowed eval + sketch counts) — default-on, like the
        # resource block above.
        finals = [
            json.loads(line)
            for line in open(os.path.join(tmpdir, "metrics.jsonl"))
        ]
        final = [r for r in finals if r.get("record") == "final"][-1]
        q = final.get("quality") or {}
        if not q.get("examples") or not q.get("sketch_examples"):
            raise SystemExit(
                f"FAIL: final record's quality block is missing or "
                f"empty: {q}"
            )
        # The /profile capture above must have logged itself into the
        # stream (`record: profile`) — a profiler window perturbs step
        # time, and the stream has to say so.
        profiles = [r for r in finals if r.get("record") == "profile"]
        if not profiles or not profiles[-1].get("profile_dir"):
            raise SystemExit(
                f"FAIL: /profile capture wrote no `record: profile` "
                f"entry to the metrics stream ({len(profiles)} found)"
            )
        # Resource vitals (ISSUE 20): uptime + the open-fd ledger must
        # ride the resource block.
        res = final.get("resource") or {}
        if res.get("uptime_s", 0) <= 0 or "open_fds" not in res:
            raise SystemExit(
                f"FAIL: resource block lacks uptime_s/open_fds: {res}"
            )
        print(
            f"obs smoke ok: /status step={status['step']}, /metrics "
            f"served {n} Prometheus samples, quality block eval'd "
            f"{q['examples']} examples, run exited 0"
        )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    # The serve smoke scores against the checkpoint the run above just
    # saved (run_tffm.py serve in its own subprocess), then the router
    # smoke mounts a 2-replica fleet over the same checkpoint.
    check_serve(cfg_path, data)
    # Incident flight recorder + capture/replay (ISSUE 20): an
    # injected alert breach must dump a valid forensic bundle,
    # report.py must render it, and the captured traffic must replay
    # bitwise against a fresh server on the same checkpoint.
    check_incident(cfg_path, data)
    check_router(cfg_path, data)
    # Fleet-training smoke (ISSUE 18): 2 spawned CPU ranks, rank 0
    # aggregating, an injected straggler tripping the live alert.
    check_fleet(tmpdir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
