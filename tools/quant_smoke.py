#!/usr/bin/env python
"""Quantized-table smoke: the end-to-end migration story, through the
real CLI — train with a bf16 cold store, score the fp32 reference
offline, CONVERT the checkpoint to int8, serve it quantized over the
socket, and tolerance-check the served scores against fp32.

    train (table_tiering=on, cold_dtype=bf16, ~20 steps)
      -> dense checkpoint (small-V merge)
      -> predict: fp32 reference scores (score_path)
      -> python -m tools.convert_checkpoint --to int8  (quant.npz)
      -> run_tffm.py serve --serve_table_dtype int8
      -> POST /score == fp32 scores within tolerance, and
         tffm_gauge_serve_table_bytes / _quant_error_max on /metrics

Run by tools/verify.sh after the observability smoke.  Exit 0 = pass.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Served int8 vs fp32 tolerance on sigmoid outputs.  The pinned unit
# tolerance (tests/test_quant.py) is 2e-2 at adversarial magnitudes;
# this freshly-trained tiny model sits far inside it.
TOL = 5e-2


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _gen_data(path: str, n_lines: int = 640, vocab: int = 64) -> None:
    import numpy as np

    rng = np.random.default_rng(21)
    with open(path, "w") as f:
        for _ in range(n_lines):
            ids = rng.choice(vocab, 3, replace=False)
            f.write(
                f"{rng.integers(0, 2)} " + " ".join(
                    f"{i}:{rng.uniform(0.1, 1.0):.3f}" for i in ids
                ) + "\n"
            )


def _run_cli(args, what: str) -> str:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable] + args, cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=300,
    )
    out = proc.stdout.decode(errors="replace")
    if proc.returncode != 0:
        sys.stderr.write(out[-3000:])
        raise SystemExit(f"FAIL: {what} exited {proc.returncode}")
    return out


def _wait_serving(base: str, proc) -> None:
    deadline = time.time() + 120
    while True:
        try:
            urllib.request.urlopen(f"{base}/healthz", timeout=2)
            return
        except (urllib.error.URLError, OSError) as e:
            if proc.poll() is not None:
                out, _ = proc.communicate()
                sys.stderr.write(out.decode(errors="replace")[-3000:])
                raise SystemExit(
                    f"FAIL: serve exited {proc.returncode} early ({e})"
                )
            if time.time() > deadline:
                raise SystemExit(f"FAIL: serve unreachable ({e})")
            time.sleep(0.2)


def _run(tmpdir: str) -> int:
    data = os.path.join(tmpdir, "train.libsvm")
    _gen_data(data)
    model = os.path.join(tmpdir, "model")
    scores_path = os.path.join(tmpdir, "scores.txt")
    cfg_path = os.path.join(tmpdir, "quant_smoke.cfg")
    with open(cfg_path, "w") as f:
        f.write(f"""[General]
vocabulary_size = 64
factor_num = 4
model_file = {model}
[Train]
train_files = {data}
epoch_num = 1
batch_size = 32
log_steps = 0
thread_num = 2
[Predict]
predict_files = {data}
score_path = {scores_path}
[Tpu]
max_features = 4
table_tiering = on
hot_rows = 60
cold_dtype = bf16
""")
    run_tffm = os.path.join(REPO, "run_tffm.py")
    # 640 lines / batch 32 = 20 training steps with a quantized (bf16)
    # cold store and eviction churn (hot_rows < vocab); small V merges
    # to the DENSE checkpoint format on save.
    _run_cli([run_tffm, "train", cfg_path], "bf16-cold training")
    # fp32 reference scores through the offline ladder (same scorer
    # the server mounts).
    _run_cli([run_tffm, "predict", cfg_path], "fp32 predict")
    with open(scores_path) as f:
        ref = [float(s) for s in f.read().split()]
    if len(ref) != 640:
        raise SystemExit(f"FAIL: predict wrote {len(ref)} scores")
    # Convert the dense checkpoint to the int8 serving format in place
    # (--force: in-place lossy conversion is refused without it, and
    # this throwaway smoke checkpoint is exactly the case it exists
    # for).
    _run_cli(
        ["-m", "tools.convert_checkpoint", model, "--to", "int8",
         "--force"],
        "fp32 -> int8 conversion",
    )
    if not os.path.isfile(os.path.join(model, "quant.npz")):
        raise SystemExit("FAIL: conversion left no quant.npz")
    # Serve the quantized table and score the first 10 examples over
    # the socket.
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, run_tffm, "serve", cfg_path,
         "--serve_port", str(port), "--serve_table_dtype", "int8",
         "--serve_poll_secs", "0"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        base = f"http://127.0.0.1:{port}"
        _wait_serving(base, proc)
        with open(data) as f:
            lines = "".join(f.readline() for _ in range(10))
        body = urllib.request.urlopen(urllib.request.Request(
            f"{base}/score", data=lines.encode(), method="POST"
        ), timeout=60).read().decode()
        served = [float(s) for s in body.split()]
        if len(served) != 10:
            raise SystemExit(
                f"FAIL: served {len(served)} scores for 10 examples"
            )
        worst = max(abs(s - r) for s, r in zip(served, ref[:10]))
        if worst > TOL:
            raise SystemExit(
                f"FAIL: served int8 scores drift {worst:.4f} from the "
                f"fp32 reference (tolerance {TOL})"
            )
        metrics = urllib.request.urlopen(
            f"{base}/metrics", timeout=10
        ).read().decode()
        for series in ("tffm_gauge_serve_table_bytes",
                       "tffm_serve_table_mb",
                       "tffm_serve_quant_error_max"):
            if series not in metrics:
                raise SystemExit(
                    f"FAIL: /metrics missing quant series {series}"
                )
        print(
            f"ok: trained bf16-cold, converted to int8, served "
            f"quantized — max |served - fp32| = {worst:.5f} "
            f"(tolerance {TOL})"
        )
        return 0
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()


def main() -> int:
    tmpdir = tempfile.mkdtemp(prefix="tffm_quant_smoke_")
    try:
        return _run(tmpdir)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
