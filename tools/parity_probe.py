"""Mixed-mesh parity probe: localize WHERE sharded training diverges.

The `[4-2]` mesh-parity red (tests/test_sharding.py::
test_sharded_step_matches_single_device[4-2]) says the (data=4,
model=2) mesh drifts from the single-device reference — but a failing
end-state assert doesn't say WHEN the drift starts or WHICH model
shard carries it.  This probe is the observability aid: it runs the
same config through both meshes for N dispatches on identical batch
streams and dumps one JSONL record per (mesh, dispatch) —

  - ``update_norm``: L2 of the dispatch's table delta (proportional to
    the gradient under the SGD-family updates, and the per-dispatch
    divergence signal);
  - ``param_hash``: sha256 of the full table bytes (bitwise identity
    check), plus per-model-shard row-block hashes so a diff names the
    shard;
  - ``loss_sum``: the running metric the parity test also checks —

then reports the FIRST divergent dispatch (earliest where the probe
mesh's table differs from the reference beyond --atol/--rtol), the
max |delta|, the row it lives at, and which model shard owns that row.

Fixing the red stays the sharding direction's job (ROADMAP direction
1); this tool only attributes it.

Usage:
  python tools/parity_probe.py [--mesh-data 4] [--mesh-model 2]
      [--dispatches 8] [--out parity_probe.jsonl]
      [--atol 1e-6] [--rtol 1e-5]

``--fleet-gate`` is the second mode (ISSUE 19): a cheap 2-rank CPU
(gloo) gate.  Two real OS processes join a jax.distributed cluster,
build the canonical fleet mesh (data=1, model=2 — one model column per
rank), and run init + N training dispatches on the same batch stream a
single-process (1x2) reference runs locally.  Each rank sha256-hashes
its ADDRESSABLE table block after init and after every dispatch; the
parent compares rank r's hash against the reference's model-shard-r
block hash.  Bitwise equality is the contract (the `[4-2]` fix made
sharded init layout-independent), so the gate catches both init drift
and cross-process step drift in ~3 dispatches.

Exit code: 0 when the meshes agree over every dispatch, 3 when a
divergent dispatch was found (so CI can notice the red moving), 1 on
setup errors.
"""

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# The 8-virtual-CPU-device pin must land before jax initializes — the
# same contract as tests/conftest.py.
from fast_tffm_tpu.platform import pin_cpu  # noqa: E402

pin_cpu(8)

import numpy as np  # noqa: E402

import jax  # noqa: E402

from fast_tffm_tpu.config import FmConfig  # noqa: E402
from fast_tffm_tpu.data.libsvm import Batch  # noqa: E402
from fast_tffm_tpu.parallel import mesh as mesh_lib  # noqa: E402
from fast_tffm_tpu.train.loop import Trainer  # noqa: E402


def _cfg(model_dir: str, **kw) -> FmConfig:
    # The exact test_sharding.py parity config.
    defaults = dict(
        vocabulary_size=256, factor_num=4, max_features=8,
        batch_size=64, model_file=os.path.join(model_dir, "model"),
        log_steps=0,
    )
    defaults.update(kw)
    return FmConfig(**defaults)


def _batch(rng, cfg: FmConfig) -> Batch:
    n, f = cfg.batch_size, cfg.max_features
    return Batch(
        labels=rng.integers(0, 2, size=(n,)).astype(np.float32),
        ids=rng.integers(
            0, cfg.vocabulary_size, size=(n, f)
        ).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, size=(n, f)).astype(np.float32),
        fields=np.zeros((n, f), np.int32),
        weights=np.ones((n,), np.float32),
    )


def _table(trainer: Trainer) -> np.ndarray:
    return np.asarray(trainer.state.params.table)


def _shard_hashes(table: np.ndarray, model_shards: int) -> list:
    """Per-model-shard row-block sha256 prefixes (the model axis
    shards table rows into contiguous blocks)."""
    rows = table.shape[0]
    per = max(1, rows // model_shards)
    return [
        hashlib.sha256(
            np.ascontiguousarray(table[i * per:(i + 1) * per]).tobytes()
        ).hexdigest()[:16]
        for i in range(model_shards)
    ]


def _record(tag: str, mesh_shape: str, dispatch: int,
            table: np.ndarray, prev: np.ndarray, loss_sum: float,
            model_shards: int) -> dict:
    return {
        "record": "parity_probe",
        "mesh": mesh_shape,
        "tag": tag,
        "dispatch": dispatch,
        "update_norm": round(
            float(np.linalg.norm(table - prev)), 10
        ),
        "param_hash": hashlib.sha256(
            np.ascontiguousarray(table).tobytes()
        ).hexdigest()[:16],
        "shard_hashes": _shard_hashes(table, model_shards),
        "loss_sum": round(loss_sum, 10),
    }


# The 2-rank gloo worker: joins the cluster, builds the canonical fleet
# mesh (data=1, model=2), trains N dispatches on the seeded batch
# stream, and prints one FLEETHASH line per (rank, dispatch) — the
# sha256 of this rank's ADDRESSABLE table block.  argv: coordinator,
# rank, seed, dispatches.
_FLEET_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
# CPU cross-process collectives need the gloo transport.
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
assert jax.process_count() == 2 and jax.device_count() == 2

import hashlib
import numpy as np
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.train.loop import Trainer

rank, seed, n = int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4])
cfg = FmConfig(
    vocabulary_size=256, factor_num=4, max_features=8, batch_size=64,
    mesh_data=1, mesh_model=2,
    model_file="/tmp/fftpu_fleet_gate_" + sys.argv[2], log_steps=0,
)
t = Trainer(cfg)
rng = np.random.default_rng(seed)


def h():
    parts = [np.ascontiguousarray(np.asarray(s.data))
             for s in t.state.params.table.addressable_shards]
    return hashlib.sha256(
        b"".join(p.tobytes() for p in parts)
    ).hexdigest()[:16]


print("FLEETHASH", rank, -1, h(), flush=True)
for i in range(n):
    b = Batch(
        labels=rng.integers(0, 2, size=(64,)).astype(np.float32),
        ids=rng.integers(0, 256, size=(64, 8)).astype(np.int32),
        vals=rng.uniform(0.1, 1.0, size=(64, 8)).astype(np.float32),
        fields=np.zeros((64, 8), np.int32),
        weights=np.ones((64,), np.float32),
    )
    t.state = t._train_step(t.state, t._put(b))
    print("FLEETHASH", rank, i, h(), flush=True)
"""


def _fleet_gate(args) -> int:
    """Init+N-step hash gate: 2 gloo ranks vs the 1-process (1x2)
    reference, compared bitwise per model shard per dispatch."""
    import socket
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    n = args.dispatches
    scratch = args.workdir or tempfile.mkdtemp(prefix="fleet_gate_")
    os.makedirs(scratch, exist_ok=True)

    # Reference: the SAME logical mesh (1 data x 2 model) on one
    # process, same seeded batch stream.
    cfg = _cfg(os.path.join(scratch, "ref"), mesh_data=1, mesh_model=2)
    t_ref = Trainer(
        cfg, mesh=mesh_lib.make_mesh(cfg, jax.devices()[:2])
    )
    rng = np.random.default_rng(args.seed)
    ref_hashes = {-1: _shard_hashes(_table(t_ref), 2)}
    for i in range(n):
        b = _batch(rng, cfg)
        t_ref.state = t_ref._train_step(t_ref.state, t_ref._put(b))
        ref_hashes[i] = _shard_hashes(_table(t_ref), 2)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    env = dict(
        os.environ,
        PALLAS_AXON_POOL_IPS="",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    script = os.path.join(scratch, "fleet_worker.py")
    with open(script, "w") as f:
        f.write(_FLEET_WORKER)
    print(f"fleet gate: 2 gloo ranks (1 device each) vs 1x2 "
          f"reference, init + {n} dispatches")
    procs = [
        subprocess.Popen(
            [sys.executable, script, coordinator, str(r),
             str(args.seed), str(n)],
            env=env, cwd=repo, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        for r in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            if p.returncode != 0:
                print(f"fleet worker failed (rc={p.returncode}):\n"
                      f"{err[-3000:]}", file=sys.stderr)
                return 1
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()

    # rank_hashes[r][d] = hash of rank r's table block after dispatch d.
    rank_hashes = {0: {}, 1: {}}
    for line in (ln for o in outs for ln in o.splitlines()):
        if line.startswith("FLEETHASH "):
            _, r, d, hx = line.split()
            rank_hashes[int(r)][int(d)] = hx
    first_divergent = None
    records = []
    for d in [-1] + list(range(n)):
        match = [
            rank_hashes[r].get(d) == ref_hashes[d][r] for r in range(2)
        ]
        records.append({
            "record": "fleet_gate",
            "dispatch": d,
            "rank_hashes": [rank_hashes[r].get(d) for r in range(2)],
            "ref_hashes": ref_hashes[d],
            "match": match,
        })
        tag = "init" if d == -1 else f"dispatch {d}"
        ok = all(match)
        if not ok and first_divergent is None:
            first_divergent = d
        print(f"  {tag}: ranks "
              f"{'== reference' if ok else '!= reference ' + str(match)}")
    with open(args.out, "w") as out:
        for rec in records:
            out.write(json.dumps(rec) + "\n")
        out.write(json.dumps({
            "record": "fleet_gate_summary",
            "dispatches": n,
            "first_divergent_dispatch": first_divergent,
            "agree": first_divergent is None,
        }) + "\n")
    if first_divergent is None:
        print(f"\nfleet gate: 2-rank table blocks bitwise-match the "
              f"single-process reference over init + {n} dispatches")
        return 0
    where = "init" if first_divergent == -1 else \
        f"dispatch {first_divergent}"
    print(f"\nfleet gate: DIVERGED at {where} — per-dispatch records "
          f"in {args.out}")
    return 3


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="localize the first divergent dispatch between a "
                    "sharded mesh and the single-device reference"
    )
    ap.add_argument("--mesh-data", type=int, default=4)
    ap.add_argument("--mesh-model", type=int, default=2)
    ap.add_argument("--dispatches", type=int, default=8)
    ap.add_argument("--atol", type=float, default=1e-6)
    ap.add_argument("--rtol", type=float, default=1e-5)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fleet-gate", action="store_true",
                    help="2-rank gloo init+N-step hash gate against "
                         "the single-process (1x2) reference")
    ap.add_argument("--out", default="parity_probe.jsonl",
                    help="per-dispatch JSONL dump (default "
                         "parity_probe.jsonl)")
    ap.add_argument("--workdir", default=None,
                    help="model_file scratch dir (default: a tempdir)")
    args = ap.parse_args(argv)

    if args.fleet_gate:
        return _fleet_gate(args)

    d, m = args.mesh_data, args.mesh_model
    if d * m > len(jax.devices()):
        print(f"mesh {d}x{m} needs {d * m} devices, have "
              f"{len(jax.devices())}", file=sys.stderr)
        return 1
    if args.workdir is None:
        import tempfile
        scratch = tempfile.mkdtemp(prefix="parity_probe_")
    else:
        scratch = args.workdir
        os.makedirs(scratch, exist_ok=True)

    rng = np.random.default_rng(args.seed)
    cfg_ref = _cfg(os.path.join(scratch, "ref"), mesh_data=1,
                   mesh_model=1)
    cfg_probe = _cfg(os.path.join(scratch, "probe"), mesh_data=d,
                     mesh_model=m)
    batches = [_batch(rng, cfg_ref) for _ in range(args.dispatches)]

    t_ref = Trainer(
        cfg_ref, mesh=mesh_lib.make_mesh(cfg_ref, jax.devices()[:1])
    )
    t_probe = Trainer(cfg_probe)
    mesh_shape = f"{d}x{m}"
    print(f"parity probe: {mesh_shape} vs 1x1 reference, "
          f"{args.dispatches} dispatches, batch {cfg_ref.batch_size}, "
          f"vocab {cfg_ref.vocabulary_size} (dump -> {args.out})")

    first_divergent = None
    worst = {"max_abs_diff": 0.0}
    rows_per_shard = max(1, cfg_ref.vocabulary_size // m)
    prev_ref, prev_probe = _table(t_ref), _table(t_probe)
    # Dispatch "-1": the INIT states.  A diff here predates any step —
    # the divergence is in sharded initialization, not the step math,
    # and every later dispatch only inherits it.
    init_diff = np.abs(prev_probe - prev_ref)
    init_divergent = bool(
        (init_diff > args.atol + args.rtol * np.abs(prev_ref)).any()
    )
    init_row = int(
        np.unravel_index(init_diff.argmax(), init_diff.shape)[0]
    )
    if init_divergent:
        print(f"  init: tables ALREADY differ (max|d|="
              f"{float(init_diff.max()):.3e} at row {init_row}, "
              f"model shard {min(m - 1, init_row // rows_per_shard)})"
              f" — divergence predates the first step")
    with open(args.out, "w") as out:
        out.write(json.dumps({
            "record": "parity_init",
            "divergent": init_divergent,
            "max_abs_diff": round(float(init_diff.max()), 10),
            "argmax_row": init_row,
            "argmax_model_shard": min(
                m - 1, init_row // rows_per_shard
            ),
        }) + "\n")
        for i, b in enumerate(batches):
            t_ref.state = t_ref._train_step(
                t_ref.state, t_ref._put(b)
            )
            t_probe.state = t_probe._train_step(
                t_probe.state, t_probe._put(b)
            )
            tab_ref, tab_probe = _table(t_ref), _table(t_probe)
            rec_ref = _record(
                "reference", "1x1", i, tab_ref, prev_ref,
                float(t_ref.state.metrics.loss_sum), m,
            )
            rec_probe = _record(
                "probe", mesh_shape, i, tab_probe, prev_probe,
                float(t_probe.state.metrics.loss_sum), m,
            )
            prev_ref, prev_probe = tab_ref, tab_probe
            diff = np.abs(tab_probe - tab_ref)
            tol = args.atol + args.rtol * np.abs(tab_ref)
            divergent = bool((diff > tol).any())
            row = int(np.unravel_index(diff.argmax(), diff.shape)[0])
            cmp = {
                "record": "parity_diff",
                "dispatch": i,
                "divergent": divergent,
                "max_abs_diff": round(float(diff.max()), 10),
                "argmax_row": row,
                "argmax_model_shard": min(m - 1, row // rows_per_shard),
                "update_norm_delta": round(
                    abs(rec_probe["update_norm"]
                        - rec_ref["update_norm"]), 10
                ),
                "loss_sum_delta": round(
                    abs(rec_probe["loss_sum"] - rec_ref["loss_sum"]),
                    10,
                ),
                "hash_match": (
                    rec_probe["param_hash"] == rec_ref["param_hash"]
                ),
                "shard_hash_match": [
                    a == b for a, b in zip(
                        rec_ref["shard_hashes"],
                        rec_probe["shard_hashes"],
                    )
                ],
            }
            for rec in (rec_ref, rec_probe, cmp):
                out.write(json.dumps(rec) + "\n")
            marker = ""
            if divergent and first_divergent is None:
                first_divergent = i
                worst = cmp
                marker = "  <-- FIRST DIVERGENT DISPATCH"
            elif divergent:
                marker = "  (divergent)"
                if cmp["max_abs_diff"] > worst.get("max_abs_diff", 0):
                    worst = cmp
            print(f"  dispatch {i}: max|d|="
                  f"{cmp['max_abs_diff']:.3e} "
                  f"update_norm ref={rec_ref['update_norm']:.6f} "
                  f"probe={rec_probe['update_norm']:.6f} "
                  f"hash={'=' if cmp['hash_match'] else '!'}"
                  f"{marker}")
        summary = {
            "record": "parity_summary",
            "mesh": mesh_shape,
            "dispatches": args.dispatches,
            "init_divergent": init_divergent,
            "first_divergent_dispatch": first_divergent,
            "max_abs_diff": worst.get("max_abs_diff", 0.0),
            "argmax_row": worst.get("argmax_row"),
            "argmax_model_shard": worst.get("argmax_model_shard"),
        }
        out.write(json.dumps(summary) + "\n")
    if init_divergent:
        print(f"\ndivergence PREDATES dispatch 0: the {mesh_shape} "
              f"mesh initializes a different table than the 1x1 "
              f"reference (first check sharded init, not the step "
              f"math) — per-dispatch records in {args.out}")
        return 3
    if first_divergent is None:
        print(f"\nno divergence over {args.dispatches} dispatches "
              f"(atol {args.atol:g}, rtol {args.rtol:g})")
        return 0
    print(f"\nFIRST divergent dispatch: {first_divergent} "
          f"(max|d| {worst['max_abs_diff']:.3e} at row "
          f"{worst['argmax_row']}, model shard "
          f"{worst['argmax_model_shard']}) — per-dispatch records in "
          f"{args.out}")
    return 3


if __name__ == "__main__":
    sys.exit(main())
