#!/usr/bin/env python
"""Re-drive a TFC1 traffic capture against a live scoring endpoint and
judge bitwise score parity.

A serving process with ``serve_capture_sample``/``serve_capture_file``
set records sampled request/response frame pairs (the binary TFB1 wire
frames, verbatim) into a rotating capture file — see SERVING.md
"Capture & replay".  This tool closes the loop: every captured request
is POSTed to a live ``/score_bin`` and the response bytes are compared
against the recorded ones BIT FOR BIT.

Bitwise is the honest bar, and it is achievable: capture happens after
decode (ids reduced mod vocabulary_size, arrays padded to the feature
cap), so a captured frame is in canonical form and re-decoding it is
idempotent — the same checkpoint must produce the same float32 scores.
A mismatch therefore means something REAL changed: a different
checkpoint step, a different kernel/dtype, a quantization change, or a
scoring regression.

Usage:
    python tools/replay.py CAPTURE --endpoint http://127.0.0.1:8300
    python tools/replay.py CAPTURE --endpoint ... --limit 100

Exit codes: 0 = every replayed response matched bitwise; 2 = at least
one mismatch (first few diffs reported with max |delta|); 1 = could
not replay at all (no records, endpoint unreachable).
"""

from __future__ import annotations

import argparse
import os
import sys
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from fast_tffm_tpu.serve import wire  # noqa: E402


def _post(url: str, body: bytes, timeout: float) -> bytes:
    req = urllib.request.Request(
        url, data=body,
        headers={"Content-Type": "application/octet-stream"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read()


def replay(capture: str, endpoint: str, limit: int = 0,
           timeout: float = 30.0, out=sys.stdout) -> int:
    """Replay ``capture`` against ``endpoint``; returns the exit code."""
    # Materialize the record list BEFORE the first POST: replaying
    # against an endpoint that is itself capturing (sample 1.0) appends
    # to a file we might otherwise still be reading.
    try:
        records = list(wire.read_capture(capture))
    except (OSError, ValueError) as e:
        print(f"replay: cannot read capture {capture!r}: {e}", file=out)
        return 1
    if limit > 0:
        records = records[:limit]
    if not records:
        print(f"replay: {capture!r} holds no records", file=out)
        return 1
    url = endpoint.rstrip("/") + "/score_bin"
    matched = 0
    mismatches = []
    for i, (_t, req_frame, resp_frame) in enumerate(records):
        try:
            got = _post(url, req_frame, timeout)
        except (urllib.error.URLError, OSError) as e:
            print(f"replay: request {i} failed against {url}: {e}",
                  file=out)
            return 1
        if got == resp_frame:
            matched += 1
            continue
        # Decode both sides for the report: bitwise already failed,
        # the float delta says whether this is noise-sized (kernel /
        # dtype change) or a different model entirely.
        detail = "undecodable"
        try:
            want_scores = wire.decode_bin_response(resp_frame)
            got_scores = wire.decode_bin_response(got)
            if want_scores.shape == got_scores.shape:
                delta = float(
                    abs(want_scores - got_scores).max()
                ) if want_scores.size else 0.0
                detail = f"max |delta| {delta:.3e}"
            else:
                detail = (
                    f"shape {want_scores.shape} -> {got_scores.shape}"
                )
        except Exception:
            pass
        mismatches.append((i, detail))
    print(
        f"replay: {matched}/{len(records)} responses bitwise-identical "
        f"({capture} -> {url})", file=out,
    )
    if mismatches:
        for i, detail in mismatches[:5]:
            print(f"  MISMATCH request {i}: {detail}", file=out)
        if len(mismatches) > 5:
            print(f"  ... and {len(mismatches) - 5} more", file=out)
        return 2
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Replay a TFC1 serve traffic capture against a "
                    "live endpoint, judging bitwise score parity."
    )
    ap.add_argument("capture", help="TFC1 capture file path")
    ap.add_argument(
        "--endpoint", required=True,
        help="live server base URL, e.g. http://127.0.0.1:8300",
    )
    ap.add_argument(
        "--limit", type=int, default=0,
        help="replay at most N records (0 = all)",
    )
    ap.add_argument(
        "--timeout", type=float, default=30.0,
        help="per-request timeout in seconds",
    )
    args = ap.parse_args(argv)
    return replay(
        args.capture, args.endpoint, limit=args.limit,
        timeout=args.timeout,
    )


if __name__ == "__main__":
    sys.exit(main())
