#!/usr/bin/env python
"""BASELINE config 3: Adagrad-vs-FTRL optimizer + L2-regularization sweep.

Trains the same data under a grid of (optimizer, lambda) settings and
prints a result table (validation logloss/AUC per cell), mirroring the
reference's sweep workflow. Each cell trains from scratch into its own
model dir.

Usage:
  python examples/gen_sample_data.py
  python examples/sweep_optimizers.py [base.cfg]
"""

import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from fast_tffm_tpu.config import load_config  # noqa: E402
from fast_tffm_tpu.train.loop import Trainer  # noqa: E402

# FTRL cells share the base config's learning rate (sample.cfg: 1.0).
# Measured healthy there — validation logloss 0.594 / AUC 0.824 vs
# Adagrad's 0.497 / 0.837; an earlier comment claiming divergence at
# lr=1.0 predated the current FTRL implementation and was re-measured
# false in round 4.
GRID = [
    {"optimizer": "adagrad", "factor_lambda": 0.0, "bias_lambda": 0.0},
    {"optimizer": "adagrad", "factor_lambda": 1e-4, "bias_lambda": 1e-4},
    {"optimizer": "adagrad", "factor_lambda": 1e-3, "bias_lambda": 1e-3},
    {"optimizer": "ftrl", "ftrl_l1": 0.0, "ftrl_l2": 0.0},
    {"optimizer": "ftrl", "ftrl_l1": 1e-3, "ftrl_l2": 1e-3},
    {"optimizer": "ftrl", "ftrl_l1": 1e-2, "ftrl_l2": 1e-2},
]


def main():
    base = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        REPO, "examples", "sample.cfg"
    )
    results = []
    for i, overrides in enumerate(GRID):
        model_file = f"/tmp/fast_tffm_tpu_sweep_{i}"
        shutil.rmtree(model_file, ignore_errors=True)
        cfg = load_config(base, overrides={**overrides,
                                           "model_file": model_file,
                                           "log_steps": 0})
        r = Trainer(cfg).train()
        m = r.get("validation", r["train"])
        row = {**overrides, "logloss": round(m["loss"], 6),
               "auc": round(m["auc"], 4)}
        results.append(row)
        print(json.dumps(row), flush=True)
    best = min(results, key=lambda r: r["logloss"])
    print("best:", json.dumps(best))


if __name__ == "__main__":
    main()
