#!/usr/bin/env python
"""Generate tiny synthetic Criteo-like libsvm sample data for smoke runs.

Creates train/validation/predict files under examples/data/ with a planted
2nd-order FM structure so training visibly reduces logloss (the reference's
de-facto smoke test, SURVEY.md §4).
"""

import argparse
import os

import numpy as np


# Score multiplier so the planted signal is strong (Bayes logloss ~0.4,
# vs 0.60 unscaled) and the convergence test has headroom below 0.693.
SCALE = 2.5


def gen(path, n, rng, vocab, n_feat, w, v, ffm=False, n_fields=0):
    with open(path, "w") as f:
        for _ in range(n):
            ids = rng.choice(vocab, size=n_feat, replace=False)
            vals = np.round(rng.uniform(0.2, 1.0, size=n_feat), 3)
            score = w[ids] @ vals
            s1 = (v[ids] * vals[:, None]).sum(0)
            s2 = ((v[ids] * vals[:, None]) ** 2).sum(0)
            score += 0.5 * (s1 @ s1 - s2.sum())
            p = 1.0 / (1.0 + np.exp(-SCALE * score))
            label = int(rng.uniform() < p)
            if ffm:
                fields = ids % n_fields
                toks = " ".join(
                    f"{fld}:{i}:{val}" for fld, i, val in zip(fields, ids, vals)
                )
            else:
                toks = " ".join(f"{i}:{val}" for i, val in zip(ids, vals))
            f.write(f"{label} {toks}\n")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(os.path.dirname(__file__), "data"))
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--n_feat", type=int, default=13)
    ap.add_argument("--factor", type=int, default=4)
    ap.add_argument("--train", type=int, default=8000)
    ap.add_argument("--valid", type=int, default=1000)
    ap.add_argument("--ffm", action="store_true")
    ap.add_argument("--n_fields", type=int, default=8)
    args = ap.parse_args()

    rng = np.random.default_rng(42)
    w = rng.normal(0, 0.5, size=args.vocab)
    v = rng.normal(0, 0.3, size=(args.vocab, args.factor))
    os.makedirs(args.out, exist_ok=True)
    suffix = "_ffm" if args.ffm else ""
    gen(os.path.join(args.out, f"train{suffix}.libsvm"), args.train, rng,
        args.vocab, args.n_feat, w, v, args.ffm, args.n_fields)
    gen(os.path.join(args.out, f"valid{suffix}.libsvm"), args.valid, rng,
        args.vocab, args.n_feat, w, v, args.ffm, args.n_fields)
    print(f"wrote sample data to {args.out}")


if __name__ == "__main__":
    main()
