#!/usr/bin/env python
"""Entry point matching the reference CLI: run_tffm.py {train|predict} <cfg>."""

import sys

from fast_tffm_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
