#!/usr/bin/env python
"""Entry point matching the reference CLI:
run_tffm.py {train|predict|serve} <cfg>.

serve mode mounts the HTTP scoring endpoint (SERVING.md); with
--replicas N (N >= 2) it launches N shared-nothing replica serve
processes behind the power-of-two-choices router in
fast_tffm_tpu/serve/router.py.
"""

import sys

from fast_tffm_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
