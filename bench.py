#!/usr/bin/env python
"""Benchmark: FM train-step throughput on a Criteo-like workload.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "examples/sec", "vs_baseline": N}

Baseline: the driver target of 2M examples/sec aggregate on a v5e-16
(BASELINE.md) = 125k examples/sec/chip; ``vs_baseline`` is the per-chip
ratio vs that target, scaled by the number of chips actually used.

Workload: 2nd-order FM, batch 16384, 39 features/example (Criteo layout),
factor_num 8, vocab 2^22 hash buckets — full train step (forward, backward,
sparse Adagrad update, metrics) with device-resident batches, steady-state
timed.

Timing note: completion is forced by reading back scalars that depend on
both the metrics chain and the updated table.  ``block_until_ready`` alone
under-reports on remote-tunnel platforms (it can return before the queued
executions drain), which would inflate throughput ~1000x.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

PER_CHIP_TARGET = 2_000_000 / 16  # BASELINE.md: 2M ex/s on v5e-16


def _drain(state) -> float:
    """Force the full dependency chain: metrics + updated params."""
    s = float(state.metrics.loss_sum)
    s += float(state.params.table[0, 0])
    s += float(state.step)
    return s


def main() -> int:
    import jax

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data.libsvm import Batch
    from fast_tffm_tpu.train.loop import Trainer

    devices = jax.devices()
    n_chips = len(devices)
    platform = devices[0].platform

    cfg = FmConfig(
        vocabulary_size=1 << 22,
        factor_num=8,
        max_features=39,
        batch_size=16384 * max(1, n_chips),
        learning_rate=0.05,
        model_file="/tmp/fast_tffm_tpu_bench_model",
        log_steps=0,
    )
    import shutil

    shutil.rmtree(cfg.model_file, ignore_errors=True)
    trainer = Trainer(cfg)

    rng = np.random.default_rng(0)
    n_batches = 4  # rotate a few so no cross-step result reuse
    batches = []
    for _ in range(n_batches):
        b = Batch(
            labels=rng.integers(0, 2, size=(cfg.batch_size,)).astype(np.float32),
            ids=rng.integers(0, cfg.vocabulary_size,
                             size=(cfg.batch_size, cfg.max_features)).astype(np.int32),
            vals=rng.uniform(0.1, 1.0,
                             size=(cfg.batch_size, cfg.max_features)).astype(np.float32),
            fields=np.zeros((cfg.batch_size, cfg.max_features), np.int32),
            weights=np.ones((cfg.batch_size,), np.float32),
        )
        batches.append(trainer._put(b))

    # Warmup: compile + a few steps, fully drained.
    for i in range(3):
        trainer.state = trainer._train_step(trainer.state, batches[i % n_batches])
    _drain(trainer.state)

    steps = 50
    t0 = time.perf_counter()
    for i in range(steps):
        trainer.state = trainer._train_step(trainer.state, batches[i % n_batches])
    _drain(trainer.state)
    dt = time.perf_counter() - t0

    ex_per_sec = steps * cfg.batch_size / dt
    per_chip = ex_per_sec / n_chips
    result = {
        "metric": f"fm_train_examples_per_sec ({platform} x{n_chips}, "
                  f"B={cfg.batch_size}, F=39, k=8, vocab=2^22)",
        "value": round(ex_per_sec, 1),
        "unit": "examples/sec",
        "vs_baseline": round(per_chip / PER_CHIP_TARGET, 4),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
