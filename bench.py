#!/usr/bin/env python
"""Benchmark: end-to-end FM training throughput on a Criteo-like workload.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "examples/sec", "vs_baseline": N, ...}

Baseline: the driver target of 2M examples/sec aggregate on a v5e-16
(BASELINE.md) = 125k examples/sec/chip; ``vs_baseline`` is the per-chip
ratio vs that target, scaled by the number of chips actually used.

Headline metric (the judged one): END-TO-END examples/sec — libsvm text
files generated on disk, parsed by the native C++ parser through
BatchPipeline (host threads overlapping device steps), trained with the
full sparse train step.  Feature ids are Zipf(1.1)-skewed then
hash-spread, matching CTR data's duplicate structure (which stresses the
dedup/carry chain in the sparse apply path) rather than uniform ids.
The e2e loop is the train() hot path: parse threads + the stacking/H2D
transfer thread (DevicePrefetcher) + the K-step fused scan dispatch
(steps_per_dispatch=8).  Also reported: device-step-only throughput at
K=8 and K=1 (their per-step difference is ``dispatch_overhead_ms``, the
amortized Python/runtime dispatch cost), e2e at K=1, the parse-only
rate, and ``h2d_overlap_frac`` — the fraction of the synchronous
stack+transfer cost the background transfer thread hides.

Robustness: the TPU tunnel on this machine ('axon' PJRT plugin, dialed by
a global sitecustomize) can be down or slow to init.  The backend is
probed in a SUBPROCESS with bounded retries + backoff (a failed in-process
init poisons jax's backend cache); if the tunnel never comes up the bench
falls back to CPU with an ``error`` note — the JSON line is emitted either
way so the driver always gets a parseable record.

The whole measured run itself also executes in a watchdog SUBPROCESS:
a tunnel that dies MID-bench leaves the client blocked in an RPC that no
exception ever escapes (observed on v5e: probe OK at start, pool gone
minutes later, main process asleep forever).  The parent kills the child
at a hard deadline and re-runs on CPU, recording the reason — hangs, not
just errors, can no longer zero a hardware window.

Timing note: completion is forced by reading back scalars that depend on
both the metrics chain and the updated table.  ``block_until_ready`` alone
under-reports on remote-tunnel platforms (it can return before the queued
executions drain), which would inflate throughput ~1000x.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

from fast_tffm_tpu import obs as obs_mod  # stdlib-only; no jax import

PER_CHIP_TARGET = 2_000_000 / 16  # BASELINE.md: 2M ex/s on v5e-16
_PROBE_MARK = "BENCH_PROBE_OK"


def _probe_backend(attempts: int = 3, timeout: int = 90):
    """Probe the default jax backend in a subprocess (retry + backoff).

    Returns (platform, n_devices, error_note).  platform is None if no
    backend (other than forcing CPU) could be brought up.

    Short-circuits without spawning anything when the environment pins
    CPU (JAX_PLATFORMS=cpu): a CPU-only box has no tunnel to probe, and
    the probe subprocess used to burn its full timeout dialing a dead
    axon tunnel and pollute the result JSON with a timeout error
    (BENCH_r05).  The timeout itself also drops 240s -> 90s — a healthy
    tunnel initializes in well under a minute; a wedged one never does.
    """
    plats = {
        p.strip().lower()
        for p in os.environ.get("JAX_PLATFORMS", "").split(",")
        if p.strip()
    }
    if plats and plats <= {"cpu"}:
        return "cpu", 0, None  # caller pins CPU in-process and counts
    code = (
        "import jax; d = jax.devices(); "
        f"print('{_PROBE_MARK}', d[0].platform, len(d))"
    )
    last_err = ""
    for i in range(attempts):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout,
            )
            for line in out.stdout.splitlines():
                if line.startswith(_PROBE_MARK):
                    _, plat, n = line.split()
                    return plat, int(n), None
            last_err = (out.stderr or out.stdout).strip()[-300:]
        except subprocess.TimeoutExpired:
            # A hung tunnel won't unhang in a few seconds — retrying at
            # full timeout would burn the driver's wall-clock budget, so
            # short-circuit straight to the CPU fallback.  Retries are for
            # fast transient errors only.
            return None, 0, f"backend probe timed out after {timeout}s"
        if i + 1 < attempts:
            time.sleep(5 * (i + 1))
    return None, 0, f"backend unavailable after {attempts} probes: {last_err}"


# Hard deadline for the watchdog child (seconds).  A healthy TPU run is
# ~3-6 min (a handful of ~40s tunnel compiles + the measured steps); a
# wedged tunnel blocks forever.  Overridable for tests.
WATCHDOG_S = int(os.environ.get("BENCH_WATCHDOG_S", "1800"))


def _run_watchdog_child(argv: list[str]):
    """Run the full bench in a killable child; return (json_line, reason).

    ``json_line`` is the child's result line (None if it hung, died, or
    printed no JSON), ``reason`` explains the failure for the fallback
    run's error note.
    """
    env = dict(os.environ, BENCH_CHILD="1")
    cmd = [sys.executable, os.path.abspath(__file__)] + argv
    try:
        # stderr inherits the parent's: progress/probe/traceback lines
        # stream live instead of vanishing into a pipe (the JSON contract
        # only covers stdout, which is captured and filtered).
        out = subprocess.run(
            cmd, env=env, stdout=subprocess.PIPE, text=True,
            timeout=WATCHDOG_S,
        )
    except subprocess.TimeoutExpired:
        return None, f"tpu bench hung; watchdog killed it after {WATCHDOG_S}s"
    for line in reversed(out.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                json.loads(line)
                return line, None
            except ValueError:
                continue
    tail = out.stdout.strip().splitlines()
    note = tail[-1][-200:] if tail else "no stdout (traceback on stderr)"
    return None, (
        f"bench child exited {out.returncode} without a JSON line: {note}"
    )


def _zipf_ids(rng, shape, vocab: int) -> np.ndarray:
    """Zipf(1.1)-skewed ids hash-spread over the bucket space: realistic
    CTR duplicate structure (a few very hot ids) without clustering the
    hot ids into adjacent buckets."""
    z = rng.zipf(1.1, size=shape).astype(np.uint64)
    return ((z * np.uint64(0x9E3779B97F4A7C15)) % np.uint64(vocab)).astype(
        np.int32
    )


def _gen_libsvm_files(tmpdir: str, rng, n_files: int, lines_per_file: int,
                      n_feat: int, vocab: int) -> list[str]:
    """Vectorized libsvm text generation: numpy bytes ops, pairwise-reduced
    concatenation (a left-fold over 39 growing columns copies quadratically;
    pure-Python per-token formatting would take minutes at multi-chip
    batch sizes)."""
    paths = []
    for fi in range(n_files):
        ids = _zipf_ids(rng, (lines_per_file, n_feat), vocab)
        # vals in [0.1, 1.0) with 4 decimals, formatted as "0.%04d".
        val4 = rng.integers(1000, 10000, size=(lines_per_file, n_feat))
        labels = rng.integers(0, 2, size=(lines_per_file,))
        cols = [labels.astype("S1")]
        for j in range(n_feat):
            cols.append(np.char.add(
                np.char.add(b" ", np.char.add(ids[:, j].astype("S10"), b":0.")),
                val4[:, j].astype("S4"),
            ))
        while len(cols) > 1:  # log-depth reduce
            nxt = [np.char.add(cols[i], cols[i + 1])
                   for i in range(0, len(cols) - 1, 2)]
            if len(cols) % 2:
                nxt.append(cols[-1])
            cols = nxt
        path = os.path.join(tmpdir, f"bench_{fi}.libsvm")
        with open(path, "wb") as f:
            f.write(b"\n".join(cols[0]))
            f.write(b"\n")
        paths.append(path)
    return paths


def _drain(state) -> float:
    """Force the full dependency chain: metrics + updated params."""
    s = float(state.metrics.loss_sum)
    s += float(state.params.table[0, 0])
    s += float(state.step)
    return s


def _make_batch(rng, cfg, vocab: int):
    from fast_tffm_tpu.data.libsvm import Batch

    return Batch(
        labels=rng.integers(0, 2, size=(cfg.batch_size,)).astype(np.float32),
        ids=_zipf_ids(rng, (cfg.batch_size, cfg.max_features), vocab),
        vals=rng.uniform(
            0.1, 1.0, size=(cfg.batch_size, cfg.max_features)
        ).astype(np.float32),
        fields=np.zeros((cfg.batch_size, cfg.max_features), np.int32),
        weights=np.ones((cfg.batch_size,), np.float32),
    )


# Degradation ladder: config overrides tried in order until a trainer
# survives a short smoke run.  A kernel that fails Mosaic compilation (the
# round-3 bench died at the first step with an unlowerable scatter-add and
# recorded 0.0 ex/s) must never zero a hardware window again — the XLA
# scatter path and the jnp-oracle path are always available fallbacks.
RUNGS = (
    ("default", {}),
    ("scatter", {"sparse_apply": "scatter"}),
    ("no_pallas", {"sparse_apply": "scatter", "use_pallas": False}),
)


def build_trainer_with_ladder(make_cfg, trainer_cls, smoke_steps=2,
                              start_rung=None):
    """Try each rung: build a trainer, run ``smoke_steps`` steps, drain.

    Returns ``(rung_name, trainer, cfg, errors)`` where ``errors`` lists
    ``"<rung>: <error>"`` for every rung that failed; ``rung_name`` is
    None when all rungs failed (errors then explains each).

    ``start_rung`` skips rungs before the named one — used to pin a
    variant measurement (bf16) to the rung the main config selected, so
    the two rates always compare the same kernel path.
    """
    errors: list[str] = []
    rng = np.random.default_rng(1)
    rungs = RUNGS
    if start_rung is not None:
        idx = [i for i, (n, _) in enumerate(RUNGS) if n == start_rung]
        rungs = RUNGS[idx[0]:] if idx else RUNGS
    for name, overrides in rungs:
        try:
            cfg = make_cfg(**overrides)
            trainer = trainer_cls(cfg)
            b = trainer._put(_make_batch(rng, cfg, cfg.vocabulary_size))
            for _ in range(smoke_steps):
                trainer.state = trainer._train_step(trainer.state, b)
            _drain(trainer.state)
            return name, trainer, cfg, errors
        except Exception as e:  # noqa: BLE001 — the ladder must not die
            errors.append(f"{name}: {type(e).__name__}: {e}")
    return None, None, None, errors


def _bench_quality_identity() -> float:
    """Self-skew floor of the quality plane: sketch two independent
    draws of the SAME synthetic example distribution (ids, values,
    lengths, scores) and report their psi_max.  The debiased PSI must
    read ~0 — `report.py --compare` gates it low, so any future sketch
    or PSI change that starts seeing drift in identical data flags."""
    from fast_tffm_tpu import obs

    rng = np.random.default_rng(7)
    ref, live = obs.SketchSet(), obs.SketchSet()
    for sk in (ref, live):
        for _ in range(64):
            ids = rng.integers(0, 1 << 20, size=(256, 16))
            vals = np.where(
                rng.random((256, 16)) < 0.8,
                rng.lognormal(size=(256, 16)), 0.0
            )
            sk.update_batch(ids, vals)
            sk.update_scores(rng.random(256))
    return round(float(live.psi_vs(ref).get("psi_max", 0.0)), 6)


def _bench_step_only(trainer, cfg, steps: int) -> float:
    rng = np.random.default_rng(0)
    batches = [trainer._put(_make_batch(rng, cfg, cfg.vocabulary_size))
               for _ in range(4)]
    for i in range(3):
        trainer.state = trainer._train_step(trainer.state, batches[i % 4])
    _drain(trainer.state)
    t0 = time.perf_counter()
    for i in range(steps):
        trainer.state = trainer._train_step(trainer.state, batches[i % 4])
    _drain(trainer.state)
    return steps * cfg.batch_size / (time.perf_counter() - t0)


def _bench_step_scan(trainer, cfg, steps: int, k: int) -> float:
    """Device-step throughput with the K-step fused dispatch: one
    lax.scan dispatch trains k steps, so Python/runtime dispatch overhead
    is paid once per k (the steps_per_dispatch hot path)."""
    from fast_tffm_tpu.data.pipeline import stack_batches

    rng = np.random.default_rng(0)
    supers = [
        trainer._put_super(stack_batches(
            [_make_batch(rng, cfg, cfg.vocabulary_size) for _ in range(k)]
        ))
        for _ in range(2)
    ]
    n_disp = max(2, steps // k)
    trainer.state = trainer._scan_train_step(trainer.state, supers[0])
    _drain(trainer.state)
    t0 = time.perf_counter()
    for i in range(n_disp):
        trainer.state = trainer._scan_train_step(
            trainer.state, supers[i % 2]
        )
    _drain(trainer.state)
    return n_disp * k * cfg.batch_size / (time.perf_counter() - t0)


def _bench_put_only(trainer, cfg, k: int, reps: int = 6) -> float:
    """Synchronous per-example transfer cost: stack K batches + shard +
    device_put, blocked to completion.  The overlap fraction compares
    this against the e2e-vs-step gap."""
    import jax

    from fast_tffm_tpu.data.pipeline import stack_batches

    rng = np.random.default_rng(2)
    groups = [
        [_make_batch(rng, cfg, cfg.vocabulary_size) for _ in range(k)]
        for _ in range(2)
    ]
    t0 = time.perf_counter()
    for i in range(reps):
        sb = trainer._put_super(stack_batches(groups[i % 2]))
        jax.block_until_ready(
            (sb.labels, sb.ids, sb.vals, sb.fields, sb.weights)
        )
    dt = time.perf_counter() - t0
    return dt / (reps * k * cfg.batch_size)


def _bench_parse_only(files, cfg) -> float:
    """Raw native-parser rate on the generated files (single pass, the
    internally-threaded parse_raw fast path)."""
    from fast_tffm_tpu.data import native as native_lib
    from fast_tffm_tpu.data.pipeline import _iter_raw_groups

    try:
        parser = native_lib.NativeParser(
            cfg.vocabulary_size, cfg.max_features, cfg.hash_feature_id,
            cfg.field_num, cfg.thread_num,
        )
    except Exception:  # pragma: no cover - env-dependent
        return 0.0
    n = 0
    t0 = time.perf_counter()
    for buf, starts, ends in _iter_raw_groups(files, cfg.batch_size):
        parser.parse_raw(buf, starts, ends, cfg.batch_size)
        n += len(starts)
    dt = time.perf_counter() - t0
    return n / dt if dt > 0 else 0.0


def _bench_e2e(trainer, cfg, files, warmup: int, epochs: int,
               k: int = 1, telemetry_enabled: bool = True,
               tracer=None, status: bool = False,
               resource: bool = False, quality: bool = False,
               fleet: bool = False) -> tuple:
    """Examples/sec through BatchPipeline + DevicePrefetcher — the
    train() hot path: parse threads, the stacking/H2D transfer thread,
    and the K-step fused dispatch all overlapped.  ``warmup`` counts
    BATCHES (rounded up to whole dispatches).

    Multi-epoch runs use the pipeline's parsed-batch cache (epoch 0
    parses the text, later epochs replay in permuted order) — on a
    host whose cores are saturated by the device step itself (1-core
    CPU boxes; a tight TPU tunnel host) re-parsing identical text
    every epoch is pure overhead no overlap can hide.

    Returns (overall_rate, cache_result, epoch0_rate, cached_rate,
    tele_report): the pipeline's in-band EpochEnd markers split the run
    into per-epoch windows (draining the device at each marker so the
    window measures completed training, not enqueue speed) — epoch 0
    pays the parse, epochs 1+ replay from the cache, and their gap is
    exactly what the cache buys.

    ``tele_report`` is the run's obs.Telemetry self-report: the final
    stage snapshot plus ``ingest_wait_frac`` over the TIMED region —
    the same per-stage attribution a training run's heartbeat emits,
    measured here instead of re-derived with bench-local stopwatches.
    With ``telemetry_enabled=False`` the run uses no-op instruments
    (the on/off rate ratio is the layer's measured overhead).

    ``tracer`` (an enabled obs.Tracer) additionally records the causal
    span layer through the pipeline + prefetcher + this loop's
    wait/dispatch — the trace-overhead probe runs the identical e2e
    with it attached and compares rates.

    ``status=True`` attaches a live obs.StatusServer serving this
    run's telemetry snapshot AND a scraper thread hitting ``/metrics``
    every 200 ms — the endpoint-overhead probe (endpoint on + scraped
    vs off) under a realistic Prometheus-ish cadence.

    ``resource=True`` attaches a resource-plane sampler thread: RSS /
    peak-RSS (``/proc`` reads) + the component byte gauges + the
    compile-sentinel snapshot, every 200 ms — the marginal cost of the
    resource plane's live sampling at an aggressive heartbeat-like
    cadence (the AOT dispatch path itself is already in the baseline:
    the trainer's cfg has resource_metrics on by default).

    ``quality=True`` attaches the model-quality plane's full run-time
    work: the parse-path drift sketches (StreamSketch on the pipeline)
    and the windowed online-eval monitor consuming each dispatch's
    scores one dispatch delayed, exactly like train() — the
    quality-overhead probe.  The scan's score EMISSION is in the
    baseline too (the bench trainer's cfg has quality on by default);
    it is one [K, B] store whose bitwise-no-op-ness the parity tests
    pin, so the on/off ratio here measures the part that does real
    work: sketch updates + the window statistics + the extra D2H.
    """
    import threading

    from fast_tffm_tpu import obs
    from fast_tffm_tpu.data.pipeline import (
        BatchPipeline, DevicePrefetcher, EpochEnd,
    )

    tel = obs.Telemetry(enabled=telemetry_enabled)
    status_server = None
    scrape_stop = threading.Event()
    scraper = None
    res_sampler = None
    fleet_plane = None

    def _start_resource():
        nonlocal res_sampler

        def _sample():
            sent = getattr(trainer, "_sentinel", None)
            while not scrape_stop.wait(0.2):
                obs.read_rss()
                gauges = tel.snapshot().get("gauges") or {}
                sum(
                    gauges.get(name, 0) or 0
                    for name in ("ingest.ring_bytes",
                                 "ingest.cache_bytes",
                                 "prefetch.staging_bytes")
                )
                if sent is not None:
                    sent.snapshot()

        res_sampler = threading.Thread(target=_sample, daemon=True)
        res_sampler.start()

    def _start_status():
        # Called inside the try below so a pipeline/prefetcher
        # construction failure cannot leak the server + scraper into
        # the rest of the bench (they would keep scraping a dead
        # probe's registry and perturb every later timing).
        nonlocal status_server, scraper
        import urllib.request

        status_server = obs.StatusServer(
            0,
            lambda: {
                "record": "status",
                "time": time.time(),
                "stages": tel.snapshot(),
            },
            telemetry=tel,
        )

        def _scrape():
            url = f"http://127.0.0.1:{status_server.port}/metrics"
            while not scrape_stop.wait(0.2):
                try:
                    urllib.request.urlopen(url, timeout=2).read()
                except Exception:  # noqa: BLE001 - probe must not die
                    pass

        scraper = threading.Thread(target=_scrape, daemon=True)
        scraper.start()

    def _start_fleet():
        # The training-fleet plane at production shape (ISSUE 18):
        # the live /status endpoint with the per-rank metrics_extra
        # hook, a TrainFleet scraping it on the heartbeat cadence
        # (0.2 s, the smoke/aggressive setting), and an external
        # /metrics scraper on top — prices scrape + merge +
        # labeled-series rendering together.
        nonlocal status_server, scraper, fleet_plane
        import urllib.request

        t0 = time.time()
        status_server = obs.StatusServer(
            0,
            lambda: {
                "record": "status",
                "time": time.time(),
                "rank": 0,
                "step": 0,
                "elapsed": round(time.time() - t0, 3),
                "stages": tel.snapshot(),
            },
            telemetry=tel,
            metrics_extra=lambda: (
                fleet_plane.metrics_lines() if fleet_plane else ""
            ),
        )
        fleet_plane = obs.TrainFleet(
            [f"127.0.0.1:{status_server.port}"], interval_s=0.2,
            telemetry=tel,
        )

        def _scrape():
            url = f"http://127.0.0.1:{status_server.port}/metrics"
            while not scrape_stop.wait(0.2):
                try:
                    urllib.request.urlopen(url, timeout=2).read()
                except Exception:  # noqa: BLE001 - probe must not die
                    pass

        scraper = threading.Thread(target=_scrape, daemon=True)
        scraper.start()
    tracer = tracer if tracer is not None else obs.NULL_TRACER
    qual_mon = None
    qual_sketch = None
    pending_q = None
    if quality:
        qual_sketch = obs.StreamSketch(cfg.quality_window)
        qual_mon = obs.QualityMonitor(
            loss_type=cfg.loss_type, window=cfg.quality_window,
            sketch=qual_sketch,
        )
    t_wait = tel.timer("train.wait_input")
    t_disp = tel.timer("train.dispatch")
    # The dataset (not epochs) bounds the cache: size the budget to hold
    # it so the reported ingest_cache outcome only says "overflow" when
    # the files genuinely outgrow host memory expectations.  ordered=True
    # matches the trainer's own pipeline (sequence-numbered delivery —
    # same throughput) and makes the marker positions exact.
    pipeline = BatchPipeline(
        files, cfg, epochs=epochs, shuffle=True, ordered=True,
        cache_epochs=True, cache_max_bytes=4 << 30, epoch_marks=True,
        # Pre-stacked cache: groups stack once at epoch-0 boundaries and
        # replay epochs hand whole super-batches to the prefetcher (the
        # trainer's cache_prestacked path).
        prestack_k=k,
        telemetry=tel,
        tracer=tracer,
        quality=qual_sketch,
    )

    # Real-example counts ride the host stack (transfer thread), keeping
    # the timed loop free of device readbacks.
    def put(stacked):
        return (
            trainer._put_super(stacked),
            int(np.sum(stacked.weights > 0)),
        )

    prefetcher = DevicePrefetcher(
        pipeline, k, put, depth=cfg.prefetch_super_batches, telemetry=tel,
        # put() device_puts (copies out of host memory), so stacking can
        # recycle the pre-allocated staging buffers like the trainer.
        staging=True,
        tracer=tracer,
    )
    it = iter(prefetcher)
    epoch_rates: dict[int, float] = {}
    try:
        if status:
            _start_status()
        if fleet:
            _start_fleet()
        if resource:
            _start_resource()
        warmed = 0
        # sb label counts from the first super-batch CONSUMED, warmup
        # included, so the trace's train.dispatch args.sb stays aligned
        # with the prefetcher's stack/h2d sb ids (trace_chains joins on
        # it).
        sb_i = 0
        while warmed < warmup:
            item = next(it)
            if isinstance(item, EpochEnd):  # tiny stream: epoch < warmup
                continue
            (sb, _), kk = item
            trainer.state = trainer._scan_train_step(trainer.state, sb)
            sb_i += 1
            warmed += kk
        _drain(trainer.state)
        n = 0
        # Wall-clock attribution over the timed region only: subtract
        # the warmup's accumulated wait/dispatch totals.
        wait0, disp0 = t_wait.total_s, t_disp.total_s
        t0 = time.perf_counter()
        n_mark, t_mark = 0, t0
        while True:
            with t_wait.time(), tracer.span("train.wait_input"):
                item = next(it, None)
            if item is None:
                break
            if isinstance(item, EpochEnd):
                _drain(trainer.state)
                now = time.perf_counter()
                if n > n_mark:
                    epoch_rates[item.epoch] = (
                        (n - n_mark) / max(now - t_mark, 1e-9)
                    )
                n_mark, t_mark = n, now
                continue
            (sb, n_real), kk = item
            with t_disp.time(), tracer.span(
                "train.dispatch", args={"sb": sb_i, "k": kk}
            ):
                trainer.state = trainer._scan_train_step(trainer.state, sb)
            sb_i += 1
            n += n_real
            if qual_mon is not None and getattr(
                trainer, "_with_scores", False
            ):
                # The trainer's one-dispatch-delayed quality feed,
                # reproduced: async D2H this dispatch's scores, consume
                # the previous dispatch's.
                arrs = (trainer._last_scores, sb.labels, sb.weights)
                for a in arrs:
                    try:
                        a.copy_to_host_async()
                    except Exception:  # noqa: BLE001 - backend drift
                        pass
                if pending_q is not None:
                    qual_mon.observe(
                        np.asarray(pending_q[0]),
                        np.asarray(pending_q[1]),
                        np.asarray(pending_q[2]),
                    )
                    qual_mon.block()
                pending_q = arrs
        _drain(trainer.state)
        dt = time.perf_counter() - t0
    finally:
        scrape_stop.set()
        if scraper is not None:
            scraper.join()
        if res_sampler is not None:
            res_sampler.join()
        if fleet_plane is not None:
            fleet_plane.close()
        if status_server is not None:
            status_server.close()
        prefetcher.close()
    epoch0 = epoch_rates.get(0, 0.0)
    replays = [r for e, r in epoch_rates.items() if e > 0]
    cached = float(np.median(replays)) if replays else 0.0
    wait_s = t_wait.total_s - wait0
    disp_s = t_disp.total_s - disp0
    snap = tel.snapshot()
    tele_report = {
        "ingest_wait_frac": round(wait_s / max(dt, 1e-9), 4),
        "wait_input_s": round(wait_s, 3),
        "dispatch_s": round(disp_s, 3),
        "timed_wall_s": round(dt, 3),
        "stages": snap,
    }
    # Prestacked-cache split: how many dispatches skipped the transfer-
    # stage stack (epoch 0 stacks once in the pipeline; replays reuse),
    # and the once-per-group stack cost wherever it was paid.
    counters = snap.get("counters", {})
    timers = snap.get("timers", {})
    supers = counters.get("prefetch.super_batches", 0)
    if supers:
        tele_report["prestack_hit_frac"] = round(
            counters.get("prefetch.prestack_hits", 0) / supers, 4
        )
    stack_n = (
        timers.get("prefetch.stack", {}).get("count", 0)
        + timers.get("ingest.prestack", {}).get("count", 0)
    )
    stack_s = (
        timers.get("prefetch.stack", {}).get("total_s", 0.0)
        + timers.get("ingest.prestack", {}).get("total_s", 0.0)
    )
    if stack_n:
        tele_report["stack_ms_per_superbatch"] = round(
            1e3 * stack_s / stack_n, 3
        )
    return (
        (n / dt if dt > 0 else 0.0), pipeline.cache_result, epoch0, cached,
        tele_report,
    )


def _rss_mb() -> float:
    """Current process RSS in MB (not peak: per-section DELTAS are the
    point — peak never comes back down, so one section's residue used
    to skew every later section's reading)."""
    from fast_tffm_tpu import obs as _obs

    return _obs.read_rss()[0] / (1 << 20)


def _with_rss_delta(section_fn, *args) -> dict:
    """Run one bench section and stamp its own RSS before/delta into
    its dict — each section's memory story is measured at its own
    boundaries, regardless of section order."""
    before = _rss_mb()
    out = section_fn(*args)
    if isinstance(out, dict):
        out["rss_before_mb"] = round(before, 1)
        out["rss_delta_mb"] = round(_rss_mb() - before, 1)
    return out


def _spread(samples) -> dict:
    """min/max of a repeated-trial rate measurement — the run-to-run
    swing the medians hide (the documented 0.99-1.10 e2e/step drift),
    quantified per BENCH record instead of folklore."""
    if not samples:
        return {"min": 0.0, "max": 0.0, "n": 0}
    return {
        "min": round(float(min(samples)), 1),
        "max": round(float(max(samples)), 1),
        "n": len(samples),
    }


def _bench_tiered(workers: int) -> dict:
    """Tiered-table section: a V=2^28 Zipf-1.1 training run that CANNOT
    exist as a dense device table (2^28 x 9 f32 params + optimizer slots
    ~= 19 GB before activations), completed through the two-tier store
    with hot_rows = 2^20, plus a dense V=2^26 baseline for the
    migration-overlap comparison (is ingest_wait_frac still ~0 with
    remap+migration riding the prefetch stage?).

    Multi-epoch on purpose: epoch 0 pays the cold-start misses (every
    distinct id loads once), replay epochs re-touch the same rows — the
    steady-state regime a production trainer lives in, and what
    hot_hit_frac is meant to measure.
    """
    import shutil as _sh

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.train.loop import Trainer

    out: dict = {"completed": False}
    tmpdir = tempfile.mkdtemp(prefix="fast_tffm_tiered_")
    try:
        vocab = 1 << 28
        hot = 1 << 20
        batch = 4096
        epochs = 8
        rng = np.random.default_rng(11)
        lines = 12 * batch
        files = _gen_libsvm_files(tmpdir, rng, 2, lines // 2, 39, vocab)

        def run(tag, **overrides):
            kw = dict(
                vocabulary_size=vocab, factor_num=8, max_features=39,
                batch_size=batch, learning_rate=0.05,
                model_file=os.path.join(tmpdir, f"model_{tag}"),
                log_steps=0, thread_num=workers, queue_size=workers,
                epoch_num=epochs, steps_per_dispatch=8,
                cache_epochs=True, cache_prestacked=True,
                cache_max_bytes=4 << 30,
                train_files=files,
                save_steps=0,
            )
            kw.update(overrides)
            c = FmConfig(**kw)
            t0 = time.perf_counter()
            r = Trainer(c).train()
            r["train"]["wall_s"] = time.perf_counter() - t0
            _sh.rmtree(c.model_file, ignore_errors=True)
            return r["train"]

        tiered = run("tiered", table_tiering="on", hot_rows=hot)
        # The dense V=2^26 baseline allocates ~5 GB of tables; its
        # failure (tight-memory box) must not discard the tiered result.
        try:
            dense = run("dense", vocabulary_size=1 << 26)
        except Exception as e:  # noqa: BLE001 - keep the tiered half
            dense = None
            out["dense_baseline_error"] = f"{type(e).__name__}: {e}"
        snap = tiered.get("tiered", {})
        out.update({
            "completed": True,
            "vocab_log2": 28,
            "hot_rows_log2": 20,
            "batch_size": batch,
            "epochs": epochs,
            "examples_per_sec": round(tiered["examples_per_sec"], 1),
            "hot_hit_frac": snap.get("hot_hit_frac", 0.0),
            "rows_loaded": snap.get("rows_loaded", 0),
            "rows_evicted": snap.get("rows_evicted", 0),
            "resident_rows": snap.get("resident_rows", 0),
            "cold_store_bytes": snap.get("cold_store_bytes", 0),
            "ingest_wait_frac": tiered["ingest_wait_frac"],
        })
        if dense is not None:
            out["dense_baseline"] = {
                "vocab_log2": 26,
                "examples_per_sec": round(dense["examples_per_sec"], 1),
                "ingest_wait_frac": dense["ingest_wait_frac"],
            }
            # The acceptance comparison: migration must hide behind the
            # prefetch transfer — the tiered run's starvation fraction
            # vs the dense baseline's, same step/ingest configuration.
            out["migration_overlap"] = {
                "ingest_wait_frac_tiered": tiered["ingest_wait_frac"],
                "ingest_wait_frac_dense": dense["ingest_wait_frac"],
                "delta": round(
                    tiered["ingest_wait_frac"]
                    - dense["ingest_wait_frac"], 4
                ),
            }
    except Exception as e:  # noqa: BLE001 - report, never sink the bench
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return out


# Fleet-bench worker (ISSUE 19): one subprocess per role.  "global" is
# the single-process host-global tiered baseline on a (1 data x 2
# model) mesh; "fleet" is one of two gloo ranks running the SAME
# config rank-sharded (one model column = one tier shard each);
# "overlap" A/Bs the compute-overlapped entries exchange on a 2x2
# mesh.  Each prints one `FLEETBENCH {json}` line.
_FLEET_BENCH_WORKER = r"""
import json, os, sys, time

mode = sys.argv[1]          # "fleet" | "global" | "overlap"
tmpdir = sys.argv[2]
threads = int(sys.argv[3])

import jax
jax.config.update("jax_platforms", "cpu")
if mode == "fleet":
    # CPU cross-process collectives need the gloo transport.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=sys.argv[4],
        num_processes=2,
        process_id=int(sys.argv[5]),
    )

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.train.loop import Trainer

VOCAB = 1 << 16
files = sorted(
    os.path.join(tmpdir, f) for f in os.listdir(tmpdir)
    if f.endswith(".libsvm")
)


def run(tag, **kw):
    base = dict(
        vocabulary_size=VOCAB, factor_num=8, max_features=16,
        batch_size=1024, learning_rate=0.05, train_files=files,
        model_file=os.path.join(tmpdir, "model_" + tag),
        log_steps=0, thread_num=threads, queue_size=threads,
        epoch_num=2, steps_per_dispatch=2, save_steps=0,
    )
    base.update(kw)
    t = Trainer(FmConfig(**base))
    t.save = lambda stepno: None  # perf section, not a checkpoint test
    t0 = time.perf_counter()
    r = t.train()
    wall = time.perf_counter() - t0
    exch = t.telemetry.timer("train.exchange").snapshot().get(
        "total_s", 0.0
    )
    return {
        "examples_per_sec": r["train"]["examples_per_sec"],
        "wall_s": round(wall, 3),
        "exchange_frac": round(exch / wall, 6) if wall > 0 else 0.0,
        "device_bytes": int(t._state_bytes_est),
        "tiered": r["train"].get("tiered"),
        "overlap_active": bool(t._overlap_active),
    }


if mode == "fleet":
    rank = int(sys.argv[5])
    port0, port1 = int(sys.argv[6]), int(sys.argv[7])
    out = run(
        "fleet%d" % rank, mesh_data=1, mesh_model=2,
        table_tiering="on", hot_rows=1 << 15,
        tiered_partition="shards",
        status_port=port0 if rank == 0 else port1,
        train_fleet_scrape="127.0.0.1:%d,127.0.0.1:%d" % (port0, port1),
        heartbeat_secs=0.5,
    )
    out["rank"] = rank
elif mode == "global":
    out = run(
        "global", mesh_data=1, mesh_model=2,
        table_tiering="on", hot_rows=1 << 15,
        tiered_partition="global",
    )
else:  # overlap: off/on A/B, same process, same files, same mesh
    port_off, port_on = int(sys.argv[4]), int(sys.argv[5])
    kw = dict(
        mesh_data=2, mesh_model=2, sparse_apply="tile",
        sparse_exchange="entries", heartbeat_secs=0.5,
    )
    out = {
        "off": run("ov_off", sparse_exchange_overlap="off",
                   status_port=port_off,
                   train_fleet_scrape="127.0.0.1:%d" % port_off, **kw),
        "on": run("ov_on", sparse_exchange_overlap="on",
                  status_port=port_on,
                  train_fleet_scrape="127.0.0.1:%d" % port_on, **kw),
    }
print("FLEETBENCH " + json.dumps(out), flush=True)
"""


def _bench_fleet_train(workers: int) -> dict:
    """Fleet-training section (ISSUE 19): the rank-sharded tiered table
    and the overlapped sparse exchange, measured as real processes.

    Three sub-runs over one generated dataset (V=2^16 Zipf, hot=2^15):

      * a single-process host-global tiered baseline on the (1x2) mesh
        — the pre-sharding memory/throughput reference;
      * a 2-rank gloo fleet running the SAME recipe rank-sharded: each
        rank's hot-table+optimizer device bytes and cold-store bytes
        must land at ~1/R of the baseline's (the tentpole's memory
        claim, asserted here as shard_bytes_frac_ok);
      * an overlap A/B on a 2x2 mesh with the entries exchange: the
        train.exchange probe's synchronous window fraction with the
        overlap off vs on — on must read strictly lower (the merge is
        hidden behind rank-local apply; parity is pinned bitwise in
        tests/test_tiered_fleet.py, this section measures the win).
    """
    import socket

    out: dict = {"completed": False}
    tmpdir = tempfile.mkdtemp(prefix="fast_tffm_fleet_")
    repo = os.path.dirname(os.path.abspath(__file__))
    try:
        rng = np.random.default_rng(13)
        _gen_libsvm_files(tmpdir, rng, 2, 8192, 16, 1 << 16)
        script = os.path.join(tmpdir, "fleet_bench_worker.py")
        with open(script, "w") as f:
            f.write(_FLEET_BENCH_WORKER)
        threads = max(2, workers // 2)

        def spawn(argv, devices):
            env = dict(
                os.environ,
                PALLAS_AXON_POOL_IPS="",
                JAX_PLATFORMS="cpu",
                XLA_FLAGS=(
                    "--xla_force_host_platform_device_count=%d"
                    % devices
                ),
                PYTHONPATH=repo + os.pathsep + os.environ.get(
                    "PYTHONPATH", ""
                ),
            )
            return subprocess.Popen(
                [sys.executable, script] + [str(a) for a in argv],
                env=env, cwd=repo, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )

        def harvest(proc, tag, timeout=600):
            o, e = proc.communicate(timeout=timeout)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"{tag} worker rc={proc.returncode}: {e[-1500:]}"
                )
            for line in o.splitlines():
                if line.startswith("FLEETBENCH "):
                    return json.loads(line[len("FLEETBENCH "):])
            raise RuntimeError(f"{tag} worker printed no result line")

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        glob_res = harvest(
            spawn(["global", tmpdir, threads], 2), "global"
        )

        coord = f"127.0.0.1:{free_port()}"
        p0, p1 = free_port(), free_port()
        procs = [
            spawn(["fleet", tmpdir, threads, coord, r, p0, p1], 1)
            for r in range(2)
        ]
        ranks = []
        try:
            for i, p in enumerate(procs):
                ranks.append(harvest(p, f"fleet rank {i}"))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()

        ov = harvest(
            spawn(["overlap", tmpdir, threads, free_port(),
                   free_port()], 4),
            "overlap",
        )

        rank_bytes = max(r["device_bytes"] for r in ranks)
        glob_bytes = max(1, glob_res["device_bytes"])
        rank_cold = max(
            (r.get("tiered") or {}).get("cold_store_bytes", 0)
            for r in ranks
        )
        glob_cold = (glob_res.get("tiered") or {}).get(
            "cold_store_bytes", 0
        )
        shard_frac = rank_bytes / glob_bytes
        out.update({
            "completed": True,
            "tier_shards": 2,
            "vocab_log2": 16,
            "hot_rows_log2": 15,
            "sharded_examples_per_sec": round(
                min(r["examples_per_sec"] for r in ranks), 1
            ),
            "global_examples_per_sec": round(
                glob_res["examples_per_sec"], 1
            ),
            "fleet_exchange_frac": round(
                max(r["exchange_frac"] for r in ranks), 6
            ),
            "rank_device_bytes": rank_bytes,
            "global_device_bytes": glob_res["device_bytes"],
            "shard_bytes_frac": round(shard_frac, 4),
            # The ~1/R acceptance at R=2: each rank's table+optimizer
            # device bytes must sit near half the host-global run's
            # (w0/scalars stay replicated, hence the band, not 0.5).
            "shard_bytes_frac_ok": bool(0.3 < shard_frac < 0.7),
            "rank_cold_store_bytes": rank_cold,
            "global_cold_store_bytes": glob_cold,
            "cold_bytes_frac": round(
                rank_cold / max(1, glob_cold), 4
            ),
            "rank_owned_shards": [
                (r.get("tiered") or {}).get("owned_shards") for r in ranks
            ],
            "exchange_frac_off": ov["off"]["exchange_frac"],
            "exchange_overlap_frac": ov["on"]["exchange_frac"],
            "overlap_active": bool(ov["on"]["overlap_active"]),
            # The overlap acceptance: the synchronous exchange window
            # must shrink when the merge rides behind rank-local apply.
            "overlap_hides_exchange": bool(
                ov["on"]["exchange_frac"] < ov["off"]["exchange_frac"]
            ),
            "overlap_examples_per_sec_off": round(
                ov["off"]["examples_per_sec"], 1
            ),
            "overlap_examples_per_sec_on": round(
                ov["on"]["examples_per_sec"], 1
            ),
        })
        if not out["shard_bytes_frac_ok"]:
            out["error"] = (
                f"per-rank device bytes {rank_bytes} not ~1/2 of "
                f"host-global {glob_res['device_bytes']}"
            )
    except Exception as e:  # noqa: BLE001 - report, never sink the bench
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return out


def _bench_quant(workers: int) -> dict:
    """Quantized-table section: the BENCH tiered config (V=2^28 Zipf,
    hot_rows=2^20) trained with each cold_dtype — step rate + real
    compact cold-store footprint fp32 vs bf16 vs int8 — plus the DENSE
    table bytes/row of each serving format (measured by quantizing a
    real table block, not derived): the two byte axes the quantization
    layer exists to shrink.  The acceptance frame: bf16 >= 2x fewer
    table bytes/row (int8 ~4x at quant_chunk=64) with e2e step rate
    within 0.95x of fp32 — quantization must buy bytes, not cost
    throughput (encode/decode rides the transfer thread, off the
    dispatch path).
    """
    import shutil as _sh

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.ops import quant as quant_mod
    from fast_tffm_tpu.train.loop import Trainer

    out: dict = {"completed": False}
    tmpdir = tempfile.mkdtemp(prefix="fast_tffm_quant_")
    try:
        vocab = 1 << 28
        hot = 1 << 20
        batch = 4096
        epochs = 4
        rng = np.random.default_rng(13)
        lines = 8 * batch
        files = _gen_libsvm_files(tmpdir, rng, 2, lines // 2, 39, vocab)
        dims = 9  # 1 + factor_num at the bench shapes

        def run(dtype):
            cfg = FmConfig(
                vocabulary_size=vocab, factor_num=8, max_features=39,
                batch_size=batch, learning_rate=0.05,
                model_file=os.path.join(tmpdir, f"model_{dtype}"),
                log_steps=0, thread_num=workers, queue_size=workers,
                epoch_num=epochs, steps_per_dispatch=8,
                cache_epochs=True, cache_prestacked=True,
                cache_max_bytes=4 << 30,
                train_files=files, save_steps=0,
                table_tiering="on", hot_rows=hot, cold_dtype=dtype,
            )
            r = Trainer(cfg).train()
            _sh.rmtree(cfg.model_file, ignore_errors=True)
            snap = r["train"].get("tiered", {})
            return {
                "examples_per_sec": round(
                    r["train"]["examples_per_sec"], 1
                ),
                "cold_store_bytes": snap.get("cold_store_bytes", 0),
                "cold_bytes_per_row": snap.get("cold_bytes_per_row", 0),
                "hot_hit_frac": snap.get("hot_hit_frac", 0.0),
            }

        runs = {}
        for dtype in ("fp32", "bf16", "int8"):
            runs[dtype] = run(dtype)
        # Dense (serving-format) bytes/row, measured on a real block.
        block = np.random.default_rng(5).normal(
            0, 0.01, (4096, dims)
        ).astype(np.float32)
        dense_bpr = {"fp32": 4.0 * dims}
        for dtype in ("bf16", "int8"):
            qt = quant_mod.quantize_table(block, dtype, 64)
            dense_bpr[dtype] = round(qt.nbytes / len(block), 3)
        fp32_rate = runs["fp32"]["examples_per_sec"]
        out.update({
            "completed": True,
            "vocab_log2": 28,
            "hot_rows_log2": 20,
            "epochs": epochs,
            "quant_chunk": 64,
            "runs": runs,
            "table_bytes_per_row": dense_bpr,
            # Bytes-per-row ratios are the gated axis (deterministic —
            # cold_store_bytes is workload-dependent: a run whose hot
            # set never overflows writes no overlay rows at all, and
            # 0/0 would gate nothing).
            "cold_bytes_per_row_frac": {
                d: round(
                    runs[d]["cold_bytes_per_row"]
                    / max(1, runs["fp32"]["cold_bytes_per_row"]), 4
                )
                for d in ("bf16", "int8")
            },
            "step_rate_frac": {
                d: round(
                    runs[d]["examples_per_sec"] / fp32_rate, 4
                ) if fp32_rate > 0 else 0.0
                for d in ("bf16", "int8")
            },
        })
    except Exception as e:  # noqa: BLE001 - report, never sink the bench
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    return out


def _bench_serve(workers: int) -> dict:
    """Serving section: latency under concurrent load through the FULL
    online path — HTTP socket -> request batcher -> compiled
    fixed-shape scorer — the numbers a million-user deployment is
    sized from.

    Client threads fire mixed-size scoring requests (1..64 examples,
    Zipf-ish small-heavy, the online-traffic shape) flat-out for a
    fixed window; latency is measured CLIENT-side (connect to last
    byte, the number a user actually sees), throughput as completed
    requests/s.  ``serve_batch_fill`` and the compile accounting come
    from the server's own telemetry — ``serve_steady_compiles`` MUST
    be 0 (every shape precompiled at warmup; a nonzero value here is
    the latency cliff the ladder exists to prevent).
    """
    import threading as _th
    import urllib.request as _rq

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.models import fm as _fm
    from fast_tffm_tpu.serve.batcher import ServeBatcher
    from fast_tffm_tpu.serve.scorer import FixedShapeScorer
    from fast_tffm_tpu.serve.server import ServeServer
    from fast_tffm_tpu import obs as _obs

    import jax as _jax

    out: dict = {"completed": False}
    server = batcher = None
    try:
        cfg = FmConfig(
            vocabulary_size=1 << 20, factor_num=8, max_features=39,
            batch_size=1024, model_file="/tmp/fast_tffm_serve_bench",
        )
        params = _jax.jit(
            lambda k: _fm.init_params(k, cfg=cfg)
        )(_jax.random.PRNGKey(3))
        tel = _obs.Telemetry()
        scorer = FixedShapeScorer(cfg, params, telemetry=tel)
        warm_compiles = scorer.warmup()
        batcher = ServeBatcher(
            scorer, max_batch_wait_ms=cfg.max_batch_wait_ms,
            queue_size=cfg.queue_size, telemetry=tel,
        )
        server = ServeServer(
            0, batcher, cfg,
            lambda: {"record": "status", "stages": tel.snapshot()},
            telemetry=tel,
        )
        rng = np.random.default_rng(5)
        # Pre-render request bodies (mixed sizes, small-request-heavy)
        # so client threads measure the SERVER, not body formatting.
        sizes = [1, 1, 2, 4, 4, 8, 16, 32, 64]
        bodies = []
        for n in sizes * 4:
            lines = []
            for _ in range(n):
                ids = rng.integers(0, cfg.vocabulary_size, 12)
                lines.append("0 " + " ".join(
                    f"{i}:{rng.uniform(0.1, 1.0):.3f}" for i in ids
                ))
            bodies.append(("\n".join(lines) + "\n").encode())
        url = f"http://127.0.0.1:{server.port}/score"
        duration = 4.0
        n_clients = min(8, max(2, workers))
        lat_lock = _th.Lock()
        lats: list = []
        errors: list = []

        def client(seed: int):
            r = np.random.default_rng(seed)
            end = time.perf_counter() + duration
            my = []
            try:
                while time.perf_counter() < end:
                    body = bodies[int(r.integers(0, len(bodies)))]
                    t0 = time.perf_counter()
                    try:
                        resp = _rq.urlopen(_rq.Request(
                            url, data=body, method="POST"
                        ), timeout=30)
                        resp.read()
                    except Exception as e:  # noqa: BLE001 - report below
                        errors.append(f"{type(e).__name__}: {e}")
                        return
                    my.append(time.perf_counter() - t0)
            finally:
                # A client dying mid-window still contributes the work
                # it DID complete — qps/percentiles must not silently
                # drop a whole client's samples over one late error.
                with lat_lock:
                    lats.extend(my)

        # Warm the HTTP+dispatch path once so client 0's first request
        # doesn't measure connection/jit-cache cold start.
        _rq.urlopen(_rq.Request(url, data=bodies[0], method="POST"),
                    timeout=60).read()
        threads = [
            _th.Thread(target=client, args=(100 + i,))
            for i in range(n_clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if not lats:
            out["error"] = "no request completed: " + "; ".join(
                errors[:3]
            )
            return out
        arr = np.array(lats) * 1e3
        # Binary-transport probe: the same mixed-size traffic shape
        # over POST /score_bin.  serve.parse_bin times the per-request
        # frame decode exactly where serve.parse times the text parse,
        # so serve_bin_p50_ms vs serve_parse_p50_ms is the measured
        # host cost the binary transport removes from the hot path.
        from fast_tffm_tpu.serve import wire as _wire

        bin_frames = []
        for n in sizes * 4:
            b_ids = rng.integers(
                0, cfg.vocabulary_size, (n, 12)
            ).astype(np.int32)
            b_vals = rng.uniform(0.1, 1.0, (n, 12)).astype(np.float32)
            bin_frames.append(_wire.encode_bin_request(b_ids, b_vals))
        bin_url = f"http://127.0.0.1:{server.port}/score_bin"
        bin_errors = []
        for frame in bin_frames * 3:
            try:
                _rq.urlopen(_rq.Request(
                    bin_url, data=frame, method="POST",
                    headers={"Content-Type":
                             "application/octet-stream"},
                ), timeout=30).read()
            except Exception as e:  # noqa: BLE001 - report below
                bin_errors.append(f"{type(e).__name__}: {e}")
                break
        if bin_errors:
            out["bin_probe_error"] = bin_errors[0]
        snap = tel.snapshot()
        counters = snap.get("counters", {})
        gauges = snap.get("gauges", {})
        timers = snap.get("timers", {})
        # Quantized-serving sizing probe: place the SAME params as an
        # int8 table (no HTTP window — placement sets the table-bytes
        # and probe-error gauges; no ladder compiles happen) so the
        # serve section reports the replica-density numbers next to
        # the latency ones.
        try:
            q_tel = _obs.Telemetry()
            q_cfg = FmConfig(
                vocabulary_size=1 << 20, factor_num=8, max_features=39,
                batch_size=1024, serve_table_dtype="int8",
                quant_chunk=64,
                model_file="/tmp/fast_tffm_serve_bench_q",
            )
            FixedShapeScorer(q_cfg, params, telemetry=q_tel)
            q_gauges = q_tel.snapshot().get("gauges", {})
            out["serve_table_mb_int8"] = round(
                q_gauges.get("serve.table_bytes", 0) / (1 << 20), 3
            )
            out["serve_quant_error_max_int8"] = round(
                float(q_gauges.get("serve.quant_error_max", 0.0)), 6
            )
        except Exception as e:  # noqa: BLE001 - probe must not sink it
            out["quant_probe_error"] = f"{type(e).__name__}: {e}"
        # Paired serve-trace overhead probe (ISSUE 14): identical
        # client windows against the SAME warm scorer — tracing OFF
        # (the main stack, no tracer) vs request sampling at 0.1 with
        # a live tracer — back-to-back so box drift can't masquerade
        # as overhead.  serve_trace_overhead = qps_off / qps_on;
        # budget <= 1.05, the standard obs-overhead budget.
        try:
            import dataclasses as _dc
            import shutil as _sh2
            import tempfile as _tf2

            from fast_tffm_tpu.obs.trace import Tracer as _Tracer

            def _probe_window(url_: str, dur: float):
                done = [0]

                def cl(seed: int):
                    r = np.random.default_rng(seed)
                    end = time.perf_counter() + dur
                    while time.perf_counter() < end:
                        body = bodies[int(r.integers(0, len(bodies)))]
                        try:
                            _rq.urlopen(_rq.Request(
                                url_, data=body, method="POST"
                            ), timeout=30).read()
                        except Exception:  # noqa: BLE001 - end window
                            return
                        with lat_lock:
                            done[0] += 1

                ths = [
                    _th.Thread(target=cl, args=(500 + i,))
                    for i in range(n_clients)
                ]
                w0 = time.perf_counter()
                for t in ths:
                    t.start()
                for t in ths:
                    t.join()
                return done[0], time.perf_counter() - w0

            trace_dir = _tf2.mkdtemp(prefix="tffm_bench_strace_")
            t_cfg = _dc.replace(
                cfg, serve_trace_sample=0.1,
                trace_file=os.path.join(trace_dir, "serve_trace.json"),
            )
            t_tel = _obs.Telemetry()
            tracer = _Tracer(enabled=True, process_name="serve-bench")
            t_batcher = ServeBatcher(
                scorer, max_batch_wait_ms=cfg.max_batch_wait_ms,
                queue_size=cfg.queue_size, telemetry=t_tel,
                tracer=tracer,
            )
            t_server = ServeServer(
                0, t_batcher, t_cfg,
                lambda: {"record": "status"}, telemetry=t_tel,
                tracer=tracer,
            )
            try:
                t_url = f"http://127.0.0.1:{t_server.port}/score"
                _rq.urlopen(_rq.Request(
                    t_url, data=bodies[0], method="POST"
                ), timeout=60).read()
                n_off, w_off = _probe_window(url, 2.0)
                n_on, w_on = _probe_window(t_url, 2.0)
                qps_off = n_off / w_off if w_off > 0 else 0.0
                qps_on = n_on / w_on if w_on > 0 else 0.0
                out["serve_trace_overhead"] = (
                    round(qps_off / qps_on, 4) if qps_on > 0 else -1.0
                )
                out["serve_trace_dropped"] = int(tracer.dropped_events)
            finally:
                t_server.close()
                t_batcher.close()
                tracer.close()
                _sh2.rmtree(trace_dir, ignore_errors=True)
        except Exception as e:  # noqa: BLE001 - probe must not sink it
            out["trace_probe_error"] = f"{type(e).__name__}: {e}"
        # Paired traffic-capture overhead probe (ISSUE 20): identical
        # client windows against the SAME warm scorer — capture OFF
        # (the main stack) vs a TFC1 CaptureWriter sampling at 0.1 —
        # back-to-back so box drift can't masquerade as overhead.
        # capture_overhead = qps_off / qps_on; budget <= 1.05, the
        # standard obs-overhead budget.
        try:
            import dataclasses as _dc4
            import shutil as _sh4
            import tempfile as _tf4

            def _cap_window(url_: str, dur: float):
                done = [0]

                def cl(seed: int):
                    r = np.random.default_rng(seed)
                    end = time.perf_counter() + dur
                    while time.perf_counter() < end:
                        body = bodies[int(r.integers(0, len(bodies)))]
                        try:
                            _rq.urlopen(_rq.Request(
                                url_, data=body, method="POST"
                            ), timeout=30).read()
                        except Exception:  # noqa: BLE001 - end window
                            return
                        with lat_lock:
                            done[0] += 1

                ths = [
                    _th.Thread(target=cl, args=(900 + i,))
                    for i in range(n_clients)
                ]
                w0 = time.perf_counter()
                for t in ths:
                    t.start()
                for t in ths:
                    t.join()
                return done[0], time.perf_counter() - w0

            cap_dir = _tf4.mkdtemp(prefix="tffm_bench_capture_")
            cap_path = os.path.join(cap_dir, "requests.capture")
            c_cfg = _dc4.replace(
                cfg, serve_capture_sample=0.1,
                serve_capture_file=cap_path,
            )
            c_tel = _obs.Telemetry()
            cap = _wire.CaptureWriter(
                cap_path, sample=0.1, telemetry=c_tel,
            )
            c_batcher = ServeBatcher(
                scorer, max_batch_wait_ms=cfg.max_batch_wait_ms,
                queue_size=cfg.queue_size, telemetry=c_tel,
            )
            c_server = ServeServer(
                0, c_batcher, c_cfg,
                lambda: {"record": "status"}, telemetry=c_tel,
                capture=cap,
            )
            try:
                c_url = f"http://127.0.0.1:{c_server.port}/score"
                _rq.urlopen(_rq.Request(
                    c_url, data=bodies[0], method="POST"
                ), timeout=60).read()
                n_off, w_off = _cap_window(url, 2.0)
                n_on, w_on = _cap_window(c_url, 2.0)
                qps_off = n_off / w_off if w_off > 0 else 0.0
                qps_on = n_on / w_on if w_on > 0 else 0.0
                out["capture_overhead"] = (
                    round(qps_off / qps_on, 4) if qps_on > 0 else -1.0
                )
                out["capture_requests"] = int(cap.count)
            finally:
                c_server.close()
                c_batcher.close()
                cap.close()
                _sh4.rmtree(cap_dir, ignore_errors=True)
        except Exception as e:  # noqa: BLE001 - probe must not sink it
            out["capture_probe_error"] = f"{type(e).__name__}: {e}"
        # Vectorized-parser speedup probe (ISSUE 16): the SAME decoded
        # request bodies through parse_request twice — the vec path
        # (the default this section serves with) vs the legacy
        # per-line loop — direct calls, no HTTP, so the ratio isolates
        # the parser.  Median-of-3 windows per mode so one GC pause
        # can't set the headline.
        try:
            import dataclasses as _dc3

            from fast_tffm_tpu.serve.textparse import parse_request

            texts = [b.decode() for b in bodies]
            leg_cfg = _dc3.replace(cfg, serve_parse_mode="legacy")

            def _parse_window(pcfg) -> float:
                p0 = time.perf_counter()
                for txt in texts:
                    parse_request(txt, pcfg)
                return time.perf_counter() - p0

            _parse_window(cfg)  # warm both paths once
            _parse_window(leg_cfg)
            vec_s = sorted(_parse_window(cfg) for _ in range(3))[1]
            leg_s = sorted(_parse_window(leg_cfg) for _ in range(3))[1]
            out["serve_parse_vec_speedup"] = (
                round(leg_s / vec_s, 3) if vec_s > 0 else -1.0
            )
        except Exception as e:  # noqa: BLE001 - probe must not sink it
            out["parse_probe_error"] = f"{type(e).__name__}: {e}"
        # Pooled-accept toggle probe (ISSUE 16): paired client windows
        # against the SAME warm batcher — the pooled front end above
        # vs a legacy thread-per-connection mount
        # (serve_http_threads=0) — back-to-back so box drift can't
        # masquerade as an accept-model difference.
        try:
            import dataclasses as _dc4

            l_cfg = _dc4.replace(cfg, serve_http_threads=0)
            l_server = ServeServer(
                0, batcher, l_cfg,
                lambda: {"record": "status"}, telemetry=tel,
            )
            try:
                l_url = f"http://127.0.0.1:{l_server.port}/score"
                _rq.urlopen(_rq.Request(
                    l_url, data=bodies[0], method="POST"
                ), timeout=60).read()

                def _accept_window(url_: str, dur: float):
                    done = [0]

                    def cl2(seed: int):
                        r = np.random.default_rng(seed)
                        end = time.perf_counter() + dur
                        while time.perf_counter() < end:
                            body = bodies[int(
                                r.integers(0, len(bodies))
                            )]
                            try:
                                _rq.urlopen(_rq.Request(
                                    url_, data=body, method="POST"
                                ), timeout=30).read()
                            except Exception:  # noqa: BLE001 - end
                                return
                            with lat_lock:
                                done[0] += 1

                    ths2 = [
                        _th.Thread(target=cl2, args=(900 + i,))
                        for i in range(n_clients)
                    ]
                    a0 = time.perf_counter()
                    for t in ths2:
                        t.start()
                    for t in ths2:
                        t.join()
                    return done[0], time.perf_counter() - a0

                n_leg, w_leg = _accept_window(l_url, 2.0)
                n_pool, w_pool = _accept_window(url, 2.0)
                qps_leg = n_leg / w_leg if w_leg > 0 else 0.0
                qps_pool = n_pool / w_pool if w_pool > 0 else 0.0
                out["serve_qps_legacy_accept"] = round(qps_leg, 1)
                out["serve_accept_pooled_x"] = (
                    round(qps_pool / qps_leg, 4)
                    if qps_leg > 0 else -1.0
                )
            finally:
                l_server.close()
        except Exception as e:  # noqa: BLE001 - probe must not sink it
            out["accept_probe_error"] = f"{type(e).__name__}: {e}"
        out.update({
            "completed": True,
            "clients": n_clients,
            "duration_s": round(wall, 2),
            "requests": len(lats),
            "serve_qps": round(len(lats) / wall, 1),
            "serve_examples_per_sec": round(
                counters.get("serve.examples", 0) / wall, 1
            ),
            "serve_p50_ms": round(float(np.percentile(arr, 50)), 3),
            "serve_p95_ms": round(float(np.percentile(arr, 95)), 3),
            "serve_p99_ms": round(float(np.percentile(arr, 99)), 3),
            "serve_batch_fill": round(batcher.batch_fill, 4),
            "serve_batches": int(counters.get("serve.batches", 0)),
            "warmup_compiles": warm_compiles,
            "serve_steady_compiles": int(scorer.steady_compiles),
            "max_batch_wait_ms": cfg.max_batch_wait_ms,
            # Device-resident table footprint of THIS (fp32) server and
            # the measured per-request text-parse cost (the host time a
            # binary transport would remove — serve.parse timer).
            "serve_table_mb": round(
                gauges.get("serve.table_bytes", 0) / (1 << 20), 3
            ),
            "serve_parse_p50_ms": float(
                (timers.get("serve.parse") or {}).get("p50_ms", 0.0)
            ),
            "serve_bin_p50_ms": float(
                (timers.get("serve.parse_bin") or {}).get("p50_ms", 0.0)
            ),
            # Which accept model served THIS section's numbers: the
            # pooled worker front end (serve_http_threads > 0) or the
            # legacy thread-per-connection server.
            "serve_http_threads": int(cfg.serve_http_threads),
            "serve_accept_pooled": (
                1 if cfg.serve_http_threads > 0 else 0
            ),
        })
        if errors:
            out["client_errors"] = errors[:5]
    except Exception as e:  # noqa: BLE001 - report, never sink the bench
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        # A failed probe must not leak the serve stack (HTTP thread,
        # dispatcher thread, the device-resident scorer) into the
        # sections that run after it — exactly the cross-section
        # contamination this section was reordered to avoid.
        if server is not None:
            server.close()
        if batcher is not None:
            batcher.close()
    return out


def _bench_serve_router(workers: int) -> dict:
    """Scale-out serving section: the 2-replica router fleet under
    concurrent load, then under a 4x-offered-load burst.

    Three numbers are the point (ROADMAP direction 3):

    - ``serve_router_qps`` vs the single-process section's
      ``serve_qps`` — does throughput scale with processes (the main
      wiring records the ratio as ``serve_router_scaleout_x``; on a
      1-core box the replicas share the core, so judge the ratio on a
      multi-core host);
    - ``serve_shed_frac`` under the burst — overload must produce fast
      429s, not an unbounded queue;
    - ``serve_burst_p99_ms`` — the ADMITTED-request tail under 4x
      offered load; graceful degradation means it stays within ~2x the
      unloaded ``serve_router_p99_ms`` instead of collapsing.

    Replicas are REAL subprocesses (shared-nothing: their own jax
    runtimes, own ports) against a checkpoint this section saves; the
    router runs in-process.
    """
    import shutil as _sh
    import tempfile as _tf
    import threading as _th
    import urllib.request as _rq

    from fast_tffm_tpu.config import FmConfig, load_config
    from fast_tffm_tpu.models import fm as _fm
    from fast_tffm_tpu.serve import router as _router
    from fast_tffm_tpu.train import checkpoint as _ckpt

    import jax as _jax

    out: dict = {"completed": False}
    handle = None
    tmpdir = _tf.mkdtemp(prefix="tffm_bench_router_")
    try:
        model_dir = os.path.join(tmpdir, "model")
        gen_cfg = FmConfig(
            vocabulary_size=1 << 20, factor_num=8, max_features=39,
            batch_size=1024, model_file=model_dir,
        )
        params = _jax.jit(
            lambda k: _fm.init_params(k, cfg=gen_cfg)
        )(_jax.random.PRNGKey(3))
        _ckpt.save(
            model_dir, 1,
            _fm.FmParams(*[np.asarray(x) for x in params]),
        )
        cfg_path = os.path.join(tmpdir, "serve.cfg")
        # 15 ms deadline budget: ~the unloaded p99 (admitted requests
        # stay bounded near it), far below the seconds-long queues a
        # 4x overload would otherwise build.
        with open(cfg_path, "w") as f:
            f.write(f"""[General]
vocabulary_size = {1 << 20}
factor_num = 8
model_file = {model_dir}
[Train]
batch_size = 1024
[Predict]
serve_replicas = 2
serve_shed_deadline_ms = 15
serve_poll_secs = 0
[Tpu]
max_features = 39
""")
        cfg = load_config(cfg_path)
        handle = _router.start_fleet(cfg, cfg_path, port=0)
        url = f"http://127.0.0.1:{handle.port}/score"
        rng = np.random.default_rng(7)

        def make_bodies(sizes):
            rendered = []
            for n in sizes:
                lines = []
                for _ in range(n):
                    ids = rng.integers(0, cfg.vocabulary_size, 12)
                    lines.append("0 " + " ".join(
                        f"{i}:{rng.uniform(0.1, 1.0):.3f}" for i in ids
                    ))
                rendered.append(("\n".join(lines) + "\n").encode())
            return rendered

        # Unloaded window: the online mixed-size shape (same as the
        # single-replica section).  Burst window: max-rung-heavy bodies
        # so 4x the client concurrency genuinely exceeds fleet
        # capacity — overload must come from offered WORK, not from
        # client-thread count.
        bodies = make_bodies([1, 1, 2, 4, 4, 8, 16, 32, 64] * 4)
        burst_bodies = make_bodies([64] * 8 + [32] * 2)
        lat_lock = _th.Lock()

        import http.client as _hc

        router_port = handle.port

        def window(n_clients: int, duration: float, bodies):
            """Closed-loop client window over PERSISTENT keep-alive
            connections (a latency-path client does not reconnect per
            request, and the router keeps 429s on the same
            connection); returns (ok_lats_ms, shed, errors, wall)."""
            lats: list = []
            shed = [0]
            errors: list = []

            def client(seed: int):
                r = np.random.default_rng(seed)
                end = time.perf_counter() + duration
                my = []
                my_shed = 0
                conn = _hc.HTTPConnection(
                    "127.0.0.1", router_port, timeout=30
                )
                try:
                    while time.perf_counter() < end:
                        body = bodies[int(r.integers(0, len(bodies)))]
                        t0 = time.perf_counter()
                        try:
                            conn.request(
                                "POST", "/score", body=body,
                                headers={"Content-Type": "text/plain"},
                            )
                            resp = conn.getresponse()
                            resp.read()
                            if resp.will_close:
                                conn.close()
                                conn = _hc.HTTPConnection(
                                    "127.0.0.1", router_port,
                                    timeout=30,
                                )
                        except (OSError, _hc.HTTPException) as e:
                            errors.append(f"{type(e).__name__}: {e}")
                            return
                        if resp.status == 200:
                            my.append(time.perf_counter() - t0)
                        elif resp.status == 429:
                            # A shed IS the overload discipline
                            # working: count it, back off briefly
                            # (real clients honor Retry-After; the
                            # bench caps it at 50 ms so the window
                            # still measures sustained overload —
                            # zero-backoff clients would just burn
                            # the box on the shed path itself).
                            my_shed += 1
                            time.sleep(0.05)
                        else:
                            errors.append(f"HTTP {resp.status}")
                            return
                finally:
                    conn.close()
                    with lat_lock:
                        lats.extend(my)
                        shed[0] += my_shed

            threads = [
                _th.Thread(target=client, args=(200 + i,))
                for i in range(n_clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return lats, shed[0], errors, time.perf_counter() - t0

        # Warm the proxy path (connection pools, both replicas) before
        # measuring.
        for _ in range(4):
            _rq.urlopen(_rq.Request(url, data=bodies[0], method="POST"),
                        timeout=60).read()
        n_clients = min(8, max(2, workers))
        lats, shed, errors, wall = window(n_clients, 4.0, bodies)
        if not lats:
            out["error"] = "no request completed: " + "; ".join(
                errors[:3]
            )
            return out
        arr = np.array(lats) * 1e3
        out.update({
            "replicas": len(handle.replicas),
            "clients": n_clients,
            "duration_s": round(wall, 2),
            "requests": len(lats),
            "serve_router_qps": round(len(lats) / wall, 1),
            "serve_router_p50_ms": round(
                float(np.percentile(arr, 50)), 3
            ),
            "serve_router_p99_ms": round(
                float(np.percentile(arr, 99)), 3
            ),
            "unloaded_shed": shed,
        })
        # The burst's fair baseline: the same max-rung-heavy bodies,
        # unloaded (a 64-example request costs more than the mixed
        # shape above even with no queue).
        h_lats, _, h_errors, _ = window(n_clients, 2.0, burst_bodies)
        h_arr = np.array(h_lats) * 1e3 if h_lats else np.zeros(1)
        heavy_unloaded_p99 = float(np.percentile(h_arr, 99))
        # Burst probe: 4x the offered concurrency for 3 s, max-rung
        # bodies.  The admission budget must shed (429) rather than
        # queue, and the ADMITTED tail must stay near the unloaded
        # tail (serve_burst_p99_x is admitted-p99 over the
        # same-bodies unloaded p99 — the graceful-degradation ratio;
        # note everything here shares one box, so core contention
        # itself inflates burst service time on small hosts).
        b_lats, b_shed, b_errors, b_wall = window(
            n_clients * 4, 3.0, burst_bodies
        )
        total = len(b_lats) + b_shed
        b_arr = np.array(b_lats) * 1e3 if b_lats else np.zeros(1)
        burst_p99 = float(np.percentile(b_arr, 99))
        out.update({
            "burst_clients": n_clients * 4,
            "burst_requests": total,
            "serve_shed_frac": round(
                b_shed / total, 4
            ) if total else 0.0,
            "serve_burst_p99_ms": round(burst_p99, 3),
            "burst_unloaded_p99_ms": round(heavy_unloaded_p99, 3),
            "serve_burst_p99_x": round(
                burst_p99 / heavy_unloaded_p99, 3
            ) if heavy_unloaded_p99 > 0 else 0.0,
            "burst_admitted_qps": round(
                len(b_lats) / b_wall, 1
            ) if b_wall > 0 else 0.0,
        })
        errors.extend(h_errors)
        if errors or b_errors:
            out["client_errors"] = (errors + b_errors)[:5]
        # Per-replica steady-compile audit: the zero-compile contract
        # must hold on every replica (scraped from each replica's own
        # /metrics), and the router must not have evicted anyone.
        steady = []
        for rep in handle.replicas:
            try:
                text = _rq.urlopen(
                    f"http://{rep.host}:{rep.port}/metrics", timeout=5
                ).read().decode()
                m = re.search(
                    r"^tffm_serve_steady_compiles (\d+)", text,
                    re.MULTILINE,
                )
                steady.append(int(m.group(1)) if m else -1)
            except Exception:  # noqa: BLE001 - audit is best-effort
                steady.append(-1)
        out["serve_router_steady_compiles"] = max(steady) if steady \
            else -1
        router_block = handle.router._build()["serve"]
        out["router_evictions"] = router_block["evictions"]
        out["router_retries"] = router_block["retries"]
        # Fleet metrics-scrape cost (ISSUE 14): one health-loop sweep
        # pulling every replica's /status serve block — the price of
        # one-scrape-sees-the-whole-fleet, kept visible so it can't
        # silently grow with fleet size.
        r_timers = handle.telemetry.snapshot().get("timers", {})
        out["fleet_scrape_ms"] = float(
            (r_timers.get("serve.fleet_scrape") or {}).get(
                "p50_ms", 0.0
            )
        )
        out["fleet_replicas_scraped"] = int(
            router_block.get("replicas_scraped", 0)
        )
        out["completed"] = True
    except Exception as e:  # noqa: BLE001 - report, never sink the bench
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        if handle is not None:
            handle.close()
        _sh.rmtree(tmpdir, ignore_errors=True)
    return out


def _bench_pipeline_ingest(files, cfg, parse_processes: int
                           ) -> tuple[float, float]:
    """(lines/sec, ring_zero_copy_frac) draining the FULL BatchPipeline
    (reader + parse workers + delivery) with no training attached —
    threads vs a process pool on the same files is the parse_processes
    scaling comparison, now running on the inbound SHM ring (the frac
    reports how many raw windows went zero-copy vs pickled; -1 when the
    mode has no ring, i.e. threads)."""
    import dataclasses

    from fast_tffm_tpu import obs
    from fast_tffm_tpu.data.pipeline import BatchPipeline

    c = dataclasses.replace(cfg, parse_processes=parse_processes)
    tel = obs.Telemetry()
    n = 0
    t0 = time.perf_counter()
    for b in BatchPipeline(files, c, epochs=1, shuffle=False,
                           telemetry=tel):
        n += int(np.count_nonzero(b.weights))
    dt = time.perf_counter() - t0
    counters = tel.snapshot().get("counters", {})
    ring = counters.get("ingest.ring_windows", 0)
    fallback = counters.get("ingest.ring_fallback_windows", 0)
    frac = ring / (ring + fallback) if (ring + fallback) else -1.0
    return (n / dt if dt > 0 else 0.0), frac


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["e2e", "step"], default="e2e")
    ap.add_argument("--steps", type=int, default=50)
    args = ap.parse_args()

    # Preflight: tier-1 marker audit (tools/check_tier1.py, static AST —
    # milliseconds).  A test file whose every test went slow has silently
    # dropped out of the correctness gate; the bench JSON records that
    # drift every run so it can't pass unnoticed.
    tier1_audit = None
    try:
        repo = os.path.dirname(os.path.abspath(__file__))
        sys.path.insert(0, os.path.join(repo, "tools"))
        import check_tier1

        a = check_tier1.audit(os.path.join(repo, "tests"), repo)
        tier1_audit = {
            "ok": a["ok"], "files": a["files"], "tier1": a["tier1"],
            "slow": a["slow"],
        }
        if a["problems"]:
            tier1_audit["problems"] = a["problems"][:5]
    except Exception as e:  # noqa: BLE001 - preflight must not sink bench
        tier1_audit = {"ok": False, "problems": [f"audit failed: {e}"]}

    # Preflight: the full static-analysis suite (tools/lint — tier-1
    # audit is one of its rules, but the bench JSON keeps tier1_audit
    # as its own back-compat block).  lint_findings_new is a gated
    # --compare key: a PR that introduces a new finding regresses the
    # bench trajectory exactly like a perf key (direction: low).
    lint_findings_new = None
    lint_findings_baselined = None
    try:
        repo = os.path.dirname(os.path.abspath(__file__))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from tools import lint as lint_mod

        lr = lint_mod.run(root=repo)
        lint_findings_new = (
            len(lr["new"]) + len(lr["stale"]) + len(lr["uncommented"])
        )
        lint_findings_baselined = len(lr["baselined"])
    except Exception as e:  # noqa: BLE001 - preflight must not sink bench
        print(f"lint preflight failed: {e}", file=sys.stderr)

    # Preflight: bench-trajectory trend (the same adjacent-step rule
    # `tools/report.py --timeline` prints) over any committed
    # BENCH_r*.json stack next to this script.  timeline_regressions
    # is the count of keys whose trend already crossed the threshold —
    # a numeric top-level key, so --compare gates a NEW one appearing
    # (direction: low) without anyone remembering to run --timeline.
    timeline_regs = None
    timeline_reg_keys = None
    try:
        import glob as glob_mod

        repo = os.path.dirname(os.path.abspath(__file__))
        hist = sorted(glob_mod.glob(os.path.join(repo, "BENCH_r*.json")))
        if len(hist) >= 2:
            if repo not in sys.path:
                sys.path.insert(0, repo)
            from tools import report as report_mod

            tr = report_mod.timeline_regressions(hist)
            timeline_regs = len(tr["regressions"])
            if tr["regressions"]:
                timeline_reg_keys = dict(
                    sorted(tr["regressions"].items())[:8]
                )
    except Exception as e:  # noqa: BLE001 - preflight must not sink bench
        print(f"timeline preflight failed: {e}", file=sys.stderr)

    watchdog_note = None
    if not os.environ.get("BENCH_CHILD") and not os.environ.get(
        "BENCH_FORCE_CPU"
    ):
        # Parent role: delegate the real run to a killable child; fall
        # through to an in-process CPU run only if the child hangs/dies.
        line, reason = _run_watchdog_child(sys.argv[1:])
        if line is not None:
            print(line)
            return 0
        os.environ["BENCH_FORCE_CPU"] = "1"
        watchdog_note = reason

    if os.environ.get("BENCH_FORCE_CPU"):
        platform, n_chips, err = None, 0, watchdog_note
    else:
        platform, n_chips, err = _probe_backend()
    if platform is None or platform == "cpu":
        # Tunnel down, or the probe itself already fell back to CPU: pin
        # CPU in-process too, otherwise backend init re-dials the axon
        # tunnel and can hang unboundedly.
        from fast_tffm_tpu.platform import pin_cpu

        import jax

        pin_cpu()
        platform, n_chips = "cpu", len(jax.devices())

    on_tpu = platform not in ("cpu",)
    step_rate, e2e_rate, parse_rate, bf16_rate = 0.0, 0.0, 0.0, 0.0
    step_rate_k1, e2e_rate_k1 = 0.0, 0.0
    s_samples, s1_samples, e_samples = [], [], []
    tiered_section = None
    fleet_section = None
    serve_section = None
    serve_router_section = None
    quant_section = None
    dispatch_overhead_ms, h2d_overlap_frac = 0.0, 0.0
    e2e_epoch0, e2e_cached = 0.0, 0.0
    ingest_threads_rate, ingest_procs_rate = 0.0, 0.0
    ring_zero_copy_frac = -1.0
    bench_procs = 0
    ingest_cache = "off"
    tele_report = None
    e2e_tel_off = 0.0
    e2e_trace_on, trace_events = 0.0, 0
    e2e_status_on = 0.0
    e2e_resource_on = 0.0
    e2e_quality_on = 0.0
    e2e_fleet_on = 0.0
    bench_compile_s = 0.0
    autotune_rate_auto, autotune_rate_ref = 0.0, 0.0
    autotune_kernel_impl, autotune_times = "", {}
    compile_s_cold, compile_s_warm = 0.0, 0.0
    compile_cache_hits = -1
    bf16_rung, bf16_errors = None, []
    e2e_err = None
    cfg = None
    ladder_rung, ladder_errors = None, []
    K = 8  # steps_per_dispatch for the headline (K=1 also reported)
    try:
        from fast_tffm_tpu.config import FmConfig
        from fast_tffm_tpu.train.loop import Trainer

        workers = min(16, max(4, (os.cpu_count() or 4) - 2))

        def make_cfg(**overrides):
            kw = dict(
                vocabulary_size=1 << 22 if on_tpu else 1 << 20,
                factor_num=8,
                max_features=39,
                batch_size=(16384 if on_tpu else 4096) * max(1, n_chips),
                learning_rate=0.05,
                model_file="/tmp/fast_tffm_tpu_bench_model",
                log_steps=0,
                thread_num=workers,
                # One queued group per worker: shallower starves parallel
                # parsers on multi-core hosts, deeper just front-loads
                # parsing (the timed-region sizing below scales with the
                # in-flight bound so warmup can't pre-parse the measured
                # region either way).
                queue_size=workers,
            )
            kw.update(overrides)
            c = FmConfig(**kw)
            shutil.rmtree(c.model_file, ignore_errors=True)
            return c

        ladder_rung, trainer, cfg, ladder_errors = build_trainer_with_ladder(
            make_cfg, Trainer
        )
        if trainer is None:
            raise RuntimeError(
                "all ladder rungs failed: " + " | ".join(ladder_errors)
            )

        steps = args.steps if on_tpu else min(args.steps, 10)
        # Dispatch split: the same device step at one dispatch per batch
        # (K=1) vs the K-step fused scan; the per-step difference is the
        # amortized Python/runtime dispatch overhead.  Step-only regions
        # are short (seconds), so each rate is a median of 3 trials —
        # single-shot step rates on a shared box swing several percent,
        # which would swamp the e2e-vs-step split the JSON reports.
        trials = 1 if on_tpu else 3
        s1_samples = [
            _bench_step_only(trainer, cfg, steps) for _ in range(trials)
        ]
        step_rate_k1 = float(np.median(s1_samples))
        s_samples = [
            _bench_step_scan(trainer, cfg, max(steps, K), K)
            for _ in range(trials)
        ]
        step_rate = float(np.median(s_samples))
        # Compile attribution so far (the step-only regions' K=8 + K=1
        # scan compiles); the e2e block re-captures after its probes.
        if getattr(trainer, "_sentinel", None) is not None:
            bench_compile_s = trainer._sentinel.compile_s

        if args.mode == "e2e":
            try:
                tmpdir = tempfile.mkdtemp(prefix="fast_tffm_bench_")
                try:
                    rng = np.random.default_rng(7)
                    # Full GLOBAL batches per epoch (scales with chip
                    # count so no partial zero-padded groups distort the
                    # judged number).  An epoch must span SEVERAL K=8
                    # dispatches: the e2e warmup consumes one whole
                    # dispatch, and the per-epoch rate split (epoch-0
                    # parse vs cached replay) needs timed batches left in
                    # epoch 0 after it — 8 batches/epoch used to leave
                    # zero and reported e2e_epoch0 = 0.  CPU pays 32
                    # (cheap lines); TPU pays 16 (disk-bound filegen).
                    n_files = 4
                    lines_per_file = (4 if on_tpu else 8) * cfg.batch_size
                    files = _gen_libsvm_files(
                        tmpdir, rng, n_files, lines_per_file,
                        cfg.max_features, cfg.vocabulary_size,
                    )
                    parse_rate = _bench_parse_only(files, cfg)
                    batches_per_epoch = n_files * lines_per_file // cfg.batch_size
                    # Timed region must be >> the max in-flight buffer
                    # (work + out queues + one batch per parser thread),
                    # else the timed loop mostly drains batches pre-parsed
                    # during warmup and overstates ingest throughput.
                    # In-flight now also counts the transfer stage's
                    # stacked super-batches (depth + 1 in flight, K
                    # batches each).
                    inflight = (
                        cfg.thread_num + 2 * cfg.queue_size + 2
                        + K * (cfg.prefetch_super_batches + 1)
                    )
                    want_batches = 4 + max(
                        64 if on_tpu else 24,
                        (5 if on_tpu else 3) * inflight,
                    )
                    # >= 3 epochs so the cached-replay rate (epochs 1+)
                    # gets at least two windows behind the epoch-0 parse.
                    epochs = max(3, -(-want_batches // batches_per_epoch))
                    # PAIRED measurement of the judged split: alternate
                    # K=8 step-only and K=8 e2e rounds and take the
                    # median of each.  The two rates are compared against
                    # each other, and on a shared box throughput drifts
                    # several percent minute to minute — separately-timed
                    # windows would hand that drift straight to the
                    # ratio, while interleaved rounds feed both medians
                    # from the same span.
                    rounds = 1 if on_tpu else 3
                    s_samples, s1_samples, e_samples = [], [], []
                    e0_samples, ec_samples, off_samples = [], [], []
                    for _ in range(rounds):
                        s1_samples.append(_bench_step_only(
                            trainer, cfg, steps
                        ))
                        s_samples.append(_bench_step_scan(
                            trainer, cfg, max(steps, 2 * K), K
                        ))
                        r, ingest_cache, r0, rc, tele_report = _bench_e2e(
                            trainer, cfg, files, warmup=4, epochs=epochs,
                            k=K,
                        )
                        e_samples.append(r)
                        e0_samples.append(r0)
                        ec_samples.append(rc)
                        # Telemetry overhead probe, PAIRED: the identical
                        # K=8 e2e with no-op instruments runs inside the
                        # same round, so the on/off ratio feeds both
                        # medians from the same machine-state span
                        # instead of handing run-to-run drift to a
                        # single trailing off-run.
                        off_r, _, _, _, _ = _bench_e2e(
                            trainer, cfg, files, warmup=4, epochs=epochs,
                            k=K, telemetry_enabled=False,
                        )
                        off_samples.append(off_r)
                    e2e_tel_off = float(np.median(off_samples))
                    # All three medians feed from the same windows, so
                    # the derived dispatch_overhead_ms and e2e/step split
                    # compare like with like.
                    step_rate_k1 = float(np.median(s1_samples))
                    step_rate = float(np.median(s_samples))
                    e2e_rate = float(np.median(e_samples))
                    e2e_epoch0 = float(np.median(e0_samples))
                    e2e_cached = float(np.median(ec_samples))
                    # K=1 comparison point (the classic per-batch loop,
                    # now also through the transfer stage).
                    e2e_rate_k1, _, _, _, _ = _bench_e2e(
                        trainer, cfg, files, warmup=4, epochs=epochs, k=1
                    )
                    # Trace-overhead probe (telemetry_on_vs_off-style):
                    # the identical K=8 e2e with the causal span layer
                    # recording through pipeline + prefetcher + the
                    # dispatch loop.  trace_overhead = off/on rate
                    # ratio; the span layer's budget is <= 1.05.
                    try:
                        from fast_tffm_tpu import obs as _obs

                        _tr = _obs.Tracer(enabled=True)
                        e2e_trace_on, _, _, _, _ = _bench_e2e(
                            trainer, cfg, files, warmup=4,
                            epochs=epochs, k=K, tracer=_tr,
                        )
                        trace_events = len(_tr.take())
                    except Exception as e:  # noqa: BLE001 - report only
                        ladder_errors.append(
                            f"trace probe: {type(e).__name__}: {e}"
                        )
                    # Status-endpoint overhead probe (same shape as the
                    # telemetry/trace probes): the identical K=8 e2e
                    # with the live /metrics endpoint up AND scraped
                    # every 200 ms.  status_endpoint_overhead = off/on
                    # rate ratio; budget <= 1.05 like the other layers.
                    try:
                        e2e_status_on, _, _, _, _ = _bench_e2e(
                            trainer, cfg, files, warmup=4,
                            epochs=epochs, k=K, status=True,
                        )
                    except Exception as e:  # noqa: BLE001 - report only
                        ladder_errors.append(
                            f"status endpoint probe: "
                            f"{type(e).__name__}: {e}"
                        )
                    # Resource-plane overhead probe (the PR 8 pillar,
                    # same paired shape): the identical K=8 e2e with
                    # RSS + component-ledger + compile-sentinel
                    # sampling at an aggressive 200 ms cadence.
                    # resource_overhead = off/on rate ratio; budget
                    # <= 1.05 like every other obs layer.
                    try:
                        e2e_resource_on, _, _, _, _ = _bench_e2e(
                            trainer, cfg, files, warmup=4,
                            epochs=epochs, k=K, resource=True,
                        )
                    except Exception as e:  # noqa: BLE001 - report only
                        ladder_errors.append(
                            f"resource probe: {type(e).__name__}: {e}"
                        )
                    # Model-quality overhead probe (ISSUE 15, same
                    # paired shape): the identical K=8 e2e with the
                    # parse-path drift sketches + windowed online-eval
                    # monitor attached.  quality_overhead = off/on
                    # rate ratio; budget <= 1.05 like every obs layer.
                    try:
                        e2e_quality_on, _, _, _, _ = _bench_e2e(
                            trainer, cfg, files, warmup=4,
                            epochs=epochs, k=K, quality=True,
                        )
                    except Exception as e:  # noqa: BLE001 - report only
                        ladder_errors.append(
                            f"quality probe: {type(e).__name__}: {e}"
                        )
                    # Training-fleet scrape overhead probe (ISSUE 18,
                    # same paired shape): the identical K=8 e2e with
                    # the live endpoint up, a TrainFleet scraping its
                    # /status every 200 ms, AND /metrics (with the
                    # per-rank labeled-series hook) scraped on top.
                    # fleet_scrape_overhead = off/on rate ratio;
                    # budget <= 1.05 like every other obs layer.
                    try:
                        e2e_fleet_on, _, _, _, _ = _bench_e2e(
                            trainer, cfg, files, warmup=4,
                            epochs=epochs, k=K, fleet=True,
                        )
                    except Exception as e:  # noqa: BLE001 - report only
                        ladder_errors.append(
                            f"fleet scrape probe: "
                            f"{type(e).__name__}: {e}"
                        )
                    # Kernel-autotune overhead probe (ISSUE 17),
                    # PAIRED: the identical K=8 step-scan through a
                    # trainer resolved via interaction_impl=auto vs
                    # one PINNED to reference, interleaved rounds.  On
                    # CPU auto collapses to reference at init (single
                    # candidate, zero measurement), so the two steady
                    # states run the same executable and the ratio
                    # prices exactly the autotuner's footprint —
                    # budget <= 1.05.  On TPU the ratio instead shows
                    # what the measured promotion buys (< 1.0 when a
                    # non-reference impl wins).  The probe keeps the
                    # autotune cache in memory only so a bench never
                    # leaves autotune_cache.json next to the
                    # throwaway /tmp model dir.
                    try:
                        _env_prev = os.environ.get(
                            "FAST_TFFM_AUTOTUNE_CACHE"
                        )
                        os.environ["FAST_TFFM_AUTOTUNE_CACHE"] = ""
                        try:
                            # Own model dirs: make_cfg rmtree's its
                            # model_file, and sharing one dir would
                            # both delete the main trainer's and make
                            # the second probe trainer restore the
                            # first's checkpoint.
                            c_auto = make_cfg(
                                interaction_impl="auto",
                                model_file=os.path.join(
                                    tmpdir, "autotune_m_auto"
                                ),
                            )
                            c_ref = make_cfg(
                                interaction_impl="reference",
                                model_file=os.path.join(
                                    tmpdir, "autotune_m_ref"
                                ),
                            )
                            t_auto = Trainer(c_auto)
                            t_ref = Trainer(c_ref)
                            autotune_kernel_impl = t_auto.kernel_impl
                            if t_auto._autotune is not None:
                                autotune_times = dict(
                                    t_auto._autotune.times_ms
                                )
                            a_samples, p_samples = [], []
                            for _ in range(rounds):
                                a_samples.append(_bench_step_scan(
                                    t_auto, c_auto, max(steps, 2 * K), K
                                ))
                                p_samples.append(_bench_step_scan(
                                    t_ref, c_ref, max(steps, 2 * K), K
                                ))
                            autotune_rate_auto = float(
                                np.median(a_samples)
                            )
                            autotune_rate_ref = float(
                                np.median(p_samples)
                            )
                            del t_auto, t_ref
                        finally:
                            if _env_prev is None:
                                os.environ.pop(
                                    "FAST_TFFM_AUTOTUNE_CACHE", None
                                )
                            else:
                                os.environ[
                                    "FAST_TFFM_AUTOTUNE_CACHE"
                                ] = _env_prev
                    except Exception as e:  # noqa: BLE001 - report only
                        ladder_errors.append(
                            f"autotune probe: {type(e).__name__}: {e}"
                        )
                    # Persistent-compile-cache probe (ISSUE 17): time
                    # one nontrivial AOT compile cold (fresh cache
                    # dir, miss) then again from a structurally
                    # identical fresh jit (persistent-cache hit) —
                    # warm vs cold compile_s is the restart/replica
                    # saving the compile_cache_dir knob buys.  The
                    # cache dir and jax config are restored after so
                    # later probes compile exactly as before.
                    try:
                        from fast_tffm_tpu import platform as _platform
                        import jax as _jax
                        import jax.numpy as _jnp

                        cc_dir = tempfile.mkdtemp(
                            prefix="fast_tffm_bench_cc_"
                        )
                        try:
                            _platform.enable_compile_cache(cc_dir)
                            st0 = _platform.compile_cache_stats()

                            def _cc_probe_fn():
                                # Fresh function object per call: same
                                # jaxpr (one persistent-cache key),
                                # but a new jit so nothing in-process
                                # memoizes the executable.
                                def f(x):
                                    y = _jnp.tanh(x @ x.T)
                                    return _jnp.sum(y * y, axis=-1)

                                return _jax.jit(f)

                            struct = _jax.ShapeDtypeStruct(
                                (256, 256), _jnp.float32
                            )
                            t0c = time.perf_counter()
                            _cc_probe_fn().lower(struct).compile()
                            compile_s_cold = time.perf_counter() - t0c
                            t0w = time.perf_counter()
                            _cc_probe_fn().lower(struct).compile()
                            compile_s_warm = time.perf_counter() - t0w
                            st1 = _platform.compile_cache_stats()
                            compile_cache_hits = (
                                st1["hits"] - st0["hits"]
                            )
                        finally:
                            _platform.disable_compile_cache()
                            shutil.rmtree(cc_dir, ignore_errors=True)
                    except Exception as e:  # noqa: BLE001 - report only
                        ladder_errors.append(
                            f"compile cache probe: "
                            f"{type(e).__name__}: {e}"
                        )
                    # Compile-sentinel attribution for the BENCH JSON:
                    # total train-step compile wall time this bench's
                    # trainer paid (the AOT cache makes it exact).
                    sent = getattr(trainer, "_sentinel", None)
                    if sent is not None:
                        bench_compile_s = sent.compile_s
                    # parse_processes scaling: drain the bare pipeline
                    # with thread workers vs a spawned process pool on
                    # the same files (no training attached).
                    try:
                        bench_procs = min(4, max(2, workers // 2))
                        ingest_threads_rate, _ = _bench_pipeline_ingest(
                            files, cfg, 0
                        )
                        ingest_procs_rate, ring_zero_copy_frac = (
                            _bench_pipeline_ingest(files, cfg, bench_procs)
                        )
                    except Exception as e:  # noqa: BLE001 - report only
                        ladder_errors.append(
                            f"parse_processes bench: "
                            f"{type(e).__name__}: {e}"
                        )
                    # How much of the synchronous stack+H2D cost the
                    # transfer thread hides: 1 - (e2e gap) / (blocking
                    # transfer cost), both per example at K=8.  An
                    # estimate — the residual gap also carries any
                    # unhidden parse time.
                    put_s = _bench_put_only(trainer, cfg, K)
                    if e2e_rate > 0 and step_rate > 0 and put_s > 0:
                        gap = max(0.0, 1.0 / e2e_rate - 1.0 / step_rate)
                        h2d_overlap_frac = max(0.0, 1.0 - gap / put_s)
                finally:
                    shutil.rmtree(tmpdir, ignore_errors=True)
            except Exception as e:  # noqa: BLE001 — always emit the JSON line
                e2e_err = f"e2e bench failed: {type(e).__name__}: {e}"

        # bf16 compute variant (rounds the interaction operands, halving
        # the gathered-rows HBM streams).  Pinned to start at the rung the
        # f32 config selected so the two rates compare the same kernel
        # path; its rung and any errors are recorded in the JSON.  Runs
        # LAST so the adjacent f32 K=8 step-only and e2e measurements
        # (the judged ratio) see the same machine state.
        try:
            bf16_rung, t16, c16, bf16_errors = build_trainer_with_ladder(
                lambda **kw: make_cfg(
                    **{"compute_dtype": "bfloat16", **kw}
                ),
                Trainer,
                start_rung=ladder_rung,
            )
            if t16 is not None:
                bf16_rate = _bench_step_only(t16, c16, steps)
                del t16
        except Exception as e:  # noqa: BLE001 — bf16 must not sink the bench
            bf16_errors = [f"bf16 bench: {type(e).__name__}: {e}"]

        if args.mode == "e2e":
            del trainer
            # Serving section: latency under concurrent load through
            # the HTTP -> batcher -> compiled-ladder path (SERVING.md).
            # Runs BEFORE the tiered section: the V=2^28 cold stores
            # leave ~7 GB of process RSS behind, and serving latency
            # measured under that allocator pressure read ~10x worse
            # than the same probe on a clean process.
            # Every section stamps its own RSS before/delta
            # (_with_rss_delta): the tiered section's ~7 GB residue can
            # never skew another section's memory reading again,
            # whatever the order.
            serve_section = _with_rss_delta(_bench_serve, workers)
            # Scale-out serving section: the 2-replica router fleet
            # (real subprocess replicas) under load and under a
            # 4x-offered burst — the shed/eviction discipline's
            # numbers.  Runs right after the single-replica section so
            # serve_router_qps / serve_qps is measured on the same box
            # state.
            serve_router_section = _with_rss_delta(
                _bench_serve_router, workers
            )
            # Tiered-table section: the V=2^28 run a dense device table
            # cannot hold, plus its dense V=2^26 overlap baseline.  Its
            # own trainers/files; isolated from the judged numbers above.
            tiered_section = _with_rss_delta(_bench_tiered, workers)
            # Quantized-table section: the same tiered config trained
            # under each cold_dtype (bytes per row vs step rate).
            quant_section = _with_rss_delta(_bench_quant, workers)
            # Fleet-training section: rank-sharded tiering (2 gloo
            # ranks vs the host-global baseline — the ~1/R memory
            # claim) and the overlapped-exchange A/B (ISSUE 19).
            fleet_section = _with_rss_delta(_bench_fleet_train, workers)
    except Exception as e:  # noqa: BLE001 — always emit the JSON line
        e2e_err = f"bench failed: {type(e).__name__}: {e}"

    # Derived AFTER every update to the step rates so the JSON is
    # internally consistent (the e2e block folds adjacent K=8 samples
    # into the step median).
    if step_rate_k1 > 0 and step_rate > 0:
        dispatch_overhead_ms = max(
            0.0,
            (1.0 / step_rate_k1 - 1.0 / step_rate) * cfg.batch_size * 1e3,
        )
    headline = e2e_rate if e2e_rate > 0 else step_rate
    kind = "e2e" if e2e_rate > 0 else "step_only"
    ingest_note = (
        "libsvm ingest via native parser" if kind == "e2e"
        else "device-resident batches, no ingest"
    )
    per_chip = headline / max(1, n_chips)
    bdesc = cfg.batch_size if cfg else 0
    vdesc = cfg.vocabulary_size.bit_length() - 1 if cfg else 0
    result = {
        "metric": (
            f"fm_train_examples_per_sec_{kind} ({platform} x{n_chips}, "
            f"B={bdesc}, F=39, k=8, vocab=2^{vdesc}, zipf1.1 ids, "
            f"{ingest_note})"
        ),
        "value": round(headline, 1),
        "unit": "examples/sec",
        "vs_baseline": round(per_chip / PER_CHIP_TARGET, 4),
        "steps_per_dispatch": K,
        "step_only_examples_per_sec": round(step_rate, 1),
        "step_only_k1_examples_per_sec": round(step_rate_k1, 1),
        "step_only_bf16_examples_per_sec": round(bf16_rate, 1),
        "e2e_examples_per_sec": round(e2e_rate, 1),
        "e2e_k1_examples_per_sec": round(e2e_rate_k1, 1),
        # Per-epoch split of the judged e2e run: epoch 0 pays the parse,
        # epochs 1+ replay the parsed-batch cache; cached/step is the
        # "ingest overhead left after caching" ratio (target >= 0.97).
        "e2e_epoch0_examples_per_sec": round(e2e_epoch0, 1),
        "e2e_cached_epoch_examples_per_sec": round(e2e_cached, 1),
        "cached_epoch_vs_step_only": round(
            e2e_cached / step_rate, 4
        ) if step_rate > 0 else 0.0,
        # min/max of the repeated trials feeding each judged median —
        # the measured run-to-run swing, no longer folklore.
        "step_rate_spread": {
            "step_only": _spread(s_samples),
            "step_only_k1": _spread(s1_samples),
            "e2e": _spread(e_samples),
        },
        "dispatch_overhead_ms": round(dispatch_overhead_ms, 3),
        "h2d_overlap_frac": round(h2d_overlap_frac, 4),
        "ingest_cache": ingest_cache,  # "cached" | "overflow" | "off"
        # Telemetry overhead: the same K=8 e2e run with instruments
        # disabled; on/off ≈ 1.0 means the layer costs noise-level time.
        "e2e_telemetry_off_examples_per_sec": round(e2e_tel_off, 1),
        "telemetry_on_vs_off": round(
            e2e_rate / e2e_tel_off, 4
        ) if e2e_tel_off > 0 and e2e_rate > 0 else 0.0,
        # Trace overhead: the same K=8 e2e with the causal span layer
        # recording (pipeline/prefetcher/dispatch spans).  off/on rate
        # ratio; budget <= 1.05 (box noise is ±3%, so ~1.0 = free).
        "e2e_trace_on_examples_per_sec": round(e2e_trace_on, 1),
        "trace_overhead": round(
            e2e_rate / e2e_trace_on, 4
        ) if e2e_trace_on > 0 and e2e_rate > 0 else 0.0,
        "trace_events_recorded": trace_events,
        # Status-endpoint overhead: the same K=8 e2e with the live
        # /metrics endpoint up and scraped every 200 ms.  off/on rate
        # ratio; budget <= 1.05 (endpoint requests only read the
        # thread-safe snapshots, so ~1.0 = free).
        "e2e_status_on_examples_per_sec": round(e2e_status_on, 1),
        "status_endpoint_overhead": round(
            e2e_rate / e2e_status_on, 4
        ) if e2e_status_on > 0 and e2e_rate > 0 else 0.0,
        # Training-fleet scrape overhead: the same K=8 e2e with the
        # endpoint up, a TrainFleet scraping /status every 200 ms, and
        # /metrics (per-rank labeled series included) scraped on top.
        # off/on rate ratio, budget <= 1.05 — scrape + merge + render
        # all run off the training thread, so ~1.0 = free.
        "e2e_fleet_on_examples_per_sec": round(e2e_fleet_on, 1),
        "fleet_scrape_overhead": round(
            e2e_rate / e2e_fleet_on, 4
        ) if e2e_fleet_on > 0 and e2e_rate > 0 else 0.0,
        # Resource-plane overhead: the same K=8 e2e with RSS/ledger/
        # sentinel sampling at 200 ms.  off/on rate ratio, budget
        # <= 1.05 — the sampler only reads /proc and lock-guarded
        # snapshots, so ~1.0 = free.
        "e2e_resource_on_examples_per_sec": round(e2e_resource_on, 1),
        "resource_overhead": round(
            e2e_rate / e2e_resource_on, 4
        ) if e2e_resource_on > 0 and e2e_rate > 0 else 0.0,
        # Model-quality overhead: the same K=8 e2e with drift sketches
        # on the parse path + the windowed online-eval monitor
        # consuming every dispatch's scores.  off/on rate ratio,
        # budget <= 1.05 — sketch updates are batch-cadence numpy and
        # the window stats are memoized.
        "e2e_quality_on_examples_per_sec": round(e2e_quality_on, 1),
        "quality_overhead": round(
            e2e_rate / e2e_quality_on, 4
        ) if e2e_quality_on > 0 and e2e_rate > 0 else 0.0,
        # Sketch/PSI correctness floor: two independent samples of the
        # SAME synthetic distribution through the full SketchSet + PSI
        # machinery must read ~0 (the debiased identity).  A rise here
        # is a sketch regression, not a data change.
        "quality_psi_identity": _bench_quality_identity(),
        # Memory & compile attribution of the bench process itself:
        # peak RSS over the whole bench (epoch caches + staged input +
        # jit artifacts), and the train-step compile seconds the AOT
        # sentinel accounted.  --compare gates both (low).
        "peak_rss_mb": round(obs_mod.read_rss()[1] / (1 << 20), 1),
        "compile_s": round(bench_compile_s, 3),
        # Kernel autotuner (ISSUE 17): which interaction impl `auto`
        # promoted for this backend/shape (informational — a string,
        # so --compare skips it), the per-candidate measurement
        # medians when a measurement ran (empty dict on CPU where
        # reference wins by single-candidate), and the paired
        # steady-state ratio reference/auto — the autotuner's whole
        # footprint, budget <= 1.05 (< 1.0 on TPU means the promoted
        # impl is actually faster).
        "kernel_impl": autotune_kernel_impl,
        "autotune_overhead": round(
            autotune_rate_ref / autotune_rate_auto, 4
        ) if autotune_rate_auto > 0 and autotune_rate_ref > 0 else 0.0,
        "autotune_times_ms": autotune_times,
        # Persistent compile cache: the same nontrivial jit compiled
        # cold (fresh cache dir, disk miss) vs from a fresh function
        # object with the persistent entry warm — warm/cold is the
        # per-executable restart saving compile_cache_dir buys.
        # compile_cache_hits counts the persistent-cache hit events
        # the warm compile produced (-1 = probe didn't run).
        "compile_s_cold": round(compile_s_cold, 4),
        "compile_s_warm": round(compile_s_warm, 4),
        "compile_cache_hits": compile_cache_hits,
        "parse_lines_per_sec": round(parse_rate, 1),
        # Bare-pipeline drain rates: thread workers vs a spawned
        # parse-process pool on the same files (GIL-free scaling probe).
        "pipeline_ingest_threads_lines_per_sec": round(
            ingest_threads_rate, 1
        ),
        "pipeline_ingest_procs_lines_per_sec": round(
            ingest_procs_rate, 1
        ),
        # Inbound SHM ring: fraction of the procs drain's raw windows
        # that went zero-copy (descriptor-only queue messages); -1 if
        # the procs drain didn't run.
        "ring_zero_copy_frac": round(ring_zero_copy_frac, 4),
        "bench_parse_processes": bench_procs,
        "platform": platform,
        "n_chips": n_chips,
    }
    if tele_report is not None:
        # The judged e2e run's per-stage self-report (what a training
        # heartbeat would have emitted): ingest_wait_frac + queue depths
        # + parse/stack/H2D/dispatch timing histograms.  Rides into
        # BENCH_r0N.json so every committed bench attributes its own
        # wall-clock.
        result["ingest_wait_frac"] = tele_report["ingest_wait_frac"]
        # Prestacked-cache split of the judged run: fraction of
        # dispatches whose stack was skipped (epoch-0 groups stack once
        # in the pipeline, replays reuse them) and the mean once-per-
        # group stack cost wherever it was paid.
        result["prestack_hit_frac"] = tele_report.get(
            "prestack_hit_frac", 0.0
        )
        result["stack_ms_per_superbatch"] = tele_report.get(
            "stack_ms_per_superbatch", 0.0
        )
        result["telemetry"] = tele_report
    if tiered_section is not None:
        result["tiered_table"] = tiered_section
    if serve_section is not None:
        result["serve"] = serve_section
        if serve_section.get("completed"):
            # Top-level copies of the gated axes: --compare only
            # flattens numeric TOP-LEVEL bench keys (serve_p99_ms low,
            # serve_qps/serve_batch_fill high, serve_steady_compiles
            # low — a nonzero steady compile is the latency cliff).
            for key in ("serve_p50_ms", "serve_p95_ms", "serve_p99_ms",
                        "serve_qps", "serve_batch_fill",
                        "serve_steady_compiles"):
                result[key] = serve_section[key]
    if serve_router_section is not None:
        result["serve_router"] = serve_router_section
        if serve_router_section.get("completed"):
            # Gated axes of the fleet (report.py directions: qps high;
            # p50/p99, the burst's admitted p99, the shed fraction at
            # fixed 4x offered load, and bin decode cost all low).
            for key in ("serve_router_qps", "serve_router_p50_ms",
                        "serve_router_p99_ms", "serve_shed_frac",
                        "serve_burst_p99_ms", "serve_burst_p99_x"):
                result[key] = serve_router_section[key]
            if (
                serve_section is not None
                and serve_section.get("completed")
                and serve_section.get("serve_qps")
            ):
                # The scale-out headline: 2-replica router throughput
                # over the single-process section's, same box, same
                # traffic shape.  Meaningful on multi-core hosts; on a
                # 1-core box both fleets share the core.
                result["serve_router_scaleout_x"] = round(
                    serve_router_section["serve_router_qps"]
                    / serve_section["serve_qps"], 4
                )
    if quant_section is not None:
        result["quantized_table"] = quant_section
        if quant_section.get("completed"):
            # Top-level copies of the gated axes (--compare flattens
            # numeric top-level keys only): table bytes must FALL
            # (that is the feature), step rate must not (encode/decode
            # rides the transfer thread, off the dispatch path).
            for d in ("bf16", "int8"):
                # Dense (serving-format) bytes/row vs fp32 — the
                # replica-density headline (bf16 0.5, int8 ~0.25 at
                # quant_chunk=64).
                result[f"quant_table_bytes_frac_{d}"] = round(
                    quant_section["table_bytes_per_row"][d]
                    / quant_section["table_bytes_per_row"]["fp32"], 4
                )
                result[f"quant_step_rate_frac_{d}"] = (
                    quant_section["step_rate_frac"][d]
                )
    if serve_section is not None and serve_section.get("completed"):
        for key in ("serve_table_mb", "serve_parse_p50_ms",
                    "serve_bin_p50_ms", "serve_quant_error_max_int8",
                    "serve_parse_vec_speedup", "serve_accept_pooled",
                    "serve_accept_pooled_x", "serve_qps_legacy_accept",
                    "serve_http_threads"):
            if key in serve_section:
                result[key] = serve_section[key]
    if fleet_section is not None:
        result["fleet_train"] = fleet_section
        if fleet_section.get("completed"):
            # Top-level copies of the gated axes (--compare flattens
            # numeric top-level keys only): the exchange windows must
            # not grow back, the per-rank byte fractions must hold the
            # ~1/R sharding claim, the sharded step rate is a plain
            # throughput axis.
            result["fleet_exchange_frac"] = (
                fleet_section["exchange_frac_off"]
            )
            result["fleet_exchange_overlap_frac"] = (
                fleet_section["exchange_overlap_frac"]
            )
            result["fleet_shard_bytes_frac"] = (
                fleet_section["shard_bytes_frac"]
            )
            result["fleet_cold_bytes_frac"] = (
                fleet_section["cold_bytes_frac"]
            )
            result["fleet_sharded_examples_per_sec"] = (
                fleet_section["sharded_examples_per_sec"]
            )
            result["fleet_global_examples_per_sec"] = (
                fleet_section["global_examples_per_sec"]
            )
            result["fleet_tier_shards"] = fleet_section["tier_shards"]
    if timeline_regs is not None:
        # Bench preflight (--timeline over BENCH_r*.json): how many
        # keys' trends already crossed their threshold, plus the first
        # few attributions.  0 -> N flags in --compare (direction low).
        result["timeline_regressions"] = timeline_regs
        if timeline_reg_keys:
            result["timeline_regression_keys"] = timeline_reg_keys
    if tier1_audit is not None:
        result["tier1_audit"] = tier1_audit
    if lint_findings_new is not None:
        # Numeric top-level keys flow into --compare automatically;
        # 0 -> N flags as a REGRESSION (direction: low in report.py).
        result["lint_findings_new"] = lint_findings_new
        result["lint_findings_baselined"] = lint_findings_baselined
    if ladder_rung is not None:
        result["ladder_rung"] = ladder_rung
    if ladder_errors:
        result["ladder_errors"] = ladder_errors
    if bf16_rung is not None and bf16_rung != ladder_rung:
        result["bf16_ladder_rung"] = bf16_rung
    if bf16_errors:
        result["bf16_ladder_errors"] = bf16_errors
    notes = [n for n in (err, e2e_err) if n]
    if notes:
        result["error"] = "; ".join(notes)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
