"""Mergeable streaming distribution sketches: the fixed-memory summaries
the model-quality plane is built on (obs/quality.py).

Two primitives, chosen for the three properties every consumer here
needs — FIXED memory however long the stream runs, MERGEABILITY
(per-worker / per-process partial sketches combine into one stream
summary; the training run's sketch ships to the serving fleet inside
``serve_manifest.json``), and cheap JSON serialization:

- :class:`QuantileSketch` — a KLL-style compactor hierarchy over float
  streams (feature values, example lengths, predicted scores).  Level
  ``i`` holds at most ``k`` items, each standing for ``2^i`` stream
  elements; a full level sorts and keeps every other item (alternating
  offset, deterministic — no RNG, so identical streams produce
  identical sketches and resume/replay stays reproducible).  Memory is
  O(k · log(n/k)); the rank error of any quantile estimate is a few
  percent at the default ``k`` (pinned empirically by
  tests/test_quality.py, not just claimed).
- :class:`FreqSketch` — a hashed occupancy histogram over id streams
  (which rows of the embedding table traffic touches).  Ids mix
  through a multiplicative hash into ``buckets`` counters; merge is
  exact (vector add).  It answers "did the ID DISTRIBUTION move", not
  "what is id 17's count" — exactly the drift question.  Sensitivity
  caveat, stated honestly: it sees changes in the occupancy SHAPE
  (mass concentrating on fewer/different-density rows — the common
  CTR drift), and it resolves disjoint-set swaps only while distinct
  ids per bucket stay small; two equal-density uniform id sets wider
  than ~buckets·lots converge to the hash's own profile and read as
  similar.  Such a swap still fires the trainer's `ids` axis at
  ingest (the window's distinct-id density shifts) but a skew
  comparison of two huge matched-density uniform sets is genuinely
  out of this sketch's reach.

Distribution distance is PSI (population stability index), the CTR-ops
standard: ``psi = Σ (q_i − p_i) · ln(q_i / p_i)`` over binned masses,
with the conventional reading psi < 0.1 stable, 0.1–0.25 drifting,
> 0.25 shifted.  Quantile distributions bin at the REFERENCE sketch's
equal-mass cut points (so the reference contributes ~uniform mass per
bin and the live distribution's movement is what the number measures);
frequency distributions compare bucket masses directly.

numpy-only (no jax): updates run inside parse workers — thread AND
spawned process — the serving batcher's dispatcher thread, and the
jax-free router would be free to import it.
"""

from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

__all__ = [
    "FreqSketch", "QuantileSketch", "SketchSet", "psi_freq",
    "psi_quantile", "DEFAULT_K", "DEFAULT_BUCKETS", "PSI_BINS",
]

DEFAULT_K = 128  # per-level capacity: ~2-3% rank error, ~KBs of state
DEFAULT_BUCKETS = 512  # FreqSketch occupancy histogram width
PSI_BINS = 10  # equal-mass bins for quantile-sketch PSI
_PSI_EPS = 1e-4  # mass smoothing so an empty bin never yields inf


def _round6(x: float) -> float:
    """Compact JSON spelling (~6 significant digits) — the manifest
    carries thousands of these and full float64 repr would triple it."""
    return float(f"{x:.6g}")


class QuantileSketch:
    """KLL-style mergeable quantile sketch over a float stream."""

    def __init__(self, k: int = DEFAULT_K):
        if k < 8:
            raise ValueError(f"k must be >= 8, got {k}")
        self.k = int(k)
        self.n = 0  # total stream elements represented
        self._levels: List[list] = [[]]  # level i item weight = 2^i
        self._flip: List[bool] = [False]  # alternating compaction offset
        self._min = math.inf
        self._max = -math.inf
        # Memoized (sorted values, cumulative weights): a PSI computes
        # dozens of rank()/quantile() queries against the same state,
        # and re-sorting the retained items per query was the dominant
        # cost of a drift check.  Invalidated by update/merge.
        self._weighted_cache = None

    # -- updates -------------------------------------------------------

    # One update() folds at most this many items into the compactor;
    # larger arrays contribute a deterministic strided subsample (plus
    # exact n/min/max).  A 4096-sample draw of one batch already pins
    # its distribution far below the sketch's own rank error, and the
    # cap keeps the per-batch cost flat however large batches get —
    # the quality plane's overhead budget is 5%, not a function of
    # batch_size * max_features.  Caveat for foreign callers: a capped
    # update contributes mass proportional to its INSERTED count, so
    # mixing very large and very small updates skews their relative
    # weight — the pipelines here feed homogeneous batch shapes, where
    # the effect is nil (one short tail batch per epoch).
    UPDATE_CAP = 4096

    def update(self, values) -> None:
        """Fold an array (or scalar) of values into the sketch."""
        arr = np.asarray(values, np.float64).reshape(-1)
        if arr.size == 0:
            return
        arr = arr[np.isfinite(arr)]
        if arr.size == 0:
            return
        self.n += int(arr.size)
        self._weighted_cache = None
        self._min = min(self._min, float(arr.min()))
        self._max = max(self._max, float(arr.max()))
        if arr.size > self.UPDATE_CAP:
            # Deterministic stride with a rotating offset (the level-0
            # flip bit doubles as the rotation) so periodic batch
            # layouts can't alias into the subsample.
            stride = -(-arr.size // self.UPDATE_CAP)
            off = (self.n + stride - 1) % stride
            arr = arr[off::stride]
        lvl0 = self._levels[0]
        lvl0.extend(arr.tolist())
        if len(lvl0) >= 2 * self.k:
            self._compact_from(0)

    def _compact_from(self, i: int) -> None:
        while i < len(self._levels) and len(self._levels[i]) >= 2 * self.k:
            items = sorted(self._levels[i])
            off = 1 if self._flip[i] else 0
            self._flip[i] = not self._flip[i]
            # An odd survivor stays at this level so no weight is lost
            # beyond the compaction's inherent halving.
            keep = items[off::2]
            self._levels[i] = []
            if i + 1 == len(self._levels):
                self._levels.append([])
                self._flip.append(False)
            self._levels[i + 1].extend(keep)
            i += 1

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self (in place; returns self).  Sketches
        of different ``k`` merge at the smaller capacity's error."""
        if other.n == 0:
            return self
        self.n += other.n
        self._weighted_cache = None
        self._min = min(self._min, other._min)
        self._max = max(self._max, other._max)
        while len(self._levels) < len(other._levels):
            self._levels.append([])
            self._flip.append(False)
        for i, items in enumerate(other._levels):
            self._levels[i].extend(items)
        self._compact_from(0)
        # A merge can overfill upper levels directly; sweep them all.
        for i in range(len(self._levels)):
            self._compact_from(i)
        return self

    # -- queries -------------------------------------------------------

    def _weighted(self):
        """(sorted values, cumulative weights) over all levels —
        memoized until the next update/merge."""
        if self._weighted_cache is not None:
            return self._weighted_cache
        vals: list = []
        wts: list = []
        for i, items in enumerate(self._levels):
            vals.extend(items)
            wts.extend([1 << i] * len(items))
        if not vals:
            self._weighted_cache = (None, None)
            return self._weighted_cache
        v = np.asarray(vals, np.float64)
        w = np.asarray(wts, np.float64)
        order = np.argsort(v, kind="stable")
        self._weighted_cache = (v[order], np.cumsum(w[order]))
        return self._weighted_cache

    def quantile(self, q: float) -> Optional[float]:
        """Estimated value at rank fraction ``q`` in [0, 1]."""
        if self.n == 0:
            return None
        if q <= 0:
            return self._min
        if q >= 1:
            return self._max
        v, cw = self._weighted()
        target = q * cw[-1]
        idx = int(np.searchsorted(cw, target, side="left"))
        return float(v[min(idx, len(v) - 1)])

    def rank(self, x: float) -> float:
        """Estimated fraction of the stream <= x (the CDF)."""
        if self.n == 0:
            return 0.0
        v, cw = self._weighted()
        idx = int(np.searchsorted(v, x, side="right"))
        if idx == 0:
            return 0.0
        return float(cw[idx - 1] / cw[-1])

    @property
    def retained(self) -> int:
        """Items held across all levels — the memory bound under test."""
        return sum(len(lvl) for lvl in self._levels)

    # -- serialization -------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "k": self.k,
            "n": self.n,
            "min": _round6(self._min) if self.n else None,
            "max": _round6(self._max) if self.n else None,
            "levels": [
                [_round6(x) for x in lvl] for lvl in self._levels
            ],
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "QuantileSketch":
        sk = cls(k=int(doc.get("k", DEFAULT_K)))
        sk.n = int(doc.get("n", 0))
        sk._levels = [list(map(float, lvl))
                      for lvl in doc.get("levels", [[]])] or [[]]
        sk._flip = [False] * len(sk._levels)
        if sk.n:
            sk._min = float(doc["min"])
            sk._max = float(doc["max"])
        return sk


class FreqSketch:
    """Hashed id-occupancy histogram: exact-merge frequency sketch."""

    # Fibonacci multiplicative hash: consecutive ids (the common CTR
    # vocab layout) spread across buckets instead of aliasing mod-B.
    _MIX = np.uint64(0x9E3779B97F4A7C15)

    def __init__(self, buckets: int = DEFAULT_BUCKETS):
        if buckets < 8:
            raise ValueError(f"buckets must be >= 8, got {buckets}")
        self.buckets = int(buckets)
        self.counts = np.zeros(self.buckets, np.int64)
        self.n = 0

    def update(self, ids) -> None:
        arr = np.asarray(ids).reshape(-1)
        if arr.size == 0:
            return
        with np.errstate(over="ignore"):
            h = (arr.astype(np.uint64) * self._MIX) >> np.uint64(17)
        # bincount, not add.at: one histogram pass instead of a
        # scattered-index loop (matters at batch_size * max_features
        # ids per parsed batch).
        self.counts += np.bincount(
            (h % np.uint64(self.buckets)).astype(np.int64),
            minlength=self.buckets,
        )
        self.n += int(arr.size)

    def merge(self, other: "FreqSketch") -> "FreqSketch":
        if other.buckets != self.buckets:
            raise ValueError(
                f"cannot merge FreqSketch buckets {other.buckets} into "
                f"{self.buckets}"
            )
        self.counts += other.counts
        self.n += other.n
        return self

    def to_dict(self) -> dict:
        return {"buckets": self.buckets, "n": self.n,
                "counts": self.counts.tolist()}

    @classmethod
    def from_dict(cls, doc: dict) -> "FreqSketch":
        sk = cls(buckets=int(doc.get("buckets", DEFAULT_BUCKETS)))
        counts = doc.get("counts")
        if counts:
            sk.counts = np.asarray(counts, np.int64)
        sk.n = int(doc.get("n", 0))
        return sk


def _debias(psi: float, dof: int, n_ref: int, n_live: int) -> float:
    """Remove the expected under-null sampling noise from a raw PSI.

    Two finite samples of the SAME distribution still produce a
    positive PSI — asymptotically ``dof · (1/n_ref + 1/n_live)`` (the
    chi-square mean of the symmetrized divergence).  Subtracting it
    (clamped at 0) makes identity read ~0 even over small windows,
    while a real shift's PSI (O(1)) is barely touched — so alert
    thresholds mean the same thing at every window size."""
    return max(0.0, psi - dof * (1.0 / max(n_ref, 1)
                                 + 1.0 / max(n_live, 1)))


def psi_freq(ref: FreqSketch, live: FreqSketch) -> Optional[float]:
    """Noise-debiased PSI between two frequency sketches' bucket-mass
    distributions."""
    if ref.n == 0 or live.n == 0 or ref.buckets != live.buckets:
        return None
    p = ref.counts / ref.n + _PSI_EPS
    q = live.counts / live.n + _PSI_EPS
    p /= p.sum()
    q /= q.sum()
    psi = float(np.sum((q - p) * np.log(q / p)))
    return _debias(psi, ref.buckets - 1, ref.n, live.n)


def psi_quantile(ref: QuantileSketch, live: QuantileSketch,
                 bins: int = PSI_BINS) -> Optional[float]:
    """PSI between two quantile sketches, binned at the REFERENCE's
    equal-mass cut points.  Degenerate references (a near-constant
    stream collapses the cut points) fall back to fewer bins; a fully
    constant reference compares point masses at its single value."""
    if ref.n == 0 or live.n == 0:
        return None
    edges = []
    for i in range(1, bins):
        e = ref.quantile(i / bins)
        if e is not None and (not edges or e > edges[-1]):
            edges.append(e)
    if not edges:
        # Constant reference: the only question is how much live mass
        # sits at (<=) that value vs beyond it.
        edges = [ref.quantile(0.5)]
    cuts = [-math.inf] + edges + [math.inf]
    p = np.asarray([
        max(0.0, ref.rank(b) - ref.rank(a)) if b != math.inf
        else max(0.0, 1.0 - ref.rank(a))
        for a, b in zip(cuts[:-1], cuts[1:])
    ])
    q = np.asarray([
        max(0.0, live.rank(b) - live.rank(a)) if b != math.inf
        else max(0.0, 1.0 - live.rank(a))
        for a, b in zip(cuts[:-1], cuts[1:])
    ])
    p = p + _PSI_EPS
    q = q + _PSI_EPS
    p /= p.sum()
    q /= q.sum()
    psi = float(np.sum((q - p) * np.log(q / p)))
    return _debias(psi, len(edges), ref.n, live.n)


class SketchSet:
    """The model-quality sketch bundle over one example stream.

    Four axes, each one drift question:

    - ``values``  — nonzero feature VALUES (quantile): did the numeric
      inputs move (a broken upstream scaler, a log/linear flip)?
    - ``lengths`` — real features per example (quantile): did example
      SHAPE move (a joiner dropping a feature family)?
    - ``ids``     — feature-id occupancy (frequency): did traffic move
      to different embedding rows (new campaign mix, vocab shift)?
    - ``scores``  — predicted scores (quantile; probabilities for
      logistic models): did the model's OUTPUT distribution move
      (updated separately — features come from the parse path, scores
      from the dispatch/serve path)?

    ``update_batch`` takes the padded ``[n, F]`` id/value arrays every
    layer here already holds (ingest Batch, serve request) — a zero
    value marks a padded slot, exactly the convention the parsers and
    the serving pad path share.
    """

    AXES = ("values", "lengths", "ids", "scores")

    def __init__(self, k: int = DEFAULT_K,
                 buckets: int = DEFAULT_BUCKETS):
        self.values = QuantileSketch(k)
        self.lengths = QuantileSketch(k)
        self.ids = FreqSketch(buckets)
        self.scores = QuantileSketch(k)
        self.examples = 0

    def update_batch(self, ids, vals, weights=None) -> None:
        ids = np.asarray(ids)
        vals = np.asarray(vals)
        if vals.ndim == 1:
            ids = ids.reshape(1, -1)
            vals = vals.reshape(1, -1)
        if weights is not None:
            rows = np.asarray(weights).reshape(-1) > 0
            ids, vals = ids[rows], vals[rows]
        if vals.shape[0] == 0:
            return
        real = vals != 0
        self.values.update(vals[real])
        self.lengths.update(real.sum(axis=1))
        self.ids.update(ids[real])
        self.examples += int(vals.shape[0])

    def update_scores(self, scores) -> None:
        self.scores.update(scores)

    def merge(self, other: "SketchSet") -> "SketchSet":
        self.values.merge(other.values)
        self.lengths.merge(other.lengths)
        self.ids.merge(other.ids)
        self.scores.merge(other.scores)
        self.examples += other.examples
        return self

    def copy(self) -> "SketchSet":
        return SketchSet.from_dict(self.to_dict())

    def psi_vs(self, ref: "SketchSet") -> dict:
        """{psi_values, psi_lengths, psi_ids, psi_scores, psi_max}
        of SELF (the live stream) against ``ref`` — axes without mass
        on both sides are simply absent."""
        out: dict = {}
        for axis, fn in (("values", psi_quantile),
                         ("lengths", psi_quantile),
                         ("ids", psi_freq),
                         ("scores", psi_quantile)):
            v = fn(getattr(ref, axis), getattr(self, axis))
            if v is not None:
                out[f"psi_{axis}"] = round(v, 6)
        psis = [v for k, v in out.items() if k.startswith("psi_")]
        if psis:
            out["psi_max"] = round(max(psis), 6)
        return out

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "examples": self.examples,
            "values": self.values.to_dict(),
            "lengths": self.lengths.to_dict(),
            "ids": self.ids.to_dict(),
            "scores": self.scores.to_dict(),
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "SketchSet":
        sk = cls.__new__(cls)
        sk.values = QuantileSketch.from_dict(doc.get("values", {}))
        sk.lengths = QuantileSketch.from_dict(doc.get("lengths", {}))
        sk.ids = FreqSketch.from_dict(doc.get("ids", {}))
        sk.scores = QuantileSketch.from_dict(doc.get("scores", {}))
        sk.examples = int(doc.get("examples", 0))
        return sk
