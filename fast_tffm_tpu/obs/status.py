"""In-process status endpoint: the run's self-reports, readable LIVE.

The heartbeat stream (heartbeat.py) and the final record answer "what
happened" after the fact; an always-on streaming trainer or a serving
host needs the same answers WHILE it runs, from standard tooling.
:class:`StatusServer` is that surface: a lightweight stdlib HTTP server
(ThreadingHTTPServer on a daemon thread) serving

- ``/metrics`` — Prometheus text exposition (text/plain; version 0.0.4)
  of every Counter / Gauge / Timing / DepthHist snapshot plus the
  ``health`` and ``tiered`` blocks and the record's own scalars
  (``ingest_wait_frac``, ``step``, ...), ready for a Prometheus scrape;
- ``/status`` — the same JSON record a heartbeat would emit, built on
  demand (``record: status``);
- ``/healthz`` — liveness probe (200 ``ok`` while the run is alive);
- ``/debug/threadz`` — an all-thread stack dump (stdlib
  ``sys._current_frames``): the hang-diagnosis tool for a pipeline
  with reader / parse-worker / prefetcher / heartbeat / status
  threads — when the run wedges, this names the frame every thread is
  stuck in, no gdb required;
- ``/profile?secs=N`` — an on-demand ``jax.profiler`` capture window
  (the owner supplies the capture callable; absent -> 404).  Strictly
  one at a time: a second request while one is in flight gets 409.

Design constraints, shared with the rest of ``obs/``:

- stdlib only (no jax, no numpy) — the builder callable owns anything
  heavier;
- read-only and off the hot path: every request calls the owner's
  ``build()`` (the trainer's heartbeat-record builder), which reads
  thread-safe snapshots and host-cached health scalars only — never a
  device readback, never a lock the hot path holds across work;
- zero cost when disabled: the server only exists when ``status_port``
  is set; nothing else changes, so training with it unset is
  bit-identical.

Request handling runs on the server's own threads; the only shared
mutable state it touches is the telemetry registry's lock-guarded
snapshots (and an optional ``status.requests`` counter so scrape load
is itself observable).
"""

from __future__ import annotations

import json
import logging
import queue
import re
import select
import socket
import sys
import threading
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

__all__ = [
    "ObsHTTPServer", "PooledHTTPServer", "QuietHandler", "StatusServer",
    "probe_reuseport", "render_prometheus", "thread_dump",
]

log = logging.getLogger(__name__)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Sanitize a dotted instrument name into a Prometheus metric name
    (``ingest.out_q_depth`` -> ``ingest_out_q_depth``)."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


_LABEL_ESC = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def _label_value(v) -> str:
    return "".join(_LABEL_ESC.get(ch, ch) for ch in str(v))


def thread_dump() -> str:
    """One text block per live thread: name/ident/daemon + its current
    stack (``sys._current_frames``).  Pure stdlib, read-only, safe to
    call from a request handler at any time — the tool you want when a
    multi-thread pipeline stops making progress."""
    frames = sys._current_frames()
    lines = []
    for t in sorted(threading.enumerate(), key=lambda t: t.name):
        lines.append(
            f"--- thread {t.name!r} (ident={t.ident}, "
            f"daemon={t.daemon}, alive={t.is_alive()}) ---"
        )
        frame = frames.get(t.ident)
        if frame is None:
            lines.append("  <no frame (not started or already gone)>")
        else:
            lines.extend(
                ln.rstrip("\n")
                for ln in traceback.format_stack(frame)
            )
        lines.append("")
    return "\n".join(lines) + "\n"


def render_prometheus(record: dict) -> str:
    """Render one heartbeat-shaped record as Prometheus text exposition.

    Layout (all names prefixed ``tffm_``):

    - record scalars -> gauges (``tffm_step``, ``tffm_ingest_wait_frac``);
    - ``stages.counters`` -> ``tffm_counter_<name>_total`` counters;
    - ``stages.gauges`` -> ``tffm_gauge_<name>`` gauges;
    - ``stages.timers`` -> ``tffm_timer_<name>_count`` /
      ``_seconds_total`` counters + ``_p50_ms``/``_p95_ms``/``_p99_ms``
      /``_max_ms``/``_mean_ms`` gauges (the percentiles describe the
      recent ring — see telemetry.Timing) + the ``_window_count``
      gauge naming how many ring samples those percentiles summarize;
    - ``stages.depths`` -> ``tffm_depth_<name>_events_total`` /
      ``_mean`` / ``_max`` plus per-band ``_bucket{band="1-3"}`` gauges
      (occupancy bands, not cumulative ``le`` buckets);
    - ``health.*`` -> ``tffm_health_<key>`` gauges;
    - ``tiered.*`` -> ``tffm_tiered_<key>`` gauges;
    - ``resource.*`` -> ``tffm_resource_<key>`` gauges (RSS, component
      byte ledger, compile counters, FLOPs attribution);
    - ``serve.*`` -> ``tffm_serve_<key>`` gauges (qps, latency
      percentiles, batch fill, steady_compiles — the serving
      endpoint's record block, including the ``skew_*`` keys as
      ``tffm_serve_skew_*``);
    - ``quality.*`` -> ``tffm_quality_<key>`` gauges (windowed online
      eval + drift signals — the model-quality record block);
    - ``build_info`` (a dict of strings) -> one ``tffm_build_info``
      info-style gauge whose LABELS carry the run identity (jax
      version, backend, mesh, K), value always 1 — the Prometheus
      idiom for making every scrape self-identifying across runs.
    """
    lines: list = []

    def emit(name: str, value, mtype: str = "gauge", help_: str = "",
             labels: str = "") -> None:
        if not _num(value):
            return
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name}{labels} {value}")

    for key, val in record.items():
        if _num(val):
            emit(f"tffm_{_prom_name(key)}", val,
                 help_="record scalar from the live status snapshot")
    stages = record.get("stages") or {}
    for name, val in sorted((stages.get("counters") or {}).items()):
        emit(f"tffm_counter_{_prom_name(name)}_total", val, "counter")
    for name, val in sorted((stages.get("gauges") or {}).items()):
        emit(f"tffm_gauge_{_prom_name(name)}", val)
    for name, snap in sorted((stages.get("timers") or {}).items()):
        base = f"tffm_timer_{_prom_name(name)}"
        emit(f"{base}_count", snap.get("count", 0), "counter")
        emit(f"{base}_seconds_total", snap.get("total_s", 0.0), "counter")
        if "window_n" in snap:
            # Sample-count companion of the percentile gauges: how many
            # ring samples p50/p95/p99 summarize — a p99 over 3 samples
            # must be distinguishable from one over 30k.
            emit(f"{base}_window_count", snap["window_n"])
        for pkey in ("mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms"):
            if pkey in snap:
                emit(f"{base}_{pkey}", snap[pkey])
    for name, snap in sorted((stages.get("depths") or {}).items()):
        if not snap.get("count"):
            continue
        base = f"tffm_depth_{_prom_name(name)}"
        emit(f"{base}_events_total", snap["count"], "counter")
        emit(f"{base}_mean", snap.get("mean", 0.0))
        emit(f"{base}_max", snap.get("max", 0))
        buckets = snap.get("buckets") or {}
        if buckets:
            lines.append(f"# TYPE {base}_bucket gauge")
            for band, n in buckets.items():
                lines.append(f'{base}_bucket{{band="{band}"}} {n}')
    for block in ("health", "tiered", "resource", "serve", "quality",
                  "fleet", "alerts"):
        for key, val in sorted((record.get(block) or {}).items()):
            emit(f"tffm_{block}_{_prom_name(key)}", val)
    # The alerts block's per-rule state renders as one labeled gauge per
    # armed rule — the live-breach surface a Prometheus scrape needs
    # (the JSONL stream only shows the breach EDGE, not the episode).
    rules = (record.get("alerts") or {}).get("rules") or []
    if rules:
        lines.append("# HELP tffm_alert_active 1 while the rule's "
                     "breach episode is live (0 = armed and quiet)")
        lines.append("# TYPE tffm_alert_active gauge")
        for rule in rules:
            lines.append(
                f'tffm_alert_active{{rule="'
                f'{_label_value(rule.get("rule", ""))}"}} '
                f'{int(rule.get("active") or 0)}'
            )
    info = record.get("build_info")
    if isinstance(info, dict) and info:
        labels = ",".join(
            f'{_prom_name(str(k))}="{_label_value(v)}"'
            for k, v in sorted(info.items())
        )
        lines.append("# HELP tffm_build_info run identity labels "
                     "(value is always 1)")
        lines.append("# TYPE tffm_build_info gauge")
        lines.append(f"tffm_build_info{{{labels}}} 1")
    return "\n".join(lines) + "\n"


class ObsHTTPServer(ThreadingHTTPServer):
    """The HTTP server every in-process endpoint mounts: handler
    threads are daemons (an endpoint must never pin process exit), and
    the accept backlog is deep — socketserver's default of 5 turns a
    connection SPIKE into dropped SYNs and ~1 s retransmit latency
    cliffs, the exact failure mode the serving router's burst probe
    measures."""

    daemon_threads = True
    request_queue_size = 128


def probe_reuseport() -> bool:
    """True when this platform both DEFINES ``SO_REUSEPORT`` and
    accepts it on a stream socket (the constant exists on some kernels
    that still reject the setsockopt) — the feature probe behind
    ``PooledHTTPServer``'s multi-listener mode.  Pure capability check:
    binds nothing."""
    if not hasattr(socket, "SO_REUSEPORT"):
        return False
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        return True
    except OSError:
        return False


class PooledHTTPServer(ObsHTTPServer):
    """:class:`ObsHTTPServer` with a FIXED pool of persistent handler
    workers instead of a thread spawn per connection.

    Thread-per-connection pays a spawn + teardown on every accepted
    socket and funnels every accept through the one ``serve_forever``
    loop; under the router's burst traffic both show up directly in
    ``serve_burst_p99_x``.  Here accepted connections land in a bounded
    hand-off queue and ``pool_size`` long-lived workers serve them —
    the router's backend connection pool lands on warm handlers, and a
    connection spike backpressures into the TCP backlog (blocking
    ``put``) instead of spawning unbounded threads.

    ``acceptors > 1`` adds N-1 extra accept loops.  When the kernel
    supports ``SO_REUSEPORT`` (:func:`probe_reuseport`), each extra
    loop gets its OWN listener socket bound to the same address — the
    kernel load-balances connections across listeners and the accept
    path stops serializing on one socket lock.  Portable fallback:
    the extra loops ``accept()`` on the shared primary socket.  The
    effective mode is published as ``self.reuseport``.

    Keep-alive interacts with pooling the obvious way: a kept-alive
    connection HOLDS its worker until the peer closes or the 60 s
    handler socket timeout fires (exactly like a handler thread did,
    but now from a finite pool) — so ``pool_size`` must cover the
    expected concurrent kept-alive connections; SERVING.md has the
    sizing rule.  The request-level discipline (60 s timeout,
    keep-alive, TCP_NODELAY, Content-Length) is the handler class's
    and is untouched.

    ``server_close()`` tears the whole shape down deterministically:
    stops the accept loops, drops queued-but-unserved connections
    (a queued slow peer must not pin close for its socket timeout),
    aborts in-flight reads with ``SHUT_RDWR``, then joins every worker
    and acceptor — zero leaked threads, pinned by test and the TL007
    lint rule.
    """

    def __init__(self, server_address, RequestHandlerClass,
                 pool_size: int = 8, acceptors: int = 1,
                 bind_and_activate: bool = True):
        self.pool_size = max(1, int(pool_size))
        self.acceptors = max(1, int(acceptors))
        self.reuseport = False
        self._stop_accept = threading.Event()
        self._pool_closed = False
        self._active: set = set()
        self._active_lock = threading.Lock()
        self._conn_q: queue.Queue = queue.Queue(
            maxsize=max(32, 2 * self.pool_size)
        )
        self._extra_socks: list = []
        self._acceptors: list = []
        self._workers = [
            threading.Thread(
                target=self._worker, name=f"tffm-http-worker-{i}",
                daemon=True,
            )
            for i in range(self.pool_size)
        ]
        # server_bind (called by super().__init__) reads self.acceptors
        # to decide on SO_REUSEPORT, so state init precedes it.
        super().__init__(server_address, RequestHandlerClass,
                         bind_and_activate=bind_and_activate)
        for t in self._workers:
            t.start()
        if bind_and_activate:
            self._start_extra_acceptors()

    # -- accept side ---------------------------------------------------

    def server_bind(self) -> None:
        if self.acceptors > 1 and probe_reuseport():
            try:
                self.socket.setsockopt(
                    socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
                )
                self.reuseport = True
            except OSError:
                self.reuseport = False
        super().server_bind()

    def _start_extra_acceptors(self) -> None:
        for i in range(self.acceptors - 1):
            sock = self.socket
            if self.reuseport:
                try:
                    s = socket.socket(
                        self.address_family, self.socket_type
                    )
                    s.setsockopt(
                        socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
                    )
                    # server_address is the RESOLVED one (port-0 safe).
                    s.bind(self.server_address)
                    s.listen(self.request_queue_size)
                    self._extra_socks.append(s)
                    sock = s
                except OSError:
                    sock = self.socket  # shared-socket fallback
            t = threading.Thread(
                target=self._accept_loop, args=(sock,),
                name=f"tffm-http-accept-{i + 1}", daemon=True,
            )
            self._acceptors.append(t)
            t.start()

    def _accept_loop(self, sock) -> None:
        """One extra acceptor: select (so shutdown is prompt) ->
        accept -> the same verify/process contract as BaseServer's
        ``_handle_request_noblock``."""
        while not self._stop_accept.is_set():
            try:
                ready, _, _ = select.select([sock], [], [], 0.5)
            except OSError:
                break  # socket closed under us: shutting down
            if not ready:
                continue
            try:
                request, client_address = sock.accept()
            except OSError:
                continue
            if self.verify_request(request, client_address):
                try:
                    self.process_request(request, client_address)
                except Exception:  # noqa: BLE001 - keep accepting
                    self.handle_error(request, client_address)
                    self.shutdown_request(request)
            else:
                self.shutdown_request(request)

    def process_request(self, request, client_address) -> None:
        """Hand the accepted connection to the pool.  The put BLOCKS
        when every worker is busy and the queue is full — backpressure
        lands in the TCP backlog, which is the overload surface the
        router's shed discipline already reasons about."""
        self._conn_q.put((request, client_address))

    # -- worker side ---------------------------------------------------

    def _worker(self) -> None:
        while True:
            item = self._conn_q.get()
            if item is None:
                return
            request, client_address = item
            with self._active_lock:
                if self._pool_closed:
                    # Raced server_close's drain: drop, don't serve.
                    dropped = True
                else:
                    self._active.add(request)
                    dropped = False
            if dropped:
                self._shutdown_quiet(request)
                continue
            try:
                self.finish_request(request, client_address)
            except Exception:  # noqa: BLE001 - mirror ThreadingMixIn
                self.handle_error(request, client_address)
            finally:
                with self._active_lock:
                    self._active.discard(request)
                self._shutdown_quiet(request)

    def _shutdown_quiet(self, request) -> None:
        try:
            self.shutdown_request(request)
        except OSError:
            pass

    # -- teardown ------------------------------------------------------

    def shutdown(self) -> None:
        self._stop_accept.set()
        super().shutdown()

    def server_close(self) -> None:
        # Belt and braces: owners call shutdown() first, but a server
        # whose serve_forever never ran is closed without it (and
        # BaseServer.shutdown would block forever there).
        self._stop_accept.set()
        super().server_close()
        for s in self._extra_socks:
            try:
                s.close()
            except OSError:
                pass
        # Acceptors exit promptly: sockets are closed and the stop
        # event is set; a put-blocked acceptor unblocks because the
        # workers below keep draining until their sentinel.
        with self._active_lock:
            self._pool_closed = True
            active = list(self._active)
        while True:
            try:
                item = self._conn_q.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                self._shutdown_quiet(item[0])
        for request in active:
            # Abort in-flight reads so a worker parked in a blocking
            # recv (kept-alive idle, slow peer) wakes NOW instead of
            # at its socket timeout.  The worker still owns the close.
            try:
                request.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for _ in self._workers:
            self._conn_q.put(None)
        for t in self._workers:
            t.join()
        for t in self._acceptors:
            t.join()


class QuietHandler(BaseHTTPRequestHandler):
    """Shared handler base for the in-process endpoints (this status
    server and the serving endpoint): silenced access log, the one
    response helper, and the common observability GET routes — so the
    surface both endpoints promise lives in one place."""

    # Keep-alive: every response carries Content-Length (see _send), so
    # HTTP/1.1 is safe and spares latency-critical clients a TCP
    # connect + handler-thread spawn per request.
    protocol_version = "HTTP/1.1"
    # TCP_NODELAY on every accepted connection: the response is two
    # writes (buffered headers, then the body through the unbuffered
    # wfile), and with Nagle on, the body write stalls behind the
    # peer's delayed ACK of the headers segment — measured as a flat
    # ~40 ms p50 on kept-alive connections (the router's proxy path),
    # which is 10x the whole scoring dispatch.
    disable_nagle_algorithm = True
    # Socket timeout: a peer that stalls mid-read (short body behind a
    # larger Content-Length, half-open connection) must release the
    # handler thread instead of pinning it forever.
    timeout = 60

    def log_message(self, *args) -> None:  # quiet access log
        pass

    def _send(self, code: int, body: bytes, ctype: str,
              headers: Optional[dict] = None,
              keep_alive: bool = False) -> None:
        if code >= 400 and not keep_alive:
            # Error paths may not have consumed the request body; a
            # kept-alive connection would misparse the leftover bytes
            # as the next request.  A caller that DID consume the body
            # passes keep_alive=True — the router's 429 shed path
            # does, because tearing down TCP connections is exactly
            # the wrong reflex under overload (every shed would force
            # a reconnect storm).
            self.close_connection = True
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for key, val in (headers or {}).items():
            self.send_header(key, val)
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self, max_bytes: int) -> Optional[bytes]:
        """Read a POST body per ``Content-Length``; returns the bytes,
        or None with the error response ALREADY SENT.  The length is
        untrusted input on an unauthenticated endpoint: absent -> 411
        (a chunked body is unreadable by length and answering 200-empty
        would silently drop the request), malformed or negative -> 400
        (a negative length would read-to-EOF, pinning the handler
        thread until the client hangs up), over ``max_bytes`` -> 413."""
        if "Content-Length" not in self.headers:
            self._send(
                411, b"Content-Length required (chunked transfer is "
                     b"not supported)\n", "text/plain",
            )
            return None
        try:
            length = int(self.headers["Content-Length"])
        except ValueError:
            self._send(400, b"bad Content-Length\n", "text/plain")
            return None
        if length < 0:
            self._send(400, b"bad Content-Length\n", "text/plain")
            return None
        if length > max_bytes:
            self._send(
                413, f"request body over the {max_bytes >> 20} MiB "
                     f"cap; split it\n".encode(), "text/plain",
            )
            return None
        return self.rfile.read(length)

    def _get_observability(self, path: str, build) -> bool:
        """Answer the shared routes (``/healthz``, ``/debug/threadz``,
        ``/metrics``, ``/status``); returns False for anything else so
        the subclass can dispatch its own.  ``build`` is the owner's
        on-demand record builder; its failures degrade to 500 — an
        observability endpoint reports errors, it never dies of them."""
        if path == "/healthz":
            self._send(200, b"ok\n", "text/plain")
            return True
        if path == "/debug/threadz":
            self._send(200, thread_dump().encode(), "text/plain")
            return True
        if path not in ("/metrics", "/status"):
            return False
        try:
            record = build() or {}
        except Exception as e:  # noqa: BLE001 - report, don't die
            self._send(
                500, f"builder failed: {e}\n".encode(), "text/plain"
            )
            return True
        if path == "/status":
            self._send(
                200, (json.dumps(record) + "\n").encode(),
                "application/json",
            )
        else:
            self._send(
                200, render_prometheus(record).encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        return True

    def _post_incident(self, query: str, incident) -> None:
        """Answer ``POST /incident[?reason=...]`` — the manual
        flight-recorder trigger shared by the trainer status endpoint,
        the serve replicas, and the router.  ``incident`` is the
        owner's ``Blackbox.incident``-shaped callable returning the
        bundle dir; ``None`` -> 503 (blackbox disabled on this run).
        Any body is consumed (keep-alive correctness) and ignored —
        the reason rides the query string."""
        if "Content-Length" in self.headers:
            if self._read_body(1 << 20) is None:
                return
        if incident is None:
            self._send(
                503, b"blackbox disabled on this run "
                     b"(--no_blackbox)\n", "text/plain",
            )
            return
        params = urllib.parse.parse_qs(query)
        reason = (params.get("reason") or ["manual"])[0] or "manual"
        try:
            out = incident(reason)
        except Exception as e:  # noqa: BLE001 - report, don't die
            self._send(
                500, f"incident dump failed: {e}\n".encode(),
                "text/plain",
            )
            return
        body = (json.dumps({"incident_dir": out}) + "\n").encode()
        self._send(200 if out else 503, body, "application/json")


class StatusServer:
    """Serve ``/metrics`` + ``/status`` + ``/healthz`` for one run.

    ``build`` returns the on-demand status record (the same callable
    shape the Heartbeat takes; ``None`` degrades to an empty record so
    the endpoint is up even before the owner has anything to report).
    ``port=0`` binds an OS-assigned port (tests); the bound port is
    ``self.port``.  ``host`` defaults to loopback — the endpoint is
    unauthenticated, so publishing beyond the host (a real Prometheus
    scrape) is an explicit opt-in (``status_host = 0.0.0.0``).
    ``telemetry`` (optional) receives a ``status.requests`` counter so
    scrape load shows up in snapshots.  ``profile`` (optional) is the
    on-demand capture callable ``profile(secs) -> output_dir`` behind
    ``/profile?secs=N`` — the server only guards it (one capture at a
    time; a concurrent request gets 409) and clamps ``secs`` to
    [0.1, 120]; without it the route 404s.  ``metrics_extra``
    (optional) returns extra pre-rendered Prometheus text appended to
    every ``/metrics`` response — the hook the training-fleet plane
    uses for its per-rank ``tffm_train_rank_*`` labeled series
    (obs/fleet.py); its failures degrade to the base exposition, never
    a dead scrape.  ``incident`` (optional) is the flight recorder's
    ``Blackbox.incident``-shaped callable behind ``POST /incident``
    (the manual forensic-bundle trigger); without it the route answers
    503.  ``close()`` shuts the server down and joins its thread;
    idempotent.
    """

    def __init__(self, port: int, build: Callable[[], Optional[dict]],
                 telemetry=None, host: str = "127.0.0.1",
                 profile: Optional[Callable[[float], str]] = None,
                 metrics_extra: Optional[Callable[[], str]] = None,
                 incident=None):
        self._build = build
        self._profile = profile
        self._metrics_extra = metrics_extra
        self._incident = incident
        self._profile_lock = threading.Lock()
        self._requests = (
            telemetry.counter("status.requests")
            if telemetry is not None else None
        )
        server = self

        class Handler(QuietHandler):
            def do_GET(self) -> None:  # noqa: N802 - http.server API
                if server._requests is not None:
                    server._requests.add()
                path, _, query = self.path.partition("?")
                if (
                    path == "/metrics"
                    and server._metrics_extra is not None
                ):
                    self._do_metrics_extra()
                    return
                if self._get_observability(path, server._build):
                    return
                if path == "/profile":
                    self._do_profile(query)
                    return
                self._send(404, b"not found\n", "text/plain")

            def do_POST(self) -> None:  # noqa: N802 - http.server API
                if server._requests is not None:
                    server._requests.add()
                path, _, query = self.path.partition("?")
                if path == "/incident":
                    self._post_incident(query, server._incident)
                    return
                self._send(404, b"not found\n", "text/plain")

            def _do_metrics_extra(self) -> None:
                """/metrics with the owner's extra labeled series
                appended (fleet per-rank series).  The base record
                keeps the shared 500-on-builder-failure contract; a
                failing extra hook degrades to the base exposition —
                per-rank decoration must never kill the scrape."""
                try:
                    record = server._build() or {}
                    body = render_prometheus(record)
                except Exception as e:  # noqa: BLE001 - report, don't die
                    self._send(
                        500, f"builder failed: {e}\n".encode(),
                        "text/plain",
                    )
                    return
                try:
                    body += server._metrics_extra() or ""
                except Exception as e:  # noqa: BLE001
                    log.warning("metrics_extra hook failed: %s", e)
                self._send(
                    200, body.encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )

            def _do_profile(self, query: str) -> None:
                """On-demand profiler window.  Blocks THIS handler
                thread for the capture (other routes keep answering —
                ThreadingHTTPServer); the non-blocking lock acquire is
                the one-at-a-time guard (two overlapping jax profiler
                traces would poison each other)."""
                if server._profile is None:
                    self._send(
                        404, b"profiler not available on this run\n",
                        "text/plain",
                    )
                    return
                params = urllib.parse.parse_qs(query)
                try:
                    secs = float(params.get("secs", ["2"])[0])
                except ValueError:
                    self._send(400, b"secs must be a number\n",
                               "text/plain")
                    return
                secs = min(max(secs, 0.1), 120.0)
                if not server._profile_lock.acquire(blocking=False):
                    self._send(
                        409, b"a profile capture is already in "
                             b"progress\n", "text/plain",
                    )
                    return
                try:
                    out = server._profile(secs)
                except Exception as e:  # noqa: BLE001 - report, don't die
                    self._send(
                        500, f"profile capture failed: {e}\n".encode(),
                        "text/plain",
                    )
                    return
                finally:
                    server._profile_lock.release()
                body = (json.dumps(
                    {"profile_dir": out, "secs": secs}
                ) + "\n").encode()
                self._send(200, body, "application/json")

        self._httpd = ObsHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tffm-status",
            daemon=True,
        )
        self._thread.start()
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._thread.join()
        self._httpd.server_close()
