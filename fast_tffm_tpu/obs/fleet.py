"""Fleet aggregation: the shared merge/render core + the live
training-fleet plane.

Two planes scrape a fleet of ``/status`` endpoints and publish one
merged view: the serving router (serve/router.py, PR 13) over its
replicas, and — this module — rank 0 of a multi-process training run
over every rank.  The MERGE SEMANTICS are identical by construction:
:class:`MergeSpec` + :func:`merge_blocks` hold the one implementation
(sums for monotonic counters and rates, weighted means for centers,
MAX for tails — a merged p99 cannot be computed from per-member
percentiles, so the max is the honest conservative bound — and the
scrape-staleness age the alert plane watches), and
:func:`labeled_lines` is the one renderer for per-member labeled
Prometheus series.  The router consumes both, so the two planes cannot
drift.

:class:`TrainFleet` is the training side: rank 0 scrapes every rank's
``/status`` on the heartbeat cadence (the ``train_fleet_scrape``
config knob lists the targets), keeps the latest record per rank (a
failed scrape keeps the previous one and lets its staleness age), and
exposes:

- ``block()`` — the ``fleet`` dict merged onto rank 0's
  heartbeat/status/final records: summed ``examples_in``,
  examples-weighted ``ingest_wait_frac``, MAX-merged dispatch/wait/
  exchange p99 tails, ``scrape_age_max_s``, plus live straggler
  attribution: ``straggler_ratio`` (slowest rank's mean dispatch wall
  over the fleet mean — 1.0 at parity), ``slowest_rank`` + its
  ``slowest_rank_share`` of the fleet's total dispatch wall,
  ``dispatch_skew_ms`` / ``wait_skew_ms`` (max-min of the per-rank
  means), step-count desync ``rank_step_skew``, and the worst
  per-rank ``exchange_frac`` (fraction of a rank's wall spent blocked
  on the cross-rank collective — see train/sparse.py's probe).
- ``metrics_lines()`` — per-rank ``tffm_train_rank_*`` labeled series
  appended to rank 0's ``/metrics`` (StatusServer ``metrics_extra``).

All of it is alertable through the usual rules grammar
(``straggler_ratio > 1.5 for 3 : warn``); config refuses fleet-plane
rules when ``train_fleet_scrape`` is unset — the established
inert-rule discipline.

Stdlib-only, like the rest of ``obs/`` (no jax, no numpy): the router
imports this module and must stay jax-free.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "MergeSpec", "merge_blocks", "labeled_lines",
    "TrainFleet", "TRAIN_MERGE_SPEC", "RANK_SERIES",
]

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class MergeSpec:
    """How a set of scraped per-member blocks folds into one fleet
    view.  Key groups (each names keys of the member blocks):

    - ``sums`` — monotonic counters and additive rates; summed,
      emitted as ``{prefix}{key}`` rounded to 2 (counter precision).
    - ``weighted`` — center statistics (p50, wait fractions); mean
      weighted by each member's ``weight_key`` value (min weight 1 so
      an idle member still counts), emitted ``{prefix}{key}`` @ 4.
    - ``tails`` — upper quantiles/maxima; MAX-merged (the honest
      conservative bound), emitted ``{prefix}{key}`` @ 4.
    - ``means`` — plain unweighted means (fill fractions), @ 6.
    - ``max_same`` — MAX-merged under the SAME key name (distribution
      distances like PSI, where the fleet's worst member is the
      aggregate and a mean would dilute it N-fold), @ 6.
    - ``sum_same_int`` — integer sums under the same key name (mass
      counters that ride next to ``max_same`` keys).

    ``count_key`` carries how many members contributed (0 on an empty
    scrape — the only key then); ``age_key`` carries the oldest
    member's scrape age in seconds @ 3 (the staleness alert signal).
    """

    sums: Tuple[str, ...] = ()
    weighted: Tuple[str, ...] = ()
    weight_key: str = ""
    tails: Tuple[str, ...] = ()
    means: Tuple[str, ...] = ()
    max_same: Tuple[str, ...] = ()
    sum_same_int: Tuple[str, ...] = ()
    prefix: str = "fleet_"
    count_key: str = "replicas_scraped"
    age_key: str = "fleet_scrape_age_max_s"


def _vals(blocks: List[Tuple[float, dict]], key: str) -> list:
    return [
        b.get(key) for _t, b in blocks
        if isinstance(b.get(key), (int, float))
    ]


def merge_blocks(spec: MergeSpec,
                 blocks: List[Tuple[float, dict]],
                 now: float) -> dict:
    """Fold ``blocks`` (``(scrape_time, member_block)`` pairs) into one
    fleet dict per ``spec``.  A key absent (or non-numeric) in a member
    simply doesn't contribute; a group with no contributors emits no
    key at all (no lying zeros)."""
    if not blocks:
        return {spec.count_key: 0}
    out: dict = {spec.count_key: len(blocks)}
    for key in spec.sums:
        vals = _vals(blocks, key)
        if vals:
            out[f"{spec.prefix}{key}"] = round(sum(vals), 2)
    if spec.weighted:
        weights = [
            max(1, int(b[spec.weight_key]))
            if isinstance(b.get(spec.weight_key), (int, float))
            else 1
            for _t, b in blocks
        ]
        for key in spec.weighted:
            pairs = [
                (b.get(key), w)
                for (_t, b), w in zip(blocks, weights)
                if isinstance(b.get(key), (int, float))
            ]
            if pairs:
                out[f"{spec.prefix}{key}"] = round(
                    sum(v * w for v, w in pairs)
                    / sum(w for _v, w in pairs),
                    4,
                )
    for key in spec.tails:
        vals = _vals(blocks, key)
        if vals:
            out[f"{spec.prefix}{key}"] = round(max(vals), 4)
    for key in spec.means:
        vals = _vals(blocks, key)
        if vals:
            out[f"{spec.prefix}{key}"] = round(sum(vals) / len(vals), 6)
    for key in spec.max_same:
        vals = _vals(blocks, key)
        if vals:
            out[key] = round(max(vals), 6)
    for key in spec.sum_same_int:
        vals = _vals(blocks, key)
        if vals:
            out[key] = int(sum(vals))
    out[spec.age_key] = round(max(now - t for t, _b in blocks), 3)
    return out


def _label_escape(value) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"')


def labeled_lines(name: str, mtype: str,
                  samples: Iterable[Tuple[dict, object]]) -> List[str]:
    """One labeled Prometheus series: a ``# TYPE`` header plus one
    ``name{k="v",...} value`` line per sample.  Empty samples render
    nothing (no headless TYPE lines) — the skip-when-absent contract
    both fleet renderers share."""
    samples = list(samples)
    if not samples:
        return []
    lines = [f"# TYPE {name} {mtype}"]
    for labels, value in samples:
        lab = ",".join(
            f'{k}="{_label_escape(v)}"' for k, v in labels.items()
        )
        lines.append(f"{name}{{{lab}}} {value}")
    return lines


# The training fleet's merge over the per-rank rows _rank_row extracts
# from scraped /status records.  prefix="" — the keys live inside the
# record's `fleet` block, which already names the plane (Prometheus
# renders them tffm_fleet_<key>).
TRAIN_MERGE_SPEC = MergeSpec(
    sums=("examples_in",),
    weighted=("ingest_wait_frac",),
    weight_key="examples_in",
    tails=("dispatch_p99_ms", "wait_p99_ms", "exchange_p99_ms"),
    prefix="",
    count_key="ranks_scraped",
    age_key="scrape_age_max_s",
)

# Per-rank labeled series on rank 0's /metrics: (row key, series name,
# Prometheus type).  Documented in OBSERVABILITY.md "Fleet training".
RANK_SERIES = (
    ("step", "tffm_train_rank_step", "gauge"),
    ("examples_in", "tffm_train_rank_examples_total", "counter"),
    ("ingest_wait_frac", "tffm_train_rank_ingest_wait_frac", "gauge"),
    ("dispatch_mean_ms", "tffm_train_rank_dispatch_mean_ms", "gauge"),
    ("dispatch_p99_ms", "tffm_train_rank_dispatch_p99_ms", "gauge"),
    ("wait_mean_ms", "tffm_train_rank_wait_mean_ms", "gauge"),
    ("wait_p99_ms", "tffm_train_rank_wait_p99_ms", "gauge"),
    ("exchange_frac", "tffm_train_rank_exchange_frac", "gauge"),
    ("scrape_age_s", "tffm_train_rank_scrape_age_s", "gauge"),
    # Rank-sharded tiering (ISSUE 19): each rank's share of the tier
    # partition — its cold-store bytes and how many shards it owns
    # (fleet-wide the owned counts must sum to num_shards; a hole
    # means some id range has no owner writing it back).
    ("tiered_cold_store_bytes", "tffm_train_rank_tiered_cold_bytes",
     "gauge"),
    ("tiered_owned_shards", "tffm_train_rank_tiered_owned_shards",
     "gauge"),
)

_TIMER_ROWS = (
    ("dispatch", "train.dispatch"),
    ("wait", "train.wait_input"),
    ("exchange", "train.exchange"),
)


def _rank_row(target: str, index: int, t: float, rec: dict,
              now: float) -> dict:
    """Flatten one scraped train /status record into the per-rank row
    the merge spec and labeled series consume."""
    row = {
        "rank": rec.get("rank", index),
        "target": target,
        "scrape_age_s": round(now - t, 3),
    }
    for key in ("step", "examples_in", "ingest_wait_frac"):
        val = rec.get(key)
        if isinstance(val, (int, float)):
            row[key] = val
    tiered = rec.get("tiered")
    if isinstance(tiered, dict):
        # Rank-sharded tiering: the per-rank partition share (sharded
        # snapshots carry num_shards/owned_shards; host-global tiered
        # ranks only the byte/row figures).
        for key in ("cold_store_bytes", "resident_rows",
                    "num_shards", "owned_shards"):
            val = tiered.get(key)
            if isinstance(val, (int, float)):
                row[f"tiered_{key}"] = val
    timers = (rec.get("stages") or {}).get("timers") or {}
    for short, name in _TIMER_ROWS:
        snap = timers.get(name) or {}
        if not snap.get("count"):
            continue
        row[f"{short}_count"] = snap["count"]
        row[f"{short}_total_s"] = snap.get("total_s", 0.0)
        for pkey in ("mean_ms", "p99_ms"):
            if isinstance(snap.get(pkey), (int, float)):
                row[f"{short}_{pkey}"] = snap[pkey]
    elapsed = rec.get("elapsed")
    if (
        isinstance(elapsed, (int, float)) and elapsed > 0
        and "exchange_total_s" in row
    ):
        # Fraction of this rank's run wall spent blocked at the
        # cross-rank collective barrier (the train.exchange probe) —
        # ~0 at parity, grows by exactly the straggler-induced wait.
        row["exchange_frac"] = round(
            row["exchange_total_s"] / elapsed, 6
        )
    return row


class TrainFleet:
    """Rank 0's live training-fleet aggregator.

    Scrapes each target's ``/status`` every ``interval_s`` seconds on
    its own daemon thread (``interval_s <= 0`` or ``start=False``
    skips the thread — tests drive :meth:`scrape_once` directly).  A
    failed scrape keeps the target's previous record and bumps the
    ``train.fleet_scrape_errors`` counter; the record's age then grows
    until ``scrape_age_max_s`` trips a staleness rule — a dead rank
    degrades to staleness, never to a crash.  ``fetch`` (tests) maps a
    target to its decoded /status record in place of HTTP.
    """

    def __init__(self, targets: Iterable[str], interval_s: float = 0.0,
                 telemetry=None, timeout: float = 2.0,
                 fetch: Optional[Callable[[str], dict]] = None,
                 start: bool = True):
        self.targets = [t.strip() for t in targets if t.strip()]
        self._timeout = timeout
        self._fetch = fetch if fetch is not None else self._http_fetch
        self._lock = threading.Lock()
        self._latest: Dict[str, Tuple[float, dict]] = {}
        self._t_scrape = (
            telemetry.timer("train.fleet_scrape")
            if telemetry is not None else None
        )
        self._c_errors = (
            telemetry.counter("train.fleet_scrape_errors")
            if telemetry is not None else None
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start and interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, args=(interval_s,),
                name="tffm-fleet-scrape", daemon=True,
            )
            self._thread.start()

    # -- scrape side ---------------------------------------------------

    def _http_fetch(self, target: str) -> dict:
        with urllib.request.urlopen(
            f"http://{target}/status", timeout=self._timeout
        ) as resp:
            return json.loads(resp.read())

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.scrape_once()
            except Exception as e:  # noqa: BLE001 - keep scraping
                log.warning("fleet scrape pass failed: %s", e)

    def scrape_once(self) -> int:
        """One pass over every target; returns how many answered."""
        if self._t_scrape is not None:
            with self._t_scrape.time():
                return self._scrape_pass()
        return self._scrape_pass()

    def _scrape_pass(self) -> int:
        ok = 0
        for target in self.targets:
            if self._stop.is_set():
                break
            try:
                rec = self._fetch(target)
            except (urllib.error.URLError, OSError, ValueError) as e:
                if self._c_errors is not None:
                    self._c_errors.add()
                log.debug("fleet scrape %s failed: %s", target, e)
                continue
            if isinstance(rec, dict):
                ok += 1
                with self._lock:
                    self._latest[target] = (time.time(), rec)
        return ok

    # -- aggregate side ------------------------------------------------

    def rank_rows(self, now: Optional[float] = None) -> List[dict]:
        now = time.time() if now is None else now
        with self._lock:
            latest = dict(self._latest)
        return [
            _rank_row(target, i, *latest[target], now)
            for i, target in enumerate(self.targets)
            if target in latest
        ]

    def block(self, now: Optional[float] = None) -> dict:
        """The ``fleet`` record block: the shared merge plus live
        straggler attribution."""
        now = time.time() if now is None else now
        with self._lock:
            latest = dict(self._latest)
        rows = [
            _rank_row(target, i, *latest[target], now)
            for i, target in enumerate(self.targets)
            if target in latest
        ]
        out = merge_blocks(
            TRAIN_MERGE_SPEC,
            [(latest[r["target"]][0], r) for r in rows],
            now,
        )
        # Straggler attribution from the per-rank dispatch/wait means.
        disp = [
            r for r in rows
            if isinstance(r.get("dispatch_mean_ms"), (int, float))
        ]
        if disp:
            fleet_mean = (
                sum(r["dispatch_mean_ms"] for r in disp) / len(disp)
            )
            slowest = max(disp, key=lambda r: r["dispatch_mean_ms"])
            if fleet_mean > 0:
                out["straggler_ratio"] = round(
                    slowest["dispatch_mean_ms"] / fleet_mean, 4
                )
            out["slowest_rank"] = slowest["rank"]
            walls = [
                r.get("dispatch_total_s") for r in disp
                if isinstance(r.get("dispatch_total_s"), (int, float))
            ]
            total_wall = sum(walls) if walls else 0.0
            if total_wall > 0 and isinstance(
                slowest.get("dispatch_total_s"), (int, float)
            ):
                out["slowest_rank_share"] = round(
                    slowest["dispatch_total_s"] / total_wall, 4
                )
            means = [r["dispatch_mean_ms"] for r in disp]
            out["dispatch_skew_ms"] = round(max(means) - min(means), 4)
        waits = [
            r["wait_mean_ms"] for r in rows
            if isinstance(r.get("wait_mean_ms"), (int, float))
        ]
        if waits:
            out["wait_skew_ms"] = round(max(waits) - min(waits), 4)
        steps = [
            r["step"] for r in rows
            if isinstance(r.get("step"), (int, float))
        ]
        if steps:
            out["rank_step_skew"] = int(max(steps) - min(steps))
        fracs = [
            r["exchange_frac"] for r in rows
            if isinstance(r.get("exchange_frac"), (int, float))
        ]
        if fracs:
            # The fleet's worst rank IS the aggregate (same reasoning
            # as the skew PSI max-merge): one rank stuck at the
            # barrier is the signal, and a mean would dilute it.
            out["exchange_frac"] = round(max(fracs), 6)
        cold = [
            r["tiered_cold_store_bytes"] for r in rows
            if isinstance(r.get("tiered_cold_store_bytes"), (int, float))
        ]
        if cold:
            # Rank-sharded tiering: the fleet's logical cold store is
            # the SUM of the rank shards (each id range lives on
            # exactly one rank); owned summed against num_shards is
            # the partition-coverage check — fewer means an id range
            # has no owner flushing its write-backs.
            out["tiered_cold_store_bytes"] = int(sum(cold))
            owned = [
                r["tiered_owned_shards"] for r in rows
                if isinstance(r.get("tiered_owned_shards"), (int, float))
            ]
            shards = [
                r["tiered_num_shards"] for r in rows
                if isinstance(r.get("tiered_num_shards"), (int, float))
            ]
            if owned and shards:
                out["tiered_owned_shards"] = int(sum(owned))
                out["tiered_num_shards"] = int(max(shards))
        return out

    def metrics_lines(self, now: Optional[float] = None) -> str:
        """Per-rank ``tffm_train_rank_*`` labeled series (the
        StatusServer ``metrics_extra`` payload)."""
        rows = self.rank_rows(now)
        lines: List[str] = []
        for key, name, mtype in RANK_SERIES:
            lines.extend(labeled_lines(name, mtype, [
                ({"rank": r["rank"]}, r[key])
                for r in rows
                if isinstance(r.get(key), (int, float))
            ]))
        return "\n".join(lines) + "\n" if lines else ""

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
