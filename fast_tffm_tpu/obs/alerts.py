"""Alert-rule watchdog: heartbeat-derived signals -> warn/halt actions.

The heartbeat stream already carries everything an operator would page
on — starvation (``ingest_wait_frac``), numerical health (grad norms,
non-finite counts), hot-set churn (``tiered.hot_hit_frac``), trace
truncation — but until now a human had to watch it.  This module makes
the run watch itself: a small declarative rule set (the ``alert_rules``
INI key) is evaluated against every heartbeat record ON the heartbeat
thread, and breaches emit self-describing ``record: alert`` JSONL
entries (summarized by ``tools/report.py``, regression-gated by
``--compare``) and either warn or halt the run.

Rule grammar (rules split on ``;`` or newlines)::

    alert_rules = ingest_wait_frac > 0.5 for 3 : warn ;
                  grad_norm_drift > 10 : halt

    rule   := SIGNAL OP THRESHOLD ["for" N] ":" ACTION
    OP     := ">" | "<"
    N      := consecutive breaching heartbeats required (default 1)
    ACTION := "warn" | "halt"

Signals resolve against the heartbeat record by dotted path
(``health.grad_norm``, ``tiered.hot_hit_frac``,
``stages.gauges.ingest.oor_batches`` — segment matching is greedy, so
instrument names containing dots resolve too), with short aliases for
the common ones and a few DERIVED signals the records don't carry
directly:

- ``grad_norm_drift`` — current ``health.grad_norm`` divided by the
  rolling mean of the previous :data:`BASELINE_WINDOW` heartbeat
  values (needs :data:`BASELINE_MIN` history first).  Catches a
  diverging run long before the loss moves.
- ``beat_gap_s`` — seconds since the previous heartbeat evaluation; a
  gap far above ``heartbeat_secs`` means the heartbeat thread (or the
  whole process) is stalling.
- ``ingest_out_empty_frac`` / ``prefetch_out_empty_frac`` — fraction
  of queue put/get events that saw the respective output queue EMPTY
  (from the DepthHist occupancy buckets): sustained emptiness of the
  prefetch output queue is dispatch starvation even when wait
  fractions look small over the whole run.

A rule whose signal is absent from a record (telemetry off, tiering
off, pre-first-dispatch) simply does not evaluate that beat — and its
breach streak resets, so ``for N`` always means N *consecutive
evaluable* breaches.

Actions: ``warn`` logs and keeps counting; ``halt`` records the alert
and arms :attr:`AlertEngine.halted` — the DISPATCH loop (not the
heartbeat thread) raises :class:`AlertHaltError` at the next boundary,
so halting follows the same path as ``nan_policy=halt``: no checkpoint
overwrite, a crash-truthful final record naming the exception.  The
boundary check is also the mechanism's limit: a loop wedged INSIDE
``next()`` (a fully deadlocked ingest) never reaches the next
boundary, so a halt on a staleness/starvation signal is best-effort
there — the alert record and warning still land in the stream, but an
external supervisor must do the killing (same property as
``nan_policy=halt``, which checks at the same boundary).

Each breach episode fires ONCE (when the streak first reaches the
rule's ``for N``); the rule re-arms after a non-breaching evaluation,
so a flapping signal produces one alert per flap, not one per beat.

Stdlib-only, like the rest of ``obs/`` (no jax, no numpy).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Callable, List, Optional

__all__ = [
    "AlertRule", "AlertEngine", "AlertHaltError", "halt_error",
    "parse_rules", "run_until_halt",
    "BASELINE_WINDOW", "BASELINE_MIN",
]

log = logging.getLogger(__name__)

# Rolling-baseline shape for grad_norm_drift: mean over up to
# BASELINE_WINDOW previous heartbeat grad norms, evaluable once
# BASELINE_MIN samples exist (a 2-beat-old baseline would make the
# drift ratio pure noise).
BASELINE_WINDOW = 16
BASELINE_MIN = 4

_ACTIONS = ("warn", "halt")

# Short spellings for the signals rules most commonly watch.
_ALIASES = {
    "grad_norm": "health.grad_norm",
    "grad_norm_rms": "health.grad_norm_rms",
    "nonfinite_steps": "health.nonfinite_steps",
    "hot_hit_frac": "tiered.hot_hit_frac",
    # Resource plane (the heartbeat's `resource` block): an unexpected
    # mid-run recompile or a climbing RSS are exactly the signals an
    # operator writes one-line rules for.
    "recompiles_unexpected": "resource.recompiles_unexpected",
    "peak_rss_mb": "resource.peak_rss_mb",
    "rss_mb": "resource.rss_mb",
    "compile_s": "resource.compile_s",
    "open_fds": "resource.open_fds",
    "uptime_s": "resource.uptime_s",
    # Serving plane (the `serve` block a serve/router heartbeat
    # carries): the SLO burn rate, the router's shed fraction and
    # eviction count, and fleet-scrape staleness — the one-line-rule
    # signals a serving operator pages on (OBSERVABILITY.md "Serving
    # SLO & burn rate").
    # Model-quality plane (the heartbeat's `quality` block,
    # obs/quality.py): the drift signals a modeling operator writes
    # one-line rules for — windowed-logloss drift vs its rolling
    # baseline, the calibration ratio, and the worst adjacent-window
    # PSI across the sketched axes.
    "logloss_drift": "quality.logloss_drift",
    "calib_ratio": "quality.calib_ratio",
    "psi_max": "quality.psi_max",
    "burn_rate": "serve.burn_rate",
    "slo_bad_frac": "serve.slo_bad_frac",
    "shed_frac": "serve.shed_frac",
    "evictions": "serve.evictions",
    "respawns": "serve.respawns",
    "fleet_scrape_age_max_s": "serve.fleet_scrape_age_max_s",
    # Training-fleet plane (the `fleet` block rank 0's records carry
    # when train_fleet_scrape is set — obs/fleet.py): live straggler
    # attribution, step desync, and the cross-rank collective's share
    # of the wall.
    "straggler_ratio": "fleet.straggler_ratio",
    "rank_step_skew": "fleet.rank_step_skew",
    "exchange_frac": "fleet.exchange_frac",
}

# Signals that exist on MORE than one plane under different spellings:
# the serve block says `fleet_scrape_age_max_s`, the train fleet block
# says `scrape_age_max_s` (its block already names the plane).  The
# primary alias keeps the historical serve path; when that resolves to
# nothing on a record, these alternates are tried in order — so one
# staleness rule works against either plane's records.
_FALLBACKS = {
    "fleet_scrape_age_max_s": ("fleet.scrape_age_max_s",),
}


def resolved_signal(signal: str) -> str:
    """The dotted heartbeat path a rule's signal resolves to (alias
    expansion only — derived and already-dotted signals pass through
    unchanged).  Lets config validation reason about WHERE a rule
    reads from, e.g. refusing resource-plane rules when the resource
    block is disabled."""
    return _ALIASES.get(signal, signal)


class AlertHaltError(RuntimeError):
    """Raised by the dispatch loop when an ``action: halt`` rule fired.
    Training stops without overwriting the checkpoint; the final
    metrics record carries this exception type (same crash-truthful
    contract as ``nan_policy=halt``)."""


def halt_error(alert: dict) -> AlertHaltError:
    """The one spelling of a halt alert's exception message — the
    training dispatch loop and both serving watch loops raise it, so
    the format can't drift between them."""
    return AlertHaltError(
        f"alert rule {alert['rule']} fired with action=halt"
        + (f" at step {alert['step']}"
           if alert.get("step") is not None else "")
        + f": {alert['signal']}={alert['value']} {alert['op']} "
          f"{alert['threshold']} (sustained {alert['sustain']} "
          "heartbeat(s))"
    )


def run_until_halt(engine: Optional["AlertEngine"],
                   poll_s: float = 1.0) -> None:
    """Block the calling (main) thread until an ``action: halt`` rule
    fires — then raise :class:`AlertHaltError` — or forever.  The
    serving entrypoints' watch loop: with no engine there is nothing
    to poll and the wait is the historical zero-wake block
    (interrupted only by KeyboardInterrupt / a signal handler)."""
    stop = threading.Event()
    while not stop.wait(poll_s if engine is not None else None):
        if engine is not None and engine.halted is not None:
            raise halt_error(engine.halted)


@dataclasses.dataclass(frozen=True)
class AlertRule:
    signal: str
    op: str  # ">" | "<"
    threshold: float
    sustain: int = 1
    action: str = "warn"

    @property
    def name(self) -> str:
        return f"{self.signal}{self.op}{self.threshold:g}"

    def breached(self, value: float) -> bool:
        return value > self.threshold if self.op == ">" \
            else value < self.threshold


def parse_rules(spec: str) -> List[AlertRule]:
    """Parse an ``alert_rules`` value; raises ValueError with the
    offending fragment on any grammar error (a silently dropped alert
    rule is the one config bug this module must never have)."""
    rules: List[AlertRule] = []
    for raw in spec.replace("\n", ";").split(";"):
        text = raw.strip()
        if not text:
            continue
        head, sep, action = text.rpartition(":")
        action = action.strip().lower()
        if not sep or action not in _ACTIONS:
            raise ValueError(
                f"alert rule {text!r}: must end with ': warn' or "
                "': halt'"
            )
        sustain = 1
        parts = head.split()
        if len(parts) >= 2 and parts[-2].lower() == "for":
            try:
                sustain = int(parts[-1])
            except ValueError:
                raise ValueError(
                    f"alert rule {text!r}: 'for' needs an integer "
                    "heartbeat count"
                ) from None
            if sustain < 1:
                raise ValueError(
                    f"alert rule {text!r}: 'for N' must be >= 1"
                )
            parts = parts[:-2]
        if len(parts) != 3 or parts[1] not in (">", "<"):
            raise ValueError(
                f"alert rule {text!r}: expected 'signal > threshold' "
                "or 'signal < threshold'"
            )
        signal, op, thr = parts
        try:
            threshold = float(thr)
        except ValueError:
            raise ValueError(
                f"alert rule {text!r}: threshold {thr!r} is not a "
                "number"
            ) from None
        rules.append(AlertRule(signal, op, threshold, sustain, action))
    return rules


def _resolve(rec, path: str) -> Optional[float]:
    """Greedy dotted-path lookup tolerating dots INSIDE keys (telemetry
    instrument names): try the whole remaining path as a key first,
    then every split point left to right."""
    if not isinstance(rec, dict):
        return None
    if path in rec:
        v = rec[path]
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        return float(v)
    i = path.find(".")
    while i != -1:
        head, rest = path[:i], path[i + 1:]
        if head in rec:
            v = _resolve(rec[head], rest)
            if v is not None:
                return v
        i = path.find(".", i + 1)
    return None


def _empty_frac(rec: dict, depth_name: str) -> Optional[float]:
    snap = ((rec.get("stages") or {}).get("depths") or {}).get(depth_name)
    if not snap or not snap.get("count"):
        return None
    return (snap.get("buckets") or {}).get("0", 0) / snap["count"]


class AlertEngine:
    """Evaluate a rule set against successive heartbeat records.

    ``observe(record)`` is called by the heartbeat builder with each
    beat's record (and by tests with synthetic streams); it returns the
    alert records emitted for that beat, after writing them to
    ``writer`` (the run's JsonlWriter) and logging.  ``halted`` holds
    the first ``action: halt`` alert record once one fires — the
    dispatch loop polls it between dispatches.
    """

    def __init__(self, rules: List[AlertRule], writer=None,
                 clock: Callable[[], float] = time.time,
                 on_alert: Optional[Callable[[dict], None]] = None):
        self.rules = list(rules)
        self.halted: Optional[dict] = None
        self.fired_total = 0
        self._writer = writer
        self._clock = clock
        # Per-alert listener (the blackbox flight recorder): called on
        # the heartbeat thread with each emitted alert record, AFTER
        # the record is written/logged.  Exceptions are swallowed — a
        # broken forensics hook must never cost the beat.
        self._on_alert = on_alert
        # Breach state is keyed by rule POSITION, not rule.name: two
        # rules can share a name while differing in sustain/action
        # (e.g. "x > 1 : warn ; x > 1 for 3 : halt" as an escalation
        # pair), and name-keyed state would let the first swallow the
        # second's halt forever.
        self._streak = [0] * len(self.rules)
        self._active = [False] * len(self.rules)
        self._grad_hist: deque = deque(maxlen=BASELINE_WINDOW)
        self._last_beat_t: Optional[float] = None

    # ------------------------------------------------------------------

    def _signal(self, rec: dict, name: str,
                now: float) -> Optional[float]:
        if name == "grad_norm_drift":
            gn = _resolve(rec, "health.grad_norm")
            if gn is None or len(self._grad_hist) < BASELINE_MIN:
                return None
            base = sum(self._grad_hist) / len(self._grad_hist)
            if base <= 0:
                return None
            return gn / base
        if name == "beat_gap_s":
            if self._last_beat_t is None:
                return None
            return now - self._last_beat_t
        if name == "ingest_out_empty_frac":
            return _empty_frac(rec, "ingest.out_q_depth")
        if name == "prefetch_out_empty_frac":
            return _empty_frac(rec, "prefetch.out_q_depth")
        value = _resolve(rec, _ALIASES.get(name, name))
        if value is None:
            for alt in _FALLBACKS.get(name, ()):
                value = _resolve(rec, alt)
                if value is not None:
                    break
        return value

    def observe(self, record: dict) -> List[dict]:
        now = self._clock()
        emitted: List[dict] = []
        for i, rule in enumerate(self.rules):
            value = self._signal(record, rule.signal, now)
            if value is None:
                # Not evaluable this beat: streak resets so "for N"
                # always means N consecutive EVALUABLE breaches.
                self._streak[i] = 0
                self._active[i] = False
                continue
            if not rule.breached(value):
                self._streak[i] = 0
                self._active[i] = False
                continue
            self._streak[i] += 1
            if self._streak[i] < rule.sustain or self._active[i]:
                continue
            self._active[i] = True
            alert = {
                "record": "alert",
                "time": now,
                "step": record.get("step"),
                "rule": rule.name,
                "signal": rule.signal,
                "value": round(value, 6),
                "threshold": rule.threshold,
                "op": rule.op,
                "sustain": rule.sustain,
                "action": rule.action,
            }
            emitted.append(alert)
            self.fired_total += 1
            log.warning(
                "ALERT %s: %s=%.6g %s %g (sustained %d beat(s); "
                "action=%s)",
                rule.name, rule.signal, value, rule.op, rule.threshold,
                rule.sustain, rule.action,
            )
            if self._writer is not None:
                try:
                    self._writer.write(alert)
                except Exception as e:  # noqa: BLE001 - never kill a beat
                    log.warning("alert record write failed: %s", e)
            if rule.action == "halt" and self.halted is None:
                self.halted = alert
            if self._on_alert is not None:
                try:
                    self._on_alert(alert)
                except Exception as e:  # noqa: BLE001 - never kill a beat
                    log.warning("alert listener failed: %s", e)
        # Update derived-signal state AFTER evaluation so rules see the
        # baseline/gap that excludes the current beat.
        gn = _resolve(record, "health.grad_norm")
        if gn is not None:
            self._grad_hist.append(gn)
        self._last_beat_t = now
        return emitted

    def active_snapshot(self) -> dict:
        """Live alert state as an ``alerts`` block for heartbeat/status
        records: armed rule count, cumulative fires, the halt latch,
        and per-rule ``active``/``streak`` (rendered by
        ``render_prometheus`` as ``tffm_alert_active{rule="..."}`` so a
        Prometheus scrape can see a currently-firing alert, not just
        the JSONL stream)."""
        return {
            "armed": len(self.rules),
            "fired_total": self.fired_total,
            "halted": int(self.halted is not None),
            "rules": [
                {
                    "rule": rule.name,
                    "action": rule.action,
                    "active": int(self._active[i]),
                    "streak": self._streak[i],
                }
                for i, rule in enumerate(self.rules)
            ],
        }
