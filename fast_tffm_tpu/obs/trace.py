"""Structured per-batch event tracing in Chrome-trace (Perfetto) format.

The telemetry layer (telemetry.py) answers "where did the run's
wall-clock go IN AGGREGATE" — p50/p95 timers, wait-vs-dispatch totals.
It cannot answer CAUSAL questions: which stage did THIS slow super-batch
stall in, was the prefetcher thread blocked on staging-buffer reuse, did
the parse workers sit idle while the reader rebuilt a window?  Those
need per-event spans ordered on a timeline.  This module is that layer:
a low-overhead structured tracer whose output loads directly into
Perfetto (https://ui.perfetto.dev) or chrome://tracing.

Design constraints, shared with telemetry.py:

- stdlib only (no jax, no numpy): spawned parse workers run a
  :class:`Tracer` of their own and ship their events back over the
  existing result messages, so this module must import clean in a
  process that never loads jax;
- one shared no-op instance per disabled tracer (:data:`NULL_TRACER`):
  instrumented code never branches — ``tracer.span(...)`` on a disabled
  tracer returns a cached null context manager, and ``emit`` returns
  immediately;
- enabled overhead is two ``perf_counter`` calls plus one lock-guarded
  list append per span; events fire per batch / window / dispatch, not
  per example.

Event model (Chrome trace "X" complete events plus flow events):

- every span carries ``pid``/``tid`` so each execution context — the
  reader thread, every parse worker (thread or spawned process), the
  transfer thread, the train loop — renders as its own lane;
- correlation ids ride ``args``: ``seq`` (reader work-item sequence
  number) joins ``read.item`` → ``ring.slot_acquire`` → ``parse.batch``;
  the pipeline's delivery point bridges ``seq`` → ``batch`` (delivered
  batch index), and the prefetcher groups batches into ``sb``
  (super-batch id) which the train loop's ``train.dispatch`` span
  closes — one super-batch's life is a connected chain from file read
  to fused-scan dispatch (tools/report.py --trace walks it);
- flow arrows (``ph: s/t/f`` with id ``sb<N>``) visually link each
  super-batch's stack → H2D → dispatch across lanes.

Timestamps are ``time.perf_counter`` microseconds (CLOCK_MONOTONIC on
Linux — one clock shared by every process on the host, so worker spans
merge without alignment).  Each dump records a wall-clock anchor so
``tools/report.py --trace`` can also merge traces from DIFFERENT hosts
(multi-rank fleets) onto one timeline.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import queue
import threading
import time
from typing import Optional

__all__ = ["Tracer", "NULL_TRACER", "SpanHandle"]

log = logging.getLogger(__name__)

# Backstop against unbounded growth on very long runs: ~1M events is
# ~250 MB of JSON — far beyond what Perfetto loads comfortably anyway.
# Past the cap new events are dropped and counted (reported in dump()).
# Runs that legitimately trace past it should ROTATE instead
# (``rotate_events``): the buffer dumps and resets at the watermark,
# producing trace.0.json, trace.1.json, ... that tools/report.py
# --trace stitches back into one stream — no cap, no drops.
_MAX_EVENTS = 1_000_000

_NULL_CTX = contextlib.nullcontext()


def _us(t: float) -> int:
    return int(t * 1e6)


class SpanHandle:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_flow", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args, flow):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._flow = flow

    def __enter__(self) -> "SpanHandle":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        self._tracer.emit(
            self._name, self._t0, t1 - self._t0,
            args=self._args, flow=self._flow,
        )


class Tracer:
    """Thread-safe in-memory Chrome-trace event collector.

    ``span(name, args=..., flow=(phase, id))`` times a block;
    ``point(name, args=...)`` marks an instant (rendered as a 1 µs
    slice so report tooling treats every event uniformly);
    ``emit(...)`` records a span from explicit timestamps (used to
    re-emit worker-shipped spans under the worker's pid);
    ``take()`` drains the buffered raw events (what a parse worker
    ships back); ``add_raw`` ingests such a shipment;
    ``dump(path)`` writes the Perfetto-loadable JSON.
    """

    def __init__(self, enabled: bool = True,
                 process_name: Optional[str] = None,
                 max_events: int = _MAX_EVENTS,
                 rotate_events: int = 0,
                 rotate_path: Optional[str] = None):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: list = []
        self._dropped = 0
        self._max = max_events
        # Windowed rotation (rotate_events > 0): when the buffer reaches
        # the watermark, it is swapped out under the lock and handed to
        # a dedicated background WRITER thread — the instrumented
        # thread that crossed the watermark never pays the window's
        # json-serialize+write (tens of MB at production watermarks; an
        # inline dump would inject a periodic stall into whichever
        # pipeline stage happened to cross).
        # With rotation on, the drop cap does not apply at all: the
        # buffer resets every window, so memory is bounded by the
        # watermark (plus one in-flight shipment), and applying the
        # cap anywhere near the watermark would drop events rotation
        # exists to preserve (a worker-shipped batch crossing the cap
        # used to truncate before the rotation check could run).
        # _rotate_cfg is the configured watermark (never changes);
        # _rotate_events is the LIVE value — close() zeroes it so
        # post-close stragglers fall back to the capped buffer, and
        # reset() re-arms it for the next run of a warm owner.
        self._rotate_cfg = int(rotate_events or 0)
        self._rotate_events = self._rotate_cfg
        self._rotate_path = rotate_path
        self._windows = 0
        self._dropped_reported = 0
        self._rotate_q: Optional[queue.Queue] = None
        self._rotate_thread: Optional[threading.Thread] = None
        if self._rotate_events and enabled:
            self._start_writer()
        self._pid = os.getpid()
        self._named_tids: set = set()
        self._process_name = process_name
        self._wall_anchor = time.time()
        self._perf_anchor = time.perf_counter()
        if enabled and process_name:
            self.name_process(process_name)

    # ------------------------------------------------------------------
    # metadata
    # ------------------------------------------------------------------

    def name_process(self, name: str) -> None:
        if not self.enabled:
            return
        self._append({
            "ph": "M", "name": "process_name", "pid": self._pid, "tid": 0,
            "args": {"name": name},
        })

    def name_thread(self, name: str) -> None:
        """Label the CURRENT thread's lane (idempotent per thread)."""
        if not self.enabled:
            return
        tid = threading.get_ident()
        with self._lock:
            if tid in self._named_tids:
                return
            self._named_tids.add(tid)
        self._append({
            "ph": "M", "name": "thread_name", "pid": self._pid, "tid": tid,
            "args": {"name": name},
        })

    # ------------------------------------------------------------------
    # recording
    # ------------------------------------------------------------------

    def span(self, name: str, args: Optional[dict] = None, flow=None):
        """``with tracer.span("stage", args={"sb": 3}): ...``

        ``flow`` is an optional ``(phase, id)`` pair with phase in
        ``{"s", "t", "f"}`` (flow start / step / end) — the arrow that
        visually links this span to the others sharing the id.
        """
        if not self.enabled:
            return _NULL_CTX
        return SpanHandle(self, name, args, flow)

    def emit(self, name: str, t0: float, dur_s: float,
             args: Optional[dict] = None, pid: Optional[int] = None,
             tid: Optional[int] = None, flow=None) -> None:
        """Record one complete event from explicit perf_counter times."""
        if not self.enabled:
            return
        pid = self._pid if pid is None else pid
        tid = threading.get_ident() if tid is None else tid
        ts = _us(t0)
        ev = {
            "ph": "X", "name": name, "cat": "tffm", "ts": ts,
            "dur": max(1, _us(dur_s)), "pid": pid, "tid": tid,
        }
        if args:
            ev["args"] = args
        self._append(ev)
        if flow is not None:
            phase, fid = flow
            fev = {
                "ph": phase, "name": "sb", "cat": "tffm_flow",
                "id": str(fid), "ts": ts, "pid": pid, "tid": tid,
            }
            if phase == "f":
                fev["bp"] = "e"  # bind the flow end to the enclosing slice
            self._append(fev)

    def point(self, name: str, args: Optional[dict] = None) -> None:
        """Mark an instant (1 µs slice, so report tooling sees one event
        shape everywhere)."""
        if not self.enabled:
            return
        self.emit(name, time.perf_counter(), 0.0, args=args)

    def _append(self, ev: dict) -> None:
        with self._lock:
            if not self._rotate_events and len(self._events) >= self._max:
                self._dropped += 1
                return
            self._events.append(ev)
            rotate = (
                self._rotate_events
                and len(self._events) >= self._rotate_events
            )
        if rotate:
            self._maybe_rotate()

    # In-memory cost of one buffered event dict, estimated: a span is a
    # small dict of short strings/ints (~120-250 B serialized) whose
    # CPython representation (dict + boxed values) runs ~2x that.  The
    # resource ledger wants an order-of-magnitude byte figure without
    # sizeof-walking a million events under the append lock.
    _EVENT_EST_BYTES = 400

    @property
    def buffer_bytes(self) -> int:
        """Estimated host bytes held by the in-memory event buffer —
        the tracer's entry in the component memory ledger (rotation
        bounds it at ~rotate_events * 400 B; unrotated traces grow to
        the cap)."""
        with self._lock:
            return len(self._events) * self._EVENT_EST_BYTES

    @property
    def dropped_events(self) -> int:
        """Events discarded at the buffer cap so far.  A nonzero value
        means the trace is TRUNCATED — chains silently stop mid-run —
        so the count is surfaced (dump() warning + the trainer's final
        metrics record) instead of only living in the dump metadata."""
        with self._lock:
            return self._dropped

    # ------------------------------------------------------------------
    # cross-process shipping
    # ------------------------------------------------------------------

    def take(self) -> list:
        """Drain and return the buffered raw events (worker side: ship
        these with the next result message)."""
        if not self.enabled:
            return []
        with self._lock:
            evs, self._events = self._events, []
        return evs

    def tail(self, n: int = 256) -> list:
        """The last ``n`` buffered events WITHOUT draining them — the
        blackbox flight recorder's view of "what was the process doing
        just now".  Shallow copies, safe to serialize after the tracer
        moves on."""
        if not self.enabled or n <= 0:
            return []
        with self._lock:
            return [dict(ev) for ev in self._events[-n:]]

    def add_raw(self, events) -> None:
        """Ingest events shipped from another Tracer (they already carry
        their own pid/tid; perf_counter is host-wide, so no shifting)."""
        if not self.enabled or not events:
            return
        with self._lock:
            if self._rotate_events:
                # No cap under rotation: a shipped batch must never
                # truncate on its way into a window (zero-drop
                # contract); the rotation below bounds memory.
                self._events.extend(events)
                rotate = len(self._events) >= self._rotate_events
            else:
                room = self._max - len(self._events)
                if room <= 0:
                    self._dropped += len(events)
                    return
                self._events.extend(events[:room])
                self._dropped += max(0, len(events) - room)
                rotate = False
        if rotate:
            self._maybe_rotate()

    # ------------------------------------------------------------------
    # windowed rotation
    # ------------------------------------------------------------------

    @property
    def windows_written(self) -> int:
        """Rotated window files dumped so far (excluding the final one
        :meth:`dump` writes)."""
        with self._lock:
            return self._windows

    def window_path(self, idx: int) -> str:
        """``trace.json`` -> ``trace.<idx>.json`` (other extensions get
        ``<path>.<idx>.json`` appended — rank-suffixed paths stay
        greppable as one family)."""
        base = self._rotate_path or "trace.json"
        stem, ext = os.path.splitext(base)
        if ext == ".json":
            return f"{stem}.{idx}.json"
        return f"{base}.{idx}.json"

    def _start_writer(self) -> None:
        self._rotate_q = queue.Queue()
        self._rotate_thread = threading.Thread(
            target=self._writer_loop, name="trace-rotate", daemon=True,
        )
        self._rotate_thread.start()

    def _writer_loop(self) -> None:
        q = self._rotate_q  # bound once: close() clears the attribute
        while True:
            item = q.get()
            try:
                if item is None:
                    return
                self._write_window(*item)
            finally:
                q.task_done()

    def _maybe_rotate(self) -> None:
        """Swap the full buffer out under the lock and enqueue it for
        the writer thread.  Instrumented threads only ever pay the
        swap; the file write happens off the hot path.  A losing racer
        sees the already-reset buffer and returns.  The queue is
        captured UNDER the lock (close() clears it under the same
        lock), so a racing close() can never strand swapped-out events
        on a writerless queue or null-deref here."""
        with self._lock:
            q = self._rotate_q
            if (
                q is None
                or not self._rotate_events
                or len(self._events) < self._rotate_events
            ):
                return  # lost the race (rotation closed or buffer reset)
            events, self._events = self._events, []
            idx = self._windows
            self._windows += 1
            dropped = self._dropped - self._dropped_reported
            self._dropped_reported = self._dropped
        q.put((idx, events, dropped))

    def _write_window(self, idx: int, events: list,
                      dropped: int) -> None:
        """One window file.  All windows of a run share the clock
        anchors (the run stays ONE timeline); ``window`` + the shared
        anchors are how ``tools/report.py --trace`` re-joins a rotated
        stream before chain reconstruction."""
        path = self.window_path(idx)
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_anchor": self._wall_anchor,
                "perf_anchor": self._perf_anchor,
                "pid": self._pid,
                "window": idx,
                "dropped_events": dropped,
            },
        }
        try:
            with open(path, "w") as f:
                json.dump(doc, f)
        except OSError as e:  # pragma: no cover - full volume
            log.warning("trace window dump failed (%s): %s", path, e)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def close(self) -> None:
        """Stop the rotation writer thread (idempotent; no-op without
        rotation).  Pending windows are flushed first.  A Tracer used
        to be leaked-by-design here — every rotating Tracer left a
        daemon ``trace-rotate`` thread alive for the life of the
        process, one more per run in a long-lived embedder (serve
        mode, test suites); flagged by tffm-lint TL005."""
        with self._lock:
            # Cleared under the append lock so a racing _maybe_rotate
            # either sees the live queue (its window will be drained by
            # the q.join() below) or sees None and backs off — never a
            # swap onto a writerless queue.  Post-close stragglers fall
            # back to the capped in-memory buffer; reset() re-arms.
            q = self._rotate_q
            self._rotate_events = 0
            self._rotate_q = None
        if q is not None:
            q.join()
            q.put(None)
        if self._rotate_thread is not None:
            self._rotate_thread.join()
            self._rotate_thread = None

    def reset(self) -> None:
        """Drop buffered events and re-anchor (per-run accounting, like
        Telemetry.reset).  The process-name metadata survives — it names
        the lane, not the run."""
        if self._rotate_q is not None:
            # A previous run's windows must finish writing before the
            # counters restart, or run-2's window 0 could interleave
            # with run-1's tail.
            self._rotate_q.join()
        with self._lock:
            self._events = []
            self._dropped = 0
            self._dropped_reported = 0
            self._windows = 0
            self._named_tids = set()
        self._wall_anchor = time.time()
        self._perf_anchor = time.perf_counter()
        # A close()d tracer re-arms for the next run: a warm owner's
        # second train() must rotate exactly like the first (close()
        # only stops the PREVIOUS run's writer thread).
        if self._rotate_cfg and self.enabled and self._rotate_q is None:
            self._rotate_events = self._rotate_cfg
            self._start_writer()
        if self.enabled and self._process_name:
            self.name_process(self._process_name)

    def dump(self, path: str) -> int:
        """Write the Perfetto-loadable JSON; returns the event count.

        With rotation configured, ``path`` is ignored in favor of the
        next window file — the run's ENTIRE output is the uniform
        ``trace.0.json .. trace.N.json`` family (the final window holds
        whatever was buffered past the last watermark crossing).

        ``otherData`` carries the wall/perf clock anchors so
        ``tools/report.py --trace`` can place traces from different
        hosts (multi-rank runs) on one wall-clock timeline.
        """
        with self._lock:
            q = self._rotate_q if self._rotate_events else None
            if q is not None:
                events, self._events = self._events, []
                idx = self._windows
                self._windows += 1
                dropped = self._dropped - self._dropped_reported
                self._dropped_reported = self._dropped
        if q is not None:
            q.put((idx, events, dropped))
            # End of run: every window must be on disk when dump
            # returns (the caller logs the family and may exit).
            q.join()
            return len(events)
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        if dropped:
            log.warning(
                "trace buffer overflowed: %d event(s) dropped past the "
                "%d-event cap — %s is TRUNCATED (chains stop mid-run); "
                "trace shorter runs, raise max_events, or rotate "
                "windows (trace_rotate_events)",
                dropped, self._max, path,
            )
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_anchor": self._wall_anchor,
                "perf_anchor": self._perf_anchor,
                "pid": self._pid,
                "dropped_events": dropped,
            },
        }
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(events)


NULL_TRACER = Tracer(enabled=False)
