"""Structured per-batch event tracing in Chrome-trace (Perfetto) format.

The telemetry layer (telemetry.py) answers "where did the run's
wall-clock go IN AGGREGATE" — p50/p95 timers, wait-vs-dispatch totals.
It cannot answer CAUSAL questions: which stage did THIS slow super-batch
stall in, was the prefetcher thread blocked on staging-buffer reuse, did
the parse workers sit idle while the reader rebuilt a window?  Those
need per-event spans ordered on a timeline.  This module is that layer:
a low-overhead structured tracer whose output loads directly into
Perfetto (https://ui.perfetto.dev) or chrome://tracing.

Design constraints, shared with telemetry.py:

- stdlib only (no jax, no numpy): spawned parse workers run a
  :class:`Tracer` of their own and ship their events back over the
  existing result messages, so this module must import clean in a
  process that never loads jax;
- one shared no-op instance per disabled tracer (:data:`NULL_TRACER`):
  instrumented code never branches — ``tracer.span(...)`` on a disabled
  tracer returns a cached null context manager, and ``emit`` returns
  immediately;
- enabled overhead is two ``perf_counter`` calls plus one lock-guarded
  list append per span; events fire per batch / window / dispatch, not
  per example.

Event model (Chrome trace "X" complete events plus flow events):

- every span carries ``pid``/``tid`` so each execution context — the
  reader thread, every parse worker (thread or spawned process), the
  transfer thread, the train loop — renders as its own lane;
- correlation ids ride ``args``: ``seq`` (reader work-item sequence
  number) joins ``read.item`` → ``ring.slot_acquire`` → ``parse.batch``;
  the pipeline's delivery point bridges ``seq`` → ``batch`` (delivered
  batch index), and the prefetcher groups batches into ``sb``
  (super-batch id) which the train loop's ``train.dispatch`` span
  closes — one super-batch's life is a connected chain from file read
  to fused-scan dispatch (tools/report.py --trace walks it);
- flow arrows (``ph: s/t/f`` with id ``sb<N>``) visually link each
  super-batch's stack → H2D → dispatch across lanes.

Timestamps are ``time.perf_counter`` microseconds (CLOCK_MONOTONIC on
Linux — one clock shared by every process on the host, so worker spans
merge without alignment).  Each dump records a wall-clock anchor so
``tools/report.py --trace`` can also merge traces from DIFFERENT hosts
(multi-rank fleets) onto one timeline.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from typing import Optional

__all__ = ["Tracer", "NULL_TRACER", "SpanHandle"]

log = logging.getLogger(__name__)

# Backstop against unbounded growth on very long runs: ~1M events is
# ~250 MB of JSON — far beyond what Perfetto loads comfortably anyway.
# Past the cap new events are dropped and counted (reported in dump()).
_MAX_EVENTS = 1_000_000

_NULL_CTX = contextlib.nullcontext()


def _us(t: float) -> int:
    return int(t * 1e6)


class SpanHandle:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_flow", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args, flow):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._flow = flow

    def __enter__(self) -> "SpanHandle":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        self._tracer.emit(
            self._name, self._t0, t1 - self._t0,
            args=self._args, flow=self._flow,
        )


class Tracer:
    """Thread-safe in-memory Chrome-trace event collector.

    ``span(name, args=..., flow=(phase, id))`` times a block;
    ``point(name, args=...)`` marks an instant (rendered as a 1 µs
    slice so report tooling treats every event uniformly);
    ``emit(...)`` records a span from explicit timestamps (used to
    re-emit worker-shipped spans under the worker's pid);
    ``take()`` drains the buffered raw events (what a parse worker
    ships back); ``add_raw`` ingests such a shipment;
    ``dump(path)`` writes the Perfetto-loadable JSON.
    """

    def __init__(self, enabled: bool = True,
                 process_name: Optional[str] = None,
                 max_events: int = _MAX_EVENTS):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: list = []
        self._dropped = 0
        self._max = max_events
        self._pid = os.getpid()
        self._named_tids: set = set()
        self._process_name = process_name
        self._wall_anchor = time.time()
        self._perf_anchor = time.perf_counter()
        if enabled and process_name:
            self.name_process(process_name)

    # ------------------------------------------------------------------
    # metadata
    # ------------------------------------------------------------------

    def name_process(self, name: str) -> None:
        if not self.enabled:
            return
        self._append({
            "ph": "M", "name": "process_name", "pid": self._pid, "tid": 0,
            "args": {"name": name},
        })

    def name_thread(self, name: str) -> None:
        """Label the CURRENT thread's lane (idempotent per thread)."""
        if not self.enabled:
            return
        tid = threading.get_ident()
        with self._lock:
            if tid in self._named_tids:
                return
            self._named_tids.add(tid)
        self._append({
            "ph": "M", "name": "thread_name", "pid": self._pid, "tid": tid,
            "args": {"name": name},
        })

    # ------------------------------------------------------------------
    # recording
    # ------------------------------------------------------------------

    def span(self, name: str, args: Optional[dict] = None, flow=None):
        """``with tracer.span("stage", args={"sb": 3}): ...``

        ``flow`` is an optional ``(phase, id)`` pair with phase in
        ``{"s", "t", "f"}`` (flow start / step / end) — the arrow that
        visually links this span to the others sharing the id.
        """
        if not self.enabled:
            return _NULL_CTX
        return SpanHandle(self, name, args, flow)

    def emit(self, name: str, t0: float, dur_s: float,
             args: Optional[dict] = None, pid: Optional[int] = None,
             tid: Optional[int] = None, flow=None) -> None:
        """Record one complete event from explicit perf_counter times."""
        if not self.enabled:
            return
        pid = self._pid if pid is None else pid
        tid = threading.get_ident() if tid is None else tid
        ts = _us(t0)
        ev = {
            "ph": "X", "name": name, "cat": "tffm", "ts": ts,
            "dur": max(1, _us(dur_s)), "pid": pid, "tid": tid,
        }
        if args:
            ev["args"] = args
        self._append(ev)
        if flow is not None:
            phase, fid = flow
            fev = {
                "ph": phase, "name": "sb", "cat": "tffm_flow",
                "id": str(fid), "ts": ts, "pid": pid, "tid": tid,
            }
            if phase == "f":
                fev["bp"] = "e"  # bind the flow end to the enclosing slice
            self._append(fev)

    def point(self, name: str, args: Optional[dict] = None) -> None:
        """Mark an instant (1 µs slice, so report tooling sees one event
        shape everywhere)."""
        if not self.enabled:
            return
        self.emit(name, time.perf_counter(), 0.0, args=args)

    def _append(self, ev: dict) -> None:
        with self._lock:
            if len(self._events) >= self._max:
                self._dropped += 1
                return
            self._events.append(ev)

    @property
    def dropped_events(self) -> int:
        """Events discarded at the buffer cap so far.  A nonzero value
        means the trace is TRUNCATED — chains silently stop mid-run —
        so the count is surfaced (dump() warning + the trainer's final
        metrics record) instead of only living in the dump metadata."""
        with self._lock:
            return self._dropped

    # ------------------------------------------------------------------
    # cross-process shipping
    # ------------------------------------------------------------------

    def take(self) -> list:
        """Drain and return the buffered raw events (worker side: ship
        these with the next result message)."""
        if not self.enabled:
            return []
        with self._lock:
            evs, self._events = self._events, []
        return evs

    def add_raw(self, events) -> None:
        """Ingest events shipped from another Tracer (they already carry
        their own pid/tid; perf_counter is host-wide, so no shifting)."""
        if not self.enabled or not events:
            return
        with self._lock:
            room = self._max - len(self._events)
            if room <= 0:
                self._dropped += len(events)
                return
            self._events.extend(events[:room])
            self._dropped += max(0, len(events) - room)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def reset(self) -> None:
        """Drop buffered events and re-anchor (per-run accounting, like
        Telemetry.reset).  The process-name metadata survives — it names
        the lane, not the run."""
        with self._lock:
            self._events = []
            self._dropped = 0
            self._named_tids = set()
        self._wall_anchor = time.time()
        self._perf_anchor = time.perf_counter()
        if self.enabled and self._process_name:
            self.name_process(self._process_name)

    def dump(self, path: str) -> int:
        """Write the Perfetto-loadable JSON; returns the event count.

        ``otherData`` carries the wall/perf clock anchors so
        ``tools/report.py --trace`` can place traces from different
        hosts (multi-rank runs) on one wall-clock timeline.
        """
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        if dropped:
            log.warning(
                "trace buffer overflowed: %d event(s) dropped past the "
                "%d-event cap — %s is TRUNCATED (chains stop mid-run); "
                "trace shorter runs or raise max_events",
                dropped, self._max, path,
            )
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_anchor": self._wall_anchor,
                "perf_anchor": self._perf_anchor,
                "pid": self._pid,
                "dropped_events": dropped,
            },
        }
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(events)


NULL_TRACER = Tracer(enabled=False)
