"""Resource & compile observability: memory ledgers + the compile
sentinel — the fourth pillar of the obs plane.

The telemetry/trace/status layers answer *where wall-clock went*; this
module answers the two questions they are blind to:

1. **Where did the memory go?**  The trainer accretes host allocations
   nobody accounts for — SHM ring slots, staging-pool buffers, the
   epoch cache (raw or prestacked), the tiered cold store, the
   tracer's event buffer — plus the device tables themselves.  The
   component owners register byte gauges into the shared telemetry
   registry (``ingest.ring_bytes``, ``ingest.cache_bytes``,
   ``prefetch.staging_bytes``); Trainer-owned components (the tiered
   cold store, the tracer's buffer) are read directly when the block
   is built — no gauge, one number per scrape.  :func:`read_rss`
   samples process
   RSS / peak-RSS from ``/proc/self/statm`` + ``/proc/self/status``
   (no new deps; ~µs, safe on the heartbeat thread).  Device bytes
   come from the backend's ``memory_stats()`` where it exists (TPU);
   the CPU backend returns None there, so the trainer supplies a
   shape-derived table+optimizer estimate as the fallback.

2. **When did the step recompile, and what does it cost?**
   :class:`CompileSentinel` accounts for every train-step compile the
   trainer's AOT cache performs: wall time (``train.compile`` timer —
   its count IS the compile count), XLA ``cost_analysis()`` /
   ``memory_analysis()`` captured at compile time (FLOPs, bytes
   accessed, output/temp bytes), and — the alerting signal — a
   ``train.recompiles_unexpected`` counter.  The documented epoch-tail
   K'=leftover compile is whitelisted (provisionally at compile time;
   the trainer confirms an epoch boundary actually follows and
   reclassifies via :meth:`CompileSentinel.reclassify_unexpected` if
   not); any OTHER mid-run recompile (batch-shape drift, sort-meta
   presence flips, a foreign K) is a silent multi-second stall and a
   sign the input stream changed shape under the run, so it warns by
   default and feeds the ``recompiles_unexpected`` alert signal.

Everything here is host-side accounting.  Like the rest of ``obs/``
this module imports neither jax nor numpy: the trainer owns anything
heavier (cost-analysis extraction, device queries) and passes plain
dicts in.  Disabled mode (``resource_metrics = off``) means the
trainer never constructs a sentinel and never builds a ``resource``
block — bit-identical training, the same contract as every prior obs
knob.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

__all__ = ["CompileSentinel", "read_rss", "read_open_fds",
           "basic_block"]

log = logging.getLogger(__name__)

_PAGE = None  # resolved once; sysconf is a syscall-free lookup after that


def _page_size() -> int:
    global _PAGE
    if _PAGE is None:
        import os

        try:
            _PAGE = os.sysconf("SC_PAGE_SIZE")
        except (ValueError, OSError, AttributeError):  # pragma: no cover
            _PAGE = 4096
    return _PAGE


def read_rss() -> tuple:
    """(rss_bytes, peak_rss_bytes) of THIS process, cheaply.

    ``/proc/self/statm`` field 2 is resident pages (one short read, no
    allocation churn — fine at heartbeat cadence); ``VmHWM`` in
    ``/proc/self/status`` is the kernel's high-water mark, which
    catches a transient spike (an epoch-cache fill, a merge) even when
    the sampler never lands on it.  Non-Linux fallback:
    ``resource.getrusage`` (stdlib) serves ``ru_maxrss`` for both.
    Returns (0, 0) only when every source fails.
    """
    rss = peak = 0
    try:
        with open("/proc/self/statm") as f:
            rss = int(f.read().split()[1]) * _page_size()
    except (OSError, ValueError, IndexError):
        pass
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    peak = int(line.split()[1]) * 1024
                    break
    except (OSError, ValueError, IndexError):
        pass
    if not rss or not peak:  # pragma: no cover - non-Linux
        try:
            import resource as _res
            import sys as _sys

            # ru_maxrss units differ by platform: kilobytes on Linux,
            # BYTES on macOS (the one platform that actually reaches
            # this fallback, /proc being absent there).
            scale = 1 if _sys.platform == "darwin" else 1024
            maxrss = _res.getrusage(_res.RUSAGE_SELF).ru_maxrss * scale
            rss = rss or maxrss
            peak = peak or maxrss
        except Exception:
            pass
    return rss, max(rss, peak)


def read_open_fds() -> int:
    """Open file-descriptor count of THIS process (one readdir of
    ``/proc/self/fd``), or -1 where /proc is absent.  The fd-leak
    signal for the socket-heavy serving fleet: a replica leaking one
    socket per kept-alive connection climbs here long before accept()
    starts failing.  Alertable as ``open_fds`` (alias for
    ``resource.open_fds``)."""
    import os

    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:  # pragma: no cover - non-Linux
        return -1


def basic_block(t0: float) -> dict:
    """The process-level slice of the ``resource`` block — RSS, uptime,
    open fds — for hosts without a compile sentinel (serve replicas,
    the router).  The trainer builds its richer block in the dispatch
    loop; key spellings here MUST match it so one alert alias covers
    both planes."""
    rss, peak = read_rss()
    out = {
        "rss_mb": round(rss / (1024 * 1024), 1),
        "peak_rss_mb": round(peak / (1024 * 1024), 1),
        "uptime_s": round(time.time() - t0, 3),
    }
    fds = read_open_fds()
    if fds >= 0:
        out["open_fds"] = fds
    return out


class CompileSentinel:
    """Accounting for train-step compilations.

    The trainer's AOT compile cache calls :meth:`record` once per
    actual compile with the wall time, the super-batch length ``k``,
    its expected/unexpected classification, and the XLA cost/memory
    numbers it extracted.  The sentinel:

    - observes the wall time into a ``train.compile`` telemetry timer
      (count == compiles) and bumps ``train.recompiles_unexpected``
      for flagged ones — both resolved lazily from the registry so a
      per-run ``Telemetry.reset()`` never orphans them;
    - writes a self-describing ``record: compile`` JSONL entry through
      the run's writer (same stream as heartbeats);
    - warns loudly on unexpected recompiles (the default-on alert);
    - keeps the steady-state dispatch's cost numbers (largest ``k``
      seen) for the ``resource`` block's throughput attribution.

    Thread-safe: compiles happen on the dispatch loop but snapshots
    run on heartbeat/status threads.
    """

    def __init__(self, telemetry=None, expected_k: int = 1):
        from fast_tffm_tpu.obs import telemetry as telemetry_mod

        self._tel = telemetry if telemetry is not None else telemetry_mod.NULL
        self._lock = threading.Lock()
        self._writer = None
        self.expected_k = int(expected_k)
        self.compiles = 0
        self.compile_s = 0.0
        self.unexpected = 0
        self._cost: dict = {}  # steady-state dispatch cost (largest k)
        self._cost_k = 0

    def set_writer(self, writer) -> None:
        """Attach the run's JsonlWriter (train() owns its lifetime)."""
        self._writer = writer

    def reset(self) -> None:
        """Per-run accounting (mirrors Telemetry.reset): a second
        train() on a warm Trainer reports ITS compiles — usually zero,
        because the AOT cache it feeds from is instance-lived."""
        with self._lock:
            self.compiles = 0
            self.compile_s = 0.0
            self.unexpected = 0
            self._writer = None
            # The cost of the cached steady-state executable still
            # describes what run 2 dispatches; keep it.

    def record(self, wall_s: float, k: int, expected: bool,
               cost: Optional[dict] = None, step: int = 0) -> None:
        """Account one actual compile (cache misses only)."""
        self._tel.timer("train.compile").observe(wall_s)
        cost = cost or {}
        with self._lock:
            self.compiles += 1
            self.compile_s += wall_s
            if not expected:
                self.unexpected += 1
            if cost and k >= self._cost_k:
                self._cost = dict(cost)
                self._cost_k = k
            writer = self._writer
        if not expected:
            self._tel.counter("train.recompiles_unexpected").add()
            log.warning(
                "UNEXPECTED train-step recompile at step %d (k=%d, "
                "%.2fs): the input stream changed shape mid-run "
                "(batch/max_features drift, sort-meta flip, or a "
                "foreign K) — only the documented epoch-tail "
                "K' < steps_per_dispatch compile is whitelisted",
                step, k, wall_s,
            )
        if writer is not None:
            rec = {
                "record": "compile",
                "time": time.time(),
                "step": step,
                "k": k,
                "compile_s": round(wall_s, 4),
                "expected": bool(expected),
            }
            rec.update(cost)
            try:
                writer.write(rec)
            except Exception as e:  # noqa: BLE001 - never kill a compile
                log.warning("compile record write failed: %s", e)

    def reclassify_unexpected(self, k: int, step: int = 0) -> None:
        """Retroactive flag for a short-k compile that was provisionally
        whitelisted as an epoch tail but turned out not to be one (the
        trainer saw another super-batch follow it instead of an epoch
        boundary).  Same counter + warn as an immediate flag; the
        original ``record: compile`` entry stays (its wall time was
        real), only the classification moves."""
        with self._lock:
            self.unexpected += 1
        self._tel.counter("train.recompiles_unexpected").add()
        log.warning(
            "UNEXPECTED train-step recompile at step %d (k=%d): a "
            "short super-batch compiled as a presumed epoch-tail K' "
            "but was NOT followed by an epoch boundary — the input "
            "stream is emitting short super-batches mid-epoch",
            step, k,
        )

    def snapshot(self) -> dict:
        """Compile-side half of the ``resource`` block (flat, numeric
        — safe from any thread, renders straight into Prometheus)."""
        with self._lock:
            out = {
                "compiles": self.compiles,
                "compile_s": round(self.compile_s, 3),
                "recompiles_unexpected": self.unexpected,
            }
            cost = dict(self._cost)
        flops = cost.get("flops", 0.0)
        bytes_acc = cost.get("bytes_accessed", 0.0)
        if flops:
            out["flops_per_dispatch"] = flops
        if bytes_acc:
            out["bytes_per_dispatch"] = bytes_acc
            if flops:
                out["arithmetic_intensity"] = round(flops / bytes_acc, 3)
        for key in ("output_bytes", "temp_bytes"):
            if cost.get(key):
                out[key] = cost[key]
        return out
