"""Run-wide telemetry core: counters, gauges, ring-buffer timings.

The trainer's stages (reader/parsers, the stacking/H2D transfer thread,
the dispatch loop) live on different threads — and, with
``parse_processes``, different processes — so the only way to attribute a
run's wall-clock is a shared, thread-safe registry every stage writes
into.  This module is that registry:

- :class:`Counter` — monotonic totals (batches parsed, examples
  delivered, cache replays, out-of-range batches);
- :class:`Gauge` — last-value instruments, plus snapshot-time *samples*
  (callables evaluated when a snapshot is taken: queue depths);
- :class:`Timing` — a lock-guarded ring of recent durations with
  monotonic count/total, reporting p50/p95/p99/max over the window (the
  fixed ring bounds memory for million-step runs; totals stay exact);
- :class:`DepthHist` — a per-event queue-depth histogram over
  power-of-two buckets.  Point-sampled depth gauges only see the queue
  at heartbeat instants; a bottleneck that flaps faster than the
  cadence (full↔empty between beats) is invisible to them.  Observing
  the depth at every put/get costs one integer bucket increment and
  makes the full occupancy distribution part of every snapshot.

Everything hangs off a :class:`Telemetry` instance.  A disabled instance
(``Telemetry(enabled=False)``, or the module-level :data:`NULL`) hands
out shared no-op instruments, so instrumented code calls them
unconditionally — no ``if telemetry:`` branches in hot paths, and
disabling telemetry is behaviorally invisible.

Enabled overhead per event is one ``perf_counter`` call plus one
uncontended lock acquire (~100 ns); events fire per *batch* (~thousands
of examples), not per example, so the hot-path cost is noise-level —
``bench.py`` measures the on/off e2e ratio to keep that claim honest.

This module deliberately imports neither jax nor numpy: the data layer
uses it, and spawned parse workers must stay jax-free.
:func:`trace_span` resolves ``jax.profiler.TraceAnnotation`` lazily and
degrades to a null context manager when jax is absent.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, Optional

__all__ = [
    "Counter", "Gauge", "Timing", "DepthHist", "Telemetry", "NULL",
    "trace_span",
]

_RING = 512  # recent-window size for percentile estimates


class Counter:
    """Thread-safe monotonic counter."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Thread-safe last-value instrument."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self) -> float:
        return self._value


class _TimingScope:
    """Context manager recording its own wall time into a Timing."""

    __slots__ = ("_timing", "_t0")

    def __init__(self, timing: "Timing") -> None:
        self._timing = timing

    def __enter__(self) -> "_TimingScope":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._timing.observe(time.perf_counter() - self._t0)


class Timing:
    """Duration histogram: monotonic count/total + a ring of recent
    observations for p50/p95/max.

    The ring holds the last :data:`_RING` durations — percentiles
    describe *recent* behavior (what a heartbeat wants: "is the parse
    slowing down NOW"), while ``count``/``total_s`` stay exact over the
    whole run so rates and wall-clock attribution never drift.
    """

    __slots__ = ("_lock", "_ring", "_idx", "_count", "_total")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ring: list = [0.0] * _RING
        self._idx = 0
        self._count = 0
        self._total = 0.0

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._ring[self._idx % _RING] = seconds
            self._idx += 1
            self._count += 1
            self._total += seconds

    def time(self) -> _TimingScope:
        """``with timing.time(): ...`` records the block's wall time."""
        return _TimingScope(self)

    @property
    def count(self) -> int:
        return self._count

    @property
    def total_s(self) -> float:
        return self._total

    def snapshot(self) -> dict:
        with self._lock:
            n = min(self._count, _RING)
            window = sorted(self._ring[:n])
            count, total = self._count, self._total
        if not count:
            return {"count": 0, "total_s": 0.0}
        # p50/p95/p99/max all describe the recent window (a cold-start
        # outlier ages out of max_ms once the ring turns over);
        # count/total_s are run-exact.  p99 exists for the serving path
        # (tail latency is the SLO number) but every timer reports it.
        p50 = window[int(0.50 * (n - 1))] if n else 0.0
        p95 = window[int(0.95 * (n - 1))] if n else 0.0
        p99 = window[int(0.99 * (n - 1))] if n else 0.0
        return {
            "count": count,
            # How many samples the percentiles below actually describe
            # (the ring, not the run): a p99 over 3 samples and one
            # over 30k are different claims, and only this number
            # distinguishes them — rendered as the `_window_count`
            # companion of every percentile series on /metrics.
            "window_n": n,
            "total_s": round(total, 6),
            "mean_ms": round(1e3 * total / count, 4),
            "p50_ms": round(1e3 * p50, 4),
            "p95_ms": round(1e3 * p95, 4),
            "p99_ms": round(1e3 * p99, 4),
            "max_ms": round(1e3 * window[-1], 4) if n else 0.0,
        }


_DEPTH_BUCKETS = 16  # bucket i holds depths with bit_length() == i; last open


def _depth_bucket_label(i: int) -> str:
    if i == 0:
        return "0"
    lo, hi = 1 << (i - 1), (1 << i) - 1
    if i == _DEPTH_BUCKETS - 1:
        return f"{lo}+"
    return str(lo) if lo == hi else f"{lo}-{hi}"


class DepthHist:
    """Per-event queue-depth histogram (power-of-two buckets).

    ``observe(depth)`` is called at every queue put/get with the depth
    the event saw; the histogram accumulates how often the queue sat at
    each occupancy band.  Unlike a snapshot-time gauge this catches
    bottlenecks that flap between heartbeats: a queue pinned full 40%
    of events and empty 60% reports exactly that, where a point sample
    would report whichever extreme the beat landed on.
    """

    __slots__ = ("_lock", "_counts", "_max", "_total", "_n")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts = [0] * _DEPTH_BUCKETS
        self._max = 0
        self._total = 0
        self._n = 0

    def observe(self, depth: int) -> None:
        d = int(depth)
        if d < 0:  # an mp.Queue qsize that raised degrades to -1
            return
        i = min(d.bit_length(), _DEPTH_BUCKETS - 1)
        with self._lock:
            self._counts[i] += 1
            self._n += 1
            self._total += d
            if d > self._max:
                self._max = d

    @property
    def count(self) -> int:
        return self._n

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            n, total, mx = self._n, self._total, self._max
        if not n:
            return {"count": 0}
        return {
            "count": n,
            "mean": round(total / n, 2),
            "max": mx,
            "buckets": {
                _depth_bucket_label(i): c
                for i, c in enumerate(counts) if c
            },
        }


class _NullCounter:
    __slots__ = ()

    def add(self, n: int = 1) -> None:
        pass

    value = 0


class _NullGauge:
    __slots__ = ()

    def set(self, v: float) -> None:
        pass

    value = 0.0


class _NullTiming:
    __slots__ = ()
    count = 0
    total_s = 0.0

    def observe(self, seconds: float) -> None:
        pass

    def time(self):
        return _NULL_CTX

    def snapshot(self) -> dict:
        return {"count": 0, "total_s": 0.0}


class _NullDepthHist:
    __slots__ = ()
    count = 0

    def observe(self, depth: int) -> None:
        pass

    def snapshot(self) -> dict:
        return {"count": 0}


_NULL_CTX = contextlib.nullcontext()
_NULL_COUNTER = _NullCounter()
_NULL_GAUGE = _NullGauge()
_NULL_TIMING = _NullTiming()
_NULL_DEPTH = _NullDepthHist()


class Telemetry:
    """Named-instrument registry shared across a run's stages.

    ``counter/gauge/timer`` create-or-return by dotted name (idempotent,
    thread-safe), so independent components — pipeline, prefetcher,
    trainer, bench — agree on instruments without passing them around.
    A disabled registry hands out shared no-op instruments and snapshots
    to ``{}``; callers never branch on ``enabled``.
    """

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._timers: Dict[str, Timing] = {}
        self._depths: Dict[str, DepthHist] = {}
        self._samples: Dict[str, Callable[[], float]] = {}

    def counter(self, name: str) -> Counter:
        if not self.enabled:
            return _NULL_COUNTER  # type: ignore[return-value]
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        if not self.enabled:
            return _NULL_GAUGE  # type: ignore[return-value]
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def timer(self, name: str) -> Timing:
        if not self.enabled:
            return _NULL_TIMING  # type: ignore[return-value]
        with self._lock:
            return self._timers.setdefault(name, Timing())

    def depth_hist(self, name: str) -> DepthHist:
        if not self.enabled:
            return _NULL_DEPTH  # type: ignore[return-value]
        with self._lock:
            return self._depths.setdefault(name, DepthHist())

    def reset(self) -> None:
        """Drop every instrument, sample, and accumulated value IN
        PLACE: references to the registry itself stay live (and future
        ``counter()``/``sample()`` calls re-create instruments), but
        previously handed-out instrument handles are orphaned.  The
        trainer resets at the top of each train() so a second run never
        reports run-1 + run-2 totals against run 2's wall clock."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            self._depths.clear()
            self._samples.clear()

    def sample(self, name: str, fn: Callable[[], float]) -> None:
        """Register (or replace) a snapshot-time sample — e.g. a queue's
        ``qsize``.  Evaluated lazily at :meth:`snapshot`; exceptions
        degrade to -1 (an mp.Queue's qsize can be unimplemented, and a
        sampled object may already be torn down)."""
        if not self.enabled:
            return
        with self._lock:
            self._samples[name] = fn

    def snapshot(self) -> dict:
        """One nested dict of everything: counters, gauges (stored values
        and live samples), timer histograms.  Safe to call from any
        thread at any time, including after the run's stages shut down."""
        if not self.enabled:
            return {}
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timers = dict(self._timers)
            depths = dict(self._depths)
            samples = dict(self._samples)
        out: dict = {
            "counters": {k: c.value for k, c in counters.items()},
            "gauges": {k: g.value for k, g in gauges.items()},
            "timers": {k: t.snapshot() for k, t in timers.items()},
            "depths": {k: d.snapshot() for k, d in depths.items()},
        }
        for name, fn in samples.items():
            try:
                out["gauges"][name] = fn()
            except Exception:  # pragma: no cover - torn-down sampled object
                out["gauges"][name] = -1
        return out


NULL = Telemetry(enabled=False)

_trace_annotation: Optional[Callable] = None
_trace_resolved = False


def trace_span(name: str):
    """``jax.profiler.TraceAnnotation(name)`` when jax is importable,
    else a null context manager.

    Makes xprof traces readable — stack/H2D/dispatch phases show up as
    named host spans — without making the data layer depend on jax (the
    spawned parse workers must never import it).  The annotation only
    resolves once jax is ALREADY imported by someone else: triggering a
    jax import from here would dial this machine's remote-TPU tunnel
    from jax-free tools (ingest_bench), and with no jax there is no
    trace to annotate anyway.  With no active trace an annotation is
    nearly free.
    """
    global _trace_annotation, _trace_resolved
    if not _trace_resolved:
        import sys as _sys

        if "jax" not in _sys.modules:
            return contextlib.nullcontext()
        _trace_resolved = True
        try:  # pragma: no cover - env-dependent
            import jax.profiler as _prof

            _trace_annotation = _prof.TraceAnnotation
        except Exception:
            _trace_annotation = None
    if _trace_annotation is None:
        return contextlib.nullcontext()
    return _trace_annotation(name)
