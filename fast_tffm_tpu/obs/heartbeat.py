"""Structured heartbeat: a run that self-reports its bottleneck.

:class:`JsonlWriter` is the ONE writer for the ``metrics_file`` stream —
the train loop's interval records, validation records, the run header,
heartbeats, and the final summary all go through it, serialized by a
lock (the heartbeat emitter runs on its own thread).

:class:`Heartbeat` wakes every ``interval_s``, asks the owner for a
record (a callable, so the trainer composes step/elapsed/telemetry
snapshot without this module knowing about jax or the loop), writes it
as one JSONL line, and logs a one-line human summary.  The builder runs
on the heartbeat thread: it must stay host-only (counters, gauges,
timers — never a device readback, which would force a sync mid-dispatch
and perturb the run it is measuring).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Optional

log = logging.getLogger(__name__)


def rank_suffix_path(path: str, rank: int) -> str:
    """The one spelling of per-rank JSONL/trace output paths: rank 0
    owns the configured path, ranks > 0 suffix ``.rank{N}``.  Every
    multi-host writer (metrics stream, trace file) routes through this
    so two ranks can never append into one stream and double-count a
    merged report (``tools/report.py`` groups the family back
    together by the suffix + each record's ``rank`` tag)."""
    if rank <= 0 or not path:
        return path
    return f"{path}.rank{rank}"


class JsonlWriter:
    """Lock-serialized line-per-record JSON writer (append mode)."""

    def __init__(self, path: str):
        self._f = open(path, "a")
        self._lock = threading.Lock()

    def write(self, record: dict) -> None:
        line = json.dumps(record)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class Heartbeat:
    """Periodic emitter thread.

    ``build`` returns the record dict for one beat (or None to skip —
    e.g. before the first dispatch there is nothing to report);
    ``writer`` is an optional :class:`JsonlWriter` (no metrics_file →
    log-only heartbeats).  ``close()`` stops the thread deterministically
    (event wakeup, no poll latency) and is idempotent; it does NOT emit
    a final beat — the owner writes its own final record with exact
    end-of-run values.
    """

    def __init__(
        self,
        interval_s: float,
        build: Callable[[], Optional[dict]],
        writer: Optional[JsonlWriter] = None,
    ):
        self._interval = interval_s
        self._build = build
        self._writer = writer
        self._write_warned = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-heartbeat", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.beat()

    def beat(self) -> None:
        """Emit one heartbeat now (also used by tests for determinism)."""
        try:
            record = self._build()
        except Exception as e:  # pragma: no cover - must never kill a run
            log.warning("heartbeat build failed: %s", e)
            return
        if record is None:
            return
        if self._writer is not None:
            try:
                self._writer.write(record)
            except Exception as e:
                # A full/unwritable metrics volume must not kill the
                # heartbeat thread — the log-line summary below is
                # exactly the channel that still works.  Warn once.
                if not self._write_warned:
                    self._write_warned = True
                    log.warning(
                        "heartbeat record write failed (%s: %s); "
                        "log-only heartbeats from here on",
                        type(e).__name__, e,
                    )
        log.info(
            "heartbeat step %s elapsed %.1fs ingest_wait_frac %.3f "
            "dispatch %.1fs wait %.1fs",
            record.get("step", "?"), record.get("elapsed", 0.0),
            record.get("ingest_wait_frac", 0.0),
            record.get("dispatch_s", 0.0), record.get("wait_input_s", 0.0),
        )

    def close(self) -> None:
        self._stop.set()
        self._thread.join()
