"""Model-quality & data-drift observability: the plane that watches the
MODEL, where everything else in obs/ watches the SYSTEM.

Three instruments built on the mergeable sketches of obs/sketch.py:

- :class:`StreamSketch` — the ingest-path accumulator.  Parse workers
  (thread and process) fold every parsed batch's feature values /
  example lengths / id occupancy into it; process workers ship
  serialized deltas back on their result messages (the same channel as
  parse timings) and the parent absorbs them here.  It keeps THREE
  views: a run-cumulative ``total`` (published into
  ``serve_manifest.json`` as the training→serving skew reference), and
  a rotating ``window``/``prev`` pair — PSI between the two adjacent
  windows is the run's own drift signal (``quality.psi_*``), a rolling
  baseline that needs no configuration and self-heals after a
  legitimate regime change (the new regime becomes the next baseline).

- :class:`QualityMonitor` — windowed online eval over the training
  stream's own scores+labels, consumed one-dispatch-delayed from the
  same async D2H discipline as ``HealthState`` (the dispatch loop hands
  it host arrays; it never touches a device).  A fixed ring of the most
  recent examples yields EXACT windowed logloss / AUC / calibration
  ratio (mean predicted vs. observed label rate — the canonical CTR
  health number), plus ``logloss_drift`` against a rolling baseline of
  previous windows (same shape as the alert plane's
  ``grad_norm_drift``).  ``block()`` builds the ``quality`` record
  block heartbeats / ``/status`` / the final record carry, memoized for
  a short interval so an aggressive scrape cadence cannot turn the
  window statistics into measurable overhead.

- :class:`ServeSkewMonitor` — the replica-side training→serving skew
  detector.  It holds the trainer-published reference sketches (from
  the manifest; refreshed on every hot swap) and a rotating live-window
  sketch of the actual request traffic + served scores, and reports
  PSI per axis plus quantile deltas as the ``skew_*`` keys of the serve
  block (``tffm_serve_skew_*`` on ``/metrics``; the router's fleet
  scrape max-merges them so one scrape sees the fleet's worst skew).

Suggested reading of the PSI numbers (the industry-standard bands):
< 0.1 stable, 0.1–0.25 drifting (warn), > 0.25 shifted (page).

numpy-only, jax-free, like sketch.py — every consumer is a host-side
thread (parse workers, the heartbeat builder, the serve dispatcher).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from fast_tffm_tpu.obs.sketch import SketchSet

__all__ = ["QualityMonitor", "ServeSkewMonitor", "StreamSketch"]

# Rolling-baseline shape for logloss_drift: mirror the alert plane's
# grad_norm_drift (obs/alerts.py BASELINE_WINDOW/BASELINE_MIN).
_BASELINE_WINDOW = 16
_BASELINE_MIN = 3
# Below this much mass a PSI between two windows is noise, not signal.
# FmConfig refuses quality_window below this value (pinned equal by
# tests/test_quality.py) so the drift signals can't be silently
# disabled by a too-small window.
_MIN_PSI_EXAMPLES = 32
# block() memo: /status can be scraped every 200 ms (the bench does);
# the window statistics only need to refresh at human cadence.
_BLOCK_MEMO_S = 0.5


class StreamSketch:
    """Thread-safe windowed + cumulative SketchSet accumulator."""

    def __init__(self, window_examples: int = 65536):
        if window_examples < 1:
            raise ValueError(
                f"window_examples must be >= 1, got {window_examples}"
            )
        self.window_examples = int(window_examples)
        self._lock = threading.Lock()
        self.total = SketchSet()
        self.window = SketchSet()
        # The two most recent COMPLETED windows: psi() prefers the
        # live window vs prev, but right after a rotation the live
        # window is near-empty — prev vs prev2 keeps the drift signal
        # defined at every instant instead of flapping to absent.
        self.prev: Optional[SketchSet] = None
        self.prev2: Optional[SketchSet] = None
        self.rotations = 0

    def _maybe_rotate_locked(self) -> None:
        if self.window.examples >= self.window_examples:
            self.prev2 = self.prev
            self.prev = self.window
            self.window = SketchSet()
            self.rotations += 1

    def update_batch(self, ids, vals, weights=None) -> None:
        """One parsed batch's features (thread-worker path)."""
        with self._lock:
            self.total.update_batch(ids, vals, weights)
            self.window.update_batch(ids, vals, weights)
            self._maybe_rotate_locked()

    def update_scores(self, scores) -> None:
        with self._lock:
            self.total.update_scores(scores)
            self.window.update_scores(scores)

    def absorb(self, delta: dict) -> None:
        """Merge a serialized SketchSet DELTA a process worker shipped
        (workers reset their local sketch at each ship, so absorbing
        every delta exactly once reconstructs the stream).  One
        deserialization feeds both views — merge() never mutates its
        argument."""
        sk = SketchSet.from_dict(delta)
        with self._lock:
            self.total.merge(sk)
            self.window.merge(sk)
            self._maybe_rotate_locked()

    def psi(self) -> dict:
        """Adjacent-window drift: the current window vs the previous
        one, falling back to the two previous COMPLETED windows while
        the current one is still filling ({} until two windows with
        enough mass exist)."""
        with self._lock:
            if self.prev is None or \
                    self.prev.examples < _MIN_PSI_EXAMPLES:
                return {}
            if self.window.examples >= _MIN_PSI_EXAMPLES:
                return self.window.psi_vs(self.prev)
            if self.prev2 is not None and \
                    self.prev2.examples >= _MIN_PSI_EXAMPLES:
                return self.prev.psi_vs(self.prev2)
            return {}

    def export(self) -> Optional[dict]:
        """Serialized cumulative sketches (the manifest payload), or
        None when nothing has been observed yet."""
        with self._lock:
            if self.total.examples == 0 and self.total.scores.n == 0:
                return None
            return self.total.to_dict()

    @property
    def examples(self) -> int:
        return self.total.examples


def window_logloss(scores, labels, weights) -> float:
    """Exact weighted logloss over probability scores."""
    p = np.clip(scores, 1e-7, 1 - 1e-7)
    ll = -(labels * np.log(p) + (1 - labels) * np.log(1 - p))
    return float(np.sum(ll * weights) / max(np.sum(weights), 1e-12))


def window_mse(scores, labels, weights) -> float:
    d = scores - labels
    return float(np.sum(d * d * weights) / max(np.sum(weights), 1e-12))


def window_auc(scores, labels, weights) -> Optional[float]:
    """Exact weighted ROC AUC via average ranks (ties handled); None
    when the window is single-class."""
    pos = weights * (labels > 0)
    neg = weights * (labels <= 0)
    wp, wn = float(pos.sum()), float(neg.sum())
    if wp <= 0 or wn <= 0:
        return None
    order = np.argsort(scores, kind="stable")
    s = scores[order]
    w = weights[order]
    # Weighted midranks: an element's rank is the total weight strictly
    # below its tie group plus half the group's weight.  Then the
    # Mann-Whitney identity AUC = (Σ_pos w·midrank − wp²/2) / (wp·wn)
    # is EXACT with ties — the parity target for the windowed test.
    cw = np.cumsum(w)
    below = cw - w
    is_new = np.empty(len(s), bool)
    is_new[0] = True
    is_new[1:] = s[1:] != s[:-1]
    group = np.cumsum(is_new) - 1
    n_groups = int(group[-1]) + 1
    # Sorted order makes each group's first element carry its minimal
    # "weight below" — that IS the group's strictly-below weight.
    g_start = np.full(n_groups, np.inf)
    np.minimum.at(g_start, group, below)
    g_w = np.zeros(n_groups)
    np.add.at(g_w, group, w)
    midrank = g_start[group] + g_w[group] / 2.0
    pos_rank_sum = float(np.sum(midrank * pos[order]))
    return float((pos_rank_sum - wp * wp / 2.0) / (wp * wn))


class QualityMonitor:
    """Windowed online eval + the ``quality`` record block."""

    def __init__(self, loss_type: str = "logistic",
                 window: int = 65536,
                 sketch: Optional[StreamSketch] = None):
        self.loss_type = loss_type
        self.window = int(max(1, window))
        self.sketch = sketch
        self._lock = threading.Lock()
        self._scores = np.zeros(self.window, np.float64)
        self._labels = np.zeros(self.window, np.float64)
        self._weights = np.zeros(self.window, np.float64)
        self._idx = 0
        self._seen = 0  # examples observed (cumulative)
        self._hist: deque = deque(maxlen=_BASELINE_WINDOW)
        self._hist_marked = 0  # examples count at last baseline append
        self._memo: Optional[dict] = None
        self._memo_t = 0.0

    # -- dispatch-loop side --------------------------------------------

    def observe(self, scores, labels, weights) -> None:
        """One consumed dispatch's host arrays (any shape; flattened).
        ``scores`` are raw model outputs — logistic models are squashed
        to probabilities here so the window, the score sketch, and the
        serving path all live in the same space."""
        s = np.asarray(scores, np.float64).reshape(-1)
        y = np.asarray(labels, np.float64).reshape(-1)
        w = np.asarray(weights, np.float64).reshape(-1)
        real = w > 0
        if not real.any():
            return
        s, y, w = s[real], y[real], w[real]
        if self.loss_type == "logistic":
            s = 1.0 / (1.0 + np.exp(-s))
        if self.sketch is not None:
            self.sketch.update_scores(s)
        with self._lock:
            n = len(s)
            if n >= self.window:
                self._scores[:] = s[-self.window:]
                self._labels[:] = y[-self.window:]
                self._weights[:] = w[-self.window:]
                self._idx = 0
            else:
                i = self._idx
                end = min(i + n, self.window)
                first = end - i
                self._scores[i:end] = s[:first]
                self._labels[i:end] = y[:first]
                self._weights[i:end] = w[:first]
                if first < n:
                    rest = n - first
                    self._scores[:rest] = s[first:]
                    self._labels[:rest] = y[first:]
                    self._weights[:rest] = w[first:]
                self._idx = (i + n) % self.window
            self._seen += n
            # The block memo is deliberately NOT invalidated here: it
            # is purely TTL'd (_BLOCK_MEMO_S).  A hot training loop
            # observes every dispatch, and recomputing the window
            # statistics per dispatch — instead of per heartbeat-ish
            # interval — was a measured 2x e2e overhead at small
            # batches.  A block is at most the TTL stale.

    # -- record-builder side -------------------------------------------

    def _window_arrays(self):
        n = min(self._seen, self.window)
        return (self._scores[:n], self._labels[:n], self._weights[:n])

    def block(self, now: Optional[float] = None,
              force: bool = False) -> dict:
        """The ``quality`` record block (flat, numeric, host-only).
        Memoized for ``_BLOCK_MEMO_S`` so a hot dispatch loop + scrape
        storms don't pay the window sort repeatedly.  ``force=True``
        (the FINAL record) bypasses the memo: end-of-run values must
        be exact, not up-to-TTL stale — a sub-second run's final block
        once reported its first heartbeat's counts."""
        now = time.time() if now is None else now
        with self._lock:
            if not force and self._memo is not None and \
                    now - self._memo_t < _BLOCK_MEMO_S:
                return dict(self._memo)
            out: dict = {"examples": int(self._seen)}
            s, y, w = self._window_arrays()
            if len(s):
                out["window_examples"] = int(len(s))
                loss = (window_mse(s, y, w)
                        if self.loss_type == "mse"
                        else window_logloss(s, y, w))
                out["logloss"] = round(loss, 6)
                auc = window_auc(s, y, w)
                if auc is not None:
                    out["auc"] = round(auc, 6)
                wsum = max(float(w.sum()), 1e-12)
                label_rate = float(np.sum(y * w) / wsum)
                mean_pred = float(np.sum(s * w) / wsum)
                out["score_mean"] = round(mean_pred, 6)
                out["label_rate"] = round(label_rate, 6)
                if label_rate > 0:
                    # mean predicted / observed rate: 1.0 = calibrated,
                    # the two-sided signal ("both" in report --compare).
                    out["calib_ratio"] = round(
                        mean_pred / label_rate, 6
                    )
                # Rolling logloss baseline: one sample per FRESH window
                # of examples (not per block() call — scrape cadence
                # must not dilute the baseline).
                if self._seen - self._hist_marked >= self.window:
                    self._hist.append(loss)
                    self._hist_marked = self._seen
                if len(self._hist) >= _BASELINE_MIN:
                    base = sum(self._hist) / len(self._hist)
                    if base > 0:
                        out["logloss_drift"] = round(loss / base, 6)
            if self.sketch is not None:
                out.update(self.sketch.psi())
                out["sketch_examples"] = int(self.sketch.examples)
            self._memo = dict(out)
            self._memo_t = now
            return out


class ServeSkewMonitor:
    """Training→serving skew: live request traffic vs the trainer's
    manifest-published reference sketches."""

    def __init__(self, window_examples: int = 65536, telemetry=None,
                 read_reference=None):
        """``read_reference`` is a zero-arg callable returning the
        manifest's ``quality`` payload dict (or None) — kept as a
        callable so this module stays import-light (no train/ import;
        the server passes a lambda over train.manifest.read_manifest).
        """
        self.window_examples = int(max(1, window_examples))
        self._read_reference = read_reference
        self._lock = threading.Lock()
        self._ref: Optional[SketchSet] = None
        self._ref_step = -1
        self._ref_stash = (None, -1)  # pre-reload reference (rollback)
        self.live = SketchSet()
        self._prev: Optional[SketchSet] = None
        self._memo: Optional[dict] = None
        self._memo_t = 0.0
        # Registered gauges (check_obs-pinned): the fleet-scrape /
        # alert-friendly summary series next to the full skew_* block.
        tel = telemetry
        self._g_psi_max = (
            tel.gauge("serve.skew_psi_max") if tel is not None else None
        )
        self._g_examples = (
            tel.gauge("serve.skew_examples") if tel is not None else None
        )

    # -- reference lifecycle -------------------------------------------

    def reload_reference(self) -> bool:
        """(Re)read the manifest's quality payload — called at startup
        and after every hot swap, so the reference always matches the
        checkpoint being served.  Returns True when a reference is
        loaded.

        A readable manifest WITHOUT a quality payload (a --no_quality
        retrain, an in-place checkpoint conversion) CLEARS the current
        reference: the served model changed and the old sketches no
        longer describe it — judging new traffic (and the new model's
        scores) against them would manufacture phantom skew.  Absence
        means no reference, never a stale one (the SERVING.md
        contract).  Only a TORN read (exception mid-swap) keeps the
        current reference and retries later."""
        if self._read_reference is None:
            return False
        try:
            doc = self._read_reference()
        except Exception:  # noqa: BLE001 - a torn manifest read
            return False
        ref, step = None, -1
        if isinstance(doc, dict) and "sketches" in doc:
            try:
                ref = SketchSet.from_dict(doc["sketches"])
                step = int(doc.get("step", -1))
            except Exception:  # noqa: BLE001 - foreign/corrupt payload
                ref, step = None, -1
        with self._lock:
            # Stash the outgoing reference so a canary /rollback can
            # restore it (the pre-canary manifest is gone from disk).
            self._ref_stash = (self._ref, self._ref_step)
            self._ref = ref
            self._ref_step = step
            self._memo = None
        return ref is not None

    def restore_previous_reference(self) -> None:
        """Undo the last :meth:`reload_reference` — the canary
        /rollback path: the served params just reverted to the
        pre-canary checkpoint, whose manifest no longer exists on
        disk, so the reference reverts from the stash instead (or to
        no-reference when there is none — honest absence either
        way)."""
        with self._lock:
            self._ref, self._ref_step = getattr(
                self, "_ref_stash", (None, -1)
            )
            self._memo = None

    def set_reference(self, sketches: SketchSet, step: int = -1) -> None:
        """Direct injection (tests, embedders)."""
        with self._lock:
            self._ref = sketches
            self._ref_step = int(step)
            self._memo = None

    # -- request path (serve dispatcher thread) ------------------------

    def observe_batch(self, ids, vals) -> None:
        with self._lock:
            self.live.update_batch(ids, vals)
            if self.live.examples >= self.window_examples:
                self._prev = self.live
                self.live = SketchSet()
                # A completed window is one of the two events worth
                # breaking the TTL memo for: a whole new traffic wave
                # just became judgeable (per-request invalidation
                # would re-pay the PSI on every dispatch — the
                # measured-2x hazard the TTL exists to prevent).
                self._memo = None
            elif (
                self._memo is not None
                and "skew_psi_max" not in self._memo
                and self.live.examples >= _MIN_PSI_EXAMPLES
            ):
                # ...the other: the live window just crossed the
                # minimum judgeable mass while the memo still says
                # "nothing to compare" — the first real psi must not
                # hide behind a pre-threshold snapshot.
                self._memo = None

    def observe_scores(self, scores) -> None:
        with self._lock:
            self.live.update_scores(np.asarray(scores, np.float64))

    # -- record-builder side -------------------------------------------

    def _recent_locked(self) -> SketchSet:
        """The live window judged against the reference: the current
        window plus (when present) the previous one, so a freshly
        rotated window never momentarily blinds the detector."""
        recent = self.live.copy()
        if self._prev is not None:
            recent.merge(self._prev.copy())
        return recent

    def block(self, now: Optional[float] = None,
              force: bool = False) -> dict:
        """``skew_*`` keys for the serve record block.  Without a
        reference (pre-quality manifest) only ``skew_ref_step = -1``
        is reported — absence of the psi keys IS the signal that no
        comparison is possible, never a lying 0.  ``force=True`` (the
        final record) bypasses the TTL memo — same exactness contract
        as QualityMonitor.block."""
        now = time.time() if now is None else now
        with self._lock:
            if not force and self._memo is not None and \
                    now - self._memo_t < _BLOCK_MEMO_S:
                return dict(self._memo)
            out: dict = {"skew_ref_step": self._ref_step}
            recent = self._recent_locked()
            out["skew_examples"] = int(recent.examples)
            if self._ref is not None and (
                recent.examples >= _MIN_PSI_EXAMPLES
                or recent.scores.n >= _MIN_PSI_EXAMPLES
            ):
                psi = recent.psi_vs(self._ref)
                out.update({f"skew_{k}": v for k, v in psi.items()})
                for axis, keys in (
                    ("scores", ("p50", "p99")),
                    ("values", ("p50",)),
                    ("lengths", ("p50",)),
                ):
                    ref_q = getattr(self._ref, axis)
                    live_q = getattr(recent, axis)
                    if ref_q.n == 0 or live_q.n == 0:
                        continue
                    for key in keys:
                        q = int(key[1:]) / 100.0
                        rv, lv = ref_q.quantile(q), live_q.quantile(q)
                        if rv is not None and lv is not None:
                            out[f"skew_{axis}_{key}_delta"] = round(
                                lv - rv, 6
                            )
            if self._g_psi_max is not None:
                self._g_psi_max.set(out.get("skew_psi_max", 0.0))
                self._g_examples.set(out["skew_examples"])
            self._memo = dict(out)
            self._memo_t = now
            return out
