"""Incident flight recorder ("blackbox"): fixed-memory evidence rings
plus alert-triggered forensic bundles.

Every long-running process (trainer rank, serve replica, router) keeps
a :class:`Blackbox`: three bounded rings — the last N heartbeat/status-
shaped records, the last M ``record: alert`` entries, and (via a
callable) the trace-buffer tail — costing a few hundred KB regardless
of run length.  Nothing is written to disk until an *incident* fires:

- an ``alert_rules`` breach (warn or halt) via ``Blackbox.on_alert``
  wired into ``AlertEngine(on_alert=...)``;
- a crash-truthful final (``NonFiniteGradError`` / ``AlertHaltError`` /
  any unhandled exception) — the host's teardown path calls
  ``incident("crash_<ExcType>")``;
- a manual ``POST /incident?reason=...`` admin route on any status/
  serve/router endpoint.

An incident dumps an ``incidents/<ts>_<reason>[_<suffix>]/`` bundle:

====================  ==================================================
``manifest.json``     the ``record: incident`` manifest (reason, time,
                      counts, which artifacts landed)
``records.jsonl``     the heartbeat/status ring, oldest first
``alerts.jsonl``      the alert ring
``trace_tail.json``   Chrome-trace events from the tracer tail
``threadz.txt``       all-thread stack dump (``/debug/threadz`` style)
``run_header.json``   run header / config fingerprint
``metrics.prom``      rendered ``/metrics`` snapshot at dump time
``requests.capture``  (serving) last K sampled request/response frames
                      in the TFC1 capture format (see serve/wire.py)
====================  ==================================================

Dump failures degrade per-artifact (a broken metrics renderer still
yields the rings) and NEVER propagate into the host process — the
recorder observes crashes, it must not cause them.  ``suffix`` keeps
concurrent dumpers (ranks, replicas, the router) collision-free;
same-second same-reason dumps from ONE process retry with a ``-2``/
``-3`` ordinal.  Stdlib-only, same as the rest of obs/.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import threading
import time

log = logging.getLogger("fast_tffm.obs")

__all__ = ["Blackbox", "NULL_BLACKBOX"]

_REASON_RE = re.compile(r"[^A-Za-z0-9_.-]+")


def _sanitize_reason(reason: str) -> str:
    """Filesystem-safe incident reason: collapse anything outside
    ``[A-Za-z0-9_.-]`` to ``_``, cap the length, never empty."""
    out = _REASON_RE.sub("_", str(reason)).strip("._-")
    return (out or "incident")[:64]


class Blackbox:
    """Fixed-memory flight recorder + incident bundle dumper.

    Parameters
    ----------
    incident_dir:
        Root directory bundles land under (created lazily on the first
        incident — an incident-free run leaves no trace on disk).
    suffix:
        Per-process discriminator appended to every bundle dir name
        (``rank0``, ``pid4242``, ``router``) so concurrent processes
        sharing one ``incident_dir`` never collide.
    records / alerts / trace_tail:
        Ring capacities.  Memory is bounded by these regardless of run
        length (pinned by test).
    run_header:
        Dict snapshot written as ``run_header.json`` (config
        fingerprint, build info).
    metrics_render / trace_tail_fn / capture_tail_fn:
        Optional callables evaluated AT DUMP TIME: a Prometheus text
        renderer, ``Tracer.tail``-shaped event source, and a
        ``CaptureWriter.tail_bytes``-shaped raw capture source.
    writer:
        Optional JsonlWriter — the incident manifest is also appended
        to the metrics stream so bundles are discoverable from JSONL
        alone.
    telemetry:
        Optional registry; bumps the ``obs.incidents`` counter per
        bundle dumped.
    max_bundles:
        Hard cap on bundles this process may dump (an alert flapping
        every heartbeat must not fill the disk).
    """

    def __init__(
        self,
        incident_dir: str,
        *,
        suffix: str = "",
        records: int = 64,
        alerts: int = 32,
        trace_tail: int = 256,
        run_header: dict | None = None,
        metrics_render=None,
        trace_tail_fn=None,
        capture_tail_fn=None,
        writer=None,
        telemetry=None,
        max_bundles: int = 16,
        enabled: bool = True,
        clock=time.time,
    ):
        self.enabled = enabled
        self.incident_dir = incident_dir
        self.suffix = suffix
        self._records = collections.deque(maxlen=max(1, records))
        self._alerts = collections.deque(maxlen=max(1, alerts))
        self._trace_tail_n = max(0, trace_tail)
        self._run_header = dict(run_header) if run_header else {}
        self._metrics_render = metrics_render
        self._trace_tail_fn = trace_tail_fn
        self._capture_tail_fn = capture_tail_fn
        self._writer = writer
        self._max_bundles = max_bundles
        self._clock = clock
        self._lock = threading.Lock()
        self.dumped = 0
        self._c_incidents = None
        if telemetry is not None:
            self._c_incidents = telemetry.counter("obs.incidents")

    # ------------------------------------------------------------------
    # Ring feeds (hot path: one lock + one deque append, no allocation
    # beyond the reference — records are shared, not copied).

    def observe_record(self, rec) -> None:
        if not self.enabled or not isinstance(rec, dict):
            return
        with self._lock:
            self._records.append(rec)

    def observe_alert(self, alert) -> None:
        if not self.enabled or not isinstance(alert, dict):
            return
        with self._lock:
            self._alerts.append(alert)

    def on_alert(self, alert) -> None:
        """``AlertEngine(on_alert=...)`` hook: ring the alert, then
        dump a bundle named after the breached rule."""
        if not self.enabled:
            return
        self.observe_alert(alert)
        rule = alert.get("rule", "rule") if isinstance(alert, dict) else "rule"
        self.incident(f"alert_{rule}")

    # ------------------------------------------------------------------
    # Incident dump

    def incident(self, reason: str, extra: dict | None = None):
        """Dump a forensic bundle; returns the bundle dir, or ``None``
        when disabled / bundle-capped / the dump itself failed."""
        if not self.enabled:
            return None
        try:
            return self._dump(reason, extra)
        except Exception as e:  # never let forensics kill the host
            log.warning("blackbox: incident dump failed: %s", e)
            return None

    def _dump(self, reason: str, extra: dict | None):
        with self._lock:
            if self.dumped >= self._max_bundles:
                log.warning(
                    "blackbox: bundle cap (%d) reached, dropping "
                    "incident %r", self._max_bundles, reason,
                )
                return None
            records = list(self._records)
            alerts = list(self._alerts)
            self.dumped += 1
        now = self._clock()
        clean = _sanitize_reason(reason)
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(now))
        base = f"{stamp}_{clean}" + (f"_{self.suffix}" if self.suffix else "")
        out = self._make_dir(base)
        if out is None:
            return None

        files = {}

        def _artifact(name, fn):
            try:
                fn(os.path.join(out, name))
                files[name] = True
            except Exception as e:
                log.warning("blackbox: %s failed: %s", name, e)
                files[name] = False

        def _jsonl(path, rows):
            with open(path, "w", encoding="utf-8") as f:
                for row in rows:
                    f.write(json.dumps(row, default=str) + "\n")

        _artifact("records.jsonl", lambda p: _jsonl(p, records))
        _artifact("alerts.jsonl", lambda p: _jsonl(p, alerts))
        _artifact("threadz.txt", self._write_threadz)
        if self._run_header:
            _artifact(
                "run_header.json",
                lambda p: _jsonl(p, [self._run_header]),
            )
        if self._trace_tail_fn is not None:
            _artifact("trace_tail.json", self._write_trace_tail)
        if self._metrics_render is not None:
            _artifact("metrics.prom", self._write_metrics)
        if self._capture_tail_fn is not None:
            _artifact("requests.capture", self._write_capture)

        manifest = {
            "record": "incident",
            "time": now,
            "reason": clean,
            "suffix": self.suffix,
            "incident_dir": out,
            "records": len(records),
            "alerts": len(alerts),
            "files": files,
        }
        if extra:
            manifest.update(extra)
        with open(
            os.path.join(out, "manifest.json"), "w", encoding="utf-8"
        ) as f:
            json.dump(manifest, f, indent=2, default=str)
            f.write("\n")
        if self._writer is not None:
            try:
                self._writer.write(manifest)
            except Exception:
                pass
        if self._c_incidents is not None:
            self._c_incidents.add()
        log.warning("blackbox: incident %r dumped to %s", clean, out)
        return out

    def _make_dir(self, base: str):
        """Create the bundle dir; ordinal-retry same-name collisions
        (two same-second incidents from this process)."""
        for ordinal in range(1, 10):
            name = base if ordinal == 1 else f"{base}-{ordinal}"
            path = os.path.join(self.incident_dir, name)
            try:
                os.makedirs(path, exist_ok=False)
                return path
            except FileExistsError:
                continue
        log.warning("blackbox: could not allocate bundle dir for %r", base)
        return None

    def _write_threadz(self, path: str) -> None:
        # Lazy sibling import: blackbox must stay importable whatever
        # order obs/__init__ wires the plane up in.
        from fast_tffm_tpu.obs.status import thread_dump

        with open(path, "w", encoding="utf-8") as f:
            f.write(thread_dump())

    def _write_trace_tail(self, path: str) -> None:
        events = self._trace_tail_fn(self._trace_tail_n) or []
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"traceEvents": events}, f, default=str)
            f.write("\n")

    def _write_metrics(self, path: str) -> None:
        text = self._metrics_render() or ""
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)

    def _write_capture(self, path: str) -> None:
        data = self._capture_tail_fn() or b""
        with open(path, "wb") as f:
            f.write(data)


#: Shared disabled instance — every observe/incident is a cheap no-op,
#: mirroring ``NULL_TRACER`` / ``obs.NULL``.
NULL_BLACKBOX = Blackbox("", enabled=False)
