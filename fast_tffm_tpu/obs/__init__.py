"""Observability layer: telemetry, heartbeat, tracing, and the live
observability plane (status endpoint + alert watchdog).

``obs.Telemetry`` is the shared instrument registry (counters, gauges,
ring-buffer timings) every pipeline stage writes into; ``obs.NULL`` is
the always-safe disabled registry; ``obs.trace_span`` names host phases
in xprof traces; ``obs.Heartbeat``/``obs.JsonlWriter`` turn a running
train into a self-reporting JSONL stream; ``obs.Tracer`` /
``obs.NULL_TRACER`` record Chrome-trace (Perfetto-loadable) spans from
every stage, correlated per batch/super-batch (trace.py), with windowed
rotation for multi-hour runs; ``obs.StatusServer`` serves ``/metrics``
(Prometheus) + ``/status`` (heartbeat JSON) live from a running
process (status.py); ``obs.AlertEngine`` evaluates declarative alert
rules against the heartbeat stream (alerts.py); ``obs.CompileSentinel``
/ ``obs.read_rss`` are the resource plane (resource.py) — component
memory ledgers, process RSS, and train-step compile accounting.  See
telemetry.py for the shared design constraints (thread-safety,
near-zero hot-path overhead, no jax or numpy imports).
"""

from fast_tffm_tpu.obs.alerts import (
    AlertEngine, AlertHaltError, AlertRule, halt_error,
    parse_rules, run_until_halt,
)
from fast_tffm_tpu.obs.blackbox import NULL_BLACKBOX, Blackbox
from fast_tffm_tpu.obs.fleet import (
    MergeSpec, TrainFleet, labeled_lines, merge_blocks,
)
from fast_tffm_tpu.obs.heartbeat import (
    Heartbeat, JsonlWriter, rank_suffix_path,
)
from fast_tffm_tpu.obs.quality import (
    QualityMonitor, ServeSkewMonitor, StreamSketch,
)
from fast_tffm_tpu.obs.resource import (
    CompileSentinel, basic_block, read_open_fds, read_rss,
)
from fast_tffm_tpu.obs.sketch import FreqSketch, QuantileSketch, SketchSet
from fast_tffm_tpu.obs.status import StatusServer, render_prometheus
from fast_tffm_tpu.obs.telemetry import (
    NULL, Counter, DepthHist, Gauge, Telemetry, Timing, trace_span,
)
from fast_tffm_tpu.obs.trace import NULL_TRACER, Tracer

__all__ = [
    "Counter", "Gauge", "Timing", "DepthHist", "Telemetry", "NULL",
    "trace_span", "Heartbeat", "JsonlWriter", "rank_suffix_path",
    "Tracer", "NULL_TRACER",
    "MergeSpec", "TrainFleet", "labeled_lines", "merge_blocks",
    "StatusServer", "render_prometheus",
    "AlertEngine", "AlertHaltError", "AlertRule", "halt_error",
    "parse_rules", "run_until_halt",
    "CompileSentinel", "read_rss", "read_open_fds", "basic_block",
    "Blackbox", "NULL_BLACKBOX",
    "FreqSketch", "QuantileSketch", "SketchSet",
    "QualityMonitor", "ServeSkewMonitor", "StreamSketch",
]
