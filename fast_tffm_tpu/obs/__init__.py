"""Observability layer: telemetry, heartbeat, and causal batch tracing.

``obs.Telemetry`` is the shared instrument registry (counters, gauges,
ring-buffer timings) every pipeline stage writes into; ``obs.NULL`` is
the always-safe disabled registry; ``obs.trace_span`` names host phases
in xprof traces; ``obs.Heartbeat``/``obs.JsonlWriter`` turn a running
train into a self-reporting JSONL stream; ``obs.Tracer`` /
``obs.NULL_TRACER`` record Chrome-trace (Perfetto-loadable) spans from
every stage, correlated per batch/super-batch (trace.py).  See
telemetry.py for the shared design constraints (thread-safety,
near-zero hot-path overhead, no jax or numpy imports).
"""

from fast_tffm_tpu.obs.heartbeat import Heartbeat, JsonlWriter
from fast_tffm_tpu.obs.telemetry import (
    NULL, Counter, DepthHist, Gauge, Telemetry, Timing, trace_span,
)
from fast_tffm_tpu.obs.trace import NULL_TRACER, Tracer

__all__ = [
    "Counter", "Gauge", "Timing", "DepthHist", "Telemetry", "NULL",
    "trace_span", "Heartbeat", "JsonlWriter", "Tracer", "NULL_TRACER",
]
