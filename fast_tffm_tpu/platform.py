"""Platform pinning for machines with a remote-TPU PJRT tunnel.

On this project's dev/driver machines a global sitecustomize registers an
'axon' PJRT plugin in every python process and sets
``jax_platforms="axon,cpu"`` via jax.config — which OVERRIDES the
``JAX_PLATFORMS`` env var — and initializing that backend dials a remote
TPU and can block for minutes.  Anything that must stay on CPU (tests,
virtual-device dry runs, bench fallback) calls :func:`pin_cpu` BEFORE the
first jax backend touch.
"""

from __future__ import annotations

import logging
import os
import re

log = logging.getLogger(__name__)

_FLAG = "xla_force_host_platform_device_count"

# Platform names that mean "a real TPU runs the Mosaic kernels".  The
# remote tunnel's PJRT plugin registers as 'axon' but serves a TPU; gating
# on the literal "tpu" alone would silently leave Pallas kernels in
# interpret mode (orders of magnitude slower) on the tunnel.
_TPU_PLATFORMS = frozenset({"tpu", "axon"})


def is_tpu_backend() -> bool:
    """True when the default jax backend executes on a TPU (directly or via
    the tunnel plugin).  Used to gate Pallas-vs-interpret and the
    tile-vs-scatter sparse apply choice."""
    import jax

    if jax.default_backend() in _TPU_PLATFORMS:
        return True
    try:
        return jax.devices()[0].platform in _TPU_PLATFORMS
    except RuntimeError:  # no backend at all
        return False


def use_interpret() -> bool:
    """Pallas kernels run in interpret mode off-TPU (correctness tool; far
    slower than compiled Mosaic).  The ONE gate all kernel call sites
    share."""
    return not is_tpu_backend()


def pin_cpu(n_devices: int | None = None) -> None:
    """Force the CPU platform, optionally with ``n_devices`` virtual CPUs.

    Must run before jax initializes a backend: the XLA flag is read at CPU
    client creation, and a backend cached from an earlier init cannot be
    replaced.  An existing ``--xla_force_host_platform_device_count`` flag
    with a different value is REPLACED (a stale count would make
    multi-device dry runs assert on device count).
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--{_FLAG}={n_devices}"
        if _FLAG in flags:
            flags = re.sub(rf"--{_FLAG}=\d+", want, flags)
        else:
            flags = (flags + " " + want).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
