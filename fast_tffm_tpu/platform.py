"""Platform pinning for machines with a remote-TPU PJRT tunnel.

On this project's dev/driver machines a global sitecustomize registers an
'axon' PJRT plugin in every python process and sets
``jax_platforms="axon,cpu"`` via jax.config — which OVERRIDES the
``JAX_PLATFORMS`` env var — and initializing that backend dials a remote
TPU and can block for minutes.  Anything that must stay on CPU (tests,
virtual-device dry runs, bench fallback) calls :func:`pin_cpu` BEFORE the
first jax backend touch.
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def pin_cpu(n_devices: int | None = None) -> None:
    """Force the CPU platform, optionally with ``n_devices`` virtual CPUs.

    Must run before jax initializes a backend: the XLA flag is read at CPU
    client creation, and a backend cached from an earlier init cannot be
    replaced.  An existing ``--xla_force_host_platform_device_count`` flag
    with a different value is REPLACED (a stale count would make
    multi-device dry runs assert on device count).
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--{_FLAG}={n_devices}"
        if _FLAG in flags:
            flags = re.sub(rf"--{_FLAG}=\d+", want, flags)
        else:
            flags = (flags + " " + want).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
