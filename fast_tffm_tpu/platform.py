"""Platform pinning for machines with a remote-TPU PJRT tunnel.

On this project's dev/driver machines a global sitecustomize registers an
'axon' PJRT plugin in every python process and sets
``jax_platforms="axon,cpu"`` via jax.config — which OVERRIDES the
``JAX_PLATFORMS`` env var — and initializing that backend dials a remote
TPU and can block for minutes.  Anything that must stay on CPU (tests,
virtual-device dry runs, bench fallback) calls :func:`pin_cpu` BEFORE the
first jax backend touch.
"""

from __future__ import annotations

import contextlib
import logging
import os
import re

log = logging.getLogger(__name__)

_FLAG = "xla_force_host_platform_device_count"

# Platform names that mean "a real TPU runs the Mosaic kernels".  The
# remote tunnel's PJRT plugin registers as 'axon' but serves a TPU; gating
# on the literal "tpu" alone would silently leave Pallas kernels in
# interpret mode (orders of magnitude slower) on the tunnel.
_TPU_PLATFORMS = frozenset({"tpu", "axon"})


def is_tpu_backend() -> bool:
    """True when the default jax backend executes on a TPU (directly or via
    the tunnel plugin).  Used to gate Pallas-vs-interpret and the
    tile-vs-scatter sparse apply choice."""
    import jax

    if jax.default_backend() in _TPU_PLATFORMS:
        return True
    try:
        return jax.devices()[0].platform in _TPU_PLATFORMS
    except RuntimeError:  # no backend at all
        return False


_force_compiled = False


def use_interpret() -> bool:
    """Pallas kernels run in interpret mode off-TPU (correctness tool; far
    slower than compiled Mosaic).  The ONE gate all kernel call sites
    share."""
    if _force_compiled:
        return False
    return not is_tpu_backend()


@contextlib.contextmanager
def force_compiled():
    """Trace Pallas calls as compiled (Mosaic) even off-TPU.

    Exists for cross-platform LOWERING tests: Mosaic's jaxpr->MLIR pass
    runs at jax lowering time, so ``jax.export(..., platforms=['tpu'])``
    under this context surfaces "Unimplemented primitive in Pallas TPU
    lowering" errors on a CPU-only machine — the exact failure class that
    interpret-mode tests structurally cannot catch (it zeroed the round-3
    hardware bench).  Never use it to *execute* kernels off-TPU.
    """
    global _force_compiled
    prev = _force_compiled
    _force_compiled = True
    try:
        yield
    finally:
        _force_compiled = prev


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across jax versions — the ONE compat gate.

    Newer jax exposes ``jax.shard_map(..., check_vma=...)``; older
    releases only have ``jax.experimental.shard_map.shard_map`` whose
    equivalent knob is ``check_rep``.  All shard_map call sites in this
    package route through here so a version bump is a one-line change.
    """
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


_rng_pinned = False


def ensure_sharding_invariant_rng() -> None:
    """Make jax.random draws invariant to the output sharding.

    jax 0.4.37 defaults ``jax_threefry_partitionable=False``; under that
    mode GSPMD partitions the threefry counter computation of a jitted
    draw in a value-CHANGING way on mixed meshes — a ``(data=4, model=2)``
    mesh initialized a different table than the 1x1 reference while the
    pure-axis 8x1/1x8 meshes agreed (tools/parity_probe.py localized the
    `[4-2]` red to INIT, before any step).  Partitionable threefry is
    sharding-invariant by construction (and upstream's forward default),
    so a table initialized under ANY mesh — including an unjitted host
    draw for the tiered cold store — is element-wise identical.

    The partitionable stream differs from the legacy one, so fresh inits
    change values once per upgrade; checkpoints store values, not keys,
    and are unaffected.  Called at ``models.fm`` import (the module that
    defines ``init_params``), so every init path inherits it.
    """
    global _rng_pinned
    if _rng_pinned:
        return
    import jax

    jax.config.update("jax_threefry_partitionable", True)
    _rng_pinned = True


def ffm_compute_dtype(compute_dtype):
    """The dtype FFM's einsum operands may use on the current target.

    XLA:CPU's DotThunk cannot EXECUTE bf16 x bf16 -> f32 dots (runtime
    UNIMPLEMENTED; inside a shard_map the aborting device strands the
    others at the next collective).  The TPU MXU runs them natively, so
    bf16 passes through on a TPU backend — and under
    :func:`force_compiled` (cross-platform lowering FOR TPU on a CPU
    host), where falling back would make lowering tests silently
    validate the f32 program instead of the advertised bf16 one.

    The ONE copy of this gate; fm.ffm_scores_from_rows and the shardmap
    FFM step both call it.
    """
    import jax.numpy as jnp

    if compute_dtype == jnp.bfloat16 and not (
        _force_compiled or is_tpu_backend()
    ):
        return jnp.float32
    return compute_dtype


# ------------------------------------------------- persistent compile cache
#
# jax's on-disk compilation cache, behind the ``compile_cache_dir``
# knob: a restart (or a replica spawn on the serve fleet) replays its
# warmup compiles from disk instead of re-lowering through XLA — the
# multi-second ladder warmup becomes a file read.  The monitoring
# listener counts hit/miss events so the zero-fresh-lowers contract of
# a warm spawn is checkable (tests + the serve log line), not assumed.

_compile_cache_dir: str | None = None
_compile_cache_events = {"hits": 0, "misses": 0}
_compile_cache_listener_installed = False


def enable_compile_cache(path: str) -> bool:
    """Point jax's persistent compilation cache at ``path`` (created if
    missing) and start counting cache hit/miss events.  Idempotent; a
    falsy path is a no-op (returns False).  The min-size/min-time
    floors are dropped so EVERY executable persists — this project's
    rung/step compiles are small but warmup-critical."""
    global _compile_cache_dir, _compile_cache_listener_installed
    if not path:
        return False
    import jax

    if _compile_cache_dir != path:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        # jax initializes its cache object AT MOST ONCE per process and
        # latches the dir it saw then — a process that compiled anything
        # before this call (tests, the bench probe) would silently keep
        # running cache-less.  Reset so the next compile re-initializes
        # against the new dir.
        try:
            from jax._src import compilation_cache as _jcc

            _jcc.reset_cache()
        except Exception as e:  # pragma: no cover - private-API drift
            log.warning("compilation-cache reset unavailable (%s); "
                        "mid-process enable may not take effect", e)
        _compile_cache_dir = path
        log.info("persistent compilation cache enabled at %s", path)
    if not _compile_cache_listener_installed:
        def _listener(event, **kw):  # noqa: ANN001 - jax callback API
            if event == "/jax/compilation_cache/cache_hits":
                _compile_cache_events["hits"] += 1
            elif event == "/jax/compilation_cache/cache_misses":
                _compile_cache_events["misses"] += 1

        try:
            jax.monitoring.register_event_listener(_listener)
            _compile_cache_listener_installed = True
        except Exception as e:  # pragma: no cover - jax API drift
            log.warning(
                "compile-cache event listener unavailable (%s); "
                "hit/miss stats will read 0", e,
            )
    return True


def disable_compile_cache() -> None:
    """Turn the persistent cache back off (tests restore global state;
    the event listener stays — it only counts)."""
    global _compile_cache_dir
    if _compile_cache_dir is None:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    try:
        from jax._src import compilation_cache as _jcc

        _jcc.reset_cache()  # drop the latched cache object (see enable)
    except Exception:  # pragma: no cover - private-API drift
        pass
    _compile_cache_dir = None


def compile_cache_stats() -> dict:
    """{'dir', 'hits', 'misses'} — cumulative persistent-cache events
    since the listener was installed.  A warm replica spawn with a
    populated cache performs zero fresh lowers: its warmup adds hits,
    never misses."""
    return {
        "dir": _compile_cache_dir or "",
        "hits": _compile_cache_events["hits"],
        "misses": _compile_cache_events["misses"],
    }


def pin_cpu(n_devices: int | None = None) -> None:
    """Force the CPU platform, optionally with ``n_devices`` virtual CPUs.

    Must run before jax initializes a backend: the XLA flag is read at CPU
    client creation, and a backend cached from an earlier init cannot be
    replaced.  An existing ``--xla_force_host_platform_device_count`` flag
    with a different value is REPLACED (a stale count would make
    multi-device dry runs assert on device count).
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--{_FLAG}={n_devices}"
        if _FLAG in flags:
            flags = re.sub(rf"--{_FLAG}=\d+", want, flags)
        else:
            flags = (flags + " " + want).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
