"""FM interaction op with custom VJP — dispatches jnp oracle or Pallas.

``fm_interaction(rows, vals)`` computes per-example FM scores (without w0)
from gathered table rows, differentiable w.r.t. ``rows`` only (feature
values are data, not parameters).  The backward pass uses the closed-form
FmGrad (SURVEY.md §3.4) instead of autodiff through the sum-square trick —
one fused kernel instead of XLA's unfused chain, and the basis for the
sparse row-update training path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from fast_tffm_tpu.ops import fm_pallas


from fast_tffm_tpu.platform import use_interpret as _use_interpret


def _scores_jnp(rows, vals):
    # Upcast once: in bf16-input mode only the STORED rows/vals are
    # rounded — accumulation and the returned scores/s1 stay f32, matching
    # the Pallas kernels' contract.  The same astype is the in-register
    # widening of a bf16-STORED serving table (ops.quant): the gather
    # reads compact rows, this cast fuses into it, and everything
    # downstream is f32 either way.
    rows = rows.astype(jnp.float32)
    vals = vals.astype(jnp.float32)
    w = rows[..., 0]
    v = rows[..., 1:]
    xv = v * vals[..., None]
    s1 = jnp.sum(xv, axis=1)
    s2 = jnp.sum(xv * xv, axis=1)
    linear = jnp.sum(w * vals, axis=-1)
    return linear + 0.5 * jnp.sum(s1 * s1 - s2, axis=-1), s1


def _grads_jnp(rows, vals, s1, g):
    in_dtype = rows.dtype
    rows = rows.astype(jnp.float32)
    vals = vals.astype(jnp.float32)
    v = rows[..., 1:]
    gx = (g[:, None] * vals)[..., None]  # [B, F, 1]
    dv = gx * (s1[:, None, :] - v * vals[..., None])
    # Cotangent dtype must match the primal's (bf16 in bf16 mode).
    return jnp.concatenate([gx, dv], axis=-1).astype(in_dtype)


# Flat-layout pure-XLA variant: the Pallas kernels' [B, F*D] one-hot-
# matmul math, but left to XLA to fuse (no pallas_call).  The [B, F, D]
# elementwise layout above runs the VPU at D/128 lane utilization; here
# the hot elementwise chain is [B, F*D] (~91% at F=39, D=9) and the
# per-feature reductions ride the MXU.  Broadcasts that the kernel
# builds with R/Mt selection matmuls become repeat/tile (XLA fuses them
# for free); only the feature-sum keeps a one-hot matmul, because the
# reshape back to [B, F, D] it would otherwise need is a real relayout
# on TPU.
def _m_matrix(fd, d, dtype):
    """M[c, c % d] = 1: sums row slot j across features on the MXU."""
    cm = jax.lax.broadcasted_iota(jnp.int32, (fd, d), 0)
    j = jax.lax.broadcasted_iota(jnp.int32, (fd, d), 1)
    return (cm % d == j).astype(dtype)


_HI = jax.lax.Precision.HIGHEST  # keep ~f32 exactness on the MXU


def _scores_flat(rows, vals):
    b, f, d = rows.shape
    rows2 = rows.reshape(b, f * d).astype(jnp.float32)
    vals = vals.astype(jnp.float32)
    xe = jnp.repeat(vals, d, axis=1)  # xe[b, f*d+j] = x_f
    y = rows2 * xe
    m = _m_matrix(f * d, d, jnp.float32)
    s = jax.lax.dot(y, m, precision=_HI,
                    preferred_element_type=jnp.float32)
    s2 = jax.lax.dot(y * y, m, precision=_HI,
                     preferred_element_type=jnp.float32)
    s1 = s[:, 1:]
    inter = 0.5 * jnp.sum(s1 * s1 - s2[:, 1:], axis=-1)
    return s[:, 0] + inter, s1


def _grads_flat(rows, vals, s1, g):
    in_dtype = rows.dtype
    b, f, d = rows.shape
    rows2 = rows.reshape(b, f * d).astype(jnp.float32)
    vals = vals.astype(jnp.float32)
    xe = jnp.repeat(vals, d, axis=1)
    y = rows2 * xe
    ones = jnp.ones((b, 1), jnp.float32)
    # s1e[b, f*d+j] = (1 if j == 0 else s1[b, j-1]): tile, not a matmul.
    s1e = jnp.tile(jnp.concatenate([ones, s1], axis=1), (1, f))
    c = jax.lax.broadcasted_iota(jnp.int32, (1, f * d), 1)
    maskv = (c % d != 0).astype(jnp.float32)  # kill the w column in y
    drows2 = (g[:, None] * xe) * (s1e - y * maskv)
    return drows2.reshape(b, f, d).astype(in_dtype)


def _impl_name(impl) -> str:
    """Normalize the static dispatch arg: bools are the legacy surface."""
    if impl is True:
        return "pallas"
    if impl is False:
        return "jnp"
    if impl in ("pallas", "jnp", "flat"):
        return impl
    raise ValueError(f"unknown interaction impl {impl!r}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fm_interaction(rows, vals, use_pallas=True):
    scores, _ = _forward(rows, vals, use_pallas)
    return scores


def fm_interaction_sharded(rows, vals, use_pallas, mesh, data_axis: str):
    """Mesh-aware wrapper: Mosaic kernels cannot be auto-partitioned by
    GSPMD, so on a multi-device mesh the pallas path must run under
    shard_map with the batch dimension sharded on the data axis (rows/vals
    are replicated across the model axis — the gather already happened)."""
    impl = _impl_name(use_pallas)
    if impl != "pallas":  # jnp/flat are plain XLA: GSPMD partitions them
        return fm_interaction(rows, vals, impl)
    if mesh is None or mesh.size == 1:
        return fm_interaction(rows, vals, impl)
    from jax.sharding import PartitionSpec as P

    from fast_tffm_tpu.platform import shard_map

    # check_vma=False: pallas_call out_shapes don't carry vma annotations.
    return shard_map(
        lambda r, v: fm_interaction(r, v, "pallas"),
        mesh=mesh,
        in_specs=(P(data_axis, None, None), P(data_axis, None)),
        out_specs=P(data_axis),
        check_vma=False,
    )(rows, vals)


def _forward(rows, vals, impl):
    impl = _impl_name(impl)
    if impl == "pallas":
        return fm_pallas.fm_scores_pallas(rows, vals,
                                          interpret=_use_interpret())
    if impl == "flat":
        return _scores_flat(rows, vals)
    return _scores_jnp(rows, vals)


def _fwd(rows, vals, impl):
    scores, s1 = _forward(rows, vals, impl)
    return scores, (rows, vals, s1)


def _bwd(impl, res, g):
    rows, vals, s1 = res
    impl = _impl_name(impl)
    if impl == "pallas":
        drows = fm_pallas.fm_grad_pallas(rows, vals, s1, g,
                                         interpret=_use_interpret())
    elif impl == "flat":
        drows = _grads_flat(rows, vals, s1, g)
    else:
        drows = _grads_jnp(rows, vals, s1, g)
    return drows, None  # no gradient w.r.t. vals


fm_interaction.defvjp(_fwd, _bwd)


# ---------------------------------------------------- field-aware FM (FFM)
#
# Closed-form forward + backward for the field-grouped FFM interaction —
# the single-device analogue of train.shardmap_step's inversion algebra
# (reference FmScorer/FmGrad roles for BASELINE config 5).  Autodiff
# through the einsum chain in models.fm.ffm_scores_from_rows re-derives
# cotangents for every intermediate (oh*vals, S, v_own, ...); the closed
# form reuses the saved field-grouped sums S and computes
#
#     dv_i^q = g x_i (S[q, f_i] - [q = f_i] v_i^{f_i} x_i),  dw_i = g x_i
#
# with one gather-by-field einsum.  Parity with the autodiff oracle is
# test-enforced (tests/test_ffm_op.py).


def _ffm_parts(rows, vals, fields, factor_num, field_num, compute_dtype):
    """Shared forward math: (linear, s, self_term).

    Mirrors models.fm.ffm_scores_from_rows operand-for-operand —
    including which products see the bf16-ROUNDED operands — so the two
    forwards agree to accumulation order in every compute_dtype.
    """
    from fast_tffm_tpu.platform import ffm_compute_dtype

    cd = ffm_compute_dtype(compute_dtype)  # f32 off-TPU: CPU can't bf16-dot
    rows = rows.astype(cd)
    vals_c = vals.astype(cd)
    b, f = vals.shape
    w = rows[..., 0]
    v = rows[..., 1:].reshape(b, f, field_num, factor_num)
    linear = jnp.sum(w * vals_c, axis=-1, dtype=jnp.float32)
    oh = (
        fields[..., None] == jnp.arange(field_num, dtype=fields.dtype)
    ).astype(cd)  # [B, F, P]
    s = jnp.einsum(
        "bfp,bfqk->bpqk", oh * vals_c[..., None], v,
        preferred_element_type=jnp.float32,
    )  # [B, P, P, k] field-grouped sums, f32
    v_own = jnp.einsum(
        "bfq,bfqk->bfk", oh, v, preferred_element_type=jnp.float32
    )  # v_i^{f_i}
    # The rounded vals square here must match the rounded diagonal of
    # `cross` or the i = j cancellation leaves a bf16-eps residual.
    self_term = jnp.sum(
        jnp.sum(v_own * v_own, axis=-1) * (vals_c * vals_c),
        axis=-1, dtype=jnp.float32,
    )
    return linear, s, self_term


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ffm_interaction(rows, vals, fields, factor_num, field_num,
                    compute_dtype=jnp.float32):
    """Per-example FFM interaction scores (without w0), differentiable
    w.r.t. ``rows`` only.  Same numeric contract as
    models.fm.ffm_scores_from_rows minus the w0 term: bf16 mode rounds
    the operands, accumulation and scores stay f32."""
    linear, s, self_term = _ffm_parts(
        rows, vals, fields, factor_num, field_num, compute_dtype
    )
    cross = jnp.einsum("bpqk,bqpk->b", s, s)
    return linear + 0.5 * (cross - self_term)


def _ffm_fwd(rows, vals, fields, factor_num, field_num, compute_dtype):
    linear, s, self_term = _ffm_parts(
        rows, vals, fields, factor_num, field_num, compute_dtype
    )
    cross = jnp.einsum("bpqk,bqpk->b", s, s)
    # Residuals: save only the inputs + S (what autodiff would keep
    # anyway); oh/v_own are cheap one-hot recomputes in the backward.
    return linear + 0.5 * (cross - self_term), (rows, vals, fields, s)


def _ffm_bwd(factor_num, field_num, compute_dtype, res, g):
    from fast_tffm_tpu.platform import ffm_compute_dtype

    rows, vals, fields, s = res
    b, f = vals.shape
    # Same operand rounding as the forward/autodiff: products see the
    # cd-rounded rows/vals, accumulation stays f32.
    cd = ffm_compute_dtype(compute_dtype)
    v = rows[..., 1:].astype(cd).reshape(b, f, field_num, factor_num)
    vals32 = vals.astype(cd).astype(jnp.float32)
    oh = (
        fields[..., None] == jnp.arange(field_num, dtype=fields.dtype)
    ).astype(cd)
    v_own = jnp.einsum(
        "bfq,bfqk->bfk", oh, v, preferred_element_type=jnp.float32
    )
    oh32 = oh.astype(jnp.float32)
    gx = g[:, None] * vals32  # [B, F]
    # T[b,f,q,:] = S[b, q, f_i, :]: gather S's second field axis by each
    # occurrence's own field, as a one-hot matmul.
    t = jnp.einsum("bqpk,bfp->bfqk", s, oh32)
    dv = gx[..., None, None] * (
        t - oh32[..., None] * v_own[:, :, None, :] * vals32[..., None, None]
    )  # [B, F, P, k]
    drows = jnp.concatenate(
        [gx[..., None], dv.reshape(b, f, field_num * factor_num)], axis=-1
    ).astype(rows.dtype)
    return drows, None, None  # no gradients w.r.t. vals/fields


ffm_interaction.defvjp(_ffm_fwd, _ffm_bwd)
