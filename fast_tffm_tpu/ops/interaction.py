"""FM interaction op with custom VJP — dispatches jnp oracle or Pallas.

``fm_interaction(rows, vals)`` computes per-example FM scores (without w0)
from gathered table rows, differentiable w.r.t. ``rows`` only (feature
values are data, not parameters).  The backward pass uses the closed-form
FmGrad (SURVEY.md §3.4) instead of autodiff through the sum-square trick —
one fused kernel instead of XLA's unfused chain, and the basis for the
sparse row-update training path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from fast_tffm_tpu.ops import fm_pallas


from fast_tffm_tpu.platform import use_interpret as _use_interpret


def _scores_jnp(rows, vals):
    # Upcast once: in bf16-input mode only the STORED rows/vals are
    # rounded — accumulation and the returned scores/s1 stay f32, matching
    # the Pallas kernels' contract.
    rows = rows.astype(jnp.float32)
    vals = vals.astype(jnp.float32)
    w = rows[..., 0]
    v = rows[..., 1:]
    xv = v * vals[..., None]
    s1 = jnp.sum(xv, axis=1)
    s2 = jnp.sum(xv * xv, axis=1)
    linear = jnp.sum(w * vals, axis=-1)
    return linear + 0.5 * jnp.sum(s1 * s1 - s2, axis=-1), s1


def _grads_jnp(rows, vals, s1, g):
    in_dtype = rows.dtype
    rows = rows.astype(jnp.float32)
    vals = vals.astype(jnp.float32)
    v = rows[..., 1:]
    gx = (g[:, None] * vals)[..., None]  # [B, F, 1]
    dv = gx * (s1[:, None, :] - v * vals[..., None])
    # Cotangent dtype must match the primal's (bf16 in bf16 mode).
    return jnp.concatenate([gx, dv], axis=-1).astype(in_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fm_interaction(rows, vals, use_pallas: bool = True):
    scores, _ = _forward(rows, vals, use_pallas)
    return scores


def fm_interaction_sharded(rows, vals, use_pallas, mesh, data_axis: str):
    """Mesh-aware wrapper: Mosaic kernels cannot be auto-partitioned by
    GSPMD, so on a multi-device mesh the pallas path must run under
    shard_map with the batch dimension sharded on the data axis (rows/vals
    are replicated across the model axis — the gather already happened)."""
    if not use_pallas:
        return fm_interaction(rows, vals, False)
    if mesh is None or mesh.size == 1:
        return fm_interaction(rows, vals, use_pallas)
    from jax.sharding import PartitionSpec as P

    # check_vma=False: pallas_call out_shapes don't carry vma annotations.
    return jax.shard_map(
        lambda r, v: fm_interaction(r, v, use_pallas),
        mesh=mesh,
        in_specs=(P(data_axis, None, None), P(data_axis, None)),
        out_specs=P(data_axis),
        check_vma=False,
    )(rows, vals)


def _forward(rows, vals, use_pallas):
    if use_pallas:
        return fm_pallas.fm_scores_pallas(rows, vals,
                                          interpret=_use_interpret())
    return _scores_jnp(rows, vals)


def _fwd(rows, vals, use_pallas):
    scores, s1 = _forward(rows, vals, use_pallas)
    return scores, (rows, vals, s1)


def _bwd(use_pallas, res, g):
    rows, vals, s1 = res
    if use_pallas:
        drows = fm_pallas.fm_grad_pallas(rows, vals, s1, g,
                                         interpret=_use_interpret())
    else:
        drows = _grads_jnp(rows, vals, s1, g)
    return drows, None  # no gradient w.r.t. vals


fm_interaction.defvjp(_fwd, _bwd)
