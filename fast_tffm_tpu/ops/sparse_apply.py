"""Tile-scan sparse optimizer apply — Pallas TPU replacement for row scatter.

The reference applies sparse updates with TF's SparseApplyAdagrad/-Ftrl over
``IndexedSlices`` (SURVEY.md §2 #8, §3.2): per step it updates only the rows
the batch touched.  The direct XLA translation (``table.at[ids].add``) is
correct but slow on TPU: a scatter of N≈640k rows costs ~73ms on v5e — the
scatter unit processes rows serially — and sparse Adagrad needs *three* such
passes (acc scatter-add, acc re-gather, table scatter).

This module replaces all of it with a sort + two Pallas kernels, turning the
random-access scatter into sequential streams and MXU matmuls:

1. XLA prep: sort occurrence ids (carrying a permutation), mark segment
   starts, prefix-sum to get each occurrence's *unique-row position* (upos).
2. ``K1`` (dedup): grid over chunks of C sorted occurrences.  A one-hot
   [C, C] matmul segment-sums each chunk's payload ``(g, g², lrow·last)``
   per unique id; a VMEM carry accumulates segments that span chunk
   boundaries (hot features can span many chunks); each chunk DMAs its
   window of unique rows to HBM at dynamic offset upos_start — last writer
   per row holds the complete sum.
3. ``K2`` (apply): grid over table tiles of R rows.  Streams the table (and
   optimizer-state tables) tile by tile, DMAs in the ≤R unique entries that
   land in the tile (a tile of R rows can hold at most R unique ids — the
   bound that makes the window exact), places them with a one-hot [R, R]
   matmul, and applies the optimizer formula on the whole tile in VPU.

Per step this costs one pass over the table (streaming) plus the MXU
placement matmuls, independent of duplicate structure — measured 2.3x
faster than the XLA scatter path on real v5e at Criteo shapes (V=2^22,
B=16k, F=39; TPU_RESULTS.md) and exact to ~1e-6 relative (one-hot
matmuls run as two-pass bf16 hi/lo splits, keeping ~f32 precision).

Semantics match train.sparse exactly: per-occurrence g² accumulation,
shared post-update denominator for duplicates (Adagrad), single -sigma*w
correction per row (FTRL).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Block sizes, overridable via env for hardware tuning (the grid-overhead
# vs MXU-work tradeoff is a chip property; tools/tpu_validate.py
# --sweep-blocks measures it).  Only CHUNK and TILE must themselves be
# multiples of 8 (sublanes).  GROUP is a plain loop trip count;
# K1_GROUP does scale a tiled dimension ([CHUNK*K1_GROUP, lanes] payload
# blocks — see its comment below) but needs no own multiple because
# CHUNK keeps the product sublane-aligned.  TILE additionally gates
# supports_tile's vocab-divisibility check.
def _env_block(name: str, default: int, multiple: int = 8) -> int:
    raw = os.environ.get(name, str(default))
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None
    if val <= 0 or val % multiple:
        kind = (
            f"a positive multiple of {multiple} (sublanes)"
            if multiple > 1 else "positive"
        )
        raise ValueError(f"{name}={val} must be {kind}")
    return val


CHUNK = _env_block("FAST_TFFM_K1_CHUNK", 512)
TILE = _env_block("FAST_TFFM_K2_TILE", 256)
# Subtiles processed per K2/K-place grid step.  On real v5e the first
# hardware sweep showed per-grid-step overhead (~2-3us: DMA latency not
# overlapped, step bookkeeping) dominating the apply at V/TILE = 16k
# steps; grouping G subtiles per step with double-buffered window DMAs
# divides that overhead by G while keeping the placement matmul at the
# MXU-optimal [TILE, TILE] shape.  Any positive count works (it is a
# loop trip count, not a tiled dimension); VMEM for the table blocks
# grows linearly with it.
GROUP = _env_block("FAST_TFFM_K2_GROUP", 8, multiple=1)
# Chunks per K1 grid step.  Same grid-overhead motivation, but K1's
# grouping IS a tiled dimension (the payload input block becomes
# [CHUNK*K1_GROUP, lanes], so pipelined VMEM grows with it), and its
# output DMA pipelines differently (one in-flight copy, ordered: see
# _k1_kernel) — hence a knob independent of the K2 one.
K1_GROUP = _env_block("FAST_TFFM_K1_GROUP", 8, multiple=1)


def ftrl_solve(z, n, lr, l1, l2, beta):
    """FTRL-proximal closed form — the ONE copy all paths share.

    Used by the scatter path (train.sparse), the K2 tile kernel, and the
    sharded elementwise update; tile/scatter parity tests assume these stay
    bit-identical.
    """
    denom = (beta + jnp.sqrt(n)) / lr + l2
    return jnp.where(
        jnp.abs(z) <= l1, jnp.zeros_like(z), -(z - jnp.sign(z) * l1) / denom
    )


# Dense-delta optimizer updates: (sum g, sum g^2, *state tables) -> new
# tables.  The ONE elementwise copy shared by the shard_map wrappers below
# and train.shardmap_step (the K2 kernels fuse the same formulas in-kernel,
# via the shared ftrl_solve).
def adagrad_update(g1, g2, table, acc, *, lr, eps):
    acc_new = acc + g2
    return table - lr * g1 * jax.lax.rsqrt(acc_new + eps), acc_new


def ftrl_update(g1, g2, table, z, n, *, lr, l1, l2, beta):
    n_new = n + g2
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g1 - sigma * table
    return ftrl_solve(z_new, n_new, lr, l1, l2, beta), z_new, n_new


def sgd_update(g1, g2, table, *, lr):
    del g2
    return (table - lr * g1,)


from fast_tffm_tpu.platform import use_interpret as _use_interpret


def supports_tile(vocab: int, optimizer: str) -> bool:
    return vocab % TILE == 0 and vocab >= TILE and optimizer in (
        "adagrad", "ftrl", "sgd",
    )


# ---------------------------------------------------------------- K1: dedup


def _k1_kernel(starts_ref, firsts_ref, ends_ref, payload_ref, upos_ref,
               out_ref, u_vmem, carry_ref, sem, *, chunk, group, lanes):
    t = pl.program_id(0)
    prev_cp = None  # the single in-flight output copy
    for j in range(group):  # unrolled: all slices static
        cj = t * group + j  # global chunk index (scalar arrays use it)
        upos_s = starts_ref[cj]
        rows = pl.ds(j * chunk, chunk)
        payload = payload_ref[rows, :]  # [C, L] f32
        # [1, C] local segment index, in [0, C)
        l = upos_ref[0:1, pl.ds(j * chunk, chunk)] - upos_s
        # onehotT[s, i] = (l[i] == s): segment s on sublanes, occurrence
        # i on lanes — built directly in the orientation the matmul
        # wants.
        s_iota = jax.lax.broadcasted_iota(jnp.int32, (chunk, chunk), 0)
        oh = (
            jnp.broadcast_to(l, (chunk, chunk)) == s_iota
        ).astype(jnp.bfloat16)
        # Segment-sum on the MXU.  f32 payload exactness via bf16 hi/lo
        # split: hi rounds to bf16, lo carries the residual; both
        # accumulate in f32.
        p_hi = payload.astype(jnp.bfloat16)
        p_lo = (payload - p_hi.astype(jnp.float32)).astype(jnp.bfloat16)
        u_local = (
            jax.lax.dot(oh, p_hi, preferred_element_type=jnp.float32)
            + jax.lax.dot(oh, p_lo, preferred_element_type=jnp.float32)
        )  # [C, L]
        # Segment spanning in from the previous chunk: add its partial
        # sums to row 0 via an iota mask — `.at[0:1].add` would emit a
        # scatter-add HLO, which Mosaic has no TPU lowering for (it
        # aborted the round-3 bench).
        continues = (firsts_ref[cj] == 0) & (cj > 0)
        row0 = jax.lax.broadcasted_iota(jnp.int32, (chunk, lanes), 0) == 0
        u_local = u_local + jnp.where(
            row0 & continues,
            jnp.broadcast_to(carry_ref[0:1, :], (chunk, lanes)),
            0.0,
        )
        # Segment spanning out into the next chunk: move it to the carry
        # and write a zero — the chunk holding the segment's last
        # occurrence is the last writer of that row and will hold the
        # complete sum.  Row l_last is selected with an iota mask:
        # value-level dynamic_slice / dynamic_update_slice have no
        # Mosaic lowering either (same class as the scatter-add above).
        l_last = ends_ref[cj] - upos_s
        cont_next = firsts_ref[cj + 1] == 0
        r_iota = jax.lax.broadcasted_iota(jnp.int32, (chunk, lanes), 0)
        is_last = r_iota == l_last
        last_row = jnp.sum(
            jnp.where(is_last, u_local, 0.0), axis=0, keepdims=True
        )  # [1, lanes] == u_local[l_last]
        carry_ref[...] = jnp.broadcast_to(
            jnp.where(cont_next, last_row, 0.0), (8, lanes)
        )
        # If the segment continues, zero its row here; otherwise leave it
        # (writing last_row back to its own row would be a no-op).
        u_local = jnp.where(is_last & cont_next, 0.0, u_local)
        # Output windows of consecutive chunks OVERLAP whenever a chunk
        # holds duplicates (upos advances by its unique count < chunk),
        # and correctness rests on the later chunk's rows landing last —
        # so at most ONE copy may be in flight.  Waiting for chunk j-1's
        # copy only HERE (after this chunk's matmul) still hides the DMA
        # behind the compute; the single buffer is safe to overwrite
        # because nothing is in flight after the wait.
        if prev_cp is not None:
            prev_cp.wait()
        u_vmem[...] = u_local
        prev_cp = pltpu.make_async_copy(
            u_vmem, out_ref.at[pl.ds(upos_s, chunk)], sem
        )
        prev_cp.start()
    # Drain before returning: the next grid step (or pallas epilogue)
    # must not race the final window's write.
    prev_cp.wait()


def _k1_dedup(payload, upos, starts, firsts, ends, n_out):
    n, lanes = payload.shape
    chunk = CHUNK
    group = _group_for(n // chunk, K1_GROUP)
    block = chunk * group
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((block, lanes), lambda j, *_: (j, 0)),
            pl.BlockSpec((1, block), lambda j, *_: (0, j)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((chunk, lanes), jnp.float32),
            pltpu.VMEM((8, lanes), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _k1_kernel, chunk=chunk, group=group, lanes=lanes
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_out, lanes), jnp.float32),
        interpret=_use_interpret(),
    )(starts, firsts, ends, payload, upos.reshape(1, n))


# ---------------------------------------------------------------- K2: apply


def _placed_sums(u, cnt, d, tile):
    """Window entries -> dense per-row sums [R, D] x2 via one-hot matmul."""
    e_iota = jax.lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
    mask = e_iota < cnt  # [R, 1] valid-entry mask
    # The window tail belongs to later tiles (or is uninitialized); zero it
    # with where() — a multiply would keep NaN garbage (NaN*0 == NaN).
    u = jnp.where(mask, u, 0.0)  # [R, L]
    # Tile-local row as int32 for the iota compare: tpu.iota is
    # integer-only (a f32 iota fails Mosaic verification).  The f32 value
    # is exact for any TILE < 2^24 (f32 integers are exact below that),
    # so the cast is too.
    lrow = u[:, 2 * d:2 * d + 1].astype(jnp.int32)  # [R, 1] tile-local row
    r_iota = jax.lax.broadcasted_iota(jnp.int32, (tile, tile), 1)
    p = ((lrow == r_iota) & mask).astype(jnp.bfloat16)  # [entry, row]
    u_hi = u.astype(jnp.bfloat16)
    u_lo = (u - u_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    dn = (((0,), (0,)), ((), ()))  # contract the entry dim of both
    dense = (
        jax.lax.dot_general(p, u_hi, dn, preferred_element_type=jnp.float32)
        + jax.lax.dot_general(p, u_lo, dn, preferred_element_type=jnp.float32)
    )  # [row, L]
    return dense[:, :d], dense[:, d:2 * d]  # sum(g), sum(g^2) per row


def _group_for(n_tiles: int, want: int | None = None) -> int:
    """Largest group <= want (default GROUP) dividing the tile count."""
    group = max(1, min(GROUP if want is None else want, n_tiles))
    while n_tiles % group:
        group -= 1
    return group


def _window_loop_raw(ts_ref, u_hbm_ref, u_vmem, sem, *, tile, group, body,
                     base=None):
    """Double-buffered entry-window loop — the ONE copy of the
    slot/semaphore rotation protocol (layout-prototype kernels in
    tools/micro_probe.py reuse it too; keep it that way).

    Walks ``group`` subtiles, DMA-ing each one's entry window while the
    previous subtile's compute runs (subtile j+1's copy is in flight
    during subtile j's compute), and calls ``body(j, u_window, cnt)``.
    ``base`` is the first subtile's global index (defaults to the grid
    position; the compact K2 variant passes the remapped group index).
    """
    if base is None:
        base = pl.program_id(0) * group

    def window(j, slot):
        start = ts_ref[base + j]
        return pltpu.make_async_copy(
            u_hbm_ref.at[pl.ds(start, tile)], u_vmem.at[slot], sem.at[slot]
        )

    window(0, 0).start()
    for j in range(group):  # unrolled: all slices static
        slot = j % 2
        if j + 1 < group:
            window(j + 1, (j + 1) % 2).start()
        window(j, slot).wait()
        cnt = ts_ref[base + j + 1] - ts_ref[base + j]
        body(j, u_vmem[slot], cnt)


def _window_loop(ts_ref, u_hbm_ref, u_vmem, sem, *, tile, group, d, body,
                 base=None):
    """_window_loop_raw + the standard [R, R] one-hot placement:
    ``body(j, g1, g2)`` receives the placed per-row sums."""

    def raw_body(j, u, cnt):
        g1, g2 = _placed_sums(u, cnt, d, tile)
        body(j, g1, g2)

    _window_loop_raw(
        ts_ref, u_hbm_ref, u_vmem, sem, tile=tile, group=group,
        body=raw_body, base=base,
    )


def _k2_group_kernel(ts_ref, *args, n_tables, tile, group, d, update):
    """Generic K2 body: a group of subtiles per grid step.

    ``update(g1, g2, *table_slices) -> new_table_slices`` is one of the
    shared elementwise optimizer formulas (adagrad_update/...).
    """
    _k2_body(ts_ref, None, args, n_tables, tile, group, d, update)


def _k2_group_kernel_compact(ts_ref, cg_ref, *args, n_tables, tile, group,
                             d, update):
    """Compact K2 body: grid step t works on group ``cg_ref[t]`` instead
    of group t — the BlockSpec index_maps use the same remapping, so the
    table blocks arriving in VMEM match the entry windows."""
    _k2_body(
        ts_ref, cg_ref[pl.program_id(0)] * group, args, n_tables, tile,
        group, d, update,
    )


def _k2_body(ts_ref, base, args, n_tables, tile, group, d, update):
    ins = args[:n_tables]
    u_hbm_ref = args[n_tables]
    outs = args[n_tables + 1:2 * n_tables + 1]
    u_vmem, sem = args[2 * n_tables + 1:]

    def body(j, g1, g2):
        rows = pl.ds(j * tile, tile)
        new = update(g1, g2, *(r[rows, :] for r in ins))
        for out_ref, val in zip(outs, new):
            out_ref[rows, :] = val

    _window_loop(
        ts_ref, u_hbm_ref, u_vmem, sem, tile=tile, group=group, d=d,
        body=body, base=base,
    )


def _compact_auto(n_entries: int, n_groups: int) -> bool:
    """Auto-engage compact K2 only when the entry count bounds touched
    groups to <= half the table's groups — streaming the whole table is
    faster when most blocks are touched anyway (no remap indirection,
    denser pipelining).  FAST_TFFM_K2_COMPACT=0/1 overrides the
    heuristic (hardware sweeps A/B it on chip)."""
    override = os.environ.get("FAST_TFFM_K2_COMPACT")
    if override in ("0", "1"):
        return override == "1"
    return 2 * min(n_entries, n_groups) <= n_groups


def _compact_groups(tile_start, n_groups, group, t_max):
    """Indices of the touched tile-groups, padded to static length t_max.

    ``comp[j]`` is the group index the j-th grid step should process:
    the j-th touched group for j < touched-count, then (padding) the
    FIRST UNTOUCHED group.  The filler must be untouched — revisiting a
    touched group would re-apply its update — and identical across all
    filler steps (consecutive same-block revisits are the pipeline
    pattern BlockSpecs handle); an untouched group's update is the
    identity, so rewriting it any number of times is safe.  When every
    group is touched (only possible when t_max == n_groups) there are no
    filler steps, so the clamped fallback index is never used.
    """
    ts_g = tile_start[::group]  # [n_groups + 1] entry offsets per group
    touched = (ts_g[1:] > ts_g[:-1]).astype(jnp.int32)
    c = _cumsum_counts(touched)  # inclusive: c[gi] = touched in [0, gi]
    count = c[-1]
    j = jnp.arange(t_max, dtype=jnp.int32)
    comp = jnp.searchsorted(c, jnp.minimum(j + 1, count)).astype(jnp.int32)
    un_c = jnp.arange(1, n_groups + 1, dtype=c.dtype) - c  # untouched cum.
    first_un = jnp.minimum(
        jnp.searchsorted(un_c, 1).astype(jnp.int32), n_groups - 1
    )
    return jnp.where(j < count, comp, first_un)


def _k2_call(update, tile_start, u, tables, lanes, compact=None):
    """Stream ``tables`` (tuple) through the grouped K2 apply kernel.

    ``compact``: None = static auto-decision, True/False = force.  The
    compact variant visits only tile-groups the entry stream touches
    (via a scalar-prefetched group list driving the BlockSpec index
    maps); unvisited blocks are never fetched or written — their rows
    survive through the input/output aliasing.  HBM streaming then
    scales with min(touched groups, V/block) instead of V — the
    IndexedSlices property (SURVEY.md §3.2) for the apply's table
    traffic.  Only engaged when the entry count bounds touched groups
    to <= half the table (streaming the whole table is faster when most
    blocks are touched anyway).
    """
    v, d = tables[0].shape
    tile = TILE
    group = _group_for(v // tile)
    n_arrays = len(tables)
    block = tile * group
    n_groups = v // block
    n_entries = u.shape[0] - tile  # stream length minus window slack
    t_max = min(n_groups, n_entries)
    if compact is None:
        compact = _compact_auto(n_entries, n_groups)
    if compact:
        comp = _compact_groups(tile_start, n_groups, group, t_max)
        grid = (t_max,)
        num_prefetch = 2
        # index_map args: (grid idx, tile_start ref, compact ref).
        block_index = lambda t, ts, cg: (cg[t], 0)  # noqa: E731
        kernel = functools.partial(
            _k2_group_kernel_compact, n_tables=n_arrays, tile=tile,
            group=group, d=d, update=update,
        )
        prefetch_args = (tile_start, comp)
    else:
        grid = (n_groups,)
        num_prefetch = 1
        block_index = lambda t, *_: (t, 0)  # noqa: E731
        kernel = functools.partial(
            _k2_group_kernel, n_tables=n_arrays, tile=tile, group=group,
            d=d, update=update,
        )
        prefetch_args = (tile_start,)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=num_prefetch,
        grid=grid,
        in_specs=[pl.BlockSpec((block, d), block_index)] * n_arrays
        + [pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec((block, d), block_index)] * n_arrays,
        scratch_shapes=[
            pltpu.VMEM((2, tile, lanes), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((v, d), jnp.float32) for _ in range(n_arrays)
        ],
        input_output_aliases={
            num_prefetch + i: i for i in range(n_arrays)
        },
        interpret=_use_interpret(),
    )(*prefetch_args, *tables, u)


# ------------------------------------------------- K-place: dense expansion


def _kplace_kernel(ts_ref, u_hbm_ref, out_ref, u_vmem, sem,
                   *, tile, group, d):
    """Expand the unique-entry stream into dense [R, 2D] delta blocks."""

    def body(j, g1, g2):
        out_ref[pl.ds(j * tile, tile), :] = jnp.concatenate(
            [g1, g2], axis=-1
        )

    _window_loop(
        ts_ref, u_hbm_ref, u_vmem, sem, tile=tile, group=group, d=d,
        body=body,
    )


def _kplace_call(tile_start, u, vocab_local, d, lanes):
    tile = TILE
    group = _group_for(vocab_local // tile)
    block = tile * group
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(vocab_local // block,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((block, 2 * d), lambda t, *_: (t, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, tile, lanes), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_kplace_kernel, tile=tile, group=group, d=d),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((vocab_local, 2 * d), jnp.float32),
        interpret=_use_interpret(),
    )(tile_start, u)


def dense_delta(ids, g_rows, *, vocab, vocab_local, row_lo):
    """Per-shard dense (sum g, sum g^2) delta [vocab_local, 2D].

    ``row_lo`` (traced OK) is the first global row of the local table
    shard; only occurrences landing in [row_lo, row_lo + vocab_local)
    contribute.  This is the sharded-tile building block: shard_map runs it
    per device on the device's data shard, psums the result over the data
    axis, and applies the optimizer formula elementwise.
    """
    d = g_rows.shape[1]
    payload, upos, starts, firsts, ends, sidx, n_pad = _prep(
        ids, g_rows, vocab
    )
    u = _k1_dedup(payload, upos, starts, firsts, ends, n_pad + TILE)
    tile_start = _tile_starts(
        sidx, upos, row_lo + jnp.arange(0, vocab_local + 1, TILE,
                                        dtype=sidx.dtype)
    )
    return _kplace_call(tile_start, u, vocab_local, d, u.shape[1])


# ------------------------------------------- entries exchange (sharded path)


def resolve_exchange(mode: str, *, n_local_occ: int, vocab_local: int,
                     d: int, data_shards: int) -> str:
    """Resolve a sparse_exchange config value for static shapes.

    "dense" psums a [vocab_local, 2D] delta over the data axis — bytes
    grow with vocab, independent of the batch.  "entries" all-gathers
    the deduped touched-row streams — bytes grow with the batch,
    independent of vocab (the reference PS design's IndexedSlices
    scaling, SURVEY.md §3.2).  "auto" picks whichever moves fewer
    words per device over a ring:

      entries  S-shard all-gather of cap*(2D+1) words:
               (S-1) * cap * (2D+1) per device,
      dense    ring all-reduce of vocab_local*2D words (reduce-scatter
               + all-gather phases): 2 * vocab_local*2D * (S-1)/S.

    Dropping the common (S-1) factor gives the comparison below; the
    dense side carries the all-reduce's 2x buffer traffic (ADVICE r5 —
    the unweighted comparison was ~2x biased toward 'dense' and could
    pick the slower exchange near the crossover).
    """
    if mode != "auto":
        return mode
    if data_shards == 1:
        # Nothing to exchange either way; entries' fast path is then the
        # plain single-device K1+K2 apply, strictly less work than
        # materializing and elementwise-applying a dense delta.
        return "entries"
    cap = entries_cap(n_local_occ, vocab_local)
    entries_words = data_shards * cap * (2 * d + 1)
    dense_words = 2 * vocab_local * 2 * d
    return "entries" if entries_words < dense_words else "dense"


def entries_cap(n_occurrences: int, vocab: int) -> int:
    """Static per-shard entry-stream capacity for the entries exchange.

    Exact worst case — unique touched rows can't exceed the occurrence
    count (CHUNK-padded, the stream's real-entry bound) or the vocab
    range (CHUNK-rounded so the merged stream stays CHUNK-divisible).
    Always-correct by construction: no overflow path exists.
    """
    n_pad = -(-n_occurrences // CHUNK) * CHUNK
    return min(n_pad, -(-vocab // CHUNK) * CHUNK)


def unique_entries(ids, g_rows, *, vocab, cap):
    """Deduped touched-row entry stream: (rows [cap] i32, pay [cap, 2D]
    f32, count).

    The batch-proportional half of the reference's IndexedSlices push
    (SURVEY.md §3.2): instead of a dense [vocab, 2D] delta, emit only
    the rows the batch touched — sorted, deduped (sum g / sum g² per
    row), sentinel-padded (row == vocab, zero payload) to the static
    ``cap``.  Rows are recovered exactly from the K1 stream's
    lrow/tidx metadata columns (integer-valued f32, exact — see _prep).
    """
    d = g_rows.shape[1]
    payload, upos, starts, firsts, ends, sidx, n_pad = _prep(
        ids, g_rows, vocab
    )
    if cap > n_pad:
        raise ValueError(f"cap={cap} exceeds padded occurrences {n_pad}")
    u = _k1_dedup(payload, upos, starts, firsts, ends, n_pad + TILE)
    count = _tile_starts(
        sidx, upos, jnp.full((1,), vocab, sidx.dtype)
    )[0]  # uniques among real (non-sentinel) rows
    valid = jnp.arange(cap, dtype=jnp.int32) < count
    lrow = u[:cap, 2 * d].astype(jnp.int32)
    tidx = u[:cap, 2 * d + 1].astype(jnp.int32)
    rows = jnp.where(valid, tidx * TILE + lrow, vocab)
    pay = jnp.where(valid[:, None], u[:cap, :2 * d], 0.0)
    return rows, pay, count


def merge_entries(rows, pay, *, vocab):
    """Merge concatenated per-shard entry streams into one K2-ready
    stream: (u [N+TILE, 128], tile_start).

    Each source stream is already deduped, so a row appears at most once
    per shard; the merge re-sorts the concatenation and K1 sums the <=S
    partial (sum g, sum g²) contributions per row — totals identical to
    the dense psum's, so the downstream optimizer math is unchanged.
    Sentinel entries (row == vocab) sort last and fall outside
    tile_start's coverage.
    """
    n = rows.shape[0]
    if n % CHUNK:
        raise ValueError(f"merged stream length {n} not a CHUNK multiple")
    sidx, perm = jax.lax.sort_key_val(rows, jnp.arange(n, dtype=jnp.int32))
    pay_sorted = pay[perm]
    upos, last, starts, firsts, ends = _sorted_stream_meta(sidx)
    lrow = (sidx % TILE).astype(jnp.float32)
    # pay already holds (sum g, sum g²) — concatenate the placement
    # metadata column instead of re-deriving squares (_payload would
    # square the partial sums).
    payload = _pad_lanes(
        jnp.concatenate([pay_sorted, (lrow * last)[:, None]], axis=1)
    )
    u = _k1_dedup(payload, upos, starts, firsts, ends, n + TILE)
    tile_start = _tile_starts(
        sidx, upos, jnp.arange(0, vocab + 1, TILE, dtype=sidx.dtype)
    )
    return u, tile_start


def k2_apply(update, tile_start, u, tables, compact=None):
    """Apply an elementwise optimizer ``update`` from a K2-ready entry
    stream (as produced by merge_entries) to ``tables``."""
    return _k2_call(update, tile_start, u, tables, u.shape[1],
                    compact=compact)


def entries_exchange(lids, g_rows, *, vocab_local, data_axis,
                     data_shards, rows_all=None):
    """The ONE copy of the entries-exchange protocol (shard_map body):
    dedupe LOCAL-coordinate occurrences (off-shard ids pre-mapped to the
    sentinel ``vocab_local``, their payloads zeroed), all-gather the
    touched-entry streams over ``data_axis``, merge.  Returns the
    K2-ready ``(u, tile_start)``.  Both the shardmap step and the GSPMD
    sharded apply call this — keep it the only copy.

    ``data_shards`` (static) short-circuits the degenerate pure
    model-parallel case: with one data shard there is nothing to
    exchange, and the single-device dedup already produces the K2
    stream — the gather + second sort + second K1 pass would only
    re-derive it.

    ``rows_all`` (optional) is the pre-gathered ID PLANE: the
    concatenated per-data-shard row streams this call would otherwise
    all-gather itself.  The id plane is a pure function of the batch
    ids (dedup order never looks at payloads), so a caller that knows
    the NEXT super-batch's ids can compute and gather it one scan step
    early (:func:`make_entries_prefetch`) and overlap that collective
    with the previous step's compute — only the payload gather stays
    on the critical path.  Bitwise-identical results by construction.
    """
    if data_shards == 1:
        return _dedup_and_starts(lids, g_rows, vocab_local)
    cap = entries_cap(lids.shape[0], vocab_local)
    rows_e, pay_e, _ = unique_entries(
        lids, g_rows, vocab=vocab_local, cap=cap
    )
    if rows_all is None:
        rows_all = jax.lax.all_gather(
            rows_e, data_axis, axis=0, tiled=True
        )
    pay_all = jax.lax.all_gather(pay_e, data_axis, axis=0, tiled=True)
    return merge_entries(rows_all, pay_all, vocab=vocab_local)


def make_entries_prefetch(mesh, data_axis, model_axis, vocab):
    """Build the id-plane prefetch for the overlapped entries exchange.

    Returns ``prefetch(ids) -> rows_all``: a shard_map program that runs
    the per-device id dedup of :func:`unique_entries` (payloads zeroed —
    the row stream is payload-independent) and all-gathers the streams
    over the data axis, producing the ``rows_all`` operand
    :func:`entries_exchange` accepts.  The output is a ``P(model)``
    global array ([model_shards * data_shards * cap]): every data
    replica of a model column computes the identical gathered stream,
    and the scan carries it to the NEXT step's apply — where it enters
    with an in_spec of ``P(model)``, landing each device exactly the
    block it would have gathered itself.
    """
    from jax.sharding import PartitionSpec as P

    from fast_tffm_tpu.platform import shard_map

    model_shards = mesh.shape[model_axis]
    vocab_local = vocab // model_shards

    def local(ids_l):
        m = jax.lax.axis_index(model_axis)
        row_lo = m * vocab_local
        in_range = (ids_l >= row_lo) & (ids_l < row_lo + vocab_local)
        lids = jnp.where(
            in_range, ids_l - row_lo, vocab_local
        ).astype(jnp.int32)
        cap = entries_cap(lids.shape[0], vocab_local)
        zeros = jnp.zeros((lids.shape[0], 1), jnp.float32)
        rows_e, _, _ = unique_entries(
            lids, zeros, vocab=vocab_local, cap=cap
        )
        return jax.lax.all_gather(rows_e, data_axis, axis=0, tiled=True)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=P(data_axis),
        out_specs=P(model_axis),
        check_vma=False,
    )


# ------------------------------------------------------------ orchestration


def _tile_starts(sidx, upos, boundaries):
    """Unique-entry index of the first id >= each row boundary."""
    n_unique = upos[-1] + 1
    upos_ext = jnp.concatenate([upos, n_unique[None]])
    ss = jnp.searchsorted(sidx, boundaries)
    return upos_ext[ss].astype(jnp.int32)


def _cumsum_mxu(flags):
    """Prefix sum of 0/1 flags via one triangular matmul — exact only
    while the total stays < 2^24 (f32 integers)."""
    n = flags.shape[0]
    m = flags.reshape(n // 128, 128).astype(jnp.float32)
    # within[r, c] = sum_{k<=c} m[r, k] needs tri[k, c] = (k <= c):
    # upper-triangular (tril would give suffix sums).
    tri = jnp.triu(jnp.ones((128, 128), jnp.float32))
    within = jax.lax.dot_general(
        m, tri, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    row_tot = within[:, -1]
    offs = jnp.cumsum(row_tot) - row_tot
    return (within + offs[:, None]).reshape(n).astype(flags.dtype)


def _cumsum_counts(flags):
    """Prefix sum of 0/1 flags, MXU-shaped and exact at any length.

    XLA lowers a length-640k 1-D cumsum to log-depth VPU passes in a
    lane-hostile layout (~4.7 ms measured on v5e — comparable to the
    whole K1 kernel).  Reshaping to [rows, 128] turns the within-row
    prefix into one [rows,128]x[128,128] triangular matmul plus a
    128x-shorter cumsum of row totals; f32 keeps that exact below 2^24
    counts.  Above (the flagship B=262k step has 10.2M occurrences),
    a two-level split stays exact: segments of < 2^24 get the MXU
    prefix (segment-LOCAL counts < segment length, f32-exact), and the
    tiny integer cumsum of segment totals supplies exact int32 offsets.
    Falls back to jnp.cumsum only when no 128-multiple segment divides n.
    """
    n = flags.shape[0]
    if n % 128:
        return jnp.cumsum(flags)
    if n < 1 << 24:
        return _cumsum_mxu(flags)
    seg = 1 << 23
    while n % seg:
        seg >>= 1
    if seg < 128:  # n % 128 == 0 makes this unreachable; belt+braces
        return jnp.cumsum(flags)
    m = flags.reshape(n // seg, seg)
    within = jax.vmap(_cumsum_mxu)(m)  # [S, seg], ints < 2^23 each
    seg_tot = within[:, -1]
    offs = jnp.cumsum(seg_tot) - seg_tot  # int32: exact at any total
    return (within + offs[:, None]).reshape(n)


def _pad_lanes(x):
    """Pad the minor dim to the 128-lane tile.

    The unique-entry stream is DMA'd at dynamic offsets (K1 out,
    K2/K-place in), and Mosaic requires manually sliced HBM memrefs to
    be lane-aligned ("Slice shape along dimension 1 must be aligned to
    tiling (128)" on real v5e — auto-pipelined BlockSpecs pad for free,
    manual `.at[pl.ds(...)]` copies do not).  HBM storage is already
    physically padded to 128 lanes by tiling, so the zeros cost no extra
    traffic.
    """
    n, lanes = x.shape
    lanes_pad = -(-lanes // 128) * 128
    if lanes_pad != lanes:
        x = jnp.concatenate(
            [x, jnp.zeros((n, lanes_pad - lanes), x.dtype)], axis=1
        )
    return x


def _payload(g_sorted, lrow_last, tidx_last=None):
    """[g | g^2 | lrow·last | tidx·last?] per sorted occurrence, 128-lane
    padded (see _pad_lanes).

    ``tidx_last`` (the occurrence's tile index, · last-in-segment flag)
    is carried only where the deduped stream's global rows must be
    recoverable afterwards — the entries exchange.  Like lrow, K1's
    segment sum leaves exactly the value on the unique entry because
    only the last occurrence contributes.
    """
    cols = [g_sorted, g_sorted * g_sorted, lrow_last[:, None]]
    if tidx_last is not None:
        cols.append(tidx_last[:, None])
    return _pad_lanes(jnp.concatenate(cols, axis=1))


def _sorted_stream_meta(sidx):
    """Segment metadata for a sorted id stream: (upos, last-flags, and the
    K1 chunk-boundary scalars).  Shared by _prep and merge_entries."""
    flag_first = jnp.concatenate([jnp.full((1,), -1, sidx.dtype), sidx[:-1]])
    flags = (sidx != flag_first).astype(jnp.int32)  # segment starts
    upos = _cumsum_counts(flags) - 1  # unique-row position per occurrence
    nxt = jnp.concatenate([sidx[1:], jnp.full((1,), -2, sidx.dtype)])
    last = (sidx != nxt).astype(jnp.float32)  # segment ends
    starts = upos[::CHUNK]
    firsts = jnp.concatenate([flags[::CHUNK], jnp.ones((1,), jnp.int32)])
    ends = upos[CHUNK - 1::CHUNK]
    return upos, last, starts, firsts, ends


def _prep(ids, g_rows, vocab):
    """Sort, dedup-position, and chunk-boundary metadata (all XLA)."""
    n = ids.shape[0]
    d = g_rows.shape[1]
    n_pad = -(-n // CHUNK) * CHUNK
    if n_pad != n:
        # Sentinel occurrences: id = vocab sorts last, lands in no real
        # tile (tile_start covers rows < vocab), grads are zero anyway.
        ids = jnp.concatenate(
            [ids, jnp.full((n_pad - n,), vocab, ids.dtype)]
        )
        g_rows = jnp.concatenate(
            [g_rows, jnp.zeros((n_pad - n, d), g_rows.dtype)]
        )
    sidx, perm = jax.lax.sort_key_val(ids, jnp.arange(n_pad, dtype=jnp.int32))
    g_sorted = g_rows[perm]
    upos, last, starts, firsts, ends = _sorted_stream_meta(sidx)
    lrow = (sidx % TILE).astype(jnp.float32)  # tile-local row, exact < TILE
    # Tile index, f32-exact while vocab/TILE < 2^24 (true for any vocab
    # < 2^31 at TILE >= 256 — int32 ids cap vocab below that anyway).
    tidx = (sidx // TILE).astype(jnp.float32)
    payload = _payload(g_sorted, lrow * last, tidx * last)
    return payload, upos, starts, firsts, ends, sidx, n_pad


def _dedup_and_starts(ids, g_rows, vocab, meta=None):
    if meta is not None:
        n, d = g_rows.shape
        n_pad = meta.perm.shape[0]
        # The producer baked CHUNK/TILE into these shapes; a mismatch
        # means pipeline and kernels disagree on the constants — running
        # anyway would misplace rows, so fail loudly at trace time.
        if (
            n_pad != -(-n // CHUNK) * CHUNK
            or meta.starts.shape[0] != n_pad // CHUNK
            or meta.tile_start.shape[0] != vocab // TILE + 1
        ):
            raise ValueError(
                "sort_meta shapes disagree with CHUNK/TILE/vocab: "
                f"perm={meta.perm.shape} starts={meta.starts.shape} "
                f"tile_start={meta.tile_start.shape} vs n={n} "
                f"CHUNK={CHUNK} TILE={TILE} vocab={vocab}"
            )
        if n_pad != n:
            g_rows = jnp.concatenate(
                [g_rows, jnp.zeros((n_pad - n, d), g_rows.dtype)]
            )
        g_sorted = g_rows[meta.perm]
        payload = _payload(g_sorted, meta.lrow_last)
        u = _k1_dedup(
            payload, meta.upos, meta.starts, meta.firsts, meta.ends,
            n_pad + TILE,
        )
        return u, meta.tile_start
    payload, upos, starts, firsts, ends, sidx, n_pad = _prep(
        ids, g_rows, vocab
    )
    u = _k1_dedup(payload, upos, starts, firsts, ends, n_pad + TILE)
    tile_start = _tile_starts(
        sidx, upos, jnp.arange(0, vocab + 1, TILE, dtype=sidx.dtype)
    )
    return u, tile_start


def adagrad_apply(table, acc, ids, g_rows, *, lr, eps, meta=None,
                  compact=None):
    """Sparse Adagrad over touched rows: exact SparseApplyAdagrad semantics."""
    vocab, d = table.shape
    u, tile_start = _dedup_and_starts(ids, g_rows, vocab, meta)
    update = functools.partial(adagrad_update, lr=lr, eps=eps)
    table, acc = _k2_call(update, tile_start, u, (table, acc), u.shape[1],
                          compact=compact)
    return table, acc


def sgd_apply(table, ids, g_rows, *, lr, meta=None, compact=None):
    vocab, d = table.shape
    u, tile_start = _dedup_and_starts(ids, g_rows, vocab, meta)
    update = functools.partial(sgd_update, lr=lr)
    (table,) = _k2_call(update, tile_start, u, (table,), u.shape[1],
                        compact=compact)
    return table


def ftrl_apply(table, z, n, ids, g_rows, *, lr, l1, l2, beta, meta=None,
               compact=None):
    # Recomputing w for untouched rows inside ftrl_update is idempotent:
    # their (z, n) are unchanged and w is always ftrl_solve(z, n)
    # (train.sparse initializes z so this holds from step 0).  This
    # invariant is a CONTRACT: the full sweep recomputes every row while
    # compact K2 skips untouched ones, and the two only agree because
    # recompute == stored value.  A caller handing in a table that is
    # not ftrl_solve(z, n) gets sweep-dependent untouched rows.
    vocab, d = table.shape
    u, tile_start = _dedup_and_starts(ids, g_rows, vocab, meta)
    update = functools.partial(ftrl_update, lr=lr, l1=l1, l2=l2, beta=beta)
    table, z, n = _k2_call(update, tile_start, u, (table, z, n), u.shape[1],
                           compact=compact)
    return table, z, n


# ------------------------------------------------------- sharded (shard_map)


def supports_tile_sharded(vocab: int, optimizer: str, model_shards: int) -> bool:
    return (
        optimizer in ("adagrad", "ftrl", "sgd")
        and vocab % (model_shards * TILE) == 0
        and vocab // model_shards >= TILE
    )


def _sharded_call(update_fn, mesh, data_axis, model_axis, tables, ids,
                  g_rows, vocab, exchange="dense", rows_all=None):
    """shard_map wrapper: per-device K1 dedup, then either a dense
    per-shard delta psum over the data axis (``exchange="dense"``) or a
    batch-proportional all-gather of the touched-entry streams
    (``"entries"``), then the optimizer update on the local table shard.

    This is the GSPMD-era replacement for the reference's PS scatter push
    (SURVEY.md §3.2): dense mode uses the sync-DP gradient-allreduce
    collective pattern (O(vocab) bytes); entries mode keeps the PS
    design's IndexedSlices property — bytes scale with the batch,
    independent of vocab.

    ``rows_all`` is the prefetched id plane for the overlapped entries
    exchange (see :func:`entries_exchange` / :func:`make_entries_prefetch`)
    — only legal with ``exchange="entries"`` and a multi-shard data axis.
    """
    from jax.sharding import PartitionSpec as P

    model_shards = mesh.shape[model_axis]
    vocab_local = vocab // model_shards
    n_tables = len(tables)
    if rows_all is not None and (
        exchange != "entries" or mesh.shape[data_axis] == 1
    ):
        raise ValueError(
            "a prefetched id plane (rows_all) only applies to the "
            "entries exchange over a multi-shard data axis"
        )

    def local(ids_l, g_l, *rest):
        if rows_all is not None:
            rows_in, tables_l = rest[0], rest[1:]
        else:
            rows_in, tables_l = None, rest
        m = jax.lax.axis_index(model_axis)
        row_lo = m * vocab_local
        d = g_l.shape[1]
        if exchange == "entries":
            in_range = (ids_l >= row_lo) & (ids_l < row_lo + vocab_local)
            lids = jnp.where(
                in_range, ids_l - row_lo, vocab_local
            ).astype(jnp.int32)
            g_masked = jnp.where(in_range[:, None], g_l, 0.0)
            u2, ts2 = entries_exchange(
                lids, g_masked, vocab_local=vocab_local,
                data_axis=data_axis, data_shards=mesh.shape[data_axis],
                rows_all=rows_in,
            )
            # k2_apply expects update -> tuple; the single-table (sgd)
            # wrapper returns a bare array.
            upd = (
                update_fn if n_tables > 1
                else (lambda g1, g2, *t: (update_fn(g1, g2, *t),))
            )
            out = k2_apply(upd, ts2, u2, tuple(tables_l))
            return tuple(out) if n_tables > 1 else out[0]
        dense = dense_delta(
            ids_l, g_l, vocab=vocab,
            vocab_local=vocab_local, row_lo=row_lo,
        )
        dense = jax.lax.psum(dense, data_axis)
        return update_fn(dense[:, :d], dense[:, d:], *tables_l)

    from fast_tffm_tpu.platform import shard_map

    extra = () if rows_all is None else (rows_all,)
    extra_specs = () if rows_all is None else (P(model_axis),)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(data_axis), P(data_axis, None)) + extra_specs
        + (P(model_axis, None),) * n_tables,
        out_specs=(P(model_axis, None),) * n_tables
        if n_tables > 1 else P(model_axis, None),
        check_vma=False,  # pallas_call outputs carry no vma annotations
    )(ids, g_rows, *extra, *tables)


def adagrad_apply_sharded(table, acc, ids, g_rows, *, lr, eps, mesh,
                          data_axis, model_axis, exchange="dense",
                          rows_all=None):
    def update(g1, g2, table_l, acc_l):
        return adagrad_update(g1, g2, table_l, acc_l, lr=lr, eps=eps)

    return _sharded_call(
        update, mesh, data_axis, model_axis, (table, acc), ids, g_rows,
        table.shape[0], exchange=exchange, rows_all=rows_all,
    )


def sgd_apply_sharded(table, ids, g_rows, *, lr, mesh, data_axis,
                      model_axis, exchange="dense", rows_all=None):
    def update(g1, g2, table_l):
        return sgd_update(g1, g2, table_l, lr=lr)[0]

    return _sharded_call(
        update, mesh, data_axis, model_axis, (table,), ids, g_rows,
        table.shape[0], exchange=exchange, rows_all=rows_all,
    )


def ftrl_apply_sharded(table, z, n, ids, g_rows, *, lr, l1, l2, beta, mesh,
                       data_axis, model_axis, exchange="dense",
                       rows_all=None):
    def update(g1, g2, table_l, z_l, n_l):
        return ftrl_update(
            g1, g2, table_l, z_l, n_l, lr=lr, l1=l1, l2=l2, beta=beta
        )

    return _sharded_call(
        update, mesh, data_axis, model_axis, (table, z, n), ids, g_rows,
        table.shape[0], exchange=exchange, rows_all=rows_all,
    )
