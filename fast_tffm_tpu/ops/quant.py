"""Quantized embedding-row storage: bf16 / int8-with-fp32-scales.

PR 8's arithmetic-intensity numbers showed the FM step is
bytes-dominated — almost all traffic per dispatch is embedding-row
reads/writes, not FLOPs — so the lever is bytes per row.  This module
is the ONE place the row formats live; every other layer (the tiered
cold store, the ``quant.npz`` checkpoint, the serving ladder, the
convert tool) composes these primitives:

- ``bf16``: rows stored as bfloat16 (half the bytes).  No scales —
  bf16 shares float32's exponent range, so truncating the mantissa is
  the whole transform.  Dequantization is a plain ``astype`` that XLA
  fuses into the gather (read compact, widen in-register).
- ``int8``: symmetric linear quantization with float32 scales.
  scale = max|x| / 127 over a scale group; codes = round(x / scale)
  in [-127, 127]; an all-zero group stores scale 0 and reproduces
  exactly.  Scale granularity differs by where the rows live:

  * DENSE tables (the device-resident serving table, the ``quant.npz``
    checkpoint): one scale per chunk of ``quant_chunk`` consecutive
    rows (:class:`QuantTable`).  At chunk 64 and D = 9 that is
    9 + 4/64 ≈ 9.06 B/row — the ≈4x the serving replica-density math
    wants.  Chunking also bounds the blast radius of an outlier row:
    it flattens the precision of its own chunk only.  ``chunk 0`` =
    one scale per row.
  * the tiered COLD store: one scale per row, always — rows migrate
    hot<->cold individually, so a shared scale would need re-encoding
    neighbors on every write-back.  D + 4 B/row (~2.8x at D = 9).

Two representations:

- UNPACKED, what compute wants: ``(codes, scales)`` arrays.
- PACKED, what row-granular storage wants: one uint8
  ``[n, bytes_per_row]`` array (:class:`RowCodec`).  The tiered
  overlay machinery (sorted merges, fancy indexing, np.savez without
  pickle) only ever shuffles rows of one 2-D array, so packing keeps
  it — and the overlay checkpoint format — completely dtype-agnostic.
  fp32 is the identity codec: rows pass through as float32, bit-exact
  (the pre-quantization behavior).

:func:`dequant_gathered` is the jax-side fused dequant the compiled
score path uses (``codes[ids]`` gathers compact rows; the cast +
scale multiply widen them in-register).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

import ml_dtypes

DTYPES = ("fp32", "bf16", "int8")

bfloat16 = ml_dtypes.bfloat16


def validate_dtype(dtype: str, what: str = "dtype") -> str:
    if dtype not in DTYPES:
        raise ValueError(f"unknown {what} {dtype!r} (one of {DTYPES})")
    return dtype


def _group_of(n: int, chunk: int) -> np.ndarray:
    """[n] i64: which scale group each row belongs to."""
    if chunk <= 1:
        return np.arange(n, dtype=np.int64)
    return np.arange(n, dtype=np.int64) // chunk


def quantize_int8(rows: np.ndarray, chunk: int = 0) -> tuple:
    """f32 [n, dim] -> (codes int8 [n, dim], scales f32 [G]).

    ``chunk`` consecutive rows share a scale (G = ceil(n/chunk));
    chunk <= 1 = one scale per row (G = n).  Symmetric: the largest
    |element| of a group maps to ±127.
    """
    rows = np.asarray(rows, np.float32)
    n = len(rows)
    per_row = np.abs(rows).max(axis=1) if rows.size else np.zeros(
        (0,), np.float32
    )
    if chunk <= 1:
        amax = per_row
    elif n == 0:
        amax = np.zeros(0, np.float32)
    else:
        # Vectorized group max: pad to a chunk multiple and reshape
        # (zeros never win a max of absolutes).  np.maximum.at is a
        # scalar loop — tens of seconds at V=2^28, and this runs on
        # the hot-swap staging path.
        g = -(-n // chunk)
        pad = g * chunk - n
        padded = np.pad(per_row, (0, pad)) if pad else per_row
        amax = padded.reshape(g, chunk).max(axis=1)
    scales = amax / np.float32(127.0)
    safe = np.where(scales > 0, scales, np.float32(1.0))
    codes = np.clip(
        np.rint(rows / safe[_group_of(n, chunk), None]), -127, 127
    ).astype(np.int8)
    return codes, scales.astype(np.float32)


def dequantize_int8(codes: np.ndarray, scales: np.ndarray,
                    chunk: int = 0) -> np.ndarray:
    return codes.astype(np.float32) * scales[
        _group_of(len(codes), chunk), None
    ]


def dequant_gathered(codes_rows, scale_rows):
    """Fused jax-side dequant for gathered rows: ``codes_rows`` int8
    ``[..., dim]`` (from ``codes[ids]``), ``scale_rows`` f32 ``[...]``
    (from ``scales[ids // chunk]``).  The cast + multiply happen
    in-register after the compact gather — the compiled step reads a
    quarter of the row bytes and widens on-chip."""
    import jax.numpy as jnp

    return codes_rows.astype(jnp.float32) * scale_rows[..., None]


# ----------------------------------------------------------------------
# Dense quantized tables (serving ladder + quant.npz checkpoint)
# ----------------------------------------------------------------------


class QuantParams(NamedTuple):
    """Device-resident int8 serving params (the quantized analogue of
    fm.FmParams): ``codes`` int8 [V, dim], ``scales`` f32
    [ceil(V/chunk)] — a NamedTuple so it is a jax pytree the compiled
    rungs take as an argument (hot-swappable by reference, like the
    fp32 params)."""

    w0: object
    codes: object
    scales: object


class QuantTable(NamedTuple):
    """One host-side quantized dense table.

    ``codes``: int8 [V, dim] (int8) or bfloat16 [V, dim] (bf16);
    ``scales``: f32 [ceil(V/chunk)] for int8, None for bf16."""

    dtype: str
    chunk: int
    codes: np.ndarray
    scales: Optional[np.ndarray]

    @property
    def nbytes(self) -> int:
        return int(self.codes.nbytes) + (
            int(self.scales.nbytes) if self.scales is not None else 0
        )

    def descriptor(self) -> dict:
        d = {
            "dtype": self.dtype,
            "vocab": int(self.codes.shape[0]),
            "dim": int(self.codes.shape[1]),
        }
        if self.dtype == "int8":
            d["chunk"] = int(self.chunk)
        return d


def quantize_table(table: np.ndarray, dtype: str,
                   chunk: int = 0) -> QuantTable:
    """f32 [V, dim] -> :class:`QuantTable` (``dtype`` bf16 or int8)."""
    validate_dtype(dtype)
    if dtype == "fp32":
        raise ValueError("fp32 tables are not quantized; use the array")
    table = np.ascontiguousarray(table, np.float32)
    if dtype == "bf16":
        return QuantTable("bf16", 0, table.astype(bfloat16), None)
    codes, scales = quantize_int8(table, chunk)
    return QuantTable("int8", chunk, codes, scales)


def dequantize_table(qt: QuantTable) -> np.ndarray:
    if qt.dtype == "bf16":
        return qt.codes.astype(np.float32)
    return dequantize_int8(qt.codes, qt.scales, qt.chunk)


def dequantize_rows(qt: QuantTable, ids: np.ndarray) -> np.ndarray:
    """f32 rows for ``ids`` (any shape) WITHOUT materializing the full
    dequantized table — O(len(ids)) work and memory (the placement-time
    probe's path; dequantize_table at V=2^28 would be a multi-GB
    allocation to read 256 rows)."""
    codes = qt.codes[ids]
    if qt.dtype == "bf16":
        return codes.astype(np.float32)
    scales = qt.scales[ids // qt.chunk if qt.chunk > 1 else ids]
    return codes.astype(np.float32) * scales[..., None]


def table_to_arrays(qt: QuantTable) -> dict:
    """npz-safe arrays (bf16 codes as a uint16 bit view)."""
    out = {"codes": (
        qt.codes.view(np.uint16) if qt.dtype == "bf16" else qt.codes
    )}
    if qt.scales is not None:
        out["scales"] = qt.scales
    return out


def table_from_arrays(descriptor: dict, arrays: dict) -> QuantTable:
    dtype = descriptor["dtype"]
    codes = arrays["codes"]
    if dtype == "bf16":
        codes = codes.view(bfloat16)
    return QuantTable(
        dtype, int(descriptor.get("chunk", 0)), codes,
        arrays.get("scales"),
    )


# ----------------------------------------------------------------------
# Row-granular packed storage (the tiered cold store)
# ----------------------------------------------------------------------


class RowCodec:
    """Encode/decode one row-block format (see module docstring).

    int8 rows pack a PER-ROW fp32 scale after the codes (rows must
    stay independent — they migrate hot<->cold one at a time), so one
    packed row is ``dim + 4`` bytes; bf16 rows are ``2 * dim`` bytes;
    fp32 rows pass through as float32.
    """

    def __init__(self, dtype: str, dim: int):
        validate_dtype(dtype)
        self.dtype = dtype
        self.dim = dim
        if dtype == "fp32":
            self.bytes_per_row = 4 * dim
            self.width = dim
            self.storage_dtype = np.dtype(np.float32)
        elif dtype == "bf16":
            self.bytes_per_row = 2 * dim
            self.width = self.bytes_per_row
            self.storage_dtype = np.dtype(np.uint8)
        else:  # int8 + one f32 scale
            self.bytes_per_row = dim + 4
            self.width = self.bytes_per_row
            self.storage_dtype = np.dtype(np.uint8)

    def empty(self, n: int) -> np.ndarray:
        return np.empty((n, self.width), self.storage_dtype)

    def encode(self, rows: np.ndarray) -> np.ndarray:
        """f32 [n, dim] -> packed [n, width] (always a fresh array)."""
        rows = np.ascontiguousarray(rows, np.float32)
        if self.dtype == "fp32":
            return rows.copy()
        if self.dtype == "bf16":
            return np.ascontiguousarray(
                rows.astype(bfloat16)
            ).view(np.uint8).reshape(len(rows), self.width)
        codes, scales = quantize_int8(rows, 0)
        packed = np.empty((len(rows), self.width), np.uint8)
        packed[:, :self.dim] = codes.view(np.uint8)
        packed[:, self.dim:] = np.ascontiguousarray(
            scales
        ).view(np.uint8).reshape(len(rows), 4)
        return packed

    def decode(self, packed: np.ndarray) -> np.ndarray:
        """packed [n, width] -> f32 [n, dim].  fp32 is the identity
        (no copy: dense-path callers rely on fancy indexing having
        copied already)."""
        if self.dtype == "fp32":
            return packed
        if self.dtype == "bf16":
            return np.ascontiguousarray(packed).view(
                bfloat16
            ).astype(np.float32)
        packed = np.ascontiguousarray(packed)
        codes = packed[:, :self.dim].view(np.int8)
        scales = np.ascontiguousarray(packed[:, self.dim:]).view(
            np.float32
        ).reshape(len(packed))
        return codes.astype(np.float32) * scales[:, None]

    def descriptor(self) -> dict:
        """The format identity an overlay checkpoint must carry (and a
        restore must match): {} for fp32, so pre-quantization
        descriptors keep matching byte-for-byte.  No ``chunk`` — the
        packed cold format is per-row-scale by construction."""
        return {} if self.dtype == "fp32" else {"dtype": self.dtype}

    def __repr__(self) -> str:
        return f"RowCodec({self.dtype}, dim={self.dim})"


def cold_codec(cfg) -> RowCodec:
    """The cold-store row codec an FmConfig implies."""
    return RowCodec(cfg.cold_dtype, cfg.embedding_dim)
