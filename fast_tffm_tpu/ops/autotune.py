"""Kernel autotuner: measured promotion of the interaction hot path.

The repo carries three implementations of the FM interaction
scores/grads (ops/interaction.py's reference elementwise math, the
Mosaic kernels in ops/fm_pallas.py, and the packed flat-layout
one-hot-matmul variant) plus the int8 fused-gather serving forward
(models.fm.fm_scores_dequant).  Which one is fastest depends on the
run's actual shapes (batch, F, D), the backend, and the table dtype —
the hardware window used to A/B them by hand.  This module is the
selection mechanism:

- ``resolve(cfg, context=...)`` maps the ``interaction_impl`` knob to a
  concrete implementation.  Pins (``reference``/``pallas``/``packed``)
  bypass measurement entirely; ``auto`` benchmarks the candidate set
  for the run's shapes, keeps only candidates that pass an element-wise
  parity gate against reference (scores AND grads in the train
  context), and picks the fastest survivor.
- Decisions persist in a per-backend/shape JSON cache
  (``autotune_cache.json``) keyed on (context, backend, batch, F, D,
  field_num, table dtype, compute dtype, jax version) — any drift in
  the key re-measures; a hit skips measurement entirely, so replica
  fleets and restarts pay nothing.
- Every decision is observable: a ``record: autotune`` JSONL entry
  (candidates, per-candidate times, winner, parity error) via
  :func:`write_record`, and ``kernel_impl`` in the run header / serve
  block.

Off-TPU the candidate set collapses to ``("reference",)`` — the Mosaic
kernels would run in interpret mode and the packed one-hot matmuls are
a CPU pessimization, so reference provably wins at zero measurement
cost (the ``autotune_overhead <= 1.05`` budget bench.py enforces).  On
a TPU backend all candidates enter measurement — that is the point.

Offline: ``python tools/autotune.py`` pre-populates the cache for a
config; ``--check`` validates cache self-consistency and the
reference-wins-on-CPU invariant (wired into tools/verify.sh).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time

log = logging.getLogger(__name__)

__all__ = [
    "Decision", "resolve", "write_record", "default_candidates",
    "cache_key", "default_cache_path", "load_cache", "save_cache",
    "measurement_count", "PARITY_TOL", "INTERNAL", "USER",
]

# User-facing impl name -> ops.interaction dispatch name.  "packed" is
# the flat [B, F*D] one-hot-matmul layout (ops.interaction._scores_flat
# — the XLA-fused twin of the packed-K2 kernel layout, see
# EMBEDDING.md "Packed layout").
INTERNAL = {"reference": "jnp", "pallas": "pallas", "packed": "flat"}
USER = {v: k for k, v in INTERNAL.items()}

# Element-wise parity gate, pinned: a candidate whose scores or grads
# drift beyond TOL * max(1, |reference|_max) from reference is rejected
# no matter how fast it measured.  2e-3 relative covers f32
# accumulation-order drift between the elementwise, MXU-matmul, and
# Mosaic formulations (their observed drift is ~1e-6..1e-5) while
# rejecting anything actually wrong.
PARITY_TOL = 2e-3

# Module-level measurement counter: bumped once per candidate actually
# benchmarked.  Tests pin cache hits / pins / single-candidate
# resolutions to "skips measurement" through this.
_MEASUREMENTS = 0

_CACHE_VERSION = 1
_MEM_CACHE: dict = {}  # in-process cache (works with cache_path="")


def measurement_count() -> int:
    """How many candidate benchmarks ran in this process."""
    return _MEASUREMENTS


@dataclasses.dataclass
class Decision:
    """One interaction-impl selection, however it was reached."""

    impl: str  # user-facing: reference | pallas | packed
    interaction: str  # ops.interaction dispatch name: jnp | pallas | flat
    source: str  # pinned | legacy | single_candidate | cache | measured
    context: str  # train | serve
    key: str  # the cache key (empty for pins/legacy)
    candidates: tuple = ()
    times_ms: dict = dataclasses.field(default_factory=dict)
    parity_err: dict = dataclasses.field(default_factory=dict)
    cache_file: str = ""


# ---------------------------------------------------------------- keys


def cache_key(context: str, backend: str, batch: int, features: int,
              dim: int, field_num: int, table_dtype: str,
              compute_dtype: str, jax_version: str | None = None) -> str:
    """The persistent-cache key: every axis that can change the winner.
    A drift in ANY component (shape, dtype, backend, jax version) is a
    miss — stale winners never leak across upgrades or re-shapes."""
    if jax_version is None:
        import jax

        jax_version = jax.__version__
    return "|".join((
        context, backend, f"b{int(batch)}", f"f{int(features)}",
        f"d{int(dim)}", f"p{int(field_num)}", table_dtype,
        compute_dtype, f"jax{jax_version}",
    ))


def default_cache_path(cfg) -> str:
    """Where the persistent cache lives for this run: the
    ``FAST_TFFM_AUTOTUNE_CACHE`` env override (empty string = memory
    only), else alongside the persistent compile cache, else next to
    the model checkpoint (the serve fleet reads the same file)."""
    env = os.environ.get("FAST_TFFM_AUTOTUNE_CACHE")
    if env is not None:
        return env
    if getattr(cfg, "compile_cache_dir", ""):
        return os.path.join(cfg.compile_cache_dir, "autotune_cache.json")
    if getattr(cfg, "model_file", ""):
        d = os.path.dirname(os.path.abspath(cfg.model_file))
        return os.path.join(d, "autotune_cache.json")
    return ""


def load_cache(path: str) -> dict:
    """Read a cache file; corruption or absence is an empty cache (the
    autotuner re-measures — never a crash)."""
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or doc.get("version") != _CACHE_VERSION:
            return {}
        entries = doc.get("entries")
        return entries if isinstance(entries, dict) else {}
    except (OSError, ValueError) as e:
        log.warning("autotune cache %s unreadable (%s); re-measuring",
                    path, e)
        return {}


def save_cache(path: str, entries: dict) -> None:
    """Atomic write (tmp + rename): a killed run never leaves a torn
    cache behind for the next one to trip on."""
    if not path:
        return
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": _CACHE_VERSION, "entries": entries},
                      f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:  # persistence is an optimization, not a need
        log.warning("autotune cache write to %s failed: %s", path, e)


# ---------------------------------------------------------- candidates


def default_candidates(field_num: int = 0) -> tuple:
    """The candidate set for the current backend.

    FFM (field_num > 0) always uses its closed-form op — impl routing
    does not apply, so reference is the only candidate.  Off-TPU the
    Mosaic kernels execute in interpret mode (orders of magnitude
    slower) and the packed one-hot matmuls pessimize the VPU-less CPU
    path, so reference wins by construction and the single-candidate
    fast path skips measurement entirely — the provably-near-zero
    overhead the CPU acceptance gate pins.  On TPU every selectable
    impl enters measurement.
    """
    if field_num:
        return ("reference",)
    from fast_tffm_tpu.platform import is_tpu_backend

    if is_tpu_backend():
        return ("reference", "pallas", "packed")
    return ("reference",)


def _candidate_fns(cfg, context: str, batch: int, table_dtype: str):
    """(make_fn, args): ``make_fn(user_impl)`` returns a jitted callable
    of ``args`` whose outputs are element-wise comparable across
    impls.

    Train context: forward scores + closed-form row grads through
    ``ops.interaction.fm_interaction`` — the fused-scan step's actual
    hot pair.  Serve context: the forward-only score path INCLUDING the
    gather (and, for an int8 table, the fused dequant gather of
    ``fm.fm_scores_dequant``) — what a compiled rung runs.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fast_tffm_tpu.models import fm
    from fast_tffm_tpu.ops import interaction

    b, feat, dim = int(batch), cfg.max_features, cfg.embedding_dim
    rng = np.random.default_rng(0xA070)
    vals = jnp.asarray(rng.uniform(0.1, 1.0, (b, feat)).astype(np.float32))

    if context == "train":
        rows = jnp.asarray(
            rng.uniform(-0.1, 0.1, (b, feat, dim)).astype(np.float32)
        )

        def make(user_impl):
            impl = INTERNAL[user_impl]

            def f(r, v):
                scores = interaction.fm_interaction(r, v, impl)
                grads = jax.grad(
                    lambda rr: jnp.sum(interaction.fm_interaction(rr, v, impl))
                )(r)
                return scores, grads

            return jax.jit(f)

        return make, (rows, vals)

    # serve: gather + score over a representative table slice (capped —
    # gather cost scales with the batch, not the vocabulary).
    vocab = min(cfg.vocabulary_size, 1 << 14)
    table = rng.uniform(-0.1, 0.1, (vocab, dim)).astype(np.float32)
    ids = jnp.asarray(
        rng.integers(0, vocab, (b, feat)).astype(np.int32)
    )
    w0 = jnp.float32(0.0)

    if table_dtype == "int8":
        from fast_tffm_tpu.ops import quant

        qt = quant.quantize_table(table, "int8", cfg.quant_chunk)
        codes = jnp.asarray(qt.codes)
        scales = jnp.asarray(qt.scales, jnp.float32)
        chunk = int(qt.chunk)

        def make(user_impl):
            impl = INTERNAL[user_impl]
            impl = None if impl == "jnp" else impl

            def f(i, v):
                return fm.fm_scores_dequant(
                    w0, codes, scales, chunk, i, v, None,
                    factor_num=cfg.factor_num, field_num=0, impl=impl,
                )

            return jax.jit(f)

        return make, (ids, vals)

    tbl = jnp.asarray(
        table, jnp.bfloat16 if table_dtype == "bf16" else jnp.float32
    )
    params = fm.FmParams(w0=w0, table=tbl)

    def make(user_impl):
        impl = INTERNAL[user_impl]
        impl = None if impl == "jnp" else impl

        def f(i, v):
            return fm.fm_scores(
                params, i, v, None,
                factor_num=cfg.factor_num, field_num=0, impl=impl,
            )

        return jax.jit(f)

    return make, (ids, vals)


def _flat_outputs(out):
    import jax

    return [x for x in jax.tree_util.tree_leaves(out)]


def _parity_error(out, ref_out) -> float:
    """Max element-wise |candidate - reference| over every output,
    relative to max(1, |reference|_max)."""
    import numpy as np

    worst = 0.0
    for a, b in zip(_flat_outputs(out), _flat_outputs(ref_out)):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        scale = max(1.0, float(np.max(np.abs(b))) if b.size else 1.0)
        worst = max(worst, float(np.max(np.abs(a - b))) / scale)
    return worst


def _time_ms(fn, args, reps: int = 3, inner: int = 5) -> float:
    """Best-of-``reps`` mean wall time per call (ms), post-compile."""
    import jax

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = None
        for _ in range(inner):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / inner)
    return best * 1000.0


def _measure(cfg, context: str, batch: int, table_dtype: str,
             candidates, candidate_fns=None):
    """Benchmark every candidate at the run's shapes; returns
    (winner_user_name, times_ms, parity_err).  Reference is always the
    parity oracle and always survives the gate."""
    global _MEASUREMENTS
    import jax

    if candidate_fns is None:
        make, args = _candidate_fns(cfg, context, batch, table_dtype)
    else:
        make, args = candidate_fns
    names = list(candidates)
    if "reference" not in names:
        names.insert(0, "reference")
    ref_fn = make("reference")
    ref_out = ref_fn(*args)
    jax.block_until_ready(ref_out)
    times_ms: dict = {}
    parity: dict = {}
    survivors = []
    for name in names:
        fn = ref_fn if name == "reference" else make(name)
        try:
            out = fn(*args)
            jax.block_until_ready(out)
        except Exception as e:  # noqa: BLE001 - a broken candidate loses
            log.warning("autotune candidate %s failed to run (%s: %s); "
                        "excluded", name, type(e).__name__, e)
            parity[name] = float("inf")
            continue
        _MEASUREMENTS += 1
        err = 0.0 if name == "reference" else _parity_error(out, ref_out)
        parity[name] = round(err, 9)
        if err > PARITY_TOL:
            log.warning(
                "autotune candidate %s FAILED the parity gate "
                "(err %.3g > %.3g) and is excluded from selection",
                name, err, PARITY_TOL,
            )
            continue
        times_ms[name] = round(_time_ms(fn, args), 4)
        survivors.append(name)
    winner = min(survivors, key=lambda n: times_ms[n])
    return winner, times_ms, parity


# ------------------------------------------------------------- resolve


def resolve(cfg, *, context: str = "train", batch: int | None = None,
            writer=None, cache_path: str | None = None,
            candidates=None, table_dtype: str | None = None,
            jax_version: str | None = None,
            candidate_fns=None) -> Decision:
    """Map ``cfg.interaction_impl`` to a concrete implementation.

    Pins and the legacy surface never measure.  ``auto`` measures only
    when the candidate set has more than one entry AND the persistent
    cache has no valid entry for this exact key.  ``writer`` (a JSONL
    writer) gets one ``record: autotune`` entry per decision.

    ``candidates`` / ``candidate_fns`` / ``jax_version`` exist for
    tests and the offline CLI: forcing a multi-candidate measurement on
    CPU, injecting a deliberately-wrong candidate at the parity gate,
    and exercising key drift without a jax upgrade.
    """
    import jax

    knob = cfg.interaction_impl
    if batch is None:
        batch = cfg.batch_size
    if table_dtype is None:
        table_dtype = (
            cfg.serve_table_dtype if context == "serve" else "fp32"
        )
    if knob in ("reference", "pallas", "packed"):
        d = Decision(impl=knob, interaction=INTERNAL[knob],
                     source="pinned", context=context, key="")
    elif knob != "auto":  # "" — the legacy interaction/use_pallas surface
        internal = cfg.interaction_resolved
        d = Decision(impl=USER.get(internal, "reference"),
                     interaction=internal, source="legacy",
                     context=context, key="")
    else:
        cands = tuple(
            candidates if candidates is not None
            else default_candidates(cfg.field_num)
        )
        key = cache_key(
            context, jax.default_backend(), batch, cfg.max_features,
            cfg.embedding_dim, cfg.field_num, table_dtype,
            cfg.compute_dtype, jax_version,
        )
        if cache_path is None:
            cache_path = default_cache_path(cfg)
        if len(cands) == 1:
            d = Decision(impl=cands[0], interaction=INTERNAL[cands[0]],
                         source="single_candidate", context=context,
                         key=key, candidates=cands,
                         cache_file=cache_path)
        else:
            entries = dict(_MEM_CACHE)
            entries.update(load_cache(cache_path))
            hit = entries.get(key)
            if (
                isinstance(hit, dict)
                and hit.get("impl") in INTERNAL
                and tuple(hit.get("candidates", ())) == cands
            ):
                d = Decision(
                    impl=hit["impl"], interaction=INTERNAL[hit["impl"]],
                    source="cache", context=context, key=key,
                    candidates=cands,
                    times_ms=dict(hit.get("times_ms") or {}),
                    parity_err=dict(hit.get("parity_err") or {}),
                    cache_file=cache_path,
                )
            else:
                winner, times_ms, parity = _measure(
                    cfg, context, batch, table_dtype, cands,
                    candidate_fns=candidate_fns,
                )
                d = Decision(
                    impl=winner, interaction=INTERNAL[winner],
                    source="measured", context=context, key=key,
                    candidates=cands, times_ms=times_ms,
                    parity_err=parity, cache_file=cache_path,
                )
                entry = {
                    "impl": winner, "candidates": list(cands),
                    "times_ms": times_ms, "parity_err": parity,
                    "written": time.time(),
                }
                _MEM_CACHE[key] = entry
                entries[key] = entry
                save_cache(cache_path, entries)
    log.info(
        "autotune[%s]: interaction_impl=%s -> %s (%s)%s",
        context, knob or "<legacy>", d.impl, d.source,
        f" times_ms={d.times_ms}" if d.times_ms else "",
    )
    if writer is not None:
        write_record(writer, d)
    return d


def write_record(writer, d: Decision) -> None:
    """One ``record: autotune`` JSONL entry per decision — the
    observability contract OBSERVABILITY.md's record schema pins."""
    try:
        writer.write({
            "record": "autotune",
            "time": time.time(),
            "impl": d.impl,
            "source": d.source,
            "context": d.context,
            "key": d.key,
            "candidates": list(d.candidates),
            "times_ms": d.times_ms,
            "parity_err": d.parity_err,
        })
    except Exception as e:  # noqa: BLE001 - never kill a run over a record
        log.warning("autotune record write failed: %s", e)
