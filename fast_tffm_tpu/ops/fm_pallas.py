"""Pallas TPU kernels for the FM interaction — the FmScorer/FmGrad rebuild.

The reference computes the 2nd-order FM score and its gradient in custom
C++/CUDA ops (SURVEY.md §2 #2-3, §3.4).  Here both are fused Pallas TPU
kernels over the *gathered* table rows:

  forward:  rows [B,F,D], vals [B,F] -> scores [B]   (saves s1 [B,K])
  backward: rows, vals, s1, dscores  -> per-occurrence row grads [B,F,D]

The gather itself (``table[ids]``) and the scatter-add of row grads stay
outside (XLA gather / ops.sparse_apply) while these kernels fuse all the
elementwise/reduction math so the [B,F,K] ``xv`` intermediates never touch
HBM.

Layout: the naive [TB, F, D] block tiles D (e.g. 9) onto the 128-lane
minor dimension — a 14x VMEM/VPU waste that OOMs scoped VMEM at B=16k.
Instead rows enter *flattened* as [B, F*D] (a free bitcast of the gather
output), whose minor dim (~F*D = 351 -> 384) tiles at ~91% utilization.
The per-feature reductions that the 3-D layout got "for free" become tiny
one-hot MXU matmuls with iota-built selection matrices:

  xe  = x @ R        R[f, f*D+j] = 1      broadcast x_f across its row slot
  y   = rows * xe                         y[b, f*D+j] = row-elem * x_f
  S   = y @ M        M[c, c mod D] = 1    S[:,0] = linear, S[:,1+k] = s1_k
  S2  = (y*y) @ M                         S2[:,1+k] = s2_k
  score = S[:,0] + 0.5 * sum_k (S[:,1+k]^2 - S2[:,1+k])

Backward (closed-form FmGrad, SURVEY.md §3.4), same layout:

  s1e = [1|s1] @ Mt  Mt[j, f*D+j] = 1     broadcast s1_k across features
  drows = (g * xe) * (s1e - y * maskv)    maskv kills the j=0 (w) column
  (j=0: g*x_f;  j=1+k: g*x_f*(s1_k - v*x_f))

One-hot matmuls run as two-pass bf16 hi/lo splits (~f32 precision, exact
0/1 lhs).  All selection matrices are built in-kernel from iota compares.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _block_b(batch: int, bytes_per_row: int) -> int:
    """Largest sublane-aligned divisor of ``batch`` whose double-buffered
    blocks stay well under the ~16MB scoped-VMEM limit."""
    budget = 6 * 1024 * 1024
    divisors = sorted(
        (tb for tb in range(1, min(batch, 2048) + 1) if batch % tb == 0),
        reverse=True,
    )
    for tb in divisors:
        if tb % 8 == 0 and 2 * 3 * tb * bytes_per_row <= budget:
            return tb
    for tb in divisors:
        if 2 * 3 * tb * bytes_per_row <= budget:
            return tb
    return divisors[-1]


def _pad128(n: int) -> int:
    return -(-n // 128) * 128


def _r_matrix(f: int, d: int):
    """R[f, f*D+j] = 1: broadcasts per-feature x into its D row slots."""
    fd = f * d
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (f, fd), 1)
    f_iota = jax.lax.broadcasted_iota(jnp.int32, (f, fd), 0)
    return (c_iota // d == f_iota).astype(jnp.bfloat16)  # [F, FD]


def _m_matrix(f: int, d: int):
    """M[c, c mod D] = 1: sums row slot j across features."""
    fd = f * d
    cm_iota = jax.lax.broadcasted_iota(jnp.int32, (fd, d), 0)
    j_iota = jax.lax.broadcasted_iota(jnp.int32, (fd, d), 1)
    return (cm_iota % d == j_iota).astype(jnp.bfloat16)  # [FD, D]


def _dot_f32_rhs(a_f32, b_bf16, *, nsplit: int = 3):
    """f32-lhs x bf16-0/1-rhs matmul at (up to) f32 precision.

    Three-term bf16 split (hi + mid + lo covers ~24 mantissa bits): the
    score's s1^2 - s2 cancellation amplifies relative error, so the
    two-term split's ~2^-17 is not enough here.  Three small bf16 matmuls
    are still negligible next to the kernel's HBM traffic.

    ``nsplit=1`` is for bf16-input mode: when the values came in as bf16
    the hi term already carries every bit, so the mid/lo matmuls would
    multiply exact zeros.
    """
    a_hi = a_f32.astype(jnp.bfloat16)
    out = jax.lax.dot(a_hi, b_bf16, preferred_element_type=jnp.float32)
    if nsplit == 1:
        return out
    r1 = a_f32 - a_hi.astype(jnp.float32)
    a_mid = r1.astype(jnp.bfloat16)
    a_lo = (r1 - a_mid.astype(jnp.float32)).astype(jnp.bfloat16)
    return (
        out
        + jax.lax.dot(a_mid, b_bf16, preferred_element_type=jnp.float32)
        + jax.lax.dot(a_lo, b_bf16, preferred_element_type=jnp.float32)
    )


def _fwd_kernel(rows_ref, vals_ref, score_ref, s1_ref, *, f, d, nsplit):
    # bf16-input mode: blocks arrive bf16 (half the HBM traffic of the
    # kernel's dominant stream) and compute upcasts to f32 — accumulation
    # precision is unchanged, only the stored rows/vals are rounded.
    rows = rows_ref[...].astype(jnp.float32)  # [TB, FD]
    vals = vals_ref[...].astype(jnp.float32)  # [TB, F]
    r_mat, m_mat = _r_matrix(f, d), _m_matrix(f, d)
    xe = _dot_f32_rhs(vals, r_mat, nsplit=nsplit)  # one term per column
    y = rows * xe
    s = _dot_f32_rhs(y, m_mat)  # [TB, D]: linear | s1
    s2 = _dot_f32_rhs(y * y, m_mat)  # [TB, D]: _ | s2
    s1 = s[:, 1:]
    inter = 0.5 * jnp.sum(s1 * s1 - s2[:, 1:], axis=-1, keepdims=True)
    score_ref[...] = s[:, 0:1] + inter  # [TB, 1]
    s1_ref[...] = s1


def _bwd_kernel(rows_ref, vals_ref, s1_ref, g_ref, drows_ref, *, f, d,
                nsplit):
    rows = rows_ref[...].astype(jnp.float32)  # [TB, FD]
    vals = vals_ref[...].astype(jnp.float32)  # [TB, F]
    s1 = s1_ref[...]  # [TB, K] f32 (saved residual)
    g = g_ref[...]  # [TB, 1] f32
    fd = f * d
    xe = _dot_f32_rhs(vals, _r_matrix(f, d), nsplit=nsplit)
    y = rows * xe
    ones = jnp.ones((s1.shape[0], 1), jnp.float32)
    u = jnp.concatenate([ones, s1], axis=1)  # [TB, D]
    # Mt[j, f*D+j] = 1, built directly (no in-kernel transpose of m_mat).
    j_iota = jax.lax.broadcasted_iota(jnp.int32, (d, fd), 0)
    cj_iota = jax.lax.broadcasted_iota(jnp.int32, (d, fd), 1)
    mt_mat = (cj_iota % d == j_iota).astype(jnp.bfloat16)  # [D, FD]
    s1e = _dot_f32_rhs(u, mt_mat)  # [TB, FD]; one term per column
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (1, fd), 1)
    maskv = (c_iota % d != 0).astype(jnp.float32)  # kill w column in y
    drows = (g * xe) * (s1e - y * maskv)
    drows_ref[...] = drows.astype(drows_ref.dtype)  # bf16 out in bf16 mode


def _pad_batch(b: int) -> int:
    """Round B up to a multiple of 128.  ``_block_b`` picks tile sizes from
    the divisors of B, so a prime or non-8-multiple batch would silently
    degenerate to 1-row blocks (a B-step grid); padding guarantees
    sublane-aligned divisors at a cost of <128 wasted rows."""
    return -(-b // 128) * 128


@functools.partial(jax.jit, static_argnames=("interpret",))
def fm_scores_pallas(rows: jax.Array, vals: jax.Array, interpret: bool = False):
    """Forward: (scores [B], s1 [B, K]) from gathered rows [B, F, D]."""
    b, f, d = rows.shape
    fd = f * d
    rows2 = rows.reshape(b, fd)  # free bitcast: same dense layout
    bp = _pad_batch(b)
    if bp != b:
        rows2 = jnp.pad(rows2, ((0, bp - b), (0, 0)))
        vals = jnp.pad(vals, ((0, bp - b), (0, 0)))
    bytes_per_row = 4 * (2 * _pad128(fd) + _pad128(f))
    tb = _block_b(bp, bytes_per_row)
    grid = (bp // tb,)
    nsplit = 1 if rows.dtype == jnp.bfloat16 else 3
    scores, s1 = pl.pallas_call(
        functools.partial(_fwd_kernel, f=f, d=d, nsplit=nsplit),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tb, fd), lambda i: (i, 0)),
            pl.BlockSpec((tb, f), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tb, 1), lambda i: (i, 0)),
            pl.BlockSpec((tb, d - 1), lambda i: (i, 0)),
        ],
        # Scores and the s1 residual stay f32 even in bf16-input mode:
        # the loss and the backward's s1 broadcast want full precision.
        out_shape=[
            jax.ShapeDtypeStruct((bp, 1), jnp.float32),
            jax.ShapeDtypeStruct((bp, d - 1), jnp.float32),
        ],
        interpret=interpret,
    )(rows2, vals)
    return scores[:b, 0], s1[:b]


@functools.partial(jax.jit, static_argnames=("interpret",))
def fm_grad_pallas(
    rows: jax.Array,
    vals: jax.Array,
    s1: jax.Array,
    dscores: jax.Array,
    interpret: bool = False,
):
    """Backward: per-occurrence row grads [B, F, D]."""
    b, f, d = rows.shape
    fd = f * d
    rows2 = rows.reshape(b, fd)
    dscores2 = dscores[:, None]
    bp = _pad_batch(b)
    if bp != b:
        rows2 = jnp.pad(rows2, ((0, bp - b), (0, 0)))
        vals = jnp.pad(vals, ((0, bp - b), (0, 0)))
        s1 = jnp.pad(s1, ((0, bp - b), (0, 0)))
        dscores2 = jnp.pad(dscores2, ((0, bp - b), (0, 0)))
    bytes_per_row = 4 * (3 * _pad128(fd) + _pad128(f))
    tb = _block_b(bp, bytes_per_row)
    grid = (bp // tb,)
    nsplit = 1 if rows.dtype == jnp.bfloat16 else 3
    drows = pl.pallas_call(
        functools.partial(_bwd_kernel, f=f, d=d, nsplit=nsplit),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tb, fd), lambda i: (i, 0)),
            pl.BlockSpec((tb, f), lambda i: (i, 0)),
            pl.BlockSpec((tb, d - 1), lambda i: (i, 0)),
            pl.BlockSpec((tb, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((tb, fd), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((bp, fd), rows.dtype),
        interpret=interpret,
    )(rows2, vals, s1, dscores2)
    return drows[:b].reshape(b, f, d)
