"""Pallas TPU kernels for the FM interaction — the FmScorer/FmGrad rebuild.

The reference computes the 2nd-order FM score and its gradient in custom
C++/CUDA ops (SURVEY.md §2 #2-3, §3.4).  Here both are fused Pallas TPU
kernels over the *gathered* table rows:

  forward:  rows [B,F,D], vals [B,F] -> scores [B]   (saves s1 [B,K])
  backward: rows, vals, s1, dscores  -> per-occurrence row grads [B,F,D]

The gather itself (``table[ids]``) and the scatter-add of row grads stay in
XLA — its gather/scatter paths are the fast ones on TPU — while these
kernels fuse all the elementwise/reduction math so the [B,F,K] ``xv``
intermediates never touch HBM.

Closed-form backward (SURVEY.md §3.4):
  dV[b,f,k] = g_b * x_bf * (s1[b,k] - V[b,f,k]*x_bf)
  dw[b,f]   = g_b * x_bf
  dw0       = sum_b g_b            (computed by the caller)

Both kernels are pure VPU work (no MXU): the op is bandwidth-bound, so the
win is fusion, not FLOPs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _padded_bytes(shape: tuple[int, ...], itemsize: int = 4) -> int:
    """VMEM footprint of one block: last two dims tile-pad to (8, 128)."""
    if len(shape) < 2:
        return itemsize * max(shape[0], 1) * 128
    dims = list(shape)
    dims[-2] = -(-dims[-2] // 8) * 8
    dims[-1] = -(-dims[-1] // 128) * 128
    n = 1
    for d in dims:
        n *= d
    return n * itemsize


def _block_b(batch: int, f: int, d: int, n_bufs: int) -> int:
    """Batch-tile size: keep double-buffered padded blocks under the
    ~16MB scoped-VMEM limit (with headroom), sublane-aligned.

    ``n_bufs`` counts the [TB, F, D]-shaped blocks in flight (the [TB, F]
    and [TB, K] blocks are small by comparison but included via the +1).
    """
    budget = 6 * 1024 * 1024  # conservative vs the 16MB scoped-VMEM limit

    def fits(tb: int) -> bool:
        per_block = (n_bufs + 1) * _padded_bytes((tb, f, d))
        return 2 * per_block <= budget  # x2 for double buffering

    divisors = sorted(
        (tb for tb in range(1, min(batch, 1024) + 1) if batch % tb == 0),
        reverse=True,
    )
    for tb in divisors:  # largest sublane-aligned divisor within budget
        if tb % 8 == 0 and fits(tb):
            return tb
    for tb in divisors:  # any divisor within budget
        if fits(tb):
            return tb
    return divisors[-1]


def _fwd_kernel(rows_ref, vals_ref, score_ref, s1_ref):
    rows = rows_ref[:]  # [TB, F, D]
    vals = vals_ref[:]  # [TB, F]
    w = rows[:, :, 0]
    v = rows[:, :, 1:]
    xv = v * vals[:, :, None]  # [TB, F, K]
    s1 = jnp.sum(xv, axis=1)  # [TB, K]
    s2 = jnp.sum(xv * xv, axis=1)
    linear = jnp.sum(w * vals, axis=1)  # [TB]
    inter = 0.5 * jnp.sum(s1 * s1 - s2, axis=-1)
    score_ref[:] = (linear + inter)[:, None]  # [TB, 1]
    s1_ref[:] = s1


def _bwd_kernel(rows_ref, vals_ref, s1_ref, g_ref, drows_ref):
    rows = rows_ref[:]  # [TB, F, D]
    vals = vals_ref[:]  # [TB, F]
    s1 = s1_ref[:]  # [TB, K]
    g = g_ref[:]  # [TB, 1]
    v = rows[:, :, 1:]
    gx = g * vals  # [TB, F]
    dv = gx[:, :, None] * (s1[:, None, :] - v * vals[:, :, None])  # [TB,F,K]
    dw = gx[:, :, None]  # [TB, F, 1]
    drows_ref[:] = jnp.concatenate([dw, dv], axis=-1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fm_scores_pallas(rows: jax.Array, vals: jax.Array, interpret: bool = False):
    """Forward: (scores [B], s1 [B, K]) from gathered rows."""
    b, f, d = rows.shape
    tb = _block_b(b, f, d, n_bufs=1)
    grid = (b // tb,)
    scores, s1 = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tb, f, d), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tb, f), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tb, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tb, d - 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, 1), rows.dtype),
            jax.ShapeDtypeStruct((b, d - 1), rows.dtype),
        ],
        interpret=interpret,
    )(rows, vals)
    return scores[:, 0], s1


@functools.partial(jax.jit, static_argnames=("interpret",))
def fm_grad_pallas(
    rows: jax.Array,
    vals: jax.Array,
    s1: jax.Array,
    dscores: jax.Array,
    interpret: bool = False,
):
    """Backward: per-occurrence row grads [B, F, D]."""
    b, f, d = rows.shape
    tb = _block_b(b, f, d, n_bufs=2)
    grid = (b // tb,)
    return pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tb, f, d), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tb, f), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tb, d - 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tb, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tb, f, d), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, f, d), rows.dtype),
        interpret=interpret,
    )(rows, vals, s1, dscores[:, None])
