from fast_tffm_tpu.ops.interaction import fm_interaction  # noqa: F401
