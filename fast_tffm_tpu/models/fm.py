"""FM / field-aware FM model core — the pure-jnp oracle.

Numeric spec (reference ``FmScorer``, SURVEY.md §3.4):

    score_e = w0 + sum_i w[i]*x_i
                 + 0.5 * sum_f [ (sum_i V[i,f]*x_i)^2 - sum_i V[i,f]^2*x_i^2 ]

The parameter store is ONE table ``[vocab, D]`` whose column 0 is the linear
weight and columns 1: the factor vector(s) — mirroring the reference's
combined bias+factor rows (SURVEY.md §2 #5) and giving a single gather per
batch.  For field-aware FM (BASELINE config 5) ``D = 1 + field_num*k`` and
the interaction uses per-field factors ``<v_{i,f_j}, v_{j,f_i}>``.

Everything here is jit-friendly: static shapes, no Python branching on traced
values.  Padded feature slots carry ``val == 0`` and thus contribute nothing
to the score or its gradient.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.platform import ensure_sharding_invariant_rng

# Any module that can init a table imports this one; pin the RNG mode
# here so a sharded init is element-wise identical on every mesh shape
# (the `[4-2]` mixed-mesh parity fix — see platform.py for the story).
ensure_sharding_invariant_rng()


class FmParams(NamedTuple):
    w0: jax.Array  # [] global bias
    table: jax.Array  # [vocab, 1 + k] or [vocab, 1 + field_num*k]


def init_params(rng: jax.Array, cfg: FmConfig, dtype=jnp.float32) -> FmParams:
    """Uniform init in ±init_value_range (reference behavior, SURVEY.md §2 #5)."""
    table = jax.random.uniform(
        rng,
        (cfg.vocabulary_size, cfg.embedding_dim),
        dtype=dtype,
        minval=-cfg.init_value_range,
        maxval=cfg.init_value_range,
    )
    return FmParams(w0=jnp.zeros((), dtype), table=table)


def interaction_terms(
    rows: jax.Array,  # [B, F, 1+k] gathered table rows
    vals: jax.Array,  # [B, F]
    compute_dtype=jnp.float32,
):
    """Per-example (linear, s1, s2) partial sums for plain FM.

    These are linear in per-feature contributions, so a row-sharded backend
    can compute them per shard and psum (SURVEY.md §7 step 4); the final
    squaring happens in :func:`scores_from_terms` after the reduction.
    """
    rows = rows.astype(compute_dtype)
    vals = vals.astype(compute_dtype)
    w = rows[..., 0]  # [B, F]
    v = rows[..., 1:]  # [B, F, k]
    # bf16 mode rounds the products; sums still accumulate in f32 (the
    # s1^2 - s2 cancellation in scores_from_terms amplifies sum error).
    linear = jnp.sum(w * vals, axis=-1, dtype=jnp.float32)  # [B]
    xv = v * vals[..., None]  # [B, F, k]
    s1 = jnp.sum(xv, axis=1, dtype=jnp.float32)  # [B, k]
    s2 = jnp.sum(xv * xv, axis=1, dtype=jnp.float32)  # [B, k]
    return linear, s1, s2


def scores_from_terms(w0, linear, s1, s2) -> jax.Array:
    return w0 + linear + 0.5 * jnp.sum(s1 * s1 - s2, axis=-1)


def ffm_scores_from_rows(
    w0: jax.Array,
    rows: jax.Array,  # [B, F, 1 + field_num*k]
    vals: jax.Array,  # [B, F]
    fields: jax.Array,  # [B, F] int32
    factor_num: int,
    field_num: int,
    compute_dtype=jnp.float32,
) -> jax.Array:
    """Field-aware FM: score = w0 + sum w_i x_i + sum_{i<j} <v_{i,f_j}, v_{j,f_i}> x_i x_j.

    MXU-friendly field-grouped form (no per-example gathers): with
    S[b,p,q,:] = sum_{i: f_i = p} v_i^q * x_i (a batched one-hot matmul),

        sum_{i != j} <v_i^{f_j}, v_j^{f_i}> x_i x_j
            = sum_{p,q} <S[p,q], S[q,p]> - sum_i <v_i^{f_i}, v_i^{f_i}> x_i^2

    and the strict-upper-triangle sum is half of that.  This replaces the
    naive [B,F,F,k] pairwise tensor (a ~800MB intermediate at Criteo
    shapes, built by row gathers) with two einsum-matmuls over [B,P,P,k].
    """
    from fast_tffm_tpu.platform import ffm_compute_dtype

    # Off-TPU the einsum operands fall back to f32 (XLA:CPU cannot run
    # bf16 dots) — see platform.ffm_compute_dtype, the one copy of that
    # gate.
    compute_dtype = ffm_compute_dtype(compute_dtype)
    rows = rows.astype(compute_dtype)
    vals = vals.astype(compute_dtype)
    b, f = vals.shape
    w = rows[..., 0]
    v = rows[..., 1:].reshape(b, f, field_num, factor_num)  # [B,F,P,k]
    # bf16 mode: bf16 operands, f32 accumulation/result throughout.
    linear = jnp.sum(w * vals, axis=-1, dtype=jnp.float32)
    oh = (
        fields[..., None] == jnp.arange(field_num, dtype=fields.dtype)
    ).astype(compute_dtype)  # [B, F, P] pure field one-hot
    s = jnp.einsum(
        "bfp,bfqk->bpqk", oh * vals[..., None], v,
        preferred_element_type=jnp.float32,
    )
    cross = jnp.einsum("bpqk,bqpk->b", s, s)  # s is f32
    v_own = jnp.einsum(
        "bfq,bfqk->bfk", oh, v, preferred_element_type=jnp.float32
    )  # v_i^{f_i}
    self_term = jnp.sum(
        jnp.sum(v_own * v_own, axis=-1)
        * (vals * vals).astype(jnp.float32),
        axis=-1,
    )
    inter = 0.5 * (cross - self_term)
    return (w0 + linear + inter).astype(jnp.float32)


def scores_from_rows(
    w0: jax.Array,
    rows: jax.Array,  # [B, F, D] gathered (and, if needed, dequantized)
    vals: jax.Array,  # [B, F]
    fields: Optional[jax.Array],
    *,
    factor_num: int,
    field_num: int = 0,
    compute_dtype=jnp.float32,
    impl: Optional[str] = None,
) -> jax.Array:
    """Score from pre-gathered rows — the shared tail of the fp32 and
    quantized forwards (plain FM and FFM both).  ``rows`` may arrive
    in any storage dtype (f32, bf16, or int8 already widened by
    ops.quant.dequant_gathered): both score paths upcast operands to
    the compute dtype and accumulate in f32.

    ``impl`` routes the plain-FM interaction through an alternative
    ops.interaction formulation ("pallas" | "flat") — the autotuner's
    serving-side promotion hook (parity-gated against this reference
    path by ops.autotune).  None/"jnp" is the reference math; FFM
    always uses its closed-form path regardless.
    """
    if field_num:
        assert fields is not None
        return ffm_scores_from_rows(
            w0, rows, vals, fields, factor_num, field_num, compute_dtype
        )
    if impl not in (None, "", "jnp"):
        from fast_tffm_tpu.ops import interaction as interaction_ops

        scores, _ = interaction_ops._forward(
            rows.astype(compute_dtype), vals.astype(compute_dtype), impl
        )
        return w0.astype(jnp.float32) + scores
    linear, s1, s2 = interaction_terms(rows, vals, compute_dtype)
    return scores_from_terms(w0.astype(compute_dtype), linear, s1, s2)


def fm_scores(
    params: FmParams,
    ids: jax.Array,  # [B, F] int32
    vals: jax.Array,  # [B, F] float32
    fields: Optional[jax.Array] = None,
    *,
    factor_num: int,
    field_num: int = 0,
    compute_dtype=jnp.float32,
    impl: Optional[str] = None,
) -> jax.Array:
    """Oracle forward: gather + score. One `take` = one gather op for XLA.

    ``params.table`` may be stored bf16 (the compact serving format):
    the gather reads compact rows and :func:`scores_from_rows` widens
    them in-register — XLA fuses the cast into the gather.  ``impl``
    passes through to :func:`scores_from_rows` (the autotuner's
    serving-side routing; None = reference).
    """
    rows = params.table[ids]  # [B, F, D]
    return scores_from_rows(
        params.w0, rows, vals, fields,
        factor_num=factor_num, field_num=field_num,
        compute_dtype=compute_dtype, impl=impl,
    )


def fm_scores_dequant(
    w0: jax.Array,
    codes: jax.Array,  # [V, D] int8 table codes
    scales: jax.Array,  # [ceil(V/chunk)] f32 scale chunks
    chunk: int,
    ids: jax.Array,  # [B, F] int32
    vals: jax.Array,  # [B, F] float32
    fields: Optional[jax.Array] = None,
    *,
    factor_num: int,
    field_num: int = 0,
    compute_dtype=jnp.float32,
    impl: Optional[str] = None,
) -> jax.Array:
    """Forward over an int8-quantized table: gather compact codes (a
    quarter of the fp32 row bytes) plus each row's scale chunk, widen
    in-register (ops.quant.dequant_gathered), score.  Identical math
    to :func:`fm_scores` on the dequantized table, pinned by
    tests/test_quant.py.  ``impl`` passes through to
    :func:`scores_from_rows` (autotuner routing; None = reference)."""
    from fast_tffm_tpu.ops import quant

    code_rows = codes[ids]  # [B, F, D] int8
    scale_rows = scales[ids // chunk if chunk > 1 else ids]
    rows = quant.dequant_gathered(code_rows, scale_rows)
    return scores_from_rows(
        w0, rows, vals, fields,
        factor_num=factor_num, field_num=field_num,
        compute_dtype=compute_dtype, impl=impl,
    )


def example_losses(scores: jax.Array, labels: jax.Array, loss_type: str) -> jax.Array:
    if loss_type == "logistic":
        # Numerically stable BCE-with-logits (labels in {0,1}).
        return jax.nn.softplus(scores) - labels * scores
    elif loss_type == "mse":
        d = scores - labels
        return d * d
    raise ValueError(f"unknown loss_type {loss_type!r}")


def l2_penalty_batch(
    params: FmParams,
    rows: jax.Array,  # [B, F, D] the rows this batch touched
    vals: jax.Array,  # [B, F] (0 marks padding)
    factor_lambda: float,
    bias_lambda: float,
) -> jax.Array:
    """Sparse-friendly L2: regularize only rows touched by the batch.

    The reference's dense full-table ``tf.nn.l2_loss`` would make every update
    dense — unaffordable for a row-sharded 1e9-row table — so the default
    regularizes per occurrence, normalized by batch size.  ``l2_mode=full``
    in the config selects the exact dense penalty instead.
    """
    mask = (vals != 0).astype(rows.dtype)[..., None]  # [B, F, 1]
    b = vals.shape[0]
    w_sq = jnp.sum((rows[..., :1] * mask) ** 2)
    v_sq = jnp.sum((rows[..., 1:] * mask) ** 2)
    return (factor_lambda * v_sq + bias_lambda * (w_sq + params.w0**2)) / b


def l2_penalty_full(
    params: FmParams, factor_lambda: float, bias_lambda: float
) -> jax.Array:
    w_sq = jnp.sum(params.table[:, 0] ** 2)
    v_sq = jnp.sum(params.table[:, 1:] ** 2)
    return factor_lambda * v_sq + bias_lambda * (w_sq + params.w0**2)


def loss_and_metrics(
    params: FmParams,
    labels: jax.Array,
    ids: jax.Array,
    vals: jax.Array,
    fields: Optional[jax.Array],
    weights: jax.Array,
    cfg: FmConfig,
    compute_dtype=jnp.float32,
):
    """Weighted training loss (+L2) and unregularized metrics.

    Padded examples carry weight 0 and drop out of both loss and metrics.
    Returns ``(loss, aux)`` for ``jax.value_and_grad(..., has_aux=True)``.
    """
    rows = params.table[ids]
    scores = scores_from_rows(
        params.w0, rows, vals, fields,
        factor_num=cfg.factor_num, field_num=cfg.field_num,
        compute_dtype=compute_dtype,
    )
    # scores are f32 regardless of compute_dtype (both score paths
    # accumulate and return f32), so loss/metrics math stays f32.
    per_ex = example_losses(scores, labels, cfg.loss_type)
    wsum = jnp.maximum(jnp.sum(weights), 1e-12)
    data_loss = jnp.sum(per_ex * weights) / wsum
    if cfg.factor_lambda or cfg.bias_lambda:
        if cfg.l2_mode == "full":
            reg = l2_penalty_full(params, cfg.factor_lambda, cfg.bias_lambda)
        else:
            reg = l2_penalty_batch(
                params, rows, vals, cfg.factor_lambda, cfg.bias_lambda
            )
    else:
        reg = jnp.zeros((), jnp.float32)
    loss = data_loss + reg
    aux = {
        "data_loss": data_loss,
        "reg": reg,
        "scores": scores,
        "weight_sum": jnp.sum(weights),
    }
    return loss, aux
