from fast_tffm_tpu.models.fm import (  # noqa: F401
    FmParams,
    fm_scores,
    init_params,
    loss_and_metrics,
)
