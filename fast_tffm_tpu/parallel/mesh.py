"""Device mesh + sharding layout — the GSPMD replacement for the PS runtime.

The reference spreads its ``vocabulary_block_num`` table blocks across
parameter-server tasks and replicates workers (SURVEY.md §2 #5, #10).  Here
the same two axes become one 2-D ``jax.sharding.Mesh``:

- ``data``  — batch dimension (sync data parallelism; replaces async
  between-graph worker replication),
- ``model`` — table rows (replaces PS block partitioning).

All cross-chip traffic is XLA collectives over ICI/DCN inserted by GSPMD
from these shardings; there is no user-visible comms API (SURVEY.md §2
"Distributed communication backend").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models.fm import FmParams

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    cfg: FmConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the (data, model) mesh.

    ``mesh_data``/``mesh_model`` come from the config; if both are 1 and
    several devices are visible, all devices go to the data axis (pure DP),
    matching the reference default of one PS "block" per worker set.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    d, m = cfg.mesh_data, cfg.mesh_model
    if d * m == 1 and n > 1:
        d, m = n, 1
    if d * m > n:
        raise ValueError(f"mesh {d}x{m} needs {d * m} devices, have {n}")
    grid = np.array(devices[: d * m]).reshape(d, m)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def param_sharding(mesh: Mesh) -> FmParams:
    """Table rows sharded over `model`, replicated over `data`."""
    return FmParams(
        w0=NamedSharding(mesh, P()),
        table=NamedSharding(mesh, P(MODEL_AXIS, None)),
    )


def batch_sharding(mesh: Mesh):
    """Batch arrays sharded over `data`, replicated over `model`.

    Returns a dict keyed like data.libsvm.Batch fields.
    """
    ex = NamedSharding(mesh, P(DATA_AXIS))
    feat = NamedSharding(mesh, P(DATA_AXIS, None))
    return {
        "labels": ex,
        "ids": feat,
        "vals": feat,
        "fields": feat,
        "weights": ex,
    }


def shard_params(params: FmParams, mesh: Mesh) -> FmParams:
    sh = param_sharding(mesh)
    return jax.tree.map(jax.device_put, params, sh)


def shard_batch(batch, mesh: Mesh):
    sh = batch_sharding(mesh)
    return type(batch)(
        *(jax.device_put(getattr(batch, k), sh[k]) for k in batch._fields)
    )
