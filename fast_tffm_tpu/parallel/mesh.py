"""Device mesh + sharding layout — the GSPMD replacement for the PS runtime.

The reference spreads its ``vocabulary_block_num`` table blocks across
parameter-server tasks and replicates workers (SURVEY.md §2 #5, #10).  Here
the same two axes become one 2-D ``jax.sharding.Mesh``:

- ``data``  — batch dimension (sync data parallelism; replaces async
  between-graph worker replication),
- ``model`` — table rows (replaces PS block partitioning).

All cross-chip traffic is XLA collectives over ICI/DCN inserted by GSPMD
from these shardings; there is no user-visible comms API (SURVEY.md §2
"Distributed communication backend").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models.fm import FmParams

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    cfg: FmConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the (data, model) mesh.

    ``mesh_data``/``mesh_model`` come from the config; if both are 1 and
    several devices are visible, all devices go to the data axis (pure DP),
    matching the reference default of one PS "block" per worker set.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    d, m = cfg.mesh_data, cfg.mesh_model
    if d * m == 1 and n > 1:
        d, m = n, 1
    if d * m > n:
        raise ValueError(f"mesh {d}x{m} needs {d * m} devices, have {n}")
    grid = np.array(devices[: d * m]).reshape(d, m)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def param_sharding(mesh: Mesh) -> FmParams:
    """Table rows sharded over `model`, replicated over `data`."""
    return FmParams(
        w0=NamedSharding(mesh, P()),
        table=NamedSharding(mesh, P(MODEL_AXIS, None)),
    )


def batch_sharding(mesh: Mesh):
    """Batch arrays sharded over `data`, replicated over `model`.

    Returns a dict keyed like data.libsvm.Batch fields.
    """
    ex = NamedSharding(mesh, P(DATA_AXIS))
    feat = NamedSharding(mesh, P(DATA_AXIS, None))
    return {
        "labels": ex,
        "ids": feat,
        "vals": feat,
        "fields": feat,
        "weights": ex,
    }


def super_batch_sharding(mesh: Mesh):
    """Sharding for a stacked [K, ...] super-batch: the leading scan axis
    is replicated (every device steps through all K slices), the batch
    axis behind it shards over `data` exactly like a single batch.

    Returns a dict keyed like data.libsvm.Batch fields.
    """
    ex = NamedSharding(mesh, P(None, DATA_AXIS))
    feat = NamedSharding(mesh, P(None, DATA_AXIS, None))
    return {
        "labels": ex,
        "ids": feat,
        "vals": feat,
        "fields": feat,
        "weights": ex,
    }


def shard_params(params: FmParams, mesh: Mesh) -> FmParams:
    sh = param_sharding(mesh)
    return jax.tree.map(jax.device_put, params, sh)


def data_partition(mesh: Mesh) -> tuple[int, int]:
    """This process's (block_index, num_blocks) of the data-axis partition.

    Multi-host input sharding (SURVEY.md §7 hard-part 2): each process
    parses only its own slice of the global batch, so the data axis must
    partition across processes in equal contiguous blocks — true for the
    default jax.distributed device order (devices grouped by process) and
    this module's row-major (data, model) grid.  num_blocks is the number
    of distinct data blocks; processes that share a block (model-axis-
    spanning processes) read the same input shard.
    """
    import jax

    arr = mesh.devices  # [data, model] ndarray of Devices
    pid = jax.process_index()
    mine = [
        i for i in range(arr.shape[0])
        if any(d.process_index == pid for d in arr[i])
    ]
    if not mine:
        raise ValueError("this process owns no devices on the data axis")
    k = len(mine)
    n_data = arr.shape[0]
    if mine != list(range(mine[0], mine[0] + k)) or mine[0] % k or n_data % k:
        raise ValueError(
            "data-axis rows owned by this process must form an aligned "
            f"contiguous block (got rows {mine} of {n_data}); use the "
            "default device order or reshape the mesh so each process's "
            "devices are contiguous along the data axis"
        )
    return mine[0] // k, n_data // k


def shard_batch(batch, mesh: Mesh):
    """Ship a host batch to the mesh.

    Single-process: device_put each array with its (data, model) sharding.
    Multi-process: ``batch`` holds only this process's LOCAL slice
    (global_batch / num_blocks rows); the global array is assembled with
    ``jax.make_array_from_process_local_data`` — the GSPMD replacement for
    feeding per-worker input queues (SURVEY.md §3.2), with no host ever
    materializing the global batch.
    """
    sh = batch_sharding(mesh)
    core = ("labels", "ids", "vals", "fields", "weights")
    meta = getattr(batch, "sort_meta", None)
    if jax.process_count() > 1:
        _, num_blocks = data_partition(mesh)

        def put(x, s):
            x = np.asarray(x)
            global_shape = (x.shape[0] * num_blocks,) + x.shape[1:]
            return jax.make_array_from_process_local_data(s, x, global_shape)

        # Host sort-meta describes one process's local ids; it cannot be
        # assembled into a global batch (the producer never attaches it
        # multi-process, so this is just defensive).
        return type(batch)(
            *(put(getattr(batch, k), sh[k]) for k in core), sort_meta=None
        )
    if meta is not None:
        rep = NamedSharding(mesh, P())
        meta = type(meta)(*(jax.device_put(x, rep) for x in meta))
    return type(batch)(
        *(jax.device_put(getattr(batch, k), sh[k]) for k in core),
        sort_meta=meta,
    )


def shard_super_batch(batch, mesh: Mesh):
    """Ship a stacked [K, batch, ...] super-batch to the mesh.

    Same contract as :func:`shard_batch` with a leading scan axis: the K
    axis is replicated, the batch axis shards over `data`.  Multi-process,
    ``batch`` holds this process's local slice on axis 1 and the global
    array is assembled without any host materializing the global batch.
    ``device_put`` is async, so calling this from a transfer thread
    overlaps the H2D copies with the previous super-batch's training.
    """
    sh = super_batch_sharding(mesh)
    core = ("labels", "ids", "vals", "fields", "weights")
    meta = getattr(batch, "sort_meta", None)
    if jax.process_count() > 1:
        _, num_blocks = data_partition(mesh)

        def put(x, s):
            x = np.asarray(x)
            global_shape = (
                x.shape[0], x.shape[1] * num_blocks
            ) + x.shape[2:]
            return jax.make_array_from_process_local_data(s, x, global_shape)

        # Host sort-meta is per-process-local (see shard_batch): never
        # assembled multi-process.
        return type(batch)(
            *(put(getattr(batch, k), sh[k]) for k in core), sort_meta=None
        )
    if meta is not None:
        rep = NamedSharding(mesh, P())
        meta = type(meta)(*(jax.device_put(x, rep) for x in meta))
    return type(batch)(
        *(jax.device_put(getattr(batch, k), sh[k]) for k in core),
        sort_meta=meta,
    )
