"""Device mesh + sharding layout — the GSPMD replacement for the PS runtime.

The reference spreads its ``vocabulary_block_num`` table blocks across
parameter-server tasks and replicates workers (SURVEY.md §2 #5, #10).  Here
the same two axes become one 2-D ``jax.sharding.Mesh``:

- ``data``  — batch dimension (sync data parallelism; replaces async
  between-graph worker replication),
- ``model`` — table rows (replaces PS block partitioning).

All cross-chip traffic is XLA collectives over ICI/DCN inserted by GSPMD
from these shardings; there is no user-visible comms API (SURVEY.md §2
"Distributed communication backend").
"""

from __future__ import annotations

from collections import deque
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models.fm import FmParams

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    cfg: FmConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the (data, model) mesh.

    ``mesh_data``/``mesh_model`` come from the config; if both are 1 and
    several devices are visible, all devices go to the data axis (pure DP),
    matching the reference default of one PS "block" per worker set.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    d, m = cfg.mesh_data, cfg.mesh_model
    if d * m == 1 and n > 1:
        d, m = n, 1
    if d * m > n:
        raise ValueError(f"mesh {d}x{m} needs {d * m} devices, have {n}")
    grid = np.array(devices[: d * m]).reshape(d, m)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def param_sharding(mesh: Mesh) -> FmParams:
    """Table rows sharded over `model`, replicated over `data`."""
    return FmParams(
        w0=NamedSharding(mesh, P()),
        table=NamedSharding(mesh, P(MODEL_AXIS, None)),
    )


def batch_sharding(mesh: Mesh):
    """Batch arrays sharded over `data`, replicated over `model`.

    Returns a dict keyed like data.libsvm.Batch fields.
    """
    ex = NamedSharding(mesh, P(DATA_AXIS))
    feat = NamedSharding(mesh, P(DATA_AXIS, None))
    return {
        "labels": ex,
        "ids": feat,
        "vals": feat,
        "fields": feat,
        "weights": ex,
    }


def super_batch_sharding(mesh: Mesh):
    """Sharding for a stacked [K, ...] super-batch: the leading scan axis
    is replicated (every device steps through all K slices), the batch
    axis behind it shards over `data` exactly like a single batch.

    Returns a dict keyed like data.libsvm.Batch fields.
    """
    ex = NamedSharding(mesh, P(None, DATA_AXIS))
    feat = NamedSharding(mesh, P(None, DATA_AXIS, None))
    return {
        "labels": ex,
        "ids": feat,
        "vals": feat,
        "fields": feat,
        "weights": ex,
    }


def shard_params(params: FmParams, mesh: Mesh) -> FmParams:
    sh = param_sharding(mesh)
    return jax.tree.map(jax.device_put, params, sh)


def data_partition(mesh: Mesh) -> tuple[int, int]:
    """This process's (block_index, num_blocks) of the data-axis partition.

    Multi-host input sharding (SURVEY.md §7 hard-part 2): each process
    parses only its own slice of the global batch, so the data axis must
    partition across processes in equal contiguous blocks — true for the
    default jax.distributed device order (devices grouped by process) and
    this module's row-major (data, model) grid.  num_blocks is the number
    of distinct data blocks; processes that share a block (model-axis-
    spanning processes) read the same input shard.
    """
    import jax

    arr = mesh.devices  # [data, model] ndarray of Devices
    pid = jax.process_index()
    mine = [
        i for i in range(arr.shape[0])
        if any(d.process_index == pid for d in arr[i])
    ]
    if not mine:
        raise ValueError("this process owns no devices on the data axis")
    k = len(mine)
    n_data = arr.shape[0]
    if mine != list(range(mine[0], mine[0] + k)) or mine[0] % k or n_data % k:
        raise ValueError(
            "data-axis rows owned by this process must form an aligned "
            f"contiguous block (got rows {mine} of {n_data}); use the "
            "default device order or reshape the mesh so each process's "
            "devices are contiguous along the data axis"
        )
    return mine[0] // k, n_data // k


def shard_batch(batch, mesh: Mesh):
    """Ship a host batch to the mesh.

    Single-process: device_put each array with its (data, model) sharding.
    Multi-process: ``batch`` holds only this process's LOCAL slice
    (global_batch / num_blocks rows); the global array is assembled with
    ``jax.make_array_from_process_local_data`` — the GSPMD replacement for
    feeding per-worker input queues (SURVEY.md §3.2), with no host ever
    materializing the global batch.
    """
    sh = batch_sharding(mesh)
    core = ("labels", "ids", "vals", "fields", "weights")
    meta = getattr(batch, "sort_meta", None)
    if jax.process_count() > 1:
        _, num_blocks = data_partition(mesh)

        def put(x, s):
            x = np.asarray(x)
            global_shape = (x.shape[0] * num_blocks,) + x.shape[1:]
            return jax.make_array_from_process_local_data(s, x, global_shape)

        # Host sort-meta describes one process's local ids; it cannot be
        # assembled into a global batch (the producer never attaches it
        # multi-process, so this is just defensive).
        return type(batch)(
            *(put(getattr(batch, k), sh[k]) for k in core), sort_meta=None
        )
    if meta is not None:
        rep = NamedSharding(mesh, P())
        meta = type(meta)(*(jax.device_put(x, rep) for x in meta))
    return type(batch)(
        *(jax.device_put(getattr(batch, k), sh[k]) for k in core),
        sort_meta=meta,
    )


_CORE_LEAVES = ("labels", "ids", "vals", "fields", "weights")
_ALIGN = 128  # TPU/host DMA friendly; also keeps every view offset aligned


def fused_h2d_enabled(mesh: Mesh) -> bool:
    """Whether the fused stack+H2D ship path may run on this mesh.

    Structural gates are unconditional: the fused buffer is shipped as
    one replicated flat array and carved on-device, which only matches
    the classic per-leaf sharding semantics on a single-device,
    single-process mesh.  Within those gates the default is
    TPU-only — on CPU ``device_put`` is zero-copy, so fusing buys
    nothing and costs one extra unpack dispatch — overridable for
    tests/bench via ``FAST_TFFM_FUSED_H2D`` (1 forces on, 0 forces
    off).
    """
    if mesh.size != 1 or jax.process_count() > 1:
        return False
    import os

    env = os.environ.get("FAST_TFFM_FUSED_H2D", "")
    if env == "0":
        return False
    if env == "1":
        return True
    from fast_tffm_tpu import platform

    return platform.is_tpu_backend()


class FusedShipper:
    """Stack K parsed batches and ship them device-side in ONE transfer.

    The classic transfer stage stacks K host batches into a [K, ...]
    super-batch (one np.stack per leaf) and then issues one
    ``device_put`` per leaf — 5-12 host-to-device DMAs per dispatch,
    each paying launch latency.  This path instead copies every leaf of
    every batch into a single contiguous uint8 staging buffer
    (128-byte-aligned segments), ships it with ONE ``device_put``, and
    carves the leaves back out on-device with a cached jitted unpack
    (static slice -> bitcast -> reshape; bitwise-exact, no arithmetic).
    The stack and the transfer fuse: the host-side np.stack writes land
    directly in the DMA source buffer.

    Calling the shipper returns the device Batch, or ``None`` to
    decline (empty group) — the caller falls back to the classic
    stack+put path.  ``sort_meta`` rides along iff every batch in the
    group carries it, mirroring :func:`...pipeline.stack_batches`.

    Staging buffers recycle through a small in-flight ring, blocking on
    the oldest transfer before reuse — except on CPU, where
    ``device_put`` is zero-copy (the device array ALIASES the host
    buffer) so reuse would corrupt in-flight data; there every ship
    allocates fresh.
    """

    def __init__(self, mesh: Mesh, depth: int = 2):
        self._mesh = mesh
        self._depth = max(1, depth)
        self._unpack_cache: dict = {}  # spec -> jitted unpack
        self._free: dict = {}  # total_bytes -> [np buffer, ...]
        self._inflight: deque = deque()  # (dev_buf, total_bytes, host_buf)
        self._reuse = jax.default_backend() != "cpu"
        self.ships = 0  # fused dispatches completed (observability)

    # -- spec -----------------------------------------------------------
    def _spec(self, group):
        """((name, dtype_str, per-batch shape), ...) for one group — the
        unpack cache key.  Meta leaves append after core iff present on
        every batch."""
        b = group[0]
        spec = [
            (n, str(getattr(b, n).dtype), getattr(b, n).shape)
            for n in _CORE_LEAVES
        ]
        if all(g.sort_meta is not None for g in group):
            for i, x in enumerate(b.sort_meta):
                spec.append((f"meta{i}", str(x.dtype), x.shape))
        return len(group), tuple(spec)

    @staticmethod
    def _layout(k, spec):
        """[(name, dtype, stacked shape, offset, nbytes), ...], total."""
        off = 0
        out = []
        for name, dt, shape in spec:
            dtype = np.dtype(dt)
            nbytes = int(np.prod((k,) + shape, dtype=np.int64)) * dtype.itemsize
            out.append((name, dtype, (k,) + shape, off, nbytes))
            off += -(-nbytes // _ALIGN) * _ALIGN
        return out, off

    def _unpack_fn(self, key):
        """Jitted buffer -> leaves carve for one (k, spec), cached."""
        fn = self._unpack_cache.get(key)
        if fn is not None:
            return fn
        import jax.numpy as jnp
        from jax import lax

        k, spec = key
        layout, _ = self._layout(k, spec)

        def unpack(buf):
            outs = []
            for _, dtype, shape, off, nbytes in layout:
                seg = buf[off:off + nbytes]
                jdt = jnp.dtype(dtype)
                if jdt.itemsize > 1:
                    seg = seg.reshape(-1, jdt.itemsize)
                seg = lax.bitcast_convert_type(seg, jdt)
                outs.append(seg.reshape(shape))
            return tuple(outs)

        fn = jax.jit(unpack)
        self._unpack_cache[key] = fn
        return fn

    def _acquire(self, total):
        bufs = self._free.get(total)
        if bufs:
            return bufs.pop()
        return np.empty(total, dtype=np.uint8)

    def _retire(self, dev_buf, total, host_buf):
        if not self._reuse:
            return  # CPU: dev_buf aliases host_buf; never recycle
        self._inflight.append((dev_buf, total, host_buf))
        while len(self._inflight) > self._depth:
            d, t, h = self._inflight.popleft()
            jax.block_until_ready(d)
            self._free.setdefault(t, []).append(h)

    def __call__(self, group):
        if not group:
            return None
        from fast_tffm_tpu.data import libsvm

        key = self._spec(group)
        k, spec = key
        layout, total = self._layout(k, spec)
        buf = self._acquire(total)
        n_core = len(_CORE_LEAVES)
        has_meta = len(spec) > n_core
        for i, (name, dtype, shape, off, nbytes) in enumerate(layout):
            view = buf[off:off + nbytes].view(dtype).reshape(shape)
            if i < n_core:
                cols = [getattr(b, name) for b in group]
            else:
                cols = [b.sort_meta[i - n_core] for b in group]
            if k == 1:
                np.copyto(view[0], cols[0])
            else:
                np.stack(cols, out=view)
        dev_buf = jax.device_put(buf, self._mesh.devices.flat[0])
        leaves = self._unpack_fn(key)(dev_buf)
        self._retire(dev_buf, total, buf)
        self.ships += 1
        meta = None
        if has_meta:
            meta = type(group[0].sort_meta)(*leaves[n_core:])
        return libsvm.Batch(*leaves[:n_core], sort_meta=meta)


def shard_super_batch(batch, mesh: Mesh):
    """Ship a stacked [K, batch, ...] super-batch to the mesh.

    Same contract as :func:`shard_batch` with a leading scan axis: the K
    axis is replicated, the batch axis shards over `data`.  Multi-process,
    ``batch`` holds this process's local slice on axis 1 and the global
    array is assembled without any host materializing the global batch.
    ``device_put`` is async, so calling this from a transfer thread
    overlaps the H2D copies with the previous super-batch's training.
    """
    sh = super_batch_sharding(mesh)
    core = ("labels", "ids", "vals", "fields", "weights")
    meta = getattr(batch, "sort_meta", None)
    if jax.process_count() > 1:
        _, num_blocks = data_partition(mesh)

        def put(x, s):
            x = np.asarray(x)
            global_shape = (
                x.shape[0], x.shape[1] * num_blocks
            ) + x.shape[2:]
            return jax.make_array_from_process_local_data(s, x, global_shape)

        # Host sort-meta is per-process-local (see shard_batch): never
        # assembled multi-process.
        return type(batch)(
            *(put(getattr(batch, k), sh[k]) for k in core), sort_meta=None
        )
    if meta is not None:
        rep = NamedSharding(mesh, P())
        meta = type(meta)(*(jax.device_put(x, rep) for x in meta))
    return type(batch)(
        *(jax.device_put(getattr(batch, k), sh[k]) for k in core),
        sort_meta=meta,
    )
