"""fast_tffm_tpu — a TPU-native factorization-machine training framework.

A ground-up rebuild of the capability surface of ``darlwen/fast_tffm``
(reference analysis in ``SURVEY.md``; the reference mount was unreadable, so
parity claims cite SURVEY.md sections rather than reference file:line):

- libsvm sparse CTR data loading with feature-id hashing into a fixed number
  of buckets (reference: C++ ``FmParser`` TF op, SURVEY.md §2 #1),
- 2nd-order FM forward/backward via the sum-square trick (reference:
  ``FmScorer``/``FmGrad`` C++/CUDA ops, SURVEY.md §2 #2-3) as Pallas TPU
  kernels with a pure-jnp oracle,
- a hash-bucketed embedding/factor table row-sharded over a
  ``jax.sharding.Mesh`` (reference: ``vocabulary_block_num`` partitioned
  variables on parameter servers, SURVEY.md §2 #5/#10),
- Adagrad/FTRL optimizers with split L2 (SURVEY.md §2 #7-8),
- INI-config-driven ``local_train``/``dist_train``/``predict`` entrypoints
  (SURVEY.md §2 #9-12) and Orbax checkpoint/resume.
"""

__version__ = "0.1.0"

from fast_tffm_tpu.config import FmConfig, load_config  # noqa: F401
