"""Explicit shard_map sparse train step — `lookup = shardmap`.

The GSPMD-auto path (train.sparse under jit with shardings) lets XLA pick
the collectives for ``table[ids]`` with a row-sharded table; depending on
shapes that can materialize gathered rows across shards.  This module is
the hand-laid-out alternative, exploiting FM's algebra (SURVEY.md §7 step
4, models.fm.interaction_terms docstring):

  * The per-example terms (linear, s1, s2) are SUMS of per-feature
    contributions, and each feature's contribution depends only on the row
    its id owns.  So each model shard computes partial terms from ITS rows
    and a psum over the model axis of [b, 2k+1] floats replaces the whole
    row exchange — per-step model-axis traffic is ~KB where a gathered-row
    exchange is ~MB-GB.  This is the PS architecture inverted: row owners
    compute, examples aggregate.
  * The backward is the closed-form FmGrad (SURVEY.md §3.4): dV = g*x*(s1
    - v*x) needs only the psum'd s1 plus the shard's own rows — each
    shard computes gradients for exactly the occurrences it owns, locally.
  * Updates: per-shard dense (sum g, sum g^2) deltas via ops.sparse_apply's
    K1+K-place kernels, psum'd over the data axis (the sync-DP gradient
    allreduce), then the optimizer formula applied elementwise in place.

Scope: FM and field-aware FM with the sparse row-local optimizers
(adagrad/ftrl/sgd) and batch-mode (or zero) L2.  Dense optimizers stay on
the GSPMD-auto path.

FFM uses the same inversion (BASELINE config 5): the field-grouped sums
``S[b,p,q,:] = sum_{i: f_i=p} v_i^q x_i`` are linear in per-feature
contributions, so each shard computes a partial S from ITS rows and one
psum completes it; the closed-form backward
``dv_i^q = g x_i (S[q, f_i] - [q=f_i] v_i^{f_i} x_i)`` needs only the
completed S plus the shard's own rows — no row exchange, exactly like
FM's s1.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import sparse_apply
from fast_tffm_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from fast_tffm_tpu.train.sparse import (
    ADAGRAD_EPS,
    SparseAdagradState,
    SparseFtrlState,
)


def supports_shardmap(cfg: FmConfig, mesh) -> bool:
    if cfg.optimizer not in ("adagrad", "ftrl", "sgd"):
        return False
    if cfg.l2_mode != "batch" and (cfg.factor_lambda or cfg.bias_lambda):
        return False
    model_shards = mesh.shape[MODEL_AXIS]
    return sparse_apply.supports_tile_sharded(
        cfg.vocabulary_size, cfg.optimizer, model_shards
    )


def exchange_mode(cfg: FmConfig, mesh, n_local_occ: int) -> str:
    """Resolve cfg.sparse_exchange for these static shapes.

    "dense" psums a [vocab_local, 2D] delta over the data axis — bytes
    grow with vocab, independent of the batch.  "entries" all-gathers
    the deduped touched-row streams — bytes grow with the batch,
    independent of vocab (the reference PS design's IndexedSlices
    scaling, SURVEY.md §3.2).  "auto" picks whichever moves fewer ring
    words per device, weighing the dense all-reduce at 2x its buffer
    (reduce-scatter + all-gather phases — see
    sparse_apply.resolve_exchange).
    """
    return sparse_apply.resolve_exchange(
        cfg.sparse_exchange,
        n_local_occ=n_local_occ,
        vocab_local=cfg.vocabulary_size // mesh.shape[MODEL_AXIS],
        d=cfg.embedding_dim,
        data_shards=mesh.shape[DATA_AXIS],
    )


def _dscore(scores, labels, loss_type):
    if loss_type == "logistic":
        return jax.nn.sigmoid(scores) - labels
    return 2.0 * (scores - labels)  # mse


def _opt_tables(cfg: FmConfig, opt_state):
    if cfg.optimizer == "adagrad":
        return (opt_state.acc.table,)
    if cfg.optimizer == "ftrl":
        return (opt_state.z.table, opt_state.n.table)
    return ()


def _rebuild_opt(cfg: FmConfig, opt_state, new_tables, dw0, w0_old):
    lr = cfg.learning_rate
    if cfg.optimizer == "adagrad":
        acc_w0 = opt_state.acc.w0 + dw0 * dw0
        w0 = w0_old - lr * dw0 * jax.lax.rsqrt(acc_w0 + ADAGRAD_EPS)
        return w0, SparseAdagradState(
            acc=fm.FmParams(w0=acc_w0, table=new_tables[0])
        )
    if cfg.optimizer == "ftrl":
        n0_new = opt_state.n.w0 + dw0 * dw0
        sigma0 = (jnp.sqrt(n0_new) - jnp.sqrt(opt_state.n.w0)) / lr
        z0 = opt_state.z.w0 + dw0 - sigma0 * w0_old
        w0 = sparse_apply.ftrl_solve(
            z0, n0_new, lr, cfg.ftrl_l1, cfg.ftrl_l2, cfg.ftrl_beta
        )
        return w0, SparseFtrlState(
            z=fm.FmParams(w0=z0, table=new_tables[0]),
            n=fm.FmParams(w0=n0_new, table=new_tables[1]),
        )
    return w0_old - lr * dw0, opt_state  # sgd


def sparse_step_shardmap(cfg: FmConfig, params, opt_state, batch: Batch,
                         mesh, health: bool = False):
    """One sparse train step, hand-sharded. Returns (params, opt, scores),
    plus a ``(grad_sq, nonfinite_count)`` health aux when ``health=True``
    — each quantity reduced locally from the shard's own (masked)
    occurrence grads and psum'd over BOTH mesh axes, so the monitor is
    global at the cost of two extra scalar collectives per step."""
    model_shards = mesh.shape[MODEL_AXIS]
    vocab_local = cfg.vocabulary_size // model_shards
    k = cfg.factor_num
    n_opt = len(_opt_tables(cfg, opt_state))
    b_local = batch.vals.shape[0] // mesh.shape[DATA_AXIS]
    exchange = exchange_mode(cfg, mesh, b_local * batch.vals.shape[1])

    cd = cfg.compute_jnp_dtype

    def _fm_fwd_bwd(w0, rows, vals, labels, weights):
        """Plain FM: partial (linear, s1, s2) -> psum -> closed-form grad."""
        w = rows[..., 0].astype(cd)
        v = rows[..., 1:].astype(cd)
        vals_c = vals.astype(cd)
        xv = v * vals_c[..., None]
        # Partial terms from this shard's rows; psum over model completes
        # them — the entire "lookup" is this [b, 2k+1] collective.
        terms = jnp.concatenate(
            [
                jnp.sum(w * vals_c, axis=-1, keepdims=True,
                        dtype=jnp.float32),  # linear
                jnp.sum(xv, axis=1, dtype=jnp.float32),  # s1 [b, k]
                jnp.sum(xv * xv, axis=1, dtype=jnp.float32),  # s2 [b, k]
            ],
            axis=-1,
        )
        terms = jax.lax.psum(terms, MODEL_AXIS)
        linear, s1, s2 = terms[:, 0], terms[:, 1:1 + k], terms[:, 1 + k:]
        scores = w0 + linear + 0.5 * jnp.sum(s1 * s1 - s2, axis=-1)
        g, gx = _g_gx(scores, labels, weights, vals)
        # Closed-form FmGrad for the occurrences this shard owns.
        dv = gx[..., None] * (s1[:, None, :] - xv.astype(jnp.float32))
        return scores, g, jnp.concatenate([gx[..., None], dv], axis=-1)

    def _ffm_fwd_bwd(w0, rows, vals, fields, labels, weights):
        """Field-aware FM, same inversion: the field-grouped sums
        S[b,p,q,:] are per-shard-linear, so partial S + ONE psum replaces
        the row exchange; backward needs only the complete S plus own
        rows: dv_i^q = g x_i (S[q, f_i] - [q=f_i] v_i^{f_i} x_i)."""
        from fast_tffm_tpu.platform import ffm_compute_dtype

        ffm_cd = ffm_compute_dtype(cd)  # f32 off-TPU: CPU can't bf16-dot
        p_num = cfg.field_num
        b, f = vals.shape
        w = rows[..., 0].astype(ffm_cd)
        v = rows[..., 1:].astype(ffm_cd).reshape(b, f, p_num, k)
        vals_c = vals.astype(ffm_cd)
        oh = (
            fields[..., None] == jnp.arange(p_num, dtype=fields.dtype)
        ).astype(ffm_cd)  # [b, F, P]
        linear_p = jnp.sum(w * vals_c, axis=-1, dtype=jnp.float32)
        s_p = jnp.einsum(
            "bfp,bfqk->bpqk", oh * vals_c[..., None], v,
            preferred_element_type=jnp.float32,
        )  # [b, P, P, k] partial field-grouped sums
        v_own = jnp.einsum(
            "bfq,bfqk->bfk", oh, v, preferred_element_type=jnp.float32
        )  # v_i^{f_i}, zero off-shard (v is masked)
        self_p = jnp.sum(
            jnp.sum(v_own * v_own, axis=-1) * (vals * vals), axis=-1
        )
        terms = jnp.concatenate(
            [linear_p[:, None], self_p[:, None],
             s_p.reshape(b, p_num * p_num * k)],
            axis=-1,
        )
        terms = jax.lax.psum(terms, MODEL_AXIS)
        linear, self_t = terms[:, 0], terms[:, 1]
        s_full = terms[:, 2:].reshape(b, p_num, p_num, k)
        cross = jnp.einsum("bpqk,bqpk->b", s_full, s_full)
        scores = w0 + linear + 0.5 * (cross - self_t)
        g, gx = _g_gx(scores, labels, weights, vals)
        oh32 = oh.astype(jnp.float32)
        # T[b,f,q,:] = S[b, q, f_i, :] — gather S's second field axis by
        # each occurrence's own field, as a one-hot matmul.
        t = jnp.einsum("bqpk,bfp->bfqk", s_full, oh32)
        dv = gx[..., None, None] * (
            t
            - oh32[..., None] * v_own[:, :, None, :] * vals[..., None, None]
        )  # [b, F, P, k]
        return scores, g, jnp.concatenate(
            [gx[..., None], dv.reshape(b, f, p_num * k)], axis=-1
        )

    def _g_gx(scores, labels, weights, vals):
        # Global weighted-mean loss: normalizer spans the data axis.
        wsum = jax.lax.psum(jnp.sum(weights), DATA_AXIS)
        g = weights * _dscore(scores, labels, cfg.loss_type) / jnp.maximum(
            wsum, 1e-12
        )  # [b] dL/dscore
        return g, g[:, None] * vals  # gx [b, F]; caller masks via rows

    def device_fn(w0, table_l, labels, ids, vals, fields, weights,
                  *opt_tables_l):
        m = jax.lax.axis_index(MODEL_AXIS)
        row_lo = m * vocab_local
        local = (ids >= row_lo) & (ids < row_lo + vocab_local)  # [b, F]
        lids = jnp.where(local, ids - row_lo, 0)
        maskf = local.astype(jnp.float32)
        rows = table_l[lids] * maskf[..., None]  # [b, F, D], 0 off-shard
        # bf16 mode (cd) rounds the [b, F, D] interaction operands (the
        # step's dominant HBM streams); sums accumulate f32, and the
        # psum'd terms, backward, and optimizer stay f32.
        if cfg.field_num:
            scores, g, drows = _ffm_fwd_bwd(
                w0, rows, vals, fields, labels, weights
            )
        else:
            scores, g, drows = _fm_fwd_bwd(w0, rows, vals, labels, weights)
        # Only occurrences this shard owns update its rows.
        drows = drows * maskf[..., None]
        if cfg.factor_lambda or cfg.bias_lambda:
            # d/drow of l2_penalty_batch: 2*lambda*row/B per occurrence.
            bsz = jax.lax.psum(jnp.float32(vals.shape[0]), DATA_AXIS)
            lam = jnp.concatenate([
                jnp.full((1,), cfg.bias_lambda, jnp.float32),
                jnp.full(
                    (rows.shape[-1] - 1,), cfg.factor_lambda, jnp.float32
                ),
            ])
            occ = (vals != 0).astype(jnp.float32)[..., None] * maskf[..., None]
            drows = drows + (2.0 / bsz) * lam * rows * occ
        # Local-coordinate occurrence list; off-shard -> sentinel row.
        b, f = vals.shape
        d = rows.shape[-1]  # 1 + k (FM) or 1 + field_num*k (FFM)
        ids_flat = jnp.where(local, ids - row_lo, vocab_local).reshape(b * f)
        g_flat = drows.reshape(b * f, d)
        if exchange == "entries":
            # Batch-proportional update exchange: dedupe locally, move
            # only the touched entries over the data axis, merge the S
            # sorted streams, apply via K2.  Comms are independent of
            # vocab — the reference's IndexedSlices scaling property.
            # (ids_flat is already local-coordinate with off-shard ->
            # sentinel, the helper's contract; drows already masked.)
            u2, ts2 = sparse_apply.entries_exchange(
                ids_flat.astype(jnp.int32), g_flat,
                vocab_local=vocab_local, data_axis=DATA_AXIS,
                data_shards=mesh.shape[DATA_AXIS],
            )
            w_new, new_tables = _apply_stream(
                cfg, ts2, u2, table_l, opt_tables_l
            )
        else:
            delta = sparse_apply.dense_delta(
                ids_flat.astype(jnp.int32), g_flat,
                vocab=vocab_local, vocab_local=vocab_local, row_lo=0,
            )
            delta = jax.lax.psum(delta, DATA_AXIS)
            w_new, new_tables = _apply_delta(
                cfg, delta[:, :d], delta[:, d:], table_l, opt_tables_l
            )
        dw0 = jax.lax.psum(jnp.sum(g), DATA_AXIS)
        if cfg.bias_lambda:
            # l2_penalty_batch includes bias_lambda*w0^2/B — its w0 grad
            # must land here too or w0 diverges from the scatter path.
            bsz_g = jax.lax.psum(jnp.float32(vals.shape[0]), DATA_AXIS)
            dw0 = dw0 + 2.0 * cfg.bias_lambda * w0 / bsz_g
        outs = (w_new, scores, dw0) + tuple(new_tables)
        if health:
            # Each occurrence's grad lives on exactly ONE model shard
            # (off-shard rows are masked to zero), so summing local
            # squares over both axes is the global occurrence-grad norm
            # — no double counting.  dw0 is already global; folded in
            # by the caller.
            gsq = jax.lax.psum(
                jnp.sum(jnp.square(g_flat)), (MODEL_AXIS, DATA_AXIS)
            )
            nonfin = jax.lax.psum(
                jnp.sum((~jnp.isfinite(g_flat)).astype(jnp.int32)),
                (MODEL_AXIS, DATA_AXIS),
            )
            outs = outs + (gsq, nonfin)
        return outs

    out_specs = (
        (P(MODEL_AXIS, None), P(DATA_AXIS), P())
        + (P(MODEL_AXIS, None),) * n_opt
        + ((P(), P()) if health else ())
    )
    from fast_tffm_tpu.platform import shard_map

    outs = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(
            (P(), P(MODEL_AXIS, None), P(DATA_AXIS), P(DATA_AXIS, None),
             P(DATA_AXIS, None), P(DATA_AXIS, None), P(DATA_AXIS))
            + (P(MODEL_AXIS, None),) * n_opt
        ),
        out_specs=out_specs,
        check_vma=False,  # pallas_call outputs carry no vma annotations
    )(
        params.w0, params.table, batch.labels, batch.ids, batch.vals,
        batch.fields, batch.weights, *_opt_tables(cfg, opt_state),
    )
    table_new, scores, dw0 = outs[0], outs[1], outs[2]
    new_opt_tables = outs[3:-2] if health else outs[3:]
    w0_new, opt_new = _rebuild_opt(
        cfg, opt_state, new_opt_tables, dw0, params.w0
    )
    new_params = fm.FmParams(w0=w0_new, table=table_new)
    if health:
        gsq, nonfin = outs[-2], outs[-1]
        grad_sq = gsq + jnp.square(dw0)
        nonfin = nonfin + (~jnp.isfinite(dw0)).astype(jnp.int32)
        return new_params, opt_new, scores, (grad_sq, nonfin)
    return new_params, opt_new, scores


def make_exchange_probe(mesh):
    """Cross-rank barrier probe for the shard_map path: the same
    contract as train.sparse.make_exchange_probe, but lowered through
    an explicit ``psum`` over both mesh axes — the collective family
    THIS step uses (partial-terms psum / delta psum), so the probe's
    barrier rides the same channel as the step's exchange.  The
    dispatch loop enqueues it after each dispatch and blocks one
    dispatch later (``train.exchange`` timer; no pipeline bubble)."""
    import numpy as np
    from jax.sharding import NamedSharding

    from fast_tffm_tpu.platform import shard_map

    spec = P((DATA_AXIS, MODEL_AXIS))
    reduce = jax.jit(shard_map(
        lambda x: jax.lax.psum(
            jnp.sum(x), (DATA_AXIS, MODEL_AXIS)
        ),
        mesh=mesh, in_specs=spec, out_specs=P(),
        check_vma=False,
    ))
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec),
        np.ones((mesh.local_mesh.size,), np.float32),
        (mesh.size,),
    )

    def probe():
        return reduce(arr)

    return probe


def _apply_stream(cfg, tile_start, u, w_l, opt_tables_l):
    """Optimizer update from a merged K2 entry stream (entries exchange).

    Same formulas as _apply_delta, fused in the K2 tile kernel — only
    streamed/touched tiles are rewritten, so untouched rows pass through
    by aliasing (bit-identical to the dense path's identity update)."""
    lr = cfg.learning_rate
    if cfg.optimizer == "adagrad":
        upd = functools.partial(
            sparse_apply.adagrad_update, lr=lr, eps=ADAGRAD_EPS
        )
        w_new, acc_new = sparse_apply.k2_apply(
            upd, tile_start, u, (w_l, opt_tables_l[0])
        )
        return w_new, (acc_new,)
    if cfg.optimizer == "ftrl":
        upd = functools.partial(
            sparse_apply.ftrl_update,
            lr=lr, l1=cfg.ftrl_l1, l2=cfg.ftrl_l2, beta=cfg.ftrl_beta,
        )
        w_new, z_new, n_new = sparse_apply.k2_apply(
            upd, tile_start, u, (w_l,) + tuple(opt_tables_l)
        )
        return w_new, (z_new, n_new)
    upd = functools.partial(sparse_apply.sgd_update, lr=lr)
    (w_new,) = sparse_apply.k2_apply(upd, tile_start, u, (w_l,))
    return w_new, ()


def _apply_delta(cfg, g1, g2, w_l, opt_tables_l):
    """Optimizer update on (table shard, opt-table shards) -> new tables.

    Delegates to ops.sparse_apply's shared elementwise update functions so
    all sharded paths stay bit-identical.
    """
    lr = cfg.learning_rate
    if cfg.optimizer == "adagrad":
        w_new, acc_new = sparse_apply.adagrad_update(
            g1, g2, w_l, opt_tables_l[0], lr=lr, eps=ADAGRAD_EPS
        )
        return w_new, (acc_new,)
    if cfg.optimizer == "ftrl":
        w_new, z_new, n_new = sparse_apply.ftrl_update(
            g1, g2, w_l, *opt_tables_l,
            lr=lr, l1=cfg.ftrl_l1, l2=cfg.ftrl_l2, beta=cfg.ftrl_beta,
        )
        return w_new, (z_new, n_new)
    (w_new,) = sparse_apply.sgd_update(g1, g2, w_l, lr=lr)
    return w_new, ()
