"""Explicit shard_map sparse train step — `lookup = shardmap`.

The GSPMD-auto path (train.sparse under jit with shardings) lets XLA pick
the collectives for ``table[ids]`` with a row-sharded table; depending on
shapes that can materialize gathered rows across shards.  This module is
the hand-laid-out alternative, exploiting FM's algebra (SURVEY.md §7 step
4, models.fm.interaction_terms docstring):

  * The per-example terms (linear, s1, s2) are SUMS of per-feature
    contributions, and each feature's contribution depends only on the row
    its id owns.  So each model shard computes partial terms from ITS rows
    and a psum over the model axis of [b, 2k+1] floats replaces the whole
    row exchange — per-step model-axis traffic is ~KB where a gathered-row
    exchange is ~MB-GB.  This is the PS architecture inverted: row owners
    compute, examples aggregate.
  * The backward is the closed-form FmGrad (SURVEY.md §3.4): dV = g*x*(s1
    - v*x) needs only the psum'd s1 plus the shard's own rows — each
    shard computes gradients for exactly the occurrences it owns, locally.
  * Updates: per-shard dense (sum g, sum g^2) deltas via ops.sparse_apply's
    K1+K-place kernels, psum'd over the data axis (the sync-DP gradient
    allreduce), then the optimizer formula applied elementwise in place.

Scope: plain FM with the sparse row-local optimizers (adagrad/ftrl/sgd)
and batch-mode (or zero) L2.  FFM and dense optimizers stay on the
GSPMD-auto path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import sparse_apply
from fast_tffm_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from fast_tffm_tpu.train.sparse import (
    ADAGRAD_EPS,
    SparseAdagradState,
    SparseFtrlState,
)


def supports_shardmap(cfg: FmConfig, mesh) -> bool:
    if cfg.field_num:
        return False
    if cfg.optimizer not in ("adagrad", "ftrl", "sgd"):
        return False
    if cfg.l2_mode != "batch" and (cfg.factor_lambda or cfg.bias_lambda):
        return False
    model_shards = mesh.shape[MODEL_AXIS]
    return sparse_apply.supports_tile_sharded(
        cfg.vocabulary_size, cfg.optimizer, model_shards
    )


def _dscore(scores, labels, loss_type):
    if loss_type == "logistic":
        return jax.nn.sigmoid(scores) - labels
    return 2.0 * (scores - labels)  # mse


def _opt_tables(cfg: FmConfig, opt_state):
    if cfg.optimizer == "adagrad":
        return (opt_state.acc.table,)
    if cfg.optimizer == "ftrl":
        return (opt_state.z.table, opt_state.n.table)
    return ()


def _rebuild_opt(cfg: FmConfig, opt_state, new_tables, dw0, w0_old):
    lr = cfg.learning_rate
    if cfg.optimizer == "adagrad":
        acc_w0 = opt_state.acc.w0 + dw0 * dw0
        w0 = w0_old - lr * dw0 * jax.lax.rsqrt(acc_w0 + ADAGRAD_EPS)
        return w0, SparseAdagradState(
            acc=fm.FmParams(w0=acc_w0, table=new_tables[0])
        )
    if cfg.optimizer == "ftrl":
        n0_new = opt_state.n.w0 + dw0 * dw0
        sigma0 = (jnp.sqrt(n0_new) - jnp.sqrt(opt_state.n.w0)) / lr
        z0 = opt_state.z.w0 + dw0 - sigma0 * w0_old
        w0 = sparse_apply.ftrl_solve(
            z0, n0_new, lr, cfg.ftrl_l1, cfg.ftrl_l2, cfg.ftrl_beta
        )
        return w0, SparseFtrlState(
            z=fm.FmParams(w0=z0, table=new_tables[0]),
            n=fm.FmParams(w0=n0_new, table=new_tables[1]),
        )
    return w0_old - lr * dw0, opt_state  # sgd


def sparse_step_shardmap(cfg: FmConfig, params, opt_state, batch: Batch,
                         mesh):
    """One sparse train step, hand-sharded. Returns (params, opt, scores)."""
    model_shards = mesh.shape[MODEL_AXIS]
    vocab_local = cfg.vocabulary_size // model_shards
    k = cfg.factor_num
    n_opt = len(_opt_tables(cfg, opt_state))

    def device_fn(w0, table_l, labels, ids, vals, weights, *opt_tables_l):
        m = jax.lax.axis_index(MODEL_AXIS)
        row_lo = m * vocab_local
        local = (ids >= row_lo) & (ids < row_lo + vocab_local)  # [b, F]
        lids = jnp.where(local, ids - row_lo, 0)
        maskf = local.astype(jnp.float32)
        rows = table_l[lids] * maskf[..., None]  # [b, F, D], 0 off-shard
        w = rows[..., 0]
        v = rows[..., 1:]
        xv = v * vals[..., None]
        # Partial terms from this shard's rows; psum over model completes
        # them — the entire "lookup" is this [b, 2k+1] collective.
        terms = jnp.concatenate(
            [
                jnp.sum(w * vals, axis=-1, keepdims=True),  # linear
                jnp.sum(xv, axis=1),  # s1 [b, k]
                jnp.sum(xv * xv, axis=1),  # s2 [b, k]
            ],
            axis=-1,
        )
        terms = jax.lax.psum(terms, MODEL_AXIS)
        linear, s1, s2 = terms[:, 0], terms[:, 1:1 + k], terms[:, 1 + k:]
        scores = w0 + linear + 0.5 * jnp.sum(s1 * s1 - s2, axis=-1)
        # Global weighted-mean loss: normalizer spans the data axis.
        wsum = jax.lax.psum(jnp.sum(weights), DATA_AXIS)
        g = weights * _dscore(scores, labels, cfg.loss_type) / jnp.maximum(
            wsum, 1e-12
        )  # [b] dL/dscore
        # Closed-form FmGrad for the occurrences this shard owns.
        gx = g[:, None] * vals * maskf  # [b, F]
        dv = gx[..., None] * (s1[:, None, :] - xv)  # [b, F, k]
        drows = jnp.concatenate([gx[..., None], dv], axis=-1)  # [b, F, D]
        if cfg.factor_lambda or cfg.bias_lambda:
            # d/drow of l2_penalty_batch: 2*lambda*row/B per occurrence.
            bsz = jax.lax.psum(jnp.float32(vals.shape[0]), DATA_AXIS)
            lam = jnp.concatenate([
                jnp.full((1,), cfg.bias_lambda, jnp.float32),
                jnp.full((k,), cfg.factor_lambda, jnp.float32),
            ])
            occ = (vals != 0).astype(jnp.float32)[..., None] * maskf[..., None]
            drows = drows + (2.0 / bsz) * lam * rows * occ
        # Local-coordinate occurrence list; off-shard -> sentinel row.
        b, f = vals.shape
        ids_flat = jnp.where(local, ids - row_lo, vocab_local).reshape(b * f)
        g_flat = drows.reshape(b * f, 1 + k)
        delta = sparse_apply.dense_delta(
            ids_flat.astype(jnp.int32), g_flat,
            vocab=vocab_local, vocab_local=vocab_local, row_lo=0,
        )
        delta = jax.lax.psum(delta, DATA_AXIS)
        d = 1 + k
        dw0 = jax.lax.psum(jnp.sum(g), DATA_AXIS)
        if cfg.bias_lambda:
            # l2_penalty_batch includes bias_lambda*w0^2/B — its w0 grad
            # must land here too or w0 diverges from the scatter path.
            bsz_g = jax.lax.psum(jnp.float32(vals.shape[0]), DATA_AXIS)
            dw0 = dw0 + 2.0 * cfg.bias_lambda * w0 / bsz_g
        w_new, new_tables = _apply_delta(
            cfg, delta[:, :d], delta[:, d:], table_l, opt_tables_l
        )
        return (w_new, scores, dw0) + tuple(new_tables)

    out_specs = (
        (P(MODEL_AXIS, None), P(DATA_AXIS), P())
        + (P(MODEL_AXIS, None),) * n_opt
    )
    outs = jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(
            (P(), P(MODEL_AXIS, None), P(DATA_AXIS), P(DATA_AXIS, None),
             P(DATA_AXIS, None), P(DATA_AXIS))
            + (P(MODEL_AXIS, None),) * n_opt
        ),
        out_specs=out_specs,
        check_vma=False,  # pallas_call outputs carry no vma annotations
    )(
        params.w0, params.table, batch.labels, batch.ids, batch.vals,
        batch.weights, *_opt_tables(cfg, opt_state),
    )
    table_new, scores, dw0 = outs[0], outs[1], outs[2]
    new_opt_tables = outs[3:]
    w0_new, opt_new = _rebuild_opt(
        cfg, opt_state, new_opt_tables, dw0, params.w0
    )
    return fm.FmParams(w0=w0_new, table=table_new), opt_new, scores


def _apply_delta(cfg, g1, g2, w_l, opt_tables_l):
    """Optimizer update on (table shard, opt-table shards) -> new tables.

    Delegates to ops.sparse_apply's shared elementwise update functions so
    all sharded paths stay bit-identical.
    """
    lr = cfg.learning_rate
    if cfg.optimizer == "adagrad":
        w_new, acc_new = sparse_apply.adagrad_update(
            g1, g2, w_l, opt_tables_l[0], lr=lr, eps=ADAGRAD_EPS
        )
        return w_new, (acc_new,)
    if cfg.optimizer == "ftrl":
        w_new, z_new, n_new = sparse_apply.ftrl_update(
            g1, g2, w_l, *opt_tables_l,
            lr=lr, l1=cfg.ftrl_l1, l2=cfg.ftrl_l2, beta=cfg.ftrl_beta,
        )
        return w_new, (z_new, n_new)
    (w_new,) = sparse_apply.sgd_update(g1, g2, w_l, lr=lr)
    return w_new, ()
