"""Rank-sharded tiered table: per-model-column ownership of the hot map.

``train.tiered.TieredTable`` is host-global: one process plans, migrates,
and checkpoints the ENTIRE logical table, which caps the trainable vocab
at what a single host holds and replicates every migration on every
rank.  This module partitions that work by id range so a fleet can train
a table no single host could (ROADMAP direction 1; the reference
system's parameter-server role, recast for SPMD):

- The logical id space splits into ``S = mesh_model`` contiguous ranges,
  one per MODEL column of the mesh.  Shard ``s`` owns ids
  ``[s*V/S, (s+1)*V/S)`` and hot slots ``[s*H/S, (s+1)*H/S)`` — exactly
  the rows of the ``P(MODEL)``-sharded device hot table that live on
  column ``s``.  Fleet tiering therefore requires every model column's
  devices to belong to ONE process (validated loudly): the process that
  holds a column's device rows is the only one that ever needs that
  shard's cold store.
- Every rank runs :class:`~fast_tffm_tpu.train.tiered.TieredTable`
  instances for ALL ``S`` shards over the SAME global batches (fleet
  tiering requires ``num_blocks == 1``), so slot maps + LRU state evolve
  in lockstep on every rank with zero coordination traffic.  Only the
  shards whose columns this process owns are full instances
  (``rows_enabled``): cold stores, write-back ledger, row fetch,
  ``tiered.*`` telemetry.  The rest are metadata MIRRORS — per-rank host
  bytes, migration H2D/D2H traffic, and telemetry all read ~1/R.
- Device-side migration runs through ``platform.shard_map`` programs
  whose bodies contain no collectives (see ``train.loop``): each column
  loads/gathers only its own rows, so cross-rank migration traffic is
  structurally zero, not merely observed to be.

Checkpointing: each rank exports ONLY its owned shards, with ids
globalized, into per-shard overlay files
(``train.checkpoint.save_tiered_shard``).  Because the payload is keyed
by GLOBAL id and the init descriptors are offset-independent, a restore
re-partitions the union of shard overlays across ANY new shard count —
the elastic-resume contract (R -> R' on super-batch boundaries).
"""

from __future__ import annotations

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.train.tiered import (
    Plan,
    ShardSpec,
    TieredTable,
    _bucket,
    opt_table_names,
)

__all__ = [
    "FleetPlan",
    "FleetShipment",
    "ShardedTiering",
    "column_owners",
    "filter_overlay_for_shard",
    "slice_dense_for_shard",
]


def column_owners(mesh) -> list:
    """The owning process index of each MODEL column of ``mesh``.

    Refuses (loudly) any column whose devices span processes: such a
    column's hot-table rows are REPLICATED across ranks, so no single
    rank could own its cold store — the geometry fleet tiering exists to
    avoid.  The canonical fleet-tiered mesh is ``(data=1, model=R)``
    with one process per column; single-process meshes trivially pass.
    """
    devs = mesh.devices  # [data, model] ndarray of jax devices
    owners = []
    for j in range(devs.shape[1]):
        procs = {d.process_index for d in devs[:, j]}
        if len(procs) != 1:
            raise ValueError(
                f"fleet tiering: mesh model column {j} spans processes "
                f"{sorted(procs)} — its hot rows would be replicated "
                "across ranks.  Use a mesh whose MODEL axis does not "
                "share columns across processes (canonically "
                "mesh_data=1, mesh_model=<process count>)."
            )
        owners.append(procs.pop())
    return owners


def filter_overlay_for_shard(overlay: dict, index: int, count: int,
                             vocab: int) -> dict:
    """Slice a GLOBAL-id overlay (the merged union of a checkpoint's
    shard files, or a legacy single-file overlay) down to one shard's
    id range, with ids localized — the restore half of elastic
    re-sharding."""
    vs = vocab // count
    lo, hi = index * vs, (index + 1) * vs
    out = {}
    for name, payload in overlay.items():
        ids = np.asarray(payload["ids"], np.int64)
        m = (ids >= lo) & (ids < hi)
        out[name] = {
            "ids": ids[m] - lo,
            "rows": np.asarray(payload["rows"])[m],
            "descriptor": payload.get("descriptor"),
        }
    return out


def slice_dense_for_shard(dense_tables: dict, index: int, count: int) -> dict:
    """Row-slice GLOBAL dense warm-start arrays to one shard's range
    (dense checkpoints re-shard trivially: contiguous row slices)."""
    out = {}
    for name, arr in dense_tables.items():
        vs = arr.shape[0] // count
        out[name] = np.ascontiguousarray(arr[index * vs:(index + 1) * vs])
    return out


class FleetPlan:
    """One super-batch's migration plan across all shards.

    ``shard_plans[s]`` is shard ``s``'s local-coordinate
    :class:`~fast_tffm_tpu.train.tiered.Plan`; ``cap_load``/``cap_evict``
    are the GLOBAL bucketed per-column capacities (max over shards,
    power-of-two padded) every rank computes identically from its
    mirrors — they size the ``P(MODEL)``-sharded device plan arrays, so
    all ranks must agree or the collective dispatch would diverge."""

    __slots__ = ("plan_id", "shard_plans", "cap_load", "cap_evict",
                 "n_load_max", "n_evict_max")

    def __init__(self, plan_id: int, shard_plans: tuple, cap_load: int,
                 cap_evict: int, n_load_max: int, n_evict_max: int):
        self.plan_id = plan_id
        self.shard_plans = shard_plans
        self.cap_load = cap_load
        self.cap_evict = cap_evict
        self.n_load_max = n_load_max
        self.n_evict_max = n_evict_max


class FleetShipment:
    """Device-side halves of a FleetPlan (built by the Trainer's put
    path): ``P(MODEL)``-sharded plan arrays where each process supplied
    only its own columns' blocks — non-owned rows never materialize on
    this rank."""

    __slots__ = ("batch", "load_slots", "load_rows", "evict_slots", "plan")

    def __init__(self, batch, load_slots, load_rows, evict_slots,
                 plan: FleetPlan):
        self.batch = batch
        self.load_slots = load_slots
        self.load_rows = load_rows
        self.evict_slots = evict_slots
        self.plan = plan


class ShardedTiering:
    """Coordinator over ``S`` shard-local :class:`TieredTable`
    instances (owned shards full, the rest mirrors — see module
    docstring).  Presents the same transfer-thread / dispatch-loop /
    heartbeat surface the host-global manager does; ``train.loop``
    branches only where device arrays are built."""

    def __init__(self, cfg: FmConfig, num_shards: int, owned,
                 telemetry=None, dense_tables: dict = None,
                 overlay: dict = None):
        if num_shards < 1:
            raise ValueError(f"num_shards={num_shards} must be >= 1")
        self.cfg = cfg
        self.num_shards = num_shards
        self.owned = frozenset(int(s) for s in owned)
        bad = [s for s in self.owned if not 0 <= s < num_shards]
        if bad:
            raise ValueError(
                f"owned shards {bad} outside [0, {num_shards})"
            )
        self.vocab = cfg.vocabulary_size
        self.hot_rows = min(cfg.hot_rows, cfg.vocabulary_size)
        if self.vocab % num_shards or self.hot_rows % num_shards:
            raise ValueError(
                f"vocabulary_size={self.vocab} and effective "
                f"hot_rows={self.hot_rows} must both divide by the tier "
                f"shard count {num_shards}"
            )
        self.vs = self.vocab // num_shards  # per-shard id span
        self.hs = self.hot_rows // num_shards  # per-shard hot slots
        self.dim = cfg.embedding_dim
        self.names = ("table",) + opt_table_names(cfg.optimizer)
        self._oor_occ = 0
        self.tables = []
        for s in range(num_shards):
            mine = s in self.owned
            self.tables.append(TieredTable(
                cfg,
                telemetry=telemetry if mine else None,
                dense_tables=(
                    slice_dense_for_shard(dense_tables, s, num_shards)
                    if mine and dense_tables is not None else None
                ),
                overlay=(
                    filter_overlay_for_shard(
                        overlay, s, num_shards, self.vocab
                    )
                    if mine and overlay is not None else None
                ),
                shard=ShardSpec(s, num_shards, rows_enabled=mine),
            ))
        self.codec = self.tables[0].codec

    # ------------------------------------------------------------------
    # transfer-thread side
    # ------------------------------------------------------------------

    def plan(self, ids: np.ndarray):
        """Remap a GLOBAL super-batch's ids to global hot-slot indices
        and produce per-shard migration plans.  Every shard — owned or
        mirror — plans every super-batch (possibly over zero ids): the
        lockstep that keeps mirrors equal to their owners."""
        H = self.hot_rows
        flat = ids.reshape(-1).astype(np.int64)
        oor = (flat < 0) | (flat >= self.vocab)
        any_oor = bool(oor.any())
        if any_oor:
            self._oor_occ += int(oor.sum())
        owner = np.where(oor, 0, flat // self.vs)
        new_flat = np.empty(flat.shape, np.int32)
        if any_oor:
            new_flat[oor] = np.int32(H)  # device scatter-drop index
        plans = []
        n_load_max = n_evict_max = 0
        for s, t in enumerate(self.tables):
            m = (owner == s) & ~oor if any_oor else owner == s
            local = flat[m] - s * self.vs
            new_local, plan_s = t.plan(local)
            new_flat[m] = new_local + np.int32(s * self.hs)
            plans.append(plan_s)
            n_load_max = max(n_load_max, plan_s.n_load)
            n_evict_max = max(n_evict_max, plan_s.n_evict)
        return new_flat.reshape(ids.shape), FleetPlan(
            plan_id=plans[0].plan_id,
            shard_plans=tuple(plans),
            cap_load=_bucket(max(1, n_load_max)),
            cap_evict=_bucket(max(1, n_evict_max)),
            n_load_max=n_load_max,
            n_evict_max=n_evict_max,
        )

    def local_load_blocks(self, plan: FleetPlan):
        """(slots_block, rows_blocks) for THIS rank's owned columns, in
        column order — the process-local data of the ``P(MODEL)``-sharded
        load arrays.  Slots are column-local with pad ``hs`` (the
        per-column scatter-drop index); rows are zero-padded."""
        cap = plan.cap_load
        slots = []
        rows = [[] for _ in self.names]
        for s in sorted(self.owned):
            p: Plan = plan.shard_plans[s]
            sl = np.full(cap, self.hs, np.int32)
            sl[:p.n_load] = p.load_slots[:p.n_load]
            slots.append(sl)
            for k, r in enumerate(p.load_rows):
                pr = np.zeros((cap, self.dim), np.float32)
                pr[:p.n_load] = r[:p.n_load]
                rows[k].append(pr)
        return (
            np.concatenate(slots),
            tuple(np.concatenate(rs) for rs in rows),
        )

    def local_evict_slots(self, plan: FleetPlan) -> np.ndarray:
        """Column-local evict-slot blocks for owned columns (pad 0 —
        garbage rows beyond each shard's ``n_evict`` are sliced off
        host-side, same contract as the host-global path)."""
        cap = plan.cap_evict
        blocks = []
        for s in sorted(self.owned):
            p: Plan = plan.shard_plans[s]
            ev = np.zeros(cap, np.int32)
            ev[:p.n_evict] = p.evict_slots[:p.n_evict]
            blocks.append(ev)
        return np.concatenate(blocks)

    def cancel_waits(self) -> None:
        for t in self.tables:
            t.cancel_waits()

    def reopen(self) -> None:
        for t in self.tables:
            t.reopen()

    # ------------------------------------------------------------------
    # dispatch-loop side
    # ------------------------------------------------------------------

    def push_writeback(self, shard: int, plan_id: int,
                       dev_rows: tuple) -> None:
        self.tables[shard].push_writeback(plan_id, dev_rows)

    def note_applied(self, plan: FleetPlan) -> None:
        for s in self.owned:
            p: Plan = plan.shard_plans[s]
            if p.n_load:
                t = self.tables[s]
                with t._cv:
                    t.id_of_slot_applied[
                        p.load_slots[:p.n_load]
                    ] = p.load_ids
        # Mirrors keep no applied view: nothing on this rank ever reads
        # their device rows back.

    def sync_from_device(self, host_tables_by_shard: dict) -> None:
        """``host_tables_by_shard[s]`` = np copies of shard ``s``'s
        device hot-table rows (this rank's columns only), ordered like
        ``self.names``."""
        for s in sorted(self.owned):
            self.tables[s].sync_from_device(host_tables_by_shard[s])

    # ------------------------------------------------------------------
    # checkpoint / eval
    # ------------------------------------------------------------------

    def export_shard_overlays(self, host_tables_by_shard: dict) -> dict:
        """{shard -> overlay payload} for OWNED shards, ids globalized —
        the elastic checkpoint unit (one ``tiered.shard{s}of{S}.npz``
        file each; see train.checkpoint)."""
        out = {}
        for s in sorted(self.owned):
            ov = self.tables[s].export_overlay(host_tables_by_shard[s])
            for payload in ov.values():
                payload["ids"] = payload["ids"] + np.int64(s * self.vs)
            out[s] = ov
        return out

    def gather_logical(self, ids: np.ndarray) -> np.ndarray:
        """Current PARAMS rows for logical (global) ids — only legal
        when every touched shard is owned (single-process sharded
        configs; fleet evaluate goes through a checkpoint instead)."""
        flat = np.asarray(ids, np.int64)
        owner = flat // self.vs
        missing = sorted(set(np.unique(owner).tolist()) - set(self.owned))
        if missing:
            raise RuntimeError(
                f"gather_logical needs shards {missing} which live on "
                "other ranks; fleet-tiered evaluation reads a checkpoint, "
                "not live remote state"
            )
        out = np.empty((len(flat), self.dim), np.float32)
        for s in self.owned:
            m = owner == s
            if m.any():
                out[m] = self.tables[s].gather_logical(flat[m] - s * self.vs)
        return out

    def merged_dense(self, host_tables_by_shard: dict) -> list:
        """Full logical arrays (params table first) — requires ALL
        shards owned (single-process sharded configs only)."""
        if len(self.owned) != self.num_shards:
            raise RuntimeError(
                "merged_dense needs every shard's cold store; this rank "
                f"owns {sorted(self.owned)} of {self.num_shards}"
            )
        self.sync_from_device(host_tables_by_shard)
        parts = [self.tables[s].stores for s in range(self.num_shards)]
        return [
            np.concatenate(
                [parts[s][k].to_dense() for s in range(self.num_shards)]
            )
            for k in range(len(self.names))
        ]

    @property
    def dense_save_ok(self) -> bool:
        """Dense-format checkpoints need the merged array: only a rank
        owning EVERY shard (single-process sharded) can write one, and
        only when the stores themselves allow it."""
        return len(self.owned) == self.num_shards and all(
            self.tables[s].dense_save_ok for s in range(self.num_shards)
        )

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    @property
    def stores(self) -> tuple:
        """All cold stores THIS rank holds (owned shards, shard-major) —
        the resource monitor sums their bytes for the per-rank
        ``cold_store_bytes`` gauge."""
        return tuple(
            s for sh in sorted(self.owned) for s in self.tables[sh].stores
        )

    def snapshot(self) -> dict:
        """Per-RANK tiered counters: owned shards summed.  Same schema
        as the host-global snapshot plus the sharding identity keys —
        ``hot_rows``/``vocab`` report this rank's OWNED capacity/span,
        which is what makes the fleet block's per-rank ~1/R claim
        directly readable."""
        snaps = [self.tables[s].snapshot() for s in sorted(self.owned)]
        hit = sum(s["hit_occurrences"] for s in snaps)
        miss = sum(s["miss_occurrences"] for s in snaps)
        total = hit + miss
        return {
            "hot_rows": self.hs * len(self.owned),
            "vocab": self.vs * len(self.owned),
            "resident_rows": sum(s["resident_rows"] for s in snaps),
            "rows_seen": sum(s["rows_seen"] for s in snaps),
            "hit_occurrences": hit,
            "miss_occurrences": miss,
            "hot_hit_frac": round(hit / total, 6) if total else 0.0,
            "rows_loaded": sum(s["rows_loaded"] for s in snaps),
            "rows_evicted": sum(s["rows_evicted"] for s in snaps),
            "writeback_rows": sum(s["writeback_rows"] for s in snaps),
            "oor_occurrences": int(self._oor_occ),
            "cold_store_bytes": sum(s["cold_store_bytes"] for s in snaps),
            "cold_written_rows": sum(
                s["cold_written_rows"] for s in snaps
            ),
            "cold_dtype": self.codec.dtype,
            "cold_bytes_per_row": int(self.codec.bytes_per_row),
            "num_shards": self.num_shards,
            "owned_shards": len(self.owned),
        }

    def health_view(self) -> dict:
        views = [self.tables[s].health_view() for s in sorted(self.owned)]
        seen = sum(v["emb_rows_touched"] for v in views)
        vocab = self.vs * max(1, len(self.owned))
        return {
            "emb_rows_touched": int(seen),
            "emb_row_occupancy": round(seen / vocab, 9),
            "hot_slots_resident": sum(
                v["hot_slots_resident"] for v in views
            ),
        }
