"""`dist_train` bring-up — the reference's PS cluster, TPU-style.

The reference's ``dist_train.py`` built a ``tf.train.ClusterSpec`` of ps +
worker tasks and parked ps processes serving variable blocks (SURVEY.md
§3.2).  Here there are no parameter servers: every process runs the SAME
training command after :func:`initialize`, and

- the embedding/factor table row-shards over the global (data, model) mesh
  (``parallel.mesh``) — GSPMD inserts the collectives the PS gather/scatter
  used to be,
- each host parses only its slice of the input stream
  (``BatchPipeline(shard=...)`` driven by ``mesh.data_partition``),
- the global batch is assembled shard-by-shard with
  ``jax.make_array_from_process_local_data`` (``mesh.shard_batch``) — no
  host ever materializes the global batch.

The CLI maps the legacy ``--ps_hosts/--worker_hosts/--job_name/
--task_index`` flags onto this (cli.py); ps tasks exit with a notice.
"""

from __future__ import annotations

import logging

log = logging.getLogger(__name__)


def initialize(
    coordinator: str, num_processes: int, process_id: int
) -> None:
    """Join the multi-host jax cluster (must run before any backend use)."""
    import jax

    log.info(
        "initializing jax.distributed: coordinator=%s (%d processes, "
        "this is %d)", coordinator, num_processes, process_id,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
