"""The serving manifest: the hot-swap handshake file, stdlib-only.

``checkpoint.py``'s save paths publish ``serve_manifest.json`` AFTER
the checkpoint files land (atomic rename), so a reader that sees a new
manifest knows the checkpoint it names is complete.  The helpers live
here — json/os/time only, no jax, no orbax — because the serving
ROUTER process polls the manifest too and must stay jax-free
(serve/router.py); checkpoint.py re-exports them for its callers.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

__all__ = ["read_manifest"]


def _manifest_path(model_file: str) -> str:
    return os.path.join(os.path.abspath(model_file),
                        "serve_manifest.json")


def _publish_manifest(model_file: str, step: int, fmt: str,
                      extra: Optional[dict] = None) -> None:
    """Publish the serving manifest AFTER the checkpoint files land.

    ``published`` disambiguates re-saves at the same step (a warm
    restart that trains zero new steps still republishes).  ``extra``
    merges additional top-level keys into the document — the trainer
    passes its ``quality`` sketch payload (the training→serving skew
    reference the serve fleet compares live traffic against; see
    OBSERVABILITY.md "Model quality & drift" and SERVING.md).
    """
    doc = {"step": int(step), "format": fmt, "published": time.time()}
    if extra:
        doc.update(extra)
    tmp = _manifest_path(model_file) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, _manifest_path(model_file))


def read_manifest(model_file: str) -> Optional[dict]:
    """The published serving manifest, or None (absent / mid-write)."""
    try:
        with open(_manifest_path(model_file)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None
