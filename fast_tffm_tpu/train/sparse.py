"""Sparse row-update training step — the IndexedSlices path, TPU-style.

The reference's PS trainer never touches the whole table per step: workers
pull only the gathered rows and push ``IndexedSlices`` updates for exactly
those rows (SURVEY.md §3.2).  A naive jit step loses that: autodiff w.r.t.
the table materializes a dense [V, D] gradient and the optimizer rewrites
every row — hundreds of GB/step of HBM traffic at Criteo-1TB vocabularies.

This step restores sparsity, TPU-style:

1. gather rows once: ``rows = table[ids]``,
2. differentiate the loss w.r.t. ``(w0, rows)`` — the Pallas FmGrad kernel
   produces per-occurrence row grads, never a dense table grad,
3. scatter-apply the optimizer to exactly the touched rows:
   ``acc.at[ids].add(g^2)`` then ``table.at[ids].add(-lr*g/sqrt(acc'))``.

Duplicate ids in a batch follow per-occurrence accumulator semantics (each
occurrence adds its own g^2, the shared denominator includes all of them) —
the same behavior as TF's SparseApplyAdagrad that the reference relies on,
vs. the dense path which squares the summed gradient.  For CTR data with
rare in-batch duplicates the difference is noise; both paths are tested.

Per-step HBM traffic scales with B*F*D instead of V*D: at B=16k, F=39,
D=9 that is ~50 MB/step regardless of vocabulary size.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import interaction, sparse_apply
from fast_tffm_tpu.parallel import mesh as mesh_lib

ADAGRAD_EPS = 1e-7  # matches optax.adagrad's default eps


def apply_mode(cfg: FmConfig, mesh=None) -> str:
    """How sparse updates hit the table: 'scatter' | 'tile' | 'sharded'.

    'tile' (single device): fused K2 streams table+state once per step.
    'sharded' (multi device): per-device dense deltas psum'd over the data
    axis, applied to the local model shard under shard_map.  Both need a
    TILE-aligned (per-shard) vocabulary and a row-local optimizer;
    otherwise the XLA row-'scatter' path handles it via GSPMD.
    """
    if cfg.sparse_apply == "scatter":
        return "scatter"
    multi = mesh is not None and mesh.size > 1
    if multi:
        ok = sparse_apply.supports_tile_sharded(
            cfg.vocabulary_size, cfg.optimizer,
            mesh.shape[mesh_lib.MODEL_AXIS],
        )
    else:
        ok = sparse_apply.supports_tile(cfg.vocabulary_size, cfg.optimizer)
    tiled = "sharded" if multi else "tile"
    if cfg.sparse_apply == "tile":
        if not ok:
            raise ValueError(
                "sparse_apply=tile needs a vocabulary_size divisible by "
                f"model_shards*{sparse_apply.TILE} and optimizer in "
                "adagrad/ftrl/sgd"
            )
        return tiled  # explicit: run even off-TPU (interpret mode, tests)
    # auto: only where the Mosaic kernels actually run (TPU) — interpret
    # mode on CPU is a correctness tool, far slower than XLA scatter.
    from fast_tffm_tpu.platform import is_tpu_backend

    if ok and is_tpu_backend():
        return tiled
    return "scatter"


class SparseAdagradState(NamedTuple):
    acc: fm.FmParams  # per-weight squared-gradient accumulators


class SparseFtrlState(NamedTuple):
    z: fm.FmParams
    n: fm.FmParams


def supports_sparse(cfg: FmConfig) -> bool:
    """Sparse updates need a row-local optimizer and row-local (batch) L2
    (or no L2 at all — l2_mode is irrelevant when both lambdas are 0)."""
    if cfg.optimizer not in ("adagrad", "ftrl", "sgd"):
        return False
    return cfg.l2_mode == "batch" or not (cfg.factor_lambda or cfg.bias_lambda)


def init_sparse_opt_state(cfg: FmConfig, params: fm.FmParams):
    if cfg.optimizer == "adagrad":
        acc = jax.tree.map(
            lambda p: jnp.full_like(p, cfg.adagrad_initial_accumulator), params
        )
        return SparseAdagradState(acc=acc)
    if cfg.optimizer == "ftrl":
        # z initialized so the FTRL closed form reproduces the incoming
        # params (warm-start correctness; see optimizers.ftrl).
        denom0 = (
            cfg.ftrl_beta + jnp.sqrt(cfg.adagrad_initial_accumulator)
        ) / cfg.learning_rate + cfg.ftrl_l2
        z = jax.tree.map(
            lambda p: -p * denom0 - jnp.sign(p) * cfg.ftrl_l1, params
        )
        n = jax.tree.map(
            lambda p: jnp.full_like(p, cfg.adagrad_initial_accumulator), params
        )
        return SparseFtrlState(z=z, n=n)
    if cfg.optimizer == "sgd":
        return ()
    raise ValueError(f"no sparse path for optimizer {cfg.optimizer!r}")


def _rows_loss_fn(
    cfg: FmConfig, batch: Batch, mesh=None, data_axis: str = "data",
    compute_dtype=jnp.float32,
):
    """loss(w0, rows) over the gathered rows — autodiff target.

    ``compute_dtype=bfloat16`` rounds the interaction inputs (rows, vals)
    to bf16 — halving the [B,F,D] HBM streams, the sparse step's dominant
    traffic — while scores, loss, and gradients stay f32 (the cast is
    inside the autodiff region, so row cotangents come back f32 for the
    optimizer).
    """

    def loss_fn(w0, rows):
        if cfg.field_num:
            # Closed-form FFM op (ops.interaction.ffm_interaction): same
            # forward math as fm.ffm_scores_from_rows, backward via the
            # shardmap inversion's closed form instead of autodiff
            # through the einsum chain — w0 enters linearly outside.
            # FAST_TFFM_FFM_AUTODIFF=1 forces the autodiff oracle so the
            # hardware sweep can time both in one window.
            import os as _os

            if _os.environ.get("FAST_TFFM_FFM_AUTODIFF") == "1":
                scores = fm.ffm_scores_from_rows(
                    w0, rows, batch.vals, batch.fields, cfg.factor_num,
                    cfg.field_num, compute_dtype,
                ).astype(jnp.float32)
            else:
                scores = (
                    w0.astype(jnp.float32) + interaction.ffm_interaction(
                        rows, batch.vals, batch.fields, cfg.factor_num,
                        cfg.field_num, compute_dtype,
                    )
                )
        else:
            scores = w0 + interaction.fm_interaction_sharded(
                rows.astype(compute_dtype),
                batch.vals.astype(compute_dtype),
                cfg.interaction_resolved, mesh, data_axis,
            )
        per_ex = fm.example_losses(scores, batch.labels, cfg.loss_type)
        wsum = jnp.maximum(jnp.sum(batch.weights), 1e-12)
        data_loss = jnp.sum(per_ex * batch.weights) / wsum
        reg = jnp.zeros((), jnp.float32)
        if cfg.factor_lambda or cfg.bias_lambda:
            reg = fm.l2_penalty_batch(
                fm.FmParams(w0=w0, table=rows), rows, batch.vals,
                cfg.factor_lambda, cfg.bias_lambda,
            )
        return data_loss + reg, scores

    return loss_fn


def _sharded_exchange(cfg, mesh, ids, g_rows) -> str:
    """Resolve cfg.sparse_exchange for the GSPMD 'sharded' apply mode."""
    return sparse_apply.resolve_exchange(
        cfg.sparse_exchange,
        n_local_occ=ids.shape[0] // mesh.shape[mesh_lib.DATA_AXIS],
        vocab_local=cfg.vocabulary_size // mesh.shape[mesh_lib.MODEL_AXIS],
        d=g_rows.shape[1],
        data_shards=mesh.shape[mesh_lib.DATA_AXIS],
    )


def overlap_active(cfg: FmConfig, mesh=None) -> bool:
    """Resolve ``cfg.sparse_exchange_overlap`` against the path actually
    taken: compute-overlapped exchange needs the entries exchange's id
    plane (the deduped row streams are a pure function of batch ids, so
    they can be computed one dispatch ahead) — i.e. the GSPMD 'sharded'
    apply with resolved exchange 'entries' over >1 data shard.

    'auto' enables exactly when those hold; 'on' refuses loudly when they
    don't (a silently inert knob would fake the overlap win); 'off' never
    overlaps.  Callers pass the cfg the step actually runs with (the
    hot-table _dcfg under tiering, whose vocabulary is the hot size).
    """
    if cfg.sparse_exchange_overlap == "off":
        return False
    ok = mesh is not None and mesh.shape[mesh_lib.DATA_AXIS] > 1
    if ok:
        ok = supports_sparse(cfg) and apply_mode(cfg, mesh) == "sharded"
    if ok:
        n_occ = cfg.batch_size * cfg.max_features
        resolved = sparse_apply.resolve_exchange(
            cfg.sparse_exchange,
            n_local_occ=n_occ // mesh.shape[mesh_lib.DATA_AXIS],
            vocab_local=(
                cfg.vocabulary_size // mesh.shape[mesh_lib.MODEL_AXIS]
            ),
            d=cfg.embedding_dim,
            data_shards=mesh.shape[mesh_lib.DATA_AXIS],
        )
        ok = resolved == "entries"
    if cfg.sparse_exchange_overlap == "on" and not ok:
        raise ValueError(
            "sparse_exchange_overlap=on requires the sharded sparse apply "
            "with resolved exchange 'entries' over >1 data shard (got "
            f"mesh={None if mesh is None else dict(mesh.shape)}, "
            f"sparse_exchange={cfg.sparse_exchange!r}); use 'auto' to "
            "overlap opportunistically"
        )
    return ok


def _apply_adagrad(cfg, params, opt, ids, g_rows, dw0, w_rows,
                   mode="scatter", mesh=None, meta=None, rows_all=None):
    del w_rows  # adagrad needs no pre-update weights
    # Same formula as optax.scale_by_rss: u = g * rsqrt(acc_new + eps),
    # so sparse and dense paths agree exactly on duplicate-free batches.
    lr = cfg.learning_rate
    if mode == "sharded":
        table, acc_table = sparse_apply.adagrad_apply_sharded(
            params.table, opt.acc.table, ids, g_rows,
            lr=lr, eps=ADAGRAD_EPS, mesh=mesh,
            data_axis=mesh_lib.DATA_AXIS, model_axis=mesh_lib.MODEL_AXIS,
            exchange=_sharded_exchange(cfg, mesh, ids, g_rows),
            rows_all=rows_all,
        )
    elif mode == "tile":
        table, acc_table = sparse_apply.adagrad_apply(
            params.table, opt.acc.table, ids, g_rows,
            lr=lr, eps=ADAGRAD_EPS, meta=meta,
        )
    else:
        acc_table = opt.acc.table.at[ids].add(g_rows * g_rows)
        acc_rows = acc_table[ids]  # post-update accumulators, touched rows
        table = params.table.at[ids].add(
            -lr * g_rows * jax.lax.rsqrt(acc_rows + ADAGRAD_EPS)
        )
    acc_w0 = opt.acc.w0 + dw0 * dw0
    w0 = params.w0 - lr * dw0 * jax.lax.rsqrt(acc_w0 + ADAGRAD_EPS)
    return (
        fm.FmParams(w0=w0, table=table),
        SparseAdagradState(acc=fm.FmParams(w0=acc_w0, table=acc_table)),
    )


# One shared closed form across scatter / tile-kernel / sharded paths.
_ftrl_solve = sparse_apply.ftrl_solve


def _apply_ftrl(cfg, params, opt, ids, g_rows, dw0, w_rows,
                mode="scatter", mesh=None, meta=None, rows_all=None):
    lr, l1, l2, beta = (
        cfg.learning_rate, cfg.ftrl_l1, cfg.ftrl_l2, cfg.ftrl_beta,
    )
    if mode == "sharded":
        table, z_table, n_table = sparse_apply.ftrl_apply_sharded(
            params.table, opt.z.table, opt.n.table, ids, g_rows,
            lr=lr, l1=l1, l2=l2, beta=beta, mesh=mesh,
            data_axis=mesh_lib.DATA_AXIS, model_axis=mesh_lib.MODEL_AXIS,
            exchange=_sharded_exchange(cfg, mesh, ids, g_rows),
            rows_all=rows_all,
        )
    elif mode == "tile":
        table, z_table, n_table = sparse_apply.ftrl_apply(
            params.table, opt.z.table, opt.n.table, ids, g_rows,
            lr=lr, l1=l1, l2=l2, beta=beta, meta=meta,
        )
    else:
        # Rows: FTRL recursion on the touched rows (w_rows is the
        # pre-update gather from sparse_step, reused — no second gather).
        #
        # Duplicate-id care: z must receive each occurrence's gradient ONCE
        # but the -sigma*w correction only once PER ROW.  Scatter-adding
        # (g - sigma*w) per occurrence would apply -sigma*w k times for a
        # row appearing k times — a positive feedback on w that diverges (w
        # grows, |z| grows with it, the closed form returns a larger w,
        # ...).  So: per-occurrence scatter-add of g, then a
        # gather-modify-set for the sigma correction.  All quantities in
        # the set are identical across duplicates (n_old/n_new/w pre-update
        # are per-row), so the duplicate writes are well-defined.
        n_old_rows = opt.n.table[ids]
        n_table = opt.n.table.at[ids].add(g_rows * g_rows)
        n_new_rows = n_table[ids]  # for dups: includes all occurrences' g^2
        sigma = (jnp.sqrt(n_new_rows) - jnp.sqrt(n_old_rows)) / lr
        zg_table = opt.z.table.at[ids].add(g_rows)
        z_rows = zg_table[ids] - sigma * w_rows
        z_table = zg_table.at[ids].set(z_rows)
        new_w_rows = _ftrl_solve(z_rows, n_new_rows, lr, l1, l2, beta)
        table = params.table.at[ids].set(new_w_rows)
    # w0 (dense scalar path, shared by both table branches).
    n0_new = opt.n.w0 + dw0 * dw0
    sigma0 = (jnp.sqrt(n0_new) - jnp.sqrt(opt.n.w0)) / lr
    z0 = opt.z.w0 + dw0 - sigma0 * params.w0
    w0 = _ftrl_solve(z0, n0_new, lr, l1, l2, beta)
    return (
        fm.FmParams(w0=w0, table=table),
        SparseFtrlState(
            z=fm.FmParams(w0=z0, table=z_table),
            n=fm.FmParams(w0=n0_new, table=n_table),
        ),
    )


def _apply_sgd(cfg, params, opt, ids, g_rows, dw0, w_rows,
               mode="scatter", mesh=None, meta=None, rows_all=None):
    del w_rows
    lr = cfg.learning_rate
    if mode == "sharded":
        table = sparse_apply.sgd_apply_sharded(
            params.table, ids, g_rows, lr=lr, mesh=mesh,
            data_axis=mesh_lib.DATA_AXIS, model_axis=mesh_lib.MODEL_AXIS,
            exchange=_sharded_exchange(cfg, mesh, ids, g_rows),
            rows_all=rows_all,
        )
    elif mode == "tile":
        table = sparse_apply.sgd_apply(
            params.table, ids, g_rows, lr=lr, meta=meta)
    else:
        table = params.table.at[ids].add(-lr * g_rows)
    return fm.FmParams(w0=params.w0 - lr * dw0, table=table), opt


_APPLY = {"adagrad": _apply_adagrad, "ftrl": _apply_ftrl, "sgd": _apply_sgd}


def make_exchange_probe(mesh):
    """Cross-rank barrier probe for the GSPMD sparse path: a tiny
    jitted all-reduce (one float per device, summed to a replicated
    scalar — GSPMD lowers it to the same all-reduce family the
    sharded apply's psum uses) that the dispatch loop enqueues right
    after each dispatch and blocks on ONE DISPATCH LATER (the
    HealthState discipline — no pipeline bubble).  Because the probe
    is enqueued behind the dispatch on every rank's stream, the
    delayed blocking wait measures exactly the straggler-induced
    collective wall: ~0 when the fleet is in step, the slowest rank's
    lag otherwise.  Feeds the ``train.exchange`` timer and the fleet
    block's ``exchange_frac``.

    Returns ``probe() -> jax.Array`` (async; callers block on the
    result to time the barrier)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(
        mesh, P((mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS))
    )
    arr = jax.make_array_from_process_local_data(
        sharding,
        np.ones((mesh.local_mesh.size,), np.float32),
        (mesh.size,),
    )
    reduce = jax.jit(
        jnp.sum, out_shardings=NamedSharding(mesh, P())
    )

    def probe():
        return reduce(arr)

    return probe


def grad_health(g_rows, dw0):
    """(grad_sq, nonfinite_count) for a step's gradients — the on-device
    training-health aux the scan carry accumulates (train.loop).

    ``grad_sq`` is the squared global gradient norm at OCCURRENCE
    granularity: duplicate ids in a batch contribute per occurrence
    (matching the per-occurrence accumulator semantics of the sparse
    optimizers), where a dense table-gradient norm would first sum
    duplicates per row.  For a health monitor the distinction is noise;
    for NaN detection it is irrelevant (any non-finite occurrence grad
    poisons the row either way).
    """
    grad_sq = jnp.sum(jnp.square(g_rows)) + jnp.square(dw0)
    nonfinite = (
        jnp.sum((~jnp.isfinite(g_rows)).astype(jnp.int32))
        + (~jnp.isfinite(dw0)).astype(jnp.int32)
    )
    return grad_sq, nonfinite


def sparse_step(
    cfg: FmConfig, params: fm.FmParams, opt_state, batch: Batch,
    mesh=None, data_axis: str = "data", health: bool = False,
    rows_all=None,
):
    """One sparse train step. Returns (params, opt_state, scores), plus
    a ``(grad_sq, nonfinite_count)`` health aux when ``health=True``
    (computed from the per-occurrence row grads this step already
    materialized — no extra memory traffic).

    ``rows_all`` is the prefetched entries-exchange id plane (see
    ops.sparse_apply.make_entries_prefetch) — only legal on the sharded
    entries path, where it lifts the deduped-stream all-gather off the
    critical path (compute-overlapped exchange)."""
    rows = params.table[batch.ids]  # [B, F, D]
    loss_fn = _rows_loss_fn(
        cfg, batch, mesh, data_axis, compute_dtype=cfg.compute_jnp_dtype
    )
    (_, scores), (dw0, drows) = jax.value_and_grad(
        loss_fn, argnums=(0, 1), has_aux=True
    )(params.w0, rows)
    b, f, d = drows.shape
    ids = batch.ids.reshape(b * f)
    g_rows = drows.reshape(b * f, d)
    mode = apply_mode(cfg, mesh)
    if rows_all is not None and mode != "sharded":
        raise ValueError(
            f"prefetched exchange streams need apply mode 'sharded', got "
            f"{mode!r}"
        )
    params, opt_state = _APPLY[cfg.optimizer](
        cfg, params, opt_state, ids, g_rows, dw0, rows.reshape(b * f, d),
        mode=mode, mesh=mesh,
        meta=batch.sort_meta if mode == "tile" else None,
        rows_all=rows_all,
    )
    if health:
        return params, opt_state, scores, grad_health(g_rows, dw0)
    return params, opt_state, scores
