"""Training loop: the `local_train` / `dist_train` engine.

One jitted train step (loss+grad+optimizer+metrics) over a (data, model)
mesh replaces the reference's per-batch ``sess.run(train_op)`` hot loop and
its async PS updates (SURVEY.md §3.1/3.2).  Updates are synchronous — GSPMD
allreduces gradients over ICI — which is a deliberate semantic upgrade from
hogwild PS training (SURVEY.md §7 step 4 notes the convergence difference).

Host-side, batches parse on background threads (data.pipeline) while the
device runs the current step; the donated carry keeps the step fully
async-dispatched.

The hot loop is device-resident: ``steps_per_dispatch`` (K) parsed batches
stack into one [K, ...] super-batch, a transfer thread ships super-batch
n+1 (DevicePrefetcher) while n trains, and ONE dispatch of the
``lax.scan``-fused step (make_scan_train_step) trains all K with no
Python/host round-trips in between.  Logging / validation / save /
profiler cadences and the checkpointed mid-epoch position advance at
K-step granularity; a resume always lands on a super-batch boundary.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from fast_tffm_tpu import obs, platform
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.ops import autotune as autotune_lib
from fast_tffm_tpu.data.libsvm import Batch
from fast_tffm_tpu.data.pipeline import (
    BatchPipeline, DevicePrefetcher, EpochEnd,
)
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.parallel import mesh as mesh_lib
from fast_tffm_tpu.train import checkpoint, metrics as metrics_lib
from fast_tffm_tpu.train import sparse as sparse_lib
from fast_tffm_tpu.train import tiered as tiered_lib
from fast_tffm_tpu.train import tiered_fleet
from fast_tffm_tpu.train.optimizers import make_optimizer

log = logging.getLogger(__name__)


class MetricState(NamedTuple):
    loss_sum: jax.Array  # weighted sum of per-example data losses
    weight_sum: jax.Array
    count: jax.Array  # UNWEIGHTED number of real (weight>0) examples
    auc: metrics_lib.AucState

    @staticmethod
    def zeros() -> "MetricState":
        return MetricState(
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
            metrics_lib.auc_init(),
        )


class TrainState(NamedTuple):
    params: fm.FmParams
    opt_state: tuple
    metrics: MetricState
    step: jax.Array


class NonFiniteGradError(RuntimeError):
    """Raised by ``nan_policy = halt`` when a dispatch produced a
    non-finite (NaN/inf) gradient.  Training stops WITHOUT overwriting
    the checkpoint with poisoned params (periodic saves from before the
    event survive); the metrics stream's final record carries the
    exception type and the health counters."""


class HealthState(NamedTuple):
    """On-device training-health monitors riding the scan carry.

    Updated once per fused-scan step from gradients the step already
    materialized, so the marginal cost is a handful of reductions plus
    one [B*F] -> [vocab] boolean scatter — noise next to the step.  The
    host reads these OUTSIDE the hot path: a one-dispatch-delayed async
    copy of the scalars drives ``nan_policy``, and the occupancy sums
    are computed at logging cadence (never from the heartbeat thread,
    which must stay host-only).
    """

    grad_sq_last: jax.Array  # squared global grad norm, last step
    grad_sq_sum: jax.Array  # running sum over all steps (RMS reporting)
    nonfinite_steps: jax.Array  # int32: steps with any non-finite grad
    first_nonfinite_step: jax.Array  # int32: step index, -1 = never
    # f32 instead of int: totals overflow int32 at scale, and jax's
    # default x64-disabled mode would silently truncate int64.  Exact to
    # 2^24 events per step-increment, which is plenty for a monitor.
    touch_events: jax.Array  # f32: cumulative real feature occurrences
    rows_touched: jax.Array  # bool[vocab]: rows ever touched this run

    @staticmethod
    def zeros(vocab: int) -> "HealthState":
        return HealthState(
            grad_sq_last=jnp.zeros((), jnp.float32),
            grad_sq_sum=jnp.zeros((), jnp.float32),
            nonfinite_steps=jnp.zeros((), jnp.int32),
            first_nonfinite_step=jnp.full((), -1, jnp.int32),
            touch_events=jnp.zeros((), jnp.float32),
            rows_touched=jnp.zeros((vocab,), jnp.bool_),
        )


def _metric_update(
    ms: MetricState, scores, labels, weights, loss_type: str
) -> MetricState:
    lsum, wsum = metrics_lib.weighted_loss(scores, labels, weights, loss_type)
    return MetricState(
        loss_sum=ms.loss_sum + lsum,
        weight_sum=ms.weight_sum + wsum,
        count=ms.count + jnp.sum((weights > 0).astype(jnp.float32)),
        auc=metrics_lib.auc_update(ms.auc, scores, labels, weights),
    )


def _tree_grad_health(grads):
    """(grad_sq, nonfinite_count) over a dense gradient pytree."""
    leaves = jax.tree.leaves(grads)
    grad_sq = sum(
        jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves
    )
    nonfinite = sum(
        jnp.sum((~jnp.isfinite(g)).astype(jnp.int32)) for g in leaves
    )
    return grad_sq, nonfinite


def make_train_step(cfg: FmConfig, optimizer, with_health: bool = False):
    """Dense train step (optax): full-table optimizer update each step.

    ``with_health=True`` returns ``(state, (grad_sq, nonfinite),
    scores)`` — the health aux the scan carry accumulates (the dense
    path reduces the full gradient pytree it already materialized) plus
    the step's raw scores, which the quality plane's scan wrapper can
    emit per-step (make_scan_train_step ``with_scores``)."""

    def step(state: TrainState, batch: Batch):
        def loss_fn(params):
            return fm.loss_and_metrics(
                params,
                batch.labels,
                batch.ids,
                batch.vals,
                batch.fields if cfg.field_num else None,
                batch.weights,
                cfg,
                compute_dtype=cfg.compute_jnp_dtype,
            )

        (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = jax.tree.map(lambda p, u: p + u, state.params, updates)
        ms = _metric_update(
            state.metrics, aux["scores"], batch.labels, batch.weights,
            cfg.loss_type,
        )
        new_state = TrainState(params, opt_state, ms, state.step + 1)
        if with_health:
            return new_state, _tree_grad_health(grads), aux["scores"]
        return new_state

    return step


def make_sparse_train_step(cfg: FmConfig, mesh=None,
                           with_health: bool = False):
    """Sparse train step: optimizer touches only the batch's rows
    (train.sparse — the IndexedSlices path, SURVEY.md §3.2).  The mesh is
    threaded through so the Pallas kernel runs under shard_map (Mosaic
    kernels cannot be auto-partitioned by GSPMD).

    ``lookup = shardmap`` on a multi-device mesh selects the hand-sharded
    step (train.shardmap_step): partial-terms psum instead of row
    gathering, closed-form local backward, dense-delta allreduce."""
    from fast_tffm_tpu.train import shardmap_step

    use_shardmap = (
        cfg.lookup == "shardmap"
        and mesh is not None
        and mesh.size > 1
    )
    if use_shardmap and not shardmap_step.supports_shardmap(cfg, mesh):
        raise ValueError(
            "lookup=shardmap needs optimizer in adagrad/ftrl/sgd, "
            "batch-mode L2, and a vocabulary divisible by "
            f"model_shards*{sparse_lib.sparse_apply.TILE}"
        )

    def step(state: TrainState, batch: Batch, rows_all=None):
        if use_shardmap:
            if rows_all is not None:
                raise ValueError(
                    "prefetched exchange streams do not compose with "
                    "lookup=shardmap"
                )
            out = shardmap_step.sparse_step_shardmap(
                cfg, state.params, state.opt_state, batch, mesh,
                health=with_health,
            )
        else:
            out = sparse_lib.sparse_step(
                cfg, state.params, state.opt_state, batch,
                mesh=mesh, data_axis=mesh_lib.DATA_AXIS,
                health=with_health, rows_all=rows_all,
            )
        params, opt_state, scores = out[0], out[1], out[2]
        ms = _metric_update(
            state.metrics, scores, batch.labels, batch.weights, cfg.loss_type
        )
        new_state = TrainState(params, opt_state, ms, state.step + 1)
        if with_health:
            return new_state, out[3], scores
        return new_state

    return step


def make_health_update(cfg: FmConfig):
    """(health, new_state, batch, aux) -> health, applied once per scan
    step: fold the step's grad aux into the carry and mark the batch's
    real (val != 0) ids in the row-touch mask.  Padded occurrences map
    to index ``vocab`` and drop out of the scatter."""
    vocab = cfg.vocabulary_size

    def update(health: HealthState, new_state: TrainState, batch: Batch,
               aux) -> HealthState:
        grad_sq, nonfinite = aux
        bad = nonfinite > 0
        real = batch.vals.reshape(-1) != 0
        ids = jnp.where(real, batch.ids.reshape(-1), vocab)
        this_step = new_state.step - 1  # the step this batch trained
        return HealthState(
            grad_sq_last=grad_sq,
            grad_sq_sum=health.grad_sq_sum + grad_sq,
            nonfinite_steps=(
                health.nonfinite_steps + bad.astype(jnp.int32)
            ),
            first_nonfinite_step=jnp.where(
                bad & (health.first_nonfinite_step < 0),
                this_step.astype(jnp.int32),
                health.first_nonfinite_step,
            ),
            touch_events=(
                health.touch_events + jnp.sum(real, dtype=jnp.float32)
            ),
            rows_touched=health.rows_touched.at[ids].set(
                True, mode="drop"
            ),
        )

    return update


def make_scan_train_step(step_fn, health_update=None,
                         with_scores: bool = False,
                         prefetch_fn=None):
    """Wrap a (state, batch) -> state train step in ``jax.lax.scan`` over
    a stacked super-batch: ONE dispatch trains K steps with zero
    intervening Python/host round-trips (the device-resident hot loop the
    reference built queue-runners for, PAPER.md §2 #6).

    The carry is the TrainState (donated at the jit boundary); xs is a
    Batch whose every leaf carries a leading K axis — including stacked
    host ``sort_meta``, so the per-step tile apply still skips its
    on-device sort.  K is baked into the trace: the jitted wrapper
    retraces per distinct K, so an epoch tail at K' = leftover costs one
    extra compile the first time that K' appears.

    With ``health_update``, ``step_fn`` must return ``(state, aux,
    scores)`` and the wrapper becomes ``(state, health, batches) ->
    (state, health)``: a :class:`HealthState` rides the scan carry
    alongside the TrainState — grad-norm / non-finite / row-touch
    monitors updated on-device every step, read back by the host only
    at dispatch boundaries.  The health carry is deliberately NOT
    donated (it is a separate argument) so the host can keep the
    previous dispatch's scalars alive for its delayed ``nan_policy``
    check without racing buffer donation.

    ``with_scores=True`` (the quality plane, cfg.quality) additionally
    stacks each step's raw scores as the scan's ys and returns
    ``(state, health, scores[K, B])`` — the per-dispatch eval feed the
    windowed online-eval monitor consumes one dispatch delayed (same
    async-D2H discipline as the health scalars).  The scores were
    already computed by every step; emitting them adds one [K, B]
    store, no math — the carry update is identical either way, so
    training stays bitwise-identical with the flag off or on (pinned
    by tests/test_quality.py).

    ``prefetch_fn`` (sparse_exchange_overlap): ``ids[flat] ->
    rows_all`` building the merged cross-rank entries stream for the
    sharded sparse apply.  The stream for step i+1 is a pure function
    of its ids — no dependency on step i's params — so the scan body
    computes it AFTER the step that consumes the carried stream: XLA
    schedules the i+1 all-gather concurrently with step i's rank-local
    apply (the no-bubble overlap).  Step 0's stream is built before
    the scan; the last body's prefetch targets a throwaway duplicate
    of the final batch (its result is discarded with the carry).
    Params are bitwise-identical to the non-overlapped path: the
    stream handed to each step is exactly the one the step would have
    computed inline (pinned by tests).
    """
    if health_update is None:
        if prefetch_fn is not None:
            raise ValueError(
                "exchange-overlap prefetch requires the health-carry "
                "scan (the trainer's only dispatch path)"
            )

        def scan_step(state: TrainState, batches: Batch) -> TrainState:
            def body(carry, batch):
                return step_fn(carry, batch), None

            state, _ = jax.lax.scan(body, state, batches)
            return state

        return scan_step

    def scan_health_step(state: TrainState, health: HealthState,
                         batches: Batch):
        if prefetch_fn is not None:
            # xs gains each step's NEXT ids (last one self-duplicated);
            # the carried stream always matches the batch it trains.
            next_ids = jnp.concatenate(
                [batches.ids[1:], batches.ids[-1:]], axis=0
            )
            streams0 = prefetch_fn(batches.ids[0].reshape(-1))

            def body(carry, xs):
                s, h, streams = carry
                batch, nids = xs
                s2, aux, scores = step_fn(s, batch, streams)
                streams2 = prefetch_fn(nids.reshape(-1))
                carry2 = (s2, health_update(h, s2, batch, aux), streams2)
                return carry2, (scores if with_scores else None)

            (state, health, _), ys = jax.lax.scan(
                body, (state, health, streams0), (batches, next_ids)
            )
            if with_scores:
                return state, health, ys
            return state, health

        def body(carry, batch):
            s, h = carry
            s2, aux, scores = step_fn(s, batch)
            carry2 = (s2, health_update(h, s2, batch, aux))
            return carry2, (scores if with_scores else None)

        (state, health), ys = jax.lax.scan(
            body, (state, health), batches
        )
        if with_scores:
            return state, health, ys
        return state, health

    return scan_health_step


def make_eval_step(cfg: FmConfig):
    def step(params: fm.FmParams, ms: MetricState, batch: Batch) -> MetricState:
        scores = fm.fm_scores(
            params,
            batch.ids,
            batch.vals,
            batch.fields if cfg.field_num else None,
            factor_num=cfg.factor_num,
            field_num=cfg.field_num,
        )
        return _metric_update(
            ms, scores, batch.labels, batch.weights, cfg.loss_type
        )

    return step


def _finalize_metrics(ms: MetricState, loss_type: str = "logistic") -> dict:
    """Streaming means. The loss key is "logloss" for logistic training and
    "mse" for mse training (plus a loss_type-agnostic "loss" alias).

    ``examples`` is the UNWEIGHTED count of real examples (a weighted run
    used to report weight-sums as examples, inflating/deflating rates);
    ``weight_sum`` carries the loss normalizer separately."""
    wsum = max(float(ms.weight_sum), 1e-12)
    loss = float(ms.loss_sum) / wsum
    out = {
        "loss": loss,
        "auc": float(metrics_lib.auc_finalize(ms.auc)),
        "examples": float(ms.count),
        "weight_sum": float(ms.weight_sum),
    }
    out["mse" if loss_type == "mse" else "logloss"] = loss
    return out


def _config_fingerprint(cfg: FmConfig) -> str:
    """Short stable hash of the FULL config — the run-header record's
    identity, so two metrics files are comparable iff fingerprints match
    (unlike Trainer._data_fingerprint, which names only the input
    stream)."""
    blob = json.dumps(dataclasses.asdict(cfg), sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _params_template(cfg: FmConfig, param_sh):
    shapes = jax.eval_shape(partial(fm.init_params, cfg=cfg), jax.random.PRNGKey(0))
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes,
        param_sh,
    )


class Trainer:
    """Drives training per an FmConfig — the `local_train` engine.

    With a multi-device mesh this same class is the `dist_train` engine:
    the only difference is the mesh passed in (and, multi-host, a
    jax.distributed.initialize() call before construction — see
    train.dist).
    """

    def __init__(self, cfg: FmConfig, mesh=None):
        self.cfg = cfg
        # Persistent XLA compilation cache (compile_cache_dir knob):
        # enabled before ANY jit below so restarts replay this run's
        # step/eval compiles from disk instead of re-lowering.
        if cfg.compile_cache_dir:
            platform.enable_compile_cache(cfg.compile_cache_dir)
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(cfg)
        # Run-wide telemetry registry, shared by the ingest pipeline, the
        # transfer thread, and the dispatch loop.  Disabled -> every
        # instrument is a shared no-op (zero behavior change).
        self.telemetry = obs.Telemetry(enabled=cfg.telemetry)
        # Causal batch tracer (Chrome-trace spans; obs/trace.py).  Only
        # live when cfg.trace_file names an output — otherwise every
        # span call is a shared no-op, and training is bit-identical.
        # One trace path per process: rank 0 owns the configured path,
        # ranks > 0 suffix theirs (tools/report.py --trace merges the
        # fleet).  With trace_rotate_events set, the tracer dumps and
        # resets at the watermark (trace.0.json, trace.1.json, ...) so
        # multi-hour traced runs never hit the in-memory event cap.
        self._trace_path = cfg.trace_file
        if cfg.trace_file and jax.process_index() > 0:
            self._trace_path = (
                f"{cfg.trace_file}.rank{jax.process_index()}"
            )
        self.tracer = obs.Tracer(
            enabled=bool(cfg.trace_file),
            process_name=f"trainer rank{jax.process_index()}",
            rotate_events=cfg.trace_rotate_events,
            rotate_path=self._trace_path,
        )
        # Input-pipeline position for checkpointed mid-epoch resume.
        self._epoch = 0
        self._batches_done = 0
        self.sparse = bool(cfg.sparse_update) and sparse_lib.supports_sparse(cfg)
        if cfg.sparse_update and not self.sparse:
            log.info(
                "sparse_update unsupported for optimizer=%s l2_mode=%s; "
                "using dense optax path", cfg.optimizer, cfg.l2_mode,
            )
        if self.sparse:
            self.optimizer = None
            self._opt_init_fn = partial(sparse_lib.init_sparse_opt_state, cfg)
        else:
            self.optimizer = make_optimizer(cfg)
            self._opt_init_fn = self.optimizer.init
        # Tiered embedding table (train.tiered): the device trains
        # against a compact HOT table of hot_rows rows; the full logical
        # table lives in a host-RAM cold store and rows migrate per
        # super-batch.  Everything device-side is built from a config
        # whose vocabulary_size is the hot-table size; ingest keeps the
        # LOGICAL vocabulary (parsing, hashing, OOR checks are stream
        # properties, not table-layout properties).
        self.tiered: Optional[tiered_lib.TieredTable] = None
        self._dcfg = cfg
        # Rank-sharded tiering: "shards" partitions the tier manager by
        # model column (tiered_fleet.ShardedTiering) so each rank plans/
        # migrates/checkpoints ONLY its own id range — the geometry that
        # makes fleet-tiered training scale (~1/R host bytes + migration
        # traffic per rank).  Resolved here so every later branch keys on
        # one boolean.
        self._tiering_sharded = False
        self._tier_shards = 1
        self._tier_owned: tuple = ()
        if cfg.table_tiering == "on":
            if not self.sparse:
                raise ValueError(
                    "table_tiering=on requires the sparse update path "
                    "(optimizer in adagrad/ftrl/sgd with batch-mode L2): "
                    "a dense optimizer rewrites every row every step, so "
                    "there is no cold set to keep off-device"
                )
            part = cfg.tiered_partition
            if part == "auto":
                part = "shards" if jax.process_count() > 1 else "global"
            if part == "global" and jax.process_count() > 1:
                raise ValueError(
                    "tiered_partition=global is single-process (the "
                    "hot-slot map is host-global); multi-process tiered "
                    "training needs tiered_partition=shards (or auto)"
                )
            if cfg.lookup == "shardmap":
                raise ValueError(
                    "table_tiering=on does not compose with "
                    "lookup=shardmap yet; use lookup=auto"
                )
            hot = min(cfg.hot_rows, cfg.vocabulary_size)
            if part == "shards":
                # Shard == model column: the owner of a column's device
                # rows is the one process allowed to hold its cold store.
                owners = tiered_fleet.column_owners(self.mesh)
                if mesh_lib.data_partition(self.mesh)[1] != 1:
                    raise ValueError(
                        "tiered_partition=shards requires every process "
                        "to parse the FULL global batch (one host data "
                        "block): the lockstep mirrors only stay equal to "
                        "their owners when all ranks plan identical "
                        "batches.  Use a mesh whose DATA axis does not "
                        "span processes (canonically mesh_data=1, "
                        "mesh_model=<process count>)."
                    )
                n_shards = self.mesh.shape[mesh_lib.MODEL_AXIS]
                if cfg.vocabulary_size % n_shards or hot % n_shards:
                    raise ValueError(
                        f"tiered_partition=shards needs vocabulary_size "
                        f"({cfg.vocabulary_size}) and effective hot_rows "
                        f"({hot}) divisible by the mesh model size "
                        f"({n_shards})"
                    )
                self._tiering_sharded = True
                self._tier_shards = n_shards
                self._tier_owned = tuple(
                    s for s, o in enumerate(owners)
                    if o == jax.process_index()
                )
                if (
                    cfg.validation_files
                    and len(self._tier_owned) != n_shards
                ):
                    raise ValueError(
                        "validation_files with fleet-sharded tiering: "
                        "evaluation needs every shard's cold store, but "
                        f"this rank owns {len(self._tier_owned)} of "
                        f"{n_shards} shards.  Evaluate from the saved "
                        "checkpoint instead (it merges all shards)."
                    )
            self._dcfg = dataclasses.replace(cfg, vocabulary_size=hot)
            if cfg.hot_rows >= cfg.vocabulary_size:
                log.info(
                    "table_tiering=on with hot_rows >= vocabulary_size: "
                    "every row fits the hot table (tiering is a no-op "
                    "beyond the remap)"
                )
        if cfg.batch_size % self.mesh.shape[mesh_lib.DATA_AXIS] != 0:
            raise ValueError(
                f"batch_size {cfg.batch_size} not divisible by data-mesh "
                f"size {self.mesh.shape[mesh_lib.DATA_AXIS]}"
            )

        param_sh = mesh_lib.param_sharding(self.mesh)
        self._param_sh = param_sh
        self._batch_sh = Batch(**mesh_lib.batch_sharding(self.mesh))
        rep = NamedSharding(self.mesh, P())

        params, opt_state = self._init_or_restore(param_sh)
        self.state = TrainState(
            params=params,
            opt_state=opt_state,
            metrics=jax.device_put(MetricState.zeros(), rep),
            step=jax.device_put(jnp.zeros((), jnp.int32), rep),
        )

        state_sh = jax.tree.map(lambda x: x.sharding, self.state)
        # Kernel autotune (ops/autotune.py): interaction_impl=auto
        # benchmarks the candidate interaction paths at THIS run's
        # (batch, dim) shapes and promotes the fastest that passes the
        # element-wise parity gate; pins and the legacy knobs resolve
        # with zero measurement.  The decision rewrites the device
        # config's legacy `interaction` field so every step builder
        # keeps its single dispatch point (cfg.interaction_resolved).
        self._autotune: Optional[autotune_lib.Decision] = None
        if self._dcfg.interaction_resolved == "auto":
            self._autotune = autotune_lib.resolve(self._dcfg, context="train")
            self._dcfg = dataclasses.replace(
                self._dcfg, interaction_impl="",
                interaction=self._autotune.interaction,
            )
        # The user-facing name of the impl this run trains with —
        # surfaced in the run header and bench JSON as `kernel_impl`.
        self.kernel_impl = autotune_lib.USER.get(
            self._dcfg.interaction_resolved, self._dcfg.interaction_resolved
        )
        # All device-side step math is built from _dcfg: identical to cfg
        # except that, with tiering on, vocabulary_size is the hot-table
        # size (the step's math never reads the vocab beyond table shape)
        # — and, after an autotune resolution, the measured interaction.
        dcfg = self._dcfg
        # Compute-overlapped sparse exchange: with the sharded apply's
        # "entries" exchange over >1 data shard, the deduped touched-row
        # stream for super-batch step i+1 is a pure function of its ids —
        # so the fused scan can prefetch it (all-gather) concurrently
        # with step i's rank-local apply (see make_scan_train_step).
        # Resolved ONCE from the device config the step actually runs
        # with; "on" on a path that cannot overlap refuses loudly
        # (sparse_lib.overlap_active), never goes silently inert.
        self._overlap_active = False
        if cfg.sparse_exchange_overlap != "off":
            blocked = not self.sparse or (
                cfg.lookup == "shardmap" and self.mesh.size > 1
            )
            if blocked:
                if cfg.sparse_exchange_overlap == "on":
                    raise ValueError(
                        "sparse_exchange_overlap=on requires the sparse "
                        "gather/apply step (optimizer in adagrad/ftrl/"
                        "sgd, lookup != shardmap); this run resolved to "
                        + ("the dense step" if not self.sparse
                           else "lookup=shardmap")
                    )
            else:
                self._overlap_active = sparse_lib.overlap_active(
                    dcfg, self.mesh
                )
        step_fn = (
            make_sparse_train_step(dcfg, self.mesh)
            if self.sparse
            else make_train_step(dcfg, self.optimizer)
        )
        # Visible record of the chosen execution strategy: a silent
        # fallback (e.g. interpret-mode Pallas on an unrecognized
        # platform) is orders of magnitude slower, so surface it once.
        from fast_tffm_tpu.platform import use_interpret

        log.info(
            "step build: sparse=%s apply_mode=%s interaction=%s "
            "interpret=%s backend=%s mesh=%s",
            self.sparse,
            sparse_lib.apply_mode(dcfg, self.mesh) if self.sparse else "dense",
            dcfg.interaction_resolved, use_interpret(), jax.default_backend(),
            dict(self.mesh.shape),
        )
        self._train_step = jax.jit(
            step_fn,
            in_shardings=(state_sh, self._batch_sh),
            out_shardings=state_sh,
            donate_argnums=0,
        )
        # K-step fused dispatch: the same step math under lax.scan over
        # a stacked [K, ...] super-batch, with the HealthState monitors
        # riding the carry (grad-norm, non-finite detection, row-touch
        # mask — updated on-device per step, read back at dispatch
        # boundaries).  train() always dispatches through this
        # (steps_per_dispatch == 1 is a scan of length 1, numerically
        # identical to the single step); _train_step stays for direct
        # single-batch callers (bench step-only mode, tests) and carries
        # no health.
        self._super_batch_sh = Batch(**mesh_lib.super_batch_sharding(self.mesh))
        step_fn_health = (
            make_sparse_train_step(dcfg, self.mesh, with_health=True)
            if self.sparse
            else make_train_step(dcfg, self.optimizer, with_health=True)
        )
        self._health = jax.device_put(
            HealthState.zeros(dcfg.vocabulary_size), rep
        )
        self._health_host: dict = {}  # last host-read health scalars
        self._health_step0 = int(self.state.step)  # run-start step base
        health_sh = jax.tree.map(lambda x: x.sharding, self._health)
        # Model-quality plane (obs/quality.py): with cfg.quality on,
        # the fused scan additionally emits each step's scores as the
        # scan ys — the feed for the windowed online-eval monitor,
        # consumed one dispatch delayed exactly like the health
        # scalars.  Multi-host runs skip the eval feed (the per-host
        # view of a globally sharded score array is partial); the
        # ingest-side drift sketches still run per host.  The objects
        # themselves are per-run (created in train()).
        self._with_scores = bool(cfg.quality) and jax.process_count() == 1
        self._quality: Optional[obs.QualityMonitor] = None
        self._quality_sketch: Optional[obs.StreamSketch] = None
        self._last_scores = None
        # Only the TrainState is donated: the un-donated health arrays
        # let the host keep the PREVIOUS dispatch's nonfinite/grad-norm
        # scalars alive for the delayed nan_policy check (a donated
        # carry would invalidate them under the next dispatch).
        scan_out_sh = (state_sh, health_sh)
        if self._with_scores:
            # ys [K, B] shards like the stacked labels it aligns with.
            scan_out_sh = scan_out_sh + (self._super_batch_sh.labels,)
        prefetch_fn = None
        if self._overlap_active:
            prefetch_fn = sparse_lib.sparse_apply.make_entries_prefetch(
                self.mesh, mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS,
                dcfg.vocabulary_size,
            )
            log.info(
                "sparse exchange overlap active: entries streams "
                "prefetch one scan step ahead of the rank-local apply"
            )
        self._scan_health_jit = jax.jit(
            make_scan_train_step(
                step_fn_health, make_health_update(dcfg),
                with_scores=self._with_scores,
                prefetch_fn=prefetch_fn,
            ),
            in_shardings=(state_sh, health_sh, self._super_batch_sh),
            out_shardings=scan_out_sh,
            donate_argnums=0,
        )
        # Resource plane (obs/resource.py): the fused-scan dispatch runs
        # through an AOT compile cache (.lower().compile() keyed on the
        # super-batch's exact shapes/dtypes/structure) so every compile
        # is an explicit, timed, cost-analyzed event the CompileSentinel
        # accounts for — instead of an invisible stall inside jit
        # dispatch.  The documented epoch-tail K' < K compile is
        # whitelisted; anything else (batch-shape drift, a sort-meta
        # presence flip, a foreign K) bumps recompiles_unexpected and
        # warns.  resource_metrics=off skips the cache entirely — the
        # historical implicit-jit path, bit-identical training.
        self._compile_cache: dict = {}
        self._primary_rest = None  # non-leading shape sig of compile #1
        self._aot_broken = False  # toolchain drift -> permanent fallback
        # A short-k compile is whitelisted PROVISIONALLY: a real epoch
        # tail is followed by the EpochEnd marker (or end of stream),
        # so the dispatch loop confirms the boundary and reclassifies
        # the compile as unexpected if any other super-batch follows.
        self._tail_probation = None  # (k, step) awaiting confirmation
        self._sentinel = (
            obs.CompileSentinel(
                telemetry=self.telemetry,
                expected_k=cfg.steps_per_dispatch,
            )
            if cfg.resource_metrics else None
        )
        self._dispatches = 0  # per-run dispatch count (throughput attr.)
        self._run_steps = 0  # per-run step count, visible to the sentinel
        # Shape-derived device-memory estimate: table + optimizer-slot
        # bytes of THIS PROCESS's device state (with tiering on, the hot
        # tables).  Summed over addressable shards with replica dedupe —
        # equal to x.nbytes single-process, and ~1/R per rank for the
        # P(MODEL)-sharded tables of a fleet (the bench's sharded-vs-
        # global byte assertion reads exactly this).  The truth where
        # the backend reports it (memory_stats on TPU); this is the
        # documented CPU fallback, computed once.
        def leaf_bytes(x):
            try:
                shards = x.addressable_shards
            except Exception:  # pragma: no cover - non-Array leaf
                return int(x.nbytes)
            uniq = {}
            for sh in shards:
                key = tuple(
                    (sl.start, sl.stop) for sl in sh.index
                )
                uniq[key] = int(sh.data.nbytes)
            return sum(uniq.values())

        self._state_bytes_est = int(sum(
            leaf_bytes(x) for x in jax.tree.leaves(
                (self.state.params, self.state.opt_state)
            )
        ))
        ms_sh = jax.tree.map(lambda _: rep, MetricState.zeros())
        self._eval_step = jax.jit(
            make_eval_step(cfg),
            in_shardings=(state_sh.params, ms_sh, self._batch_sh),
            out_shardings=ms_sh,
            donate_argnums=1,
        )
        if self.tiered is not None:
            # Migration jits: gather the evicted slots' current rows
            # (async D2H write-back source) and overwrite loaded slots
            # with cold rows (the pad slot index == hot_rows scatter-
            # drops).  Tables keep their row sharding.  The load donates
            # the old tables so the hot-table buffers are reused in
            # place.
            n_tab = 1 + len(tiered_lib.opt_table_names(cfg.optimizer))
            tab_sh = (param_sh.table,) * n_tab
            if self._tiering_sharded:
                # Fleet variant: slot/row plan arrays are P(MODEL)-
                # sharded (each process supplied only its own columns'
                # blocks in _put_super) and the bodies run under
                # shard_map with NO collectives — each column touches
                # only its own rows, so cross-rank migration traffic is
                # structurally zero.  Slots are column-LOCAL with pad
                # == hs (the per-column scatter-drop index).
                mp = mesh_lib.MODEL_AXIS
                tab_spec = (P(mp, None),) * n_tab
                slot_sh = NamedSharding(self.mesh, P(mp))
                row_sh = (param_sh.table,) * n_tab

                def _gather_fn(tables, slots):
                    return tuple(t[slots] for t in tables)

                def _load_fn(tables, slots, rows):
                    return tuple(
                        t.at[slots].set(r, mode="drop")
                        for t, r in zip(tables, rows)
                    )

                self._tier_gather_jit = jax.jit(
                    platform.shard_map(
                        _gather_fn, mesh=self.mesh,
                        in_specs=(tab_spec, P(mp)),
                        out_specs=tab_spec,
                    ),
                    in_shardings=(tab_sh, slot_sh),
                    out_shardings=tab_sh,
                )
                self._tier_load_jit = jax.jit(
                    platform.shard_map(
                        _load_fn, mesh=self.mesh,
                        in_specs=(tab_spec, P(mp), tab_spec),
                        out_specs=tab_spec,
                    ),
                    in_shardings=(tab_sh, slot_sh, row_sh),
                    out_shardings=tab_sh,
                    donate_argnums=0,
                )
            else:
                # Host-global variant: slot/row operands replicated.

                def _gather_fn(tables, slots):
                    return tuple(t[slots] for t in tables)

                def _load_fn(tables, slots, rows):
                    return tuple(
                        t.at[slots].set(r, mode="drop")
                        for t, r in zip(tables, rows)
                    )

                self._tier_gather_jit = jax.jit(
                    _gather_fn,
                    in_shardings=(tab_sh, rep),
                    out_shardings=(rep,) * n_tab,
                )
                self._tier_load_jit = jax.jit(
                    _load_fn,
                    in_shardings=(tab_sh, rep, (rep,) * n_tab),
                    out_shardings=tab_sh,
                    donate_argnums=0,
                )
            self._tiered_eval_jit = None  # built lazily (merged eval)

    def _opt_shardings(self, param_sh, params_template):
        """Sharding for each optimizer-state leaf: table-shaped accumulators
        follow the table's row sharding, everything else is replicated
        (SURVEY.md §7 hard-part 4: optimizer state never gathers)."""
        rep = NamedSharding(self.mesh, P())
        table_shape = params_template.table.shape
        opt_shapes = jax.eval_shape(self._opt_init_fn, params_template)
        return jax.tree.map(
            lambda s: param_sh.table if s.shape == table_shape else rep,
            opt_shapes,
        )

    def _init_or_restore(self, param_sh):
        if self.cfg.table_tiering == "on":
            return self._init_or_restore_tiered(param_sh)
        cfg = self.cfg
        if checkpoint.exists_tiered(cfg.model_file):
            # Refuse loudly rather than silently cold-starting over (or
            # preferring possibly-stale dense dirs beside) a tiered
            # overlay: the two formats carry no shared freshness marker,
            # and the overlay holds a table too large to restore densely.
            raise ValueError(
                f"{cfg.model_file} holds a tiered overlay checkpoint "
                "(written by table_tiering=on at a vocabulary too large "
                "for the dense format); resume it with table_tiering=on, "
                "or point model_file somewhere fresh to train dense"
            )
        if checkpoint.exists_quant(cfg.model_file):
            # Same refusal discipline for the quantized serving format:
            # training warm-starts want full-precision params (and the
            # quantized table carries no optimizer state) — silently
            # cold-starting over it would discard a model.
            raise ValueError(
                f"{cfg.model_file} holds a quantized serving checkpoint "
                "(quant.npz); training cannot warm-start from it — "
                "convert it back to the dense format first "
                "(python -m tools.convert_checkpoint <dir> --to fp32), "
                "or point model_file somewhere fresh"
            )
        template = _params_template(cfg, param_sh)
        opt_sh = self._opt_shardings(param_sh, template)
        opt_init = jax.jit(self._opt_init_fn, out_shardings=opt_sh)
        if checkpoint.exists(cfg.model_file):
            log.info("warm-starting from %s", cfg.model_file)
            params, self._restored_step = checkpoint.restore_params(
                cfg.model_file, template
            )
            params = fm.FmParams(*params)
            opt_shapes = jax.eval_shape(self._opt_init_fn, template)
            opt_template = jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
                opt_shapes,
                opt_sh,
            )
            opt_state = checkpoint.restore_opt(cfg.model_file, opt_template)
            if opt_state is None:
                opt_state = opt_init(params)
            elif self.sparse and cfg.optimizer == "ftrl":
                params = self._check_ftrl_invariant(params, opt_state)
            return params, opt_state
        self._restored_step = 0
        init = jax.jit(partial(fm.init_params, cfg=cfg), out_shardings=param_sh)
        params = init(jax.random.PRNGKey(cfg.seed))
        return params, opt_init(params)

    def _init_or_restore_tiered(self, param_sh):
        """Build the HOT device state + the host-side TieredTable.

        The hot tables' initial values are placeholders: a slot only
        influences training after a migration load overwrites it with
        its cold row, so any deterministic init works.  The checkpoint
        of record is the LOGICAL table: a tiered overlay
        (checkpoint.restore_tiered) when present, else the ordinary
        dense checkpoint restored to host numpy and used to seed the
        cold store — so a tiered run resumes from a dense run's
        checkpoint (and vice versa, via the merged dense save) with any
        hot_rows.
        """
        cfg, dcfg = self.cfg, self._dcfg
        rep = NamedSharding(self.mesh, P())
        template = _params_template(dcfg, param_sh)
        opt_sh = self._opt_shardings(param_sh, template)
        opt_init = jax.jit(self._opt_init_fn, out_shardings=opt_sh)
        init = jax.jit(
            partial(fm.init_params, cfg=dcfg), out_shardings=param_sh
        )
        params = init(jax.random.PRNGKey(cfg.seed))

        def put_scalar(x):
            return jax.device_put(jnp.asarray(x, jnp.float32), rep)

        if checkpoint.exists_quant(cfg.model_file):
            raise ValueError(
                f"{cfg.model_file} holds a quantized serving checkpoint "
                "(quant.npz); a tiered trainer cannot warm-start from "
                "it — convert it back to the dense format first "
                "(python -m tools.convert_checkpoint <dir> --to fp32)"
            )
        overlay = checkpoint.restore_tiered(cfg.model_file)
        if overlay is not None:
            step, scalars, stores = overlay
            self._restored_step = step
            log.info(
                "warm-starting tiered table from overlay checkpoint %s "
                "(step %d)", cfg.model_file, step,
            )
            self.tiered = self._make_tier_manager(overlay=stores)
            params = params._replace(w0=put_scalar(scalars["w0"]))
            opt_state = tiered_lib.set_opt_scalars(
                cfg.optimizer, opt_init(params), scalars, put_scalar
            )
            return params, opt_state
        if checkpoint.exists(cfg.model_file):
            log.info(
                "warm-starting tiered table from dense checkpoint %s",
                cfg.model_file,
            )
            # Restore to HOST numpy at the logical shape (templates
            # without shardings), never materializing on device.
            np_tmpl = jax.eval_shape(
                partial(fm.init_params, cfg=cfg), jax.random.PRNGKey(0)
            )
            np_params, self._restored_step = checkpoint.restore_params(
                cfg.model_file, np_tmpl
            )
            np_params = fm.FmParams(*np_params)
            opt_np = checkpoint.restore_opt(
                cfg.model_file, jax.eval_shape(self._opt_init_fn, np_tmpl)
            )
            if opt_np is not None and cfg.optimizer == "ftrl":
                # Same contract as the dense path's _check_ftrl_invariant:
                # the sparse FTRL applies rely on w == ftrl_solve(z, n),
                # so a table edited outside train.sparse is loudly
                # normalized before it seeds the cold store.
                np_params = self._ftrl_normalize_np(np_params, opt_np)
            dense_tables = {"table": np.asarray(np_params.table)}
            params = params._replace(w0=put_scalar(np_params.w0))
            # Scalar (w0) optimizer slots: restored when present, else
            # derived from the restored w0 — the same thing the dense
            # path's opt_init-on-restored-params does.
            opt_state = opt_init(params)
            if opt_np is not None:
                for name, tab in zip(
                    tiered_lib.opt_table_names(cfg.optimizer),
                    tiered_lib.get_opt_tables(cfg.optimizer, opt_np),
                ):
                    dense_tables[name] = np.asarray(tab)
                opt_state = tiered_lib.set_opt_scalars(
                    cfg.optimizer, opt_state,
                    tiered_lib.get_opt_scalars(cfg.optimizer, opt_np),
                    put_scalar,
                )
            self.tiered = self._make_tier_manager(
                dense_tables=dense_tables
            )
            return params, opt_state
        self._restored_step = 0
        self.tiered = self._make_tier_manager()
        return params, opt_init(params)

    def _make_tier_manager(self, dense_tables=None, overlay=None):
        """The tier manager this run's partition mode calls for: the
        host-global :class:`tiered_lib.TieredTable`, or (tiered_partition
        = shards) the rank-sharded coordinator — restore payloads are
        GLOBAL either way (the coordinator slices per shard itself, which
        is what makes checkpoints elastic across shard counts)."""
        if self._tiering_sharded:
            return tiered_fleet.ShardedTiering(
                self.cfg, self._tier_shards, self._tier_owned,
                telemetry=self.telemetry, dense_tables=dense_tables,
                overlay=overlay,
            )
        return tiered_lib.TieredTable(
            self.cfg, telemetry=self.telemetry,
            dense_tables=dense_tables, overlay=overlay,
        )

    def _ftrl_normalize_np(self, np_params, opt_np):
        """Host-side mirror of :meth:`_check_ftrl_invariant` for the
        tiered warm start (the restored table lives in host numpy on its
        way into the cold store, never on device)."""
        cfg = self.cfg
        solve = partial(
            sparse_lib.sparse_apply.ftrl_solve,
            lr=cfg.learning_rate, l1=cfg.ftrl_l1, l2=cfg.ftrl_l2,
            beta=cfg.ftrl_beta,
        )
        expect = fm.FmParams(
            w0=np.asarray(solve(jnp.asarray(opt_np.z.w0),
                                jnp.asarray(opt_np.n.w0))),
            table=np.asarray(solve(jnp.asarray(opt_np.z.table),
                                   jnp.asarray(opt_np.n.table))),
        )
        dev = max(
            float(np.max(np.abs(expect.w0 - np.asarray(np_params.w0)))),
            float(np.max(np.abs(expect.table - np.asarray(np_params.table)))),
        )
        if dev <= 1e-6:
            return np_params
        log.warning(
            "warm-started FTRL params violate w == ftrl_solve(z, n) "
            "(max |dev| %.3g) — the table was edited outside "
            "train.sparse.  Normalizing before seeding the tiered cold "
            "store, matching the dense restore path.", dev,
        )
        return expect

    def _check_ftrl_invariant(self, params, opt_state):
        """Enforce the FTRL closed-form invariant on a warm start.

        Every sparse FTRL path maintains ``w == ftrl_solve(z, n)``, and
        the compact-K2 tile apply RELIES on it: compact sweeps skip
        untouched rows while the full sweep recomputes them, and the two
        only agree because recompute == stored value (ops.sparse_apply.
        ftrl_apply).  A checkpoint whose table was edited outside
        train.sparse would otherwise drift silently, sweep-dependently.
        Restore-time normalization makes the violation loud and fixes it:
        ``w = ftrl_solve(z, n)`` is a no-op for invariant-respecting
        checkpoints (our own, and fresh z inits) and canonicalizes the
        rest.
        """
        cfg = self.cfg
        solve = jax.jit(
            partial(
                sparse_lib.sparse_apply.ftrl_solve,
                lr=cfg.learning_rate, l1=cfg.ftrl_l1, l2=cfg.ftrl_l2,
                beta=cfg.ftrl_beta,
            )
        )
        expect = fm.FmParams(
            w0=solve(opt_state.z.w0, opt_state.n.w0),
            table=solve(opt_state.z.table, opt_state.n.table),
        )
        dev = max(
            float(jnp.max(jnp.abs(expect.w0 - params.w0))),
            float(jnp.max(jnp.abs(expect.table - params.table))),
        )
        if dev <= 1e-6:
            return params  # invariant holds; keep the restored bits
        log.warning(
            "warm-started FTRL params violate w == ftrl_solve(z, n) "
            "(max |dev| %.3g) — the table was edited outside train.sparse. "
            "Normalizing w = ftrl_solve(z, n) so the compact-K2 apply "
            "stays sweep-independent.", dev,
        )
        return expect

    def _scan_train_step(self, state: TrainState, batches: Batch):
        """One fused K-step dispatch (the hot-loop entry point).

        Keeps the historical ``(state, batches) -> state`` surface —
        bench step timing and the resume tests wrap exactly this — while
        threading the health carry through ``self._health`` (monitors
        never change the TrainState math, so scan parity with K single
        ``_train_step`` calls stays bitwise).  With the resource plane
        on, dispatch goes through the AOT compile cache so the compile
        sentinel sees every (re)compilation; the executable is the same
        lowering jit would have produced, so the math is identical
        either way."""
        if self._sentinel is not None and not self._aot_broken:
            fn = self._compiled_scan(state, batches)
        else:
            fn = self._scan_health_jit
        if self._with_scores:
            state, self._health, self._last_scores = fn(
                state, self._health, batches
            )
        else:
            state, self._health = fn(state, self._health, batches)
        return state

    def _compiled_scan(self, state: TrainState, batches: Batch):
        """AOT compile cache for the fused-scan step.

        Keyed on the super-batch's pytree structure + per-leaf
        shape/dtype (structure matters: a sort_meta that flips between
        present and None retraces, and that flip is exactly a silent
        recompile worth flagging).  A miss compiles explicitly
        (``.lower().compile()``), timed and cost-analyzed for the
        sentinel.  Expected compiles: the first ever (startup), and an
        epoch-tail K' < steps_per_dispatch whose non-leading shapes
        match the first compile's — whitelisted provisionally, then
        confirmed by the dispatch loop (an epoch boundary must follow;
        see _resolve_tail_probation).  Any API drift in the AOT path
        degrades permanently to the implicit-jit call — observability
        must never take down the training it observes."""
        leaves, treedef = jax.tree_util.tree_flatten(batches)
        key = (treedef, tuple((x.shape, str(x.dtype)) for x in leaves))
        fn = self._compile_cache.get(key)
        if fn is not None:
            return fn
        k = int(batches.labels.shape[0])
        rest = tuple(x.shape[1:] for x in leaves)
        try:
            t0 = time.perf_counter()
            with self.tracer.span("train.compile", args={"k": k}), \
                    obs.trace_span("tffm:compile"):
                fn = self._scan_health_jit.lower(
                    state, self._health, batches
                ).compile()
            wall = time.perf_counter() - t0
        except Exception as e:  # pragma: no cover - jax API drift
            self._aot_broken = True
            log.warning(
                "AOT compile path unavailable (%s: %s); compile "
                "sentinel disabled, dispatching through plain jit",
                type(e).__name__, e,
            )
            return self._scan_health_jit
        if self._primary_rest is None:
            expected = True  # startup compile (whatever its K)
            self._primary_rest = rest
        else:
            expected = (
                rest == self._primary_rest
                and k <= self._sentinel.expected_k
            )
            if expected and k < self._sentinel.expected_k:
                # Provisional: only a real epoch tail earns the
                # whitelist.  _resolve_tail_probation (dispatch loop)
                # checks that an epoch boundary actually follows this
                # super-batch and reclassifies if not.
                self._tail_probation = (k, self._run_steps)
        self._sentinel.record(
            wall, k, expected, cost=self._cost_of(fn),
            step=self._run_steps,
        )
        self._compile_cache[key] = fn
        return fn

    def _resolve_tail_probation(self, item) -> None:
        """Confirm or refute a provisionally-whitelisted short-k
        compile with what the pipeline delivered NEXT: an EpochEnd
        marker or end of stream (``None``) confirms the epoch tail;
        another super-batch means the stream is emitting short groups
        mid-epoch — the drift class the sentinel exists to flag."""
        if self._tail_probation is None:
            return
        k, step = self._tail_probation
        self._tail_probation = None
        if item is not None and not isinstance(item, EpochEnd):
            self._sentinel.reclassify_unexpected(k, step)

    @staticmethod
    def _cost_of(compiled) -> dict:
        """FLOPs / bytes from the compiled executable's XLA analyses.
        Best-effort: backends disagree on what they report (and older
        jax returns cost_analysis as a one-element list), so absent
        numbers are simply omitted."""
        out: dict = {}
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if isinstance(ca, dict):
                if ca.get("flops"):
                    out["flops"] = float(ca["flops"])
                if ca.get("bytes accessed"):
                    out["bytes_accessed"] = float(ca["bytes accessed"])
        except Exception:  # noqa: BLE001 - analysis is optional
            pass
        try:
            ma = compiled.memory_analysis()
            for attr, name in (
                ("output_size_in_bytes", "output_bytes"),
                ("temp_size_in_bytes", "temp_bytes"),
                ("argument_size_in_bytes", "argument_bytes"),
            ):
                v = int(getattr(ma, attr, 0) or 0)
                if v:
                    out[name] = v
        except Exception:  # noqa: BLE001 - analysis is optional
            pass
        return out

    def _device_mem(self) -> dict:
        """Device-memory figures for the resource block: the backend's
        allocator stats where supported (an allocator query, not a
        device sync — safe at heartbeat cadence), else only the
        shape-derived estimate computed at construction."""
        out: dict = {}
        try:
            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 - backend drift
            stats = None
        if stats:
            if stats.get("bytes_in_use") is not None:
                out["device_bytes_in_use"] = int(stats["bytes_in_use"])
            if stats.get("peak_bytes_in_use") is not None:
                out["device_peak_bytes"] = int(
                    stats["peak_bytes_in_use"]
                )
        out["device_bytes_est"] = self._state_bytes_est
        return out

    def _resource_block(self, stages: dict, wall: float) -> dict:
        """The ``resource`` record block (flat, numeric): process RSS,
        the component byte ledger (read from the same telemetry gauges
        their owners maintain), device memory, and the compile
        sentinel's counters + throughput attribution.  Host-side only —
        callable from heartbeat/status threads."""
        rss, peak = obs.read_rss()
        gauges = (stages or {}).get("gauges") or {}

        def comp(name: str) -> int:
            try:
                return max(0, int(gauges.get(name, 0) or 0))
            except (TypeError, ValueError):
                return 0

        out = {
            "rss_mb": round(rss / (1 << 20), 1),
            "peak_rss_mb": round(peak / (1 << 20), 1),
            # Process vitals the incident/alert plane watches: run
            # uptime (alert alias `uptime_s`) and the open-descriptor
            # count from /proc/self/fd (alias `open_fds`) — a leaking
            # fd ledger is the classic slow-burn incident.  The fd key
            # is omitted where /proc is unavailable.
            "uptime_s": round(wall, 3),
        }
        fds = obs.read_open_fds()
        if fds >= 0:
            out["open_fds"] = fds
        if self.telemetry.enabled:
            # The owner-maintained gauges are no-op instruments when
            # telemetry is off — a hard 0 next to a real RSS would be
            # a lying ledger, so the keys are OMITTED (report.py
            # prints n/a, /metrics emits no series).
            out["ring_bytes"] = comp("ingest.ring_bytes")
            out["staging_bytes"] = comp("prefetch.staging_bytes")
            out["cache_bytes"] = comp("ingest.cache_bytes")
        # Trainer-owned components read directly (no extra gauge —
        # a registered sample would duplicate the same number in
        # every /metrics scrape): cold-store nbytes are plain int
        # attributes, the tracer property takes its own lock.
        out["cold_store_bytes"] = (
            int(sum(s.nbytes for s in self.tiered.stores))
            if self.tiered is not None else 0
        )
        out["trace_buffer_bytes"] = int(self.tracer.buffer_bytes)
        out.update(self._device_mem())
        snap = self._sentinel.snapshot()
        out.update(snap)
        flops = snap.get("flops_per_dispatch", 0.0)
        if flops and wall > 0 and self._dispatches:
            # Model FLOP/s from the steady-state dispatch's compile-time
            # cost analysis (epoch tails run fewer flops, so this is a
            # mild overestimate on short epochs — attribution, not
            # billing).
            out["model_flops_per_s"] = round(
                flops * self._dispatches / wall, 1
            )
        return out

    def _ondemand_profile(self, secs: float) -> str:
        """/profile route backend: one jax.profiler window into the run
        dir.  The StatusServer's lock is the one-at-a-time guard; a
        clash with the config-driven profiler (profile_dir) raises and
        surfaces as the route's 500."""
        out = self._profile_capture_dir
        jax.profiler.start_trace(out)
        try:
            time.sleep(secs)
        finally:
            jax.profiler.stop_trace()
        log.info("on-demand profiler capture (%.1fs) written to %s",
                 secs, out)
        writer = getattr(self, "_metrics_writer", None)
        if writer is not None:
            # The stream records that (and when) a capture perturbed
            # the run — a profiler window shows up as a step-time blip
            # that would otherwise read as a real regression.
            writer.write({
                "record": "profile",
                "time": time.time(),
                "secs": float(secs),
                "profile_dir": out,
            })
        return out

    def _reset_health(self) -> None:
        """Fresh per-run health carry (mirrors telemetry.reset).

        ``state.step`` is instance-cumulative (a second train() on a
        warm Trainer keeps counting), so the run's starting step is
        pinned here: health reporting divides by PER-RUN steps and
        rebases ``first_nonfinite_step`` to match the per-run ``step``
        every other record carries."""
        rep = NamedSharding(self.mesh, P())
        self._health = jax.device_put(
            HealthState.zeros(self._dcfg.vocabulary_size), rep
        )
        self._health_step0 = int(self.state.step)

    def _health_summary(self, exact: bool = False) -> dict:
        """Host-side view of the health carry for records/results.

        ``exact=False`` (heartbeat path) reports only the cached scalars
        the dispatch loop already read back — never a device readback
        from the heartbeat thread.  ``exact=True`` (log cadence / final)
        syncs the scalars and computes the row-occupancy sums on device.
        """
        out = dict(self._health_host)
        if exact:
            try:
                h = self._health
                step0 = getattr(self, "_health_step0", 0)
                steps = max(1, int(self.state.step) - step0)
                rows = int(jnp.sum(h.rows_touched))
                vocab = self._dcfg.vocabulary_size
                first_nf = int(h.first_nonfinite_step)
                out.update({
                    "grad_norm": round(
                        float(jnp.sqrt(h.grad_sq_last)), 6
                    ),
                    "grad_norm_rms": round(
                        float(jnp.sqrt(h.grad_sq_sum / steps)), 6
                    ),
                    "nonfinite_steps": int(h.nonfinite_steps),
                    # Rebased to the per-run step every record carries.
                    "first_nonfinite_step": (
                        first_nf - step0 if first_nf >= 0 else -1
                    ),
                    "emb_rows_touched": rows,
                    "emb_row_occupancy": round(rows / vocab, 6),
                    "emb_touch_events": float(h.touch_events),
                })
                if self.tiered is not None:
                    # The scan-carry mask counts HOT SLOTS under
                    # tiering; the manager sees every logical id
                    # host-side and overrides with logical occupancy.
                    out.update(self.tiered.health_view())
                self._health_host = dict(out)
            except Exception:  # pragma: no cover - wedged device
                pass  # crash path: serve whatever was cached
        return out

    def _put(self, batch: Batch, want_meta: bool = True) -> Batch:
        spec = self._sort_meta_spec() if want_meta else None
        if spec is not None and batch.sort_meta is None:
            from fast_tffm_tpu.data import native as native_mod

            try:
                batch = batch._replace(
                    sort_meta=native_mod.sort_meta(batch.ids, *spec)
                )
            except native_mod.OutOfRangeIdsError as e:
                # Data/vocabulary_size integrity bug — same policy as the
                # pipeline workers: warn EVERY bad batch and keep the
                # spec (the device-sort path silently drops updates for
                # out-of-range ids, so this must not go quiet).
                log.warning(
                    "host sort_meta rejected a batch (%s); the input "
                    "data or vocabulary_size is wrong", e,
                )
            except Exception as e:
                # Lib unavailable (no g++?) or a real sort_meta bug: the
                # device-sort path is always correct, so train on — but
                # say so, or a ~11 ms/step regression has no trail.
                log.warning(
                    "host_sort disabled: native sort_meta failed (%s)", e
                )
                self._meta_spec = None
        return mesh_lib.shard_batch(batch, self.mesh)

    def _put_super(self, batch: Batch):
        """Ship a stacked [K, ...] super-batch — DevicePrefetcher's put_fn,
        called from the transfer thread so the H2D copies overlap the
        previous super-batch's training.  Host sort_meta is attached by
        the pipeline workers (sort_meta_spec); no fallback computation
        here — a meta-less stack trains through the device-sort path.

        With tiering on, this is where migration happens: the batch's
        logical ids are remapped to hot-slot indices (allocating slots
        for misses, fetching their cold rows) and the migration plan's
        device halves ship on the same async H2D path as the batch —
        the dispatch loop receives a :class:`tiered_lib.Shipment`.
        """
        if self.tiered is None:
            return mesh_lib.shard_super_batch(batch, self.mesh)
        if self._tiering_sharded:
            # Fleet tiering: every rank remaps the SAME global batch
            # through its lockstep shard mirrors, then materializes the
            # P(MODEL)-sharded plan arrays from PROCESS-LOCAL blocks —
            # each rank stages only its own columns' cold rows, so
            # migration H2D is ~1/R per rank by construction.
            new_ids, fplan = self.tiered.plan(batch.ids)
            batch = batch._replace(ids=new_ids, sort_meta=None)
            dev = mesh_lib.shard_super_batch(batch, self.mesh)
            slots_h, rows_h = self.tiered.local_load_blocks(fplan)
            evict_h = self.tiered.local_evict_slots(fplan)
            S = self.tiered.num_shards
            dim = self.tiered.dim
            slot_sh = NamedSharding(self.mesh, P(mesh_lib.MODEL_AXIS))
            row_sh = NamedSharding(
                self.mesh, P(mesh_lib.MODEL_AXIS, None)
            )
            return tiered_fleet.FleetShipment(
                batch=dev,
                load_slots=jax.make_array_from_process_local_data(
                    slot_sh, slots_h, (S * fplan.cap_load,)
                ),
                load_rows=tuple(
                    jax.make_array_from_process_local_data(
                        row_sh, r, (S * fplan.cap_load, dim)
                    )
                    for r in rows_h
                ),
                evict_slots=jax.make_array_from_process_local_data(
                    slot_sh, evict_h, (S * fplan.cap_evict,)
                ),
                plan=fplan,
            )
        new_ids, plan = self.tiered.plan(batch.ids)
        batch = batch._replace(ids=new_ids, sort_meta=None)
        dev = mesh_lib.shard_super_batch(batch, self.mesh)
        rep = NamedSharding(self.mesh, P())
        return tiered_lib.Shipment(
            batch=dev,
            load_slots=jax.device_put(plan.load_slots, rep),
            load_rows=tuple(
                jax.device_put(r, rep) for r in plan.load_rows
            ),
            evict_slots=jax.device_put(plan.evict_slots, rep),
            load_slots_h=plan.load_slots,
            load_ids=plan.load_ids,
            plan_id=plan.plan_id,
            n_load=plan.n_load,
            n_evict=plan.n_evict,
        )

    def _apply_migration(self, shipment: tiered_lib.Shipment) -> Batch:
        """Apply one super-batch's migration plan to the hot tables.

        Runs in the dispatch loop BETWEEN dispatches, so device-stream
        order guarantees correctness: the eviction gather reads the
        post-previous-dispatch row values (async D2H; consumed one-plus
        dispatches later by the cold store), and the load overwrite
        lands before the dispatch that needs the new rows.  Returns the
        device super-batch to dispatch.
        """
        if self._tiering_sharded:
            return self._apply_migration_fleet(shipment)
        man = self.tiered
        state = self.state
        tables = (state.params.table,) + tiered_lib.get_opt_tables(
            self.cfg.optimizer, state.opt_state
        )
        if shipment.n_evict:
            rows = self._tier_gather_jit(tables, shipment.evict_slots)
            for r in rows:
                try:
                    r.copy_to_host_async()
                except Exception:  # pragma: no cover - backend drift
                    pass
            man.push_writeback(shipment.plan_id, rows)
        if shipment.n_load:
            new_tables = self._tier_load_jit(
                tables, shipment.load_slots, shipment.load_rows
            )
            self.state = state._replace(
                params=state.params._replace(table=new_tables[0]),
                opt_state=tiered_lib.set_opt_tables(
                    self.cfg.optimizer, state.opt_state, new_tables[1:]
                ),
            )
            man.note_applied(shipment)
        return shipment.batch

    def _apply_migration_fleet(
        self, shipment: "tiered_fleet.FleetShipment"
    ) -> Batch:
        """Fleet half of :meth:`_apply_migration`: the gathered evict
        rows come back P(MODEL)-sharded, and each OWNED column's block
        is handed to its shard's write-back ledger directly from the
        device shard — no rank ever holds another rank's rows."""
        man = self.tiered
        state = self.state
        fplan = shipment.plan
        tables = (state.params.table,) + tiered_lib.get_opt_tables(
            self.cfg.optimizer, state.opt_state
        )
        if fplan.n_evict_max:
            rows = self._tier_gather_jit(tables, shipment.evict_slots)
            cap_e = fplan.cap_evict
            # shard index -> per-table device blocks, deduped across
            # data-axis replicas (same column, same values).
            blocks: dict = {}
            for r in rows:
                got: dict = {}
                for sh in r.addressable_shards:
                    s = (sh.index[0].start or 0) // cap_e
                    if s in got:
                        continue
                    got[s] = sh.data
                    try:
                        sh.data.copy_to_host_async()
                    except Exception:  # pragma: no cover - drift
                        pass
                for s, d in got.items():
                    blocks.setdefault(s, []).append(d)
            for s in sorted(man.owned):
                if fplan.shard_plans[s].n_evict and s in blocks:
                    man.push_writeback(
                        s, fplan.plan_id, tuple(blocks[s])
                    )
        if fplan.n_load_max:
            new_tables = self._tier_load_jit(
                tables, shipment.load_slots, shipment.load_rows
            )
            self.state = state._replace(
                params=state.params._replace(table=new_tables[0]),
                opt_state=tiered_lib.set_opt_tables(
                    self.cfg.optimizer, state.opt_state, new_tables[1:]
                ),
            )
            man.note_applied(fplan)
        return shipment.batch

    def _sort_meta_spec(self):
        """(vocab, CHUNK, TILE) when host-side sort prep applies, else None.

        Host prep rides the single-process tile path only: sharded and
        scatter applies derive their own metadata, and multi-process
        batches hold per-host slices the global-sort metadata would not
        match.  Cached; flips off permanently if the native lib fails.
        """
        if hasattr(self, "_meta_spec"):
            return self._meta_spec
        spec = None
        cfg = self.cfg
        if (
            cfg.host_sort
            and self.tiered is None  # sort prep keys on pre-remap ids
            and jax.process_count() == 1
            and self.mesh.size == 1
        ):
            try:
                if sparse_lib.apply_mode(cfg, self.mesh) == "tile":
                    spec = (
                        cfg.vocabulary_size,
                        sparse_lib.sparse_apply.CHUNK,
                        sparse_lib.sparse_apply.TILE,
                    )
            except ValueError:
                spec = None
        self._meta_spec = spec
        return spec

    def _input_plan(self):
        """(pipeline_cfg, shard, ordered) for host-sharded input.

        Multi-process: each host parses only its strided share of the
        global stream at LOCAL batch size (global / num_blocks); the global
        batch is assembled shard-by-shard in mesh_lib.shard_batch.  Hosts
        that share a data block (model-axis-spanning processes) must
        produce bit-identical batches in identical order, so their
        pipelines run ordered (parallel parse, sequence-ordered
        delivery)."""
        import dataclasses

        n_procs = jax.process_count()
        if n_procs == 1:
            return self.cfg, (0, 1), False
        shard = mesh_lib.data_partition(self.mesh)
        num_blocks = shard[1]
        if self.cfg.batch_size % num_blocks:
            raise ValueError(
                f"batch_size {self.cfg.batch_size} not divisible by "
                f"{num_blocks} host data blocks"
            )
        pipe_cfg = dataclasses.replace(
            self.cfg, batch_size=self.cfg.batch_size // num_blocks
        )
        return pipe_cfg, shard, n_procs > num_blocks

    def reset_metrics(self):
        rep = NamedSharding(self.mesh, P())
        self.state = self.state._replace(
            metrics=jax.device_put(MetricState.zeros(), rep)
        )

    def train(self) -> dict:
        cfg = self.cfg
        if not cfg.train_files:
            raise ValueError("no train_files configured")
        # Mid-epoch resume: a checkpoint carries the input-pipeline position
        # (epoch, batches consumed).  With the same seed/files, the stream
        # continues where the interrupted run stopped instead of replaying
        # the epoch from scratch.  A completed run's position (epoch ==
        # epoch_num) means a warm start trains epoch_num fresh epochs.
        resume_epoch, resume_skip = 0, 0
        # Only resume the data position when params actually warm-started —
        # a stale data_state.json next to cleared params must not make a
        # fresh model skip training data.
        ds = (
            checkpoint.restore_data_state(cfg.model_file)
            if self._restored_step else None
        )
        if ds is not None:
            # The position only means "continue where we stopped" under
            # the SAME stream definition: seed, batch size, file list.
            # A changed config would make the skip land on the wrong data
            # — warn and start the epoch from scratch instead.
            fp = ds.get("fingerprint")
            if fp is not None and fp != self._data_fingerprint():
                log.warning(
                    "checkpoint data position was saved under a different "
                    "input config (seed/batch_size/files changed); "
                    "ignoring it and reading the epoch from the start"
                )
                ds = None
        if ds is not None and 0 <= ds.get("epoch", -1) < cfg.epoch_num:
            resume_epoch = int(ds["epoch"])
            resume_skip = int(ds.get("batches_done", 0))
            if resume_epoch or resume_skip:
                log.info(
                    "resuming data stream at epoch %d, skipping %d batches",
                    resume_epoch, resume_skip,
                )
        # One metrics stream per process, like the trace files: rank 0
        # owns the configured path, ranks > 0 suffix .rankN
        # (obs.rank_suffix_path — the shared spelling).  Before this
        # guard every rank of a shared-filesystem fleet APPENDED into
        # one file and a merged report double-counted the run.
        rank = jax.process_index()
        metrics_out = (
            obs.JsonlWriter(obs.rank_suffix_path(cfg.metrics_file, rank))
            if cfg.metrics_file else None
        )
        pipe_cfg, shard, _ = self._input_plan()
        profiling = False
        profile_started = False
        profile_stop_at = 0
        k = cfg.steps_per_dispatch
        t0 = time.time()
        # A self-describing stream starts with its run identity: one
        # header record carries the config fingerprint, dispatch/ingest
        # mode, and platform versions, so any metrics file can be read
        # without the .cfg that produced it.
        if metrics_out is not None:
            metrics_out.write({
                "record": "run_header",
                "time": t0,
                # Which process of a multi-host fleet wrote this stream:
                # every process writes its own metrics_file, and the
                # rank tag is what lets tools/report.py merge them.
                "rank": rank,
                "config_fingerprint": _config_fingerprint(cfg),
                "steps_per_dispatch": k,
                "ingest_mode": (
                    "procs" if cfg.parse_processes > 0 else "threads"
                ),
                "fast_ingest": cfg.fast_ingest,
                "cache_epochs": cfg.cache_epochs,
                "cache_prestacked": cfg.cache_prestacked,
                "ring_slots": cfg.ring_slots,
                "table_tiering": cfg.table_tiering,
                "hot_rows": (
                    cfg.hot_rows if cfg.table_tiering == "on" else 0
                ),
                "tiered_partition": cfg.tiered_partition,
                "tiered_shards": (
                    self._tier_shards if self._tiering_sharded else 0
                ),
                "sparse_exchange_overlap": cfg.sparse_exchange_overlap,
                "exchange_overlap_active": self._overlap_active,
                "cold_dtype": cfg.cold_dtype,
                "batch_size": cfg.batch_size,
                "epoch_num": cfg.epoch_num,
                "optimizer": cfg.optimizer,
                "telemetry": cfg.telemetry,
                "heartbeat_secs": cfg.heartbeat_secs,
                "trace_file": cfg.trace_file,
                "trace_rotate_events": cfg.trace_rotate_events,
                "nan_policy": cfg.nan_policy,
                "status_port": cfg.status_port,
                "alert_rules": cfg.alert_rules,
                "resource_metrics": cfg.resource_metrics,
                "quality": cfg.quality,
                "quality_window": cfg.quality_window,
                "jax_version": jax.__version__,
                "backend": jax.default_backend(),
                "mesh": {str(a): int(n) for a, n in self.mesh.shape.items()},
                "n_processes": jax.process_count(),
                "resume_step": self._restored_step,
                "resume_epoch": resume_epoch,
                "resume_skip": resume_skip,
                "kernel_impl": self.kernel_impl,
                "interaction_impl": cfg.interaction_impl,
                "compile_cache_dir": cfg.compile_cache_dir,
            })
            if self._autotune is not None:
                autotune_lib.write_record(metrics_out, self._autotune)
        # Seed the step-rate interval from the CURRENT metric state, not
        # 0: a warm-started Trainer (or a second train() on the same
        # instance) carries pre-resume examples in metrics.count, and the
        # first ex/s interval used to be inflated by all of them.
        last_log_t = t0
        last_log_ex = float(self.state.metrics.count)
        stepno = 0
        # Per-run accounting: instruments persisted across runs would
        # report run-1+run-2 totals against run 2's wall clock
        # (ingest_wait_frac > 1 on a second train() of a warm Trainer).
        # Reset IN PLACE so external references to trainer.telemetry
        # stay live.
        self.telemetry.reset()
        self.tracer.reset()
        # Fresh health carry + host cache per run; the nan_policy check
        # below reads the PREVIOUS dispatch's scalars (async-copied right
        # after each dispatch) so detection costs no pipeline bubble:
        # by the time dispatch n+1 is enqueued, n has long finished on
        # device and its scalars are already on the host.
        self._reset_health()
        self._health_host = {}
        # Resource plane, per-run: fresh sentinel accounting (the AOT
        # cache itself is instance-lived — a second train() on a warm
        # Trainer truthfully reports zero compiles) and the run's
        # writer for `record: compile` entries.
        self._dispatches = 0
        self._run_steps = 0
        self._tail_probation = None
        if self._sentinel is not None:
            self._sentinel.reset()
            self._sentinel.set_writer(metrics_out)
        # /profile captures land beside the metrics stream (or cwd);
        # the writer is stashed so the route can log each capture as a
        # `record: profile` entry in the same stream.
        self._profile_capture_dir = os.path.join(
            os.path.dirname(cfg.metrics_file) or ".",
            "tffm_profile_ondemand",
        )
        self._metrics_writer = metrics_out
        # /metrics self-identification: one info-style gauge whose
        # labels name the run (tffm_build_info) so scrapes from
        # different runs/configs are distinguishable in Prometheus.
        self._build_info = {
            "jax_version": jax.__version__,
            "backend": jax.default_backend(),
            "mesh": "x".join(
                f"{a}{n}" for a, n in self.mesh.shape.items()
            ),
            "steps_per_dispatch": str(k),
            "rank": str(jax.process_index()),
            "config_fingerprint": _config_fingerprint(cfg),
        }
        if self.tiered is not None:
            self.tiered.reopen()  # re-arm after a cancelled prior run
        # Model-quality plane, per-run (same reset discipline as
        # telemetry/tracer/health): the drift-sketch accumulator the
        # parse workers feed, and the windowed online-eval monitor the
        # dispatch loop feeds one dispatch delayed.
        self._quality_sketch = (
            obs.StreamSketch(cfg.quality_window) if cfg.quality else None
        )
        self._quality = (
            obs.QualityMonitor(
                loss_type=cfg.loss_type, window=cfg.quality_window,
                sketch=self._quality_sketch,
            )
            if cfg.quality else None
        )
        self._last_scores = None
        pending_health = None  # (nonfinite_arr, grad_sq_arr, grad_sq_sum_arr, stepno)
        pending_quality = None  # (scores_arr, labels_arr, weights_arr)
        nonfinite_warned = False

        def check_health(pending) -> None:
            """Consume one delayed health readback; apply nan_policy."""
            nonlocal nonfinite_warned
            nf_arr, gs_arr, ss_arr, at_step = pending
            nf = int(nf_arr)
            gs = float(gs_arr)
            ss = float(ss_arr)
            self._health_host["grad_norm"] = round(
                float(np.sqrt(gs)) if np.isfinite(gs) else gs, 6
            )
            # RMS from the same readback: heartbeat-path rules on the
            # documented grad_norm_rms signal (and /status scrapes)
            # must see it live, not only at log cadence — a halt rule
            # on a signal that never materializes is silently inert.
            rms = ss / max(1, at_step)
            self._health_host["grad_norm_rms"] = round(
                float(np.sqrt(rms)) if np.isfinite(rms) else rms, 6
            )
            self._health_host["nonfinite_steps"] = nf
            if nf <= 0:
                return
            if not nonfinite_warned:
                nonfinite_warned = True
                log.warning(
                    "non-finite (NaN/inf) gradient detected by step %d "
                    "(%d bad step(s) so far; nan_policy=%s)",
                    at_step, nf, cfg.nan_policy,
                )
            if cfg.nan_policy == "halt":
                raise NonFiniteGradError(
                    f"non-finite gradient within the first {at_step} "
                    f"step(s) ({nf} bad step(s)); halting per "
                    "nan_policy=halt — the checkpoint was NOT "
                    "overwritten with poisoned params"
                )
        # Starvation-vs-dispatch split: wait_input times next() on the
        # prefetcher (the loop is input-starved), dispatch times the
        # fused-scan call (includes any device backpressure block); wall
        # minus the two is "other" (logging/validation/save).  The
        # heartbeat derives ingest_wait_frac = wait / wall from these.
        t_wait = self.telemetry.timer("train.wait_input")
        t_disp = self.telemetry.timer("train.dispatch")
        # Tiered-table migration time (eviction gather enqueue + load
        # apply): part of "other" in the wall split — the H2D of the
        # cold rows themselves already overlapped in the prefetcher.
        t_migr = self.telemetry.timer("train.migrate")
        # Cadences move to super-batch (K-step) granularity: a trigger
        # fires at the first dispatch boundary where at least its period
        # of NEW steps has elapsed since it last fired.  At K == 1 this
        # reduces exactly to the old per-step ``stepno % period == 0``.
        last_log_step = last_val_step = last_save_step = 0
        trunc_logged = 0
        # ONE pipeline spans every remaining epoch of the run (the
        # epoch-persistent ingest): the reader reseeds per epoch
        # (seed + e, identical streams to the old one-pipeline-per-epoch
        # construction), the resume position (start_epoch, skip_batches)
        # lives inside the pipeline, and in-band EpochEnd markers carry
        # the epoch boundaries out — so parser workers, the native
        # parser, and (with cache_epochs) the parsed-batch cache all
        # survive across epochs instead of being torn down per epoch.
        #
        # ordered=True always for training: delivery follows the
        # (seeded, deterministic) reader order, so the saved
        # batches_done position identifies EXACTLY the prefix that
        # trained — with free-running workers a mid-epoch resume could
        # double- or never-train boundary batches.  Parsing still fans
        # out to thread_num workers (sequence-numbered delivery), so
        # this costs no throughput.
        self._epoch = resume_epoch
        self._batches_done = resume_skip
        pipeline = BatchPipeline(
            cfg.train_files,
            pipe_cfg,
            weight_files=cfg.weight_files or None,
            epochs=cfg.epoch_num,
            shuffle=True,
            seed=cfg.seed,
            start_epoch=resume_epoch,
            skip_batches=resume_skip,
            shard=shard,
            ordered=True,
            sort_meta_spec=self._sort_meta_spec(),
            cache_epochs=cfg.cache_epochs,
            cache_max_bytes=cfg.cache_max_bytes,
            # Pre-stacked cache storage: groups stack once at epoch-0
            # dispatch boundaries (K = steps_per_dispatch) and replay
            # epochs hand whole super-batches to the prefetcher.
            prestack_k=(k if cfg.cache_prestacked else 0),
            epoch_marks=True,
            telemetry=self.telemetry,
            tracer=self.tracer,
            quality=self._quality_sketch,
        )
        # Transfer stage: a background thread stacks K parsed batches
        # and ships super-batch n+1 (shard + device_put) while n trains;
        # an epoch's tail arrives as one short super-batch (K' =
        # leftover, the EpochEnd marker flushes the group), so every
        # batch trains exactly once and ``batches_done`` only ever
        # advances by whole dispatches — a saved position always lands
        # on a super-batch boundary.
        # Fused stack+H2D (mesh.FusedShipper): where the mesh/backend
        # allow it, the transfer thread copies the K batches into ONE
        # contiguous staging buffer and ships it with a single
        # device_put instead of stack-then-put-per-leaf.  Tiering needs
        # the host-side id remap between stack and put, so it keeps the
        # classic path.
        ship_fn = None
        if self.tiered is None and mesh_lib.fused_h2d_enabled(self.mesh):
            ship_fn = mesh_lib.FusedShipper(
                self.mesh, depth=cfg.prefetch_super_batches
            )
            log.info("fused stack+H2D transfer path enabled")
        prefetcher = DevicePrefetcher(
            pipeline, k, self._put_super,
            depth=cfg.prefetch_super_batches,
            telemetry=self.telemetry,
            # _put_super copies host->device, so stacking can recycle
            # pre-allocated staging buffers instead of allocating a
            # super-batch of host memory per dispatch.
            staging=True,
            tracer=self.tracer,
            ship_fn=ship_fn,
        )
        cache_logged = not cfg.cache_epochs

        # Live training-fleet plane (obs/fleet.py): rank 0 scrapes
        # every rank's /status on the heartbeat cadence and publishes
        # the merged `fleet` block + per-rank tffm_train_rank_* series.
        # Ranks > 0 only SERVE their /status — aggregation is rank 0's.
        fleet = None
        if cfg.train_fleet_scrape and rank == 0:
            fleet = obs.TrainFleet(
                cfg.train_fleet_scrape.split(","),
                interval_s=cfg.heartbeat_secs,
                telemetry=self.telemetry,
            )
        elif jax.process_count() > 1 and rank == 0 and cfg.status_port:
            # A real fleet with live endpoints but no aggregation
            # plane: nudge, don't act — peer addresses are not
            # discoverable from here.
            log.info(
                "multi-process run with status endpoints but no "
                "train_fleet_scrape targets; set it to each rank's "
                "host:port for live fleet aggregation and straggler "
                "alerts"
            )

        def telemetry_record(kind: str):
            """One structured self-report (heartbeat/final), host-side
            only: counters/gauges/timers — never a device readback, which
            would force a sync from the heartbeat thread mid-dispatch.

            Heartbeats return None (skip the beat) until the FIRST
            dispatch completes: before that, the wait timer has been
            running since before any dispatch could exist (jit compile,
            a resume's cached-epoch rebuild parse), and a wait-only
            window would report ingest_wait_frac ≈ 1 — an over-count
            that used to finger ingest for what is really startup.
            The guard reads ``stepno`` (not the dispatch timer's count,
            which is a permanent 0 with telemetry disabled — that would
            silence every liveness beat of a --no_telemetry run).  The
            final record always emits.
            """
            if kind == "heartbeat" and stepno == 0:
                return None
            now = time.time()
            wall = max(now - t0, 1e-9)
            wait_s, disp_s = t_wait.total_s, t_disp.total_s
            rec = {
                "record": kind,
                "time": now,
                # Self-identifying for the fleet scrape (and report
                # merges): which rank produced this record.
                "rank": rank,
                "step": stepno,
                "epoch": self._epoch,
                "elapsed": round(wall, 3),
                "examples_in": self.telemetry.counter(
                    "ingest.examples"
                ).value,
                "wait_input_s": round(wait_s, 3),
                "dispatch_s": round(disp_s, 3),
                "other_s": round(max(0.0, wall - wait_s - disp_s), 3),
                "ingest_wait_frac": round(wait_s / wall, 4),
                # Data-integrity counters (pipeline.stats): truncation,
                # out-of-range batches, cache outcome.
                **pipeline.stats(),
                # Training-health monitors (scan-carry): host-cached
                # scalars only on the heartbeat path; exact values are
                # refreshed at log cadence and for the final record.
                "health": self._health_summary(exact=(kind == "final")),
                "stages": self.telemetry.snapshot(),
            }
            if self._sentinel is not None:
                # Memory & compile self-report (obs/resource.py): RSS,
                # the component byte ledger, device memory, compile
                # sentinel counters, FLOP/s attribution.  Host-side
                # reads only — safe on the heartbeat/status threads.
                rec["resource"] = self._resource_block(
                    rec["stages"], wall
                )
            if kind == "status":
                # Scrapes are self-identifying: /metrics renders this
                # as the tffm_build_info info-style gauge.
                rec["build_info"] = dict(self._build_info)
            if kind == "status" and stepno == 0:
                # Same over-count the heartbeat path suppresses by
                # skipping the beat (see the docstring): before the
                # first dispatch the wait timer has only startup (jit
                # compile, cache rebuild) to attribute against, and a
                # scraped ingest_wait_frac ~= 1 would page someone for
                # a startup artifact.  /status must still ANSWER, so
                # the attribution keys are omitted (no Prometheus
                # series yet, rather than a lying one) and the record
                # says why.
                for key in ("wait_input_s", "dispatch_s", "other_s",
                            "ingest_wait_frac"):
                    del rec[key]
                rec["warming_up"] = True
            if self.tiered is not None:
                # Hot/cold cache behavior (host-side counters only —
                # safe from the heartbeat thread).
                rec["tiered"] = self.tiered.snapshot()
            if self._quality is not None:
                # Model-quality self-report: windowed online eval +
                # drift signals (host-side numpy over the consumed
                # score window; memoized inside the monitor so scrape
                # storms don't repeat the window statistics — the
                # final record forces a fresh compute, its values
                # must be end-of-run exact).
                rec["quality"] = self._quality.block(
                    force=(kind == "final")
                )
            if self.tracer.enabled:
                # Truncation truthfulness: a trace that hit the event
                # cap silently lies by omission; the count rides every
                # self-report (heartbeat / status / final) so the alert
                # watchdog and report tooling can flag it live.
                rec["trace_dropped_events"] = self.tracer.dropped_events
                if cfg.trace_rotate_events:
                    rec["trace_windows"] = self.tracer.windows_written
            if fleet is not None:
                # The merged fleet view (cached scrape state only —
                # nothing here blocks on the network, so heartbeat /
                # status threads stay host-fast).  Alert rules resolve
                # straggler_ratio / rank_step_skew / exchange_frac /
                # scrape_age_max_s from this block.
                rec["fleet"] = fleet.block(now)
            if alert_engine is not None:
                # Armed-rule states for /status and the per-rule
                # tffm_alert_active gauges (the engine is created just
                # below; every record is built after that).
                rec["alerts"] = alert_engine.active_snapshot()
            return rec

        # Incident flight recorder (obs/blackbox.py): fixed-memory
        # rings of recent heartbeats/alerts; rule breaches, crashes,
        # and POST /incident dump forensic bundles under
        # <model_file>/incidents (incident_dir overrides).  The rank
        # suffix keeps a fleet's bundles collision-free.
        # blackbox=false = None = rings never touched, training
        # bitwise-identical (pinned by test).
        blackbox = None
        if cfg.blackbox:
            blackbox = obs.Blackbox(
                cfg.incident_dir
                or os.path.join(cfg.model_file, "incidents"),
                suffix=f"rank{rank}",
                run_header=dict(self._build_info),
                metrics_render=lambda: obs.render_prometheus(
                    telemetry_record("status")
                ),
                trace_tail_fn=(
                    self.tracer.tail if self.tracer.enabled else None
                ),
                writer=metrics_out,
                telemetry=self.telemetry,
            )
        # Alert watchdog: declarative rules evaluated against every
        # heartbeat record ON the heartbeat thread (obs/alerts.py).
        # Breaches emit `record: alert` JSONL entries; an action=halt
        # rule arms engine.halted and the DISPATCH loop below raises
        # AlertHaltError at the next boundary (same no-poisoned-
        # checkpoint contract as nan_policy=halt).  Every emitted
        # alert also reaches the blackbox, which dumps a bundle.
        alert_engine = None
        if cfg.alert_rules:
            # FmConfig already guarantees heartbeat_secs > 0 whenever
            # rules are set (a watchdog with no heartbeat to ride
            # would be silently inert).
            alert_engine = obs.AlertEngine(
                obs.parse_rules(cfg.alert_rules), writer=metrics_out,
                on_alert=(
                    blackbox.on_alert if blackbox is not None else None
                ),
            )

        def heartbeat_build():
            rec = telemetry_record("heartbeat")
            if rec is not None:
                # Ring BEFORE the alert engine observes, so an alert-
                # triggered bundle contains the breaching record.
                if blackbox is not None:
                    blackbox.observe_record(rec)
                if alert_engine is not None:
                    alert_engine.observe(rec)
            return rec

        heartbeat = None
        if cfg.heartbeat_secs > 0:
            heartbeat = obs.Heartbeat(
                cfg.heartbeat_secs, heartbeat_build, writer=metrics_out,
            )
        # Live status endpoint: /metrics (Prometheus) + /status (the
        # heartbeat-shaped JSON record, on demand) from an in-process
        # stdlib HTTP server.  Requests read the same thread-safe
        # snapshots a heartbeat does; with status_port unset no server
        # exists and training is bit-identical.  A taken port degrades
        # to a warning — an observability convenience must never kill
        # the run it observes.
        status_server = None
        if cfg.status_port:
            try:
                status_server = obs.StatusServer(
                    cfg.status_port, partial(telemetry_record, "status"),
                    telemetry=self.telemetry, host=cfg.status_host,
                    profile=self._ondemand_profile,
                    incident=(
                        blackbox.incident if blackbox is not None
                        else None
                    ),
                    # Rank 0 of a fleet decorates /metrics with the
                    # per-rank tffm_train_rank_* labeled series.
                    metrics_extra=(
                        fleet.metrics_lines if fleet is not None
                        else None
                    ),
                )
                log.info(
                    "status endpoint listening on %s:%d "
                    "(/metrics, /status, /healthz, /debug/threadz, "
                    "/profile)", cfg.status_host,
                    status_server.port,
                )
            except OSError as e:
                log.warning(
                    "status endpoint failed to bind port %d: %s",
                    cfg.status_port, e,
                )
        # Cross-rank exchange probe (train.exchange): a tiny jitted
        # all-reduce enqueued after every dispatch and blocked on one
        # dispatch later — the HealthState discipline, so the timing
        # costs no pipeline bubble.  At parity the previous probe has
        # long finished and the wait is ~0; a straggling rank shows up
        # as exactly its lag.  Gated on the fleet plane being on AND a
        # real multi-device mesh; off-path training is untouched.
        exchange_probe = None
        pending_exchange = None
        t_exch = None
        if cfg.train_fleet_scrape and self.mesh.size > 1:
            try:
                if cfg.lookup == "shardmap":
                    from fast_tffm_tpu.train import (
                        shardmap_step as shardmap_lib,
                    )
                    exchange_probe = shardmap_lib.make_exchange_probe(
                        self.mesh
                    )
                else:
                    exchange_probe = sparse_lib.make_exchange_probe(
                        self.mesh
                    )
                t_exch = self.telemetry.timer("train.exchange")
            except Exception as e:  # noqa: BLE001 - obs must not kill
                log.warning("train.exchange probe unavailable: %s", e)
        run_exc: Optional[BaseException] = None
        total_trunc = 0
        try:
            try:
                self.tracer.name_thread("train-loop")
                source = iter(prefetcher)
                # Dispatch counter = super-batch id: the prefetcher
                # assigns sb in emission order and its bounded FIFO
                # output queue preserves it, so this counter names the
                # same super-batch its stack/h2d spans did — the trace
                # chain's final link.
                dispatch_idx = 0
                while True:
                    # Starvation accounting: time blocked waiting for the
                    # next staged super-batch.
                    with t_wait.time(), self.tracer.span(
                        "train.wait_input"
                    ):
                        item = next(source, None)
                    # A short-k compile from the PREVIOUS dispatch is
                    # only a legit epoch tail if a boundary follows it.
                    self._resolve_tail_probation(item)
                    if item is None:
                        break
                    if isinstance(item, EpochEnd):
                        self._epoch = item.epoch + 1
                        self._batches_done = 0
                        if not cache_logged:
                            # The cache outcome is known once epoch 0
                            # finishes parsing; surface it exactly once.
                            cache_logged = True
                            log.info(
                                "ingest cache after epoch %d: %s",
                                item.epoch, pipeline.cache_result,
                            )
                        continue
                    super_batch, kk = item
                    if self.tiered is not None:
                        # Migration first: eviction gather reads the
                        # previous dispatch's row values, the load lands
                        # before this dispatch gathers its rows.
                        plan = getattr(super_batch, "plan", None)
                        with t_migr.time(), self.tracer.span(
                            "train.migrate",
                            args={"sb": dispatch_idx,
                                  "loads": (
                                      plan.n_load_max if plan is not None
                                      else super_batch.n_load
                                  ),
                                  "evicts": (
                                      plan.n_evict_max if plan is not None
                                      else super_batch.n_evict
                                  )},
                        ):
                            super_batch = self._apply_migration(super_batch)
                    if (
                        cfg.profile_dir
                        and not profile_started
                        and stepno >= cfg.profile_start_step
                    ):
                        jax.profiler.start_trace(cfg.profile_dir)
                        profiling = profile_started = True
                        profile_stop_at = stepno + cfg.profile_steps
                    # ONE dispatch = kk fused train steps (lax.scan).
                    # The dispatch is async: this wall time is enqueue
                    # cost plus any device backpressure block — the
                    # compute-bound half of the wall-clock split.
                    with t_disp.time(), obs.trace_span("tffm:dispatch"), \
                            self.tracer.span(
                                "train.dispatch",
                                args={"sb": dispatch_idx, "k": kk,
                                      "step0": stepno},
                                flow=("f", f"sb{dispatch_idx}"),
                            ):
                        self.state = self._scan_train_step(
                            self.state, super_batch
                        )
                    dispatch_idx += 1
                    stepno += kk
                    self._batches_done += kk
                    # Resource-plane attribution state: dispatch count
                    # for model_flops_per_s, and the step the compile
                    # sentinel stamps on `record: compile` entries.
                    self._dispatches = dispatch_idx
                    self._run_steps = stepno
                    # Exchange timing: with the overlapped exchange
                    # active, one dispatch delayed — enqueue THIS
                    # dispatch's barrier probe (it runs behind the
                    # dispatch on every rank's stream), then block on
                    # the PREVIOUS one, already resolved at parity, so
                    # the wait measures only the residual cross-rank
                    # lag the overlap did not hide.  WITHOUT overlap
                    # the probe blocks immediately: the synchronous
                    # window (dispatch + exchange at the barrier) is
                    # exactly the cost the overlap exists to remove,
                    # so the off/on pair of exchange_frac readings is
                    # directly comparable (bench fleet_train's A/B).
                    if exchange_probe is not None:
                        probe_out = exchange_probe()
                        if self._overlap_active:
                            if pending_exchange is not None:
                                with t_exch.time():
                                    jax.block_until_ready(
                                        pending_exchange
                                    )
                            pending_exchange = probe_out
                        else:
                            with t_exch.time():
                                jax.block_until_ready(probe_out)
                    # Health readback, one dispatch delayed: start an
                    # async D2H copy of THIS dispatch's scalars, then
                    # consume the PREVIOUS dispatch's (already resident —
                    # that dispatch finished on device while this one's
                    # input staged, so the read never stalls the
                    # pipeline).  nan_policy=halt therefore fires within
                    # one dispatch of the poisoned one.
                    nf_arr = self._health.nonfinite_steps
                    gs_arr = self._health.grad_sq_last
                    ss_arr = self._health.grad_sq_sum
                    try:
                        nf_arr.copy_to_host_async()
                        gs_arr.copy_to_host_async()
                        ss_arr.copy_to_host_async()
                    except Exception:  # pragma: no cover - backend drift
                        pass
                    if pending_health is not None:
                        check_health(pending_health)
                    pending_health = (nf_arr, gs_arr, ss_arr, stepno)
                    # Quality eval feed, same one-dispatch-delayed
                    # discipline: start an async D2H of THIS dispatch's
                    # stacked scores (+ the labels/weights the batch
                    # already holds — the super-batch is not donated, so
                    # its buffers stay valid), then consume the PREVIOUS
                    # dispatch's arrays, which are already resident.
                    if self._quality is not None and self._with_scores:
                        q_arrs = (
                            self._last_scores, super_batch.labels,
                            super_batch.weights,
                        )
                        for a in q_arrs:
                            try:
                                a.copy_to_host_async()
                            except Exception:  # pragma: no cover - drift
                                pass
                        if pending_quality is not None:
                            self._quality.observe(
                                np.asarray(pending_quality[0]),
                                np.asarray(pending_quality[1]),
                                np.asarray(pending_quality[2]),
                            )
                        pending_quality = q_arrs
                    # Alert halt: the watchdog armed the flag on the
                    # heartbeat thread; raising HERE (between
                    # dispatches) keeps the halt on the main thread —
                    # no checkpoint overwrite, crash-truthful final
                    # record, same path as nan_policy=halt.
                    if (
                        alert_engine is not None
                        and alert_engine.halted is not None
                    ):
                        raise obs.halt_error(alert_engine.halted)
                    if profiling and stepno >= profile_stop_at:
                        jax.block_until_ready(self.state)
                        jax.profiler.stop_trace()
                        profiling = False
                        log.info(
                            "profiler trace written to %s",
                            cfg.profile_dir,
                        )
                    if (
                        cfg.log_steps
                        and stepno - last_log_step >= cfg.log_steps
                    ):
                        last_log_step = stepno
                        # Examples come from the on-device weight sum —
                        # the GLOBAL count in multi-host runs (each host
                        # only sees its local shard).
                        m = _finalize_metrics(
                            self.state.metrics, cfg.loss_type
                        )
                        now = time.time()
                        rate = (m["examples"] - last_log_ex) / max(
                            now - last_log_t, 1e-9
                        )
                        last_log_t, last_log_ex = now, m["examples"]
                        # The log readback already synced the host;
                        # piggyback the exact health refresh (row
                        # occupancy included) so heartbeats between
                        # logs serve fresh cached values.
                        self._health_summary(exact=True)
                        log.info(
                            "step %d examples %d loss %.6f auc %.4f "
                            "ex/s %.0f",
                            stepno, int(m["examples"]), m["loss"],
                            m["auc"], rate,
                        )
                        # Surface parser truncation (reference FmParser
                        # warned; silently vanishing features hide data
                        # bugs like a too-small max_features).  The
                        # counter spans the whole run now — it folds in
                        # process-worker drops and cached-epoch replays.
                        cur_trunc = pipeline.truncated_features
                        if cur_trunc > trunc_logged:
                            log.warning(
                                "%d feature occurrences dropped by "
                                "max_features=%d since last report "
                                "(total %d)",
                                cur_trunc - trunc_logged,
                                cfg.max_features, cur_trunc,
                            )
                            trunc_logged = cur_trunc
                        if metrics_out is not None:
                            metrics_out.write({
                                "record": "train",
                                "step": stepno,
                                "examples": m["examples"],
                                "loss": m["loss"],
                                "auc": m["auc"],
                                "examples_per_sec": rate,
                                "elapsed": now - t0,
                            })
                    if (
                        cfg.validation_steps
                        and cfg.validation_files
                        and stepno - last_val_step >= cfg.validation_steps
                    ):
                        last_val_step = stepno
                        vm = self.evaluate(cfg.validation_files)
                        log.info(
                            "step %d validation loss %.6f auc %.4f",
                            stepno, vm["loss"], vm["auc"],
                        )
                        if metrics_out is not None:
                            # Same shape as train records (elapsed /
                            # examples alongside the losses) so one file
                            # plots both streams on one time axis.
                            metrics_out.write({
                                "record": "validation",
                                "step": stepno,
                                "examples": vm["examples"],
                                "loss": vm["loss"],
                                "auc": vm["auc"],
                                "validation_loss": vm["loss"],
                                "validation_auc": vm["auc"],
                                "elapsed": time.time() - t0,
                            })
                    if (
                        cfg.save_steps
                        and stepno - last_save_step >= cfg.save_steps
                    ):
                        # Consume THIS dispatch's health scalars before
                        # writing the checkpoint: the delayed check
                        # alone would let a save in the same iteration
                        # persist NaN-poisoned params, breaking halt's
                        # "checkpoint not overwritten" guarantee.  The
                        # blocking read costs one device sync at save
                        # cadence only.
                        if pending_health is not None:
                            check_health(pending_health)
                            pending_health = None
                        last_save_step = stepno
                        self.save(stepno)
                # Stream exhausted: consume the last delayed health
                # readback so a NaN in the final dispatch still trips
                # nan_policy before the end-of-run save.
                if pending_health is not None:
                    check_health(pending_health)
                    pending_health = None
                # ... and the last delayed quality feed, so the final
                # record's windowed eval covers every dispatched step.
                if pending_quality is not None and self._quality is not None:
                    self._quality.observe(
                        np.asarray(pending_quality[0]),
                        np.asarray(pending_quality[1]),
                        np.asarray(pending_quality[2]),
                    )
                    pending_quality = None
            finally:
                if heartbeat is not None:
                    heartbeat.close()
                if status_server is not None:
                    status_server.close()
                if fleet is not None:
                    # Stop scraping; the cached state stays readable —
                    # the final record (outer finally) still carries
                    # the last merged fleet view.
                    fleet.close()
                if self.tiered is not None:
                    # Wake a transfer thread blocked on a write-back
                    # fill that will never come — prefetcher.close()
                    # joins that thread, and an untimed cv wait would
                    # deadlock shutdown under nan_policy=halt /
                    # KeyboardInterrupt / validation errors.
                    self.tiered.cancel_waits()
                prefetcher.close()
            self._epoch = cfg.epoch_num
            self._batches_done = 0
            total_trunc = pipeline.truncated_features
            if total_trunc > trunc_logged:
                log.warning(
                    "%d feature occurrences dropped by max_features=%d "
                    "over the run", total_trunc, cfg.max_features,
                )
        except BaseException as e:
            run_exc = e
            raise
        finally:
            # An abandoned trace poisons any later start_trace in-process.
            if profiling:
                jax.profiler.stop_trace()
            # Crash-truthful stream: the final record is written from
            # this finally, so a run that died mid-flight (preemption,
            # worker crash, nan_policy=halt) still closes its JSONL with
            # exception type + partial counters — tools/report.py can
            # summarize exactly what happened instead of trailing off at
            # the last heartbeat.
            self._final_record = telemetry_record("final")
            if run_exc is not None:
                self._final_record["exception"] = type(run_exc).__name__
                self._final_record["exception_msg"] = str(run_exc)[:300]
            if blackbox is not None:
                blackbox.observe_record(self._final_record)
                if run_exc is not None and not isinstance(
                    run_exc, KeyboardInterrupt
                ):
                    # Crash-truthful bundle (NonFiniteGradError,
                    # AlertHaltError, anything unhandled): dumped
                    # before the writer closes so the incident
                    # manifest still reaches the metrics stream.
                    blackbox.incident(
                        "crash_" + type(run_exc).__name__
                    )
            if metrics_out is not None:
                try:
                    metrics_out.write(self._final_record)
                except Exception as e:
                    # A full metrics volume must not mask the run's own
                    # outcome (this block runs on the crash path too).
                    log.warning("final record write failed: %s", e)
                metrics_out.close()
            if self.tracer.enabled:
                # One trace path per process: rank 0 writes the
                # configured path, ranks > 0 suffix theirs (the
                # documented naming — computed once in __init__), and
                # tools/report.py --trace merges the fleet.  With
                # rotation on, this final dump closes the last window
                # of the trace.0.json .. trace.N.json family.
                try:
                    n_ev = self.tracer.dump(self._trace_path)
                    if cfg.trace_rotate_events:
                        n_win = self.tracer.windows_written
                        log.info(
                            "wrote %d trace window(s) (%d events in "
                            "the last) — %s .. %s; merge with "
                            "tools/report.py --trace",
                            n_win, n_ev,
                            self.tracer.window_path(0),
                            self.tracer.window_path(n_win - 1),
                        )
                    else:
                        log.info(
                            "wrote %d trace events to %s", n_ev,
                            self._trace_path,
                        )
                except OSError as e:  # pragma: no cover - full volume
                    log.warning("trace dump failed: %s", e)
                # Stop the rotation writer thread (idempotent; no-op
                # without rotation) — each run used to leak one.
                self.tracer.close()
        train_metrics = _finalize_metrics(self.state.metrics, cfg.loss_type)
        train_metrics["examples_per_sec"] = (
            train_metrics["examples"] / max(time.time() - t0, 1e-9)
        )
        train_metrics["steps"] = stepno
        # Cache observability rides the result too ("off" | "cached" |
        # "overflow") so sweeps can tell which runs actually replayed,
        # alongside the run's data-integrity counters (truncation and
        # out-of-range-id batches used to be log-only) and the
        # wall-clock split the telemetry layer measured.
        train_metrics["ingest_cache"] = pipeline.cache_result
        train_metrics["truncated_features"] = int(total_trunc)
        train_metrics["out_of_range_batches"] = int(pipeline.oor_batches)
        train_metrics["ingest_wait_frac"] = (
            self._final_record["ingest_wait_frac"]
        )
        train_metrics["wait_input_s"] = self._final_record["wait_input_s"]
        train_metrics["dispatch_s"] = self._final_record["dispatch_s"]
        # Training-health summary (exact end-of-run values from the scan
        # carry): grad norms, non-finite counts, embedding-row touch /
        # occupancy — the model-health companions to the data-integrity
        # counters above.
        train_metrics["health"] = dict(
            self._final_record.get("health", {})
        )
        if "resource" in self._final_record:
            train_metrics["resource"] = dict(
                self._final_record["resource"]
            )
        if self.tiered is not None:
            train_metrics["tiered"] = dict(
                self._final_record.get("tiered", {})
            )
        if "quality" in self._final_record:
            # End-of-run windowed eval + drift signals (the model-
            # quality companion of the health block above).
            train_metrics["quality"] = dict(
                self._final_record["quality"]
            )
        self.save(stepno)
        result = {"train": train_metrics}
        if cfg.validation_files:
            result["validation"] = self.evaluate(cfg.validation_files)
            log.info(
                "validation loss %.6f auc %.4f",
                result["validation"]["loss"],
                result["validation"]["auc"],
            )
        return result

    def evaluate(self, files) -> dict:
        rep = NamedSharding(self.mesh, P())
        ms = jax.device_put(MetricState.zeros(), rep)
        pipe_cfg, shard, ordered = self._input_plan()
        pipeline = BatchPipeline(
            files, pipe_cfg, epochs=1, shuffle=False, shard=shard,
            ordered=ordered,
        )
        if self.tiered is not None:
            # Evaluation scores against the MERGED logical table (cold
            # rows included — evaluation must not be blind to rows that
            # happen to be cold right now).  Small logical tables merge
            # densely; huge-V virtual stores score each batch against a
            # compact per-batch table instead (no dense table ever
            # materializes).
            if self._tiering_sharded and (
                len(self.tiered.owned) != self.tiered.num_shards
            ):
                raise RuntimeError(
                    "evaluate with fleet-sharded tiering needs every "
                    "shard's cold store; this rank owns "
                    f"{sorted(self.tiered.owned)} of "
                    f"{self.tiered.num_shards}.  Evaluate from the "
                    "saved checkpoint instead (it merges all shards)."
                )
            if self._tiered_eval_jit is None:
                self._tiered_eval_jit = jax.jit(
                    make_eval_step(self.cfg), donate_argnums=1
                )
            if not self.tiered.dense_save_ok:
                return self._evaluate_tiered_virtual(pipeline, ms)
            params = self._tiered_logical_params()
            for batch in pipeline:
                ms = self._tiered_eval_jit(
                    params, ms, self._put(batch, want_meta=False)
                )
            return _finalize_metrics(ms, self.cfg.loss_type)
        for batch in pipeline:
            ms = self._eval_step(
                self.state.params, ms, self._put(batch, want_meta=False)
            )
        return _finalize_metrics(ms, self.cfg.loss_type)

    def _data_fingerprint(self) -> dict:
        """Identity of the training input stream; the saved data position
        is only valid for an identical stream.  Everything that changes
        batch composition or order belongs here: files, batch size, seed,
        the shuffle window, and which ingest path (they shuffle with
        different RNG streams)."""
        fp = {
            "seed": self.cfg.seed,
            "batch_size": self.cfg.batch_size,
            "train_files": list(self.cfg.train_files),
            "shuffle_buffer": self.cfg.shuffle_buffer,
            "fast_ingest": self.cfg.fast_ingest,
            # Cached replays permute epoch-0 BATCHES per epoch while
            # streaming re-shuffles LINES — toggling the cache redefines
            # every epoch > 0, so a saved position must not survive it.
            "cache_epochs": self.cfg.cache_epochs,
        }
        # Prestacked replay permutes at SUPER-batch granularity, another
        # stream redefinition for epochs > 0.  Only stamped when on, so
        # fingerprints from pre-prestack checkpoints still match runs
        # that leave it off.
        if self.cfg.cache_prestacked:
            fp["cache_prestacked"] = True
            fp["steps_per_dispatch"] = self.cfg.steps_per_dispatch
        return fp

    def _evaluate_tiered_virtual(self, pipeline, ms) -> dict:
        """Huge-V tiered evaluation: sync the hot rows back once, then
        score every eval batch against a COMPACT per-batch table — the
        batch's unique rows gathered from the cold store, ids remapped
        to local indices.  Same math as a full-table gather (row values
        are identical), without ever materializing [V, D].  No new
        dispatches run during evaluation, so the synced cold store is a
        consistent snapshot."""
        self.tiered.sync_from_device(self._tier_host_tables())
        rep = NamedSharding(self.mesh, P())
        w0 = jax.device_put(self.state.params.w0, rep)
        vocab = self.cfg.vocabulary_size
        dim = self.cfg.embedding_dim
        for batch in pipeline:
            flat = batch.ids.reshape(-1)
            safe = np.where((flat >= 0) & (flat < vocab), flat, 0)
            u, inv = np.unique(safe, return_inverse=True)
            # Bucket-pad the compact table so the eval jit retraces
            # O(log) times, not once per distinct unique count.
            mp = tiered_lib._bucket(len(u))
            mini = np.zeros((mp, dim), np.float32)
            mini[:len(u)] = self.tiered.gather_logical(u)
            params = fm.FmParams(
                w0=w0, table=jax.device_put(mini, rep)
            )
            b = batch._replace(
                ids=inv.astype(np.int32).reshape(batch.ids.shape)
            )
            ms = self._tiered_eval_jit(
                params, ms, self._put(b, want_meta=False)
            )
        return _finalize_metrics(ms, self.cfg.loss_type)

    def _hot_host_tables(self) -> list:
        """np copies of the current device hot tables (params first),
        ordered like the manager's stores.  Blocks until the device is
        caught up — only called from checkpoint/eval paths."""
        tabs = (self.state.params.table,) + tiered_lib.get_opt_tables(
            self.cfg.optimizer, self.state.opt_state
        )
        return [np.asarray(t) for t in tabs]

    def _hot_host_tables_by_shard(self) -> dict:
        """Fleet view of :meth:`_hot_host_tables`: {shard -> np copies
        of that COLUMN's hot-table rows, params first} for this rank's
        owned shards, read straight from the addressable device shards
        (deduped across data-axis replicas) — a rank never materializes
        another rank's rows."""
        tabs = (self.state.params.table,) + tiered_lib.get_opt_tables(
            self.cfg.optimizer, self.state.opt_state
        )
        hs = self._dcfg.vocabulary_size // self.tiered.num_shards
        out = {s: [] for s in sorted(self.tiered.owned)}
        for t in tabs:
            got = {}
            for sh in t.addressable_shards:
                s = (sh.index[0].start or 0) // hs
                if s in got:
                    continue
                got[s] = np.asarray(sh.data)
            for s in out:
                out[s].append(got[s])
        return out

    def _tier_host_tables(self):
        """The host-table payload the active tier manager expects."""
        if self._tiering_sharded:
            return self._hot_host_tables_by_shard()
        return self._hot_host_tables()

    def _tiered_logical_params(self) -> fm.FmParams:
        """The merged logical params (hot written back over cold) as a
        replicated device FmParams — the eval/predict view of a tiered
        table.  Only feasible when the logical table materializes
        densely (small V); huge-V tiered runs score via the training
        path, not a merged table."""
        merged = self.tiered.merged_dense(self._tier_host_tables())
        rep = NamedSharding(self.mesh, P())
        return fm.FmParams(
            w0=jax.device_put(self.state.params.w0, rep),
            table=jax.device_put(merged[0], rep),
        )

    def _manifest_quality(self) -> Optional[dict]:
        """The training→serving skew reference: this run's cumulative
        feature/score sketches, published into ``serve_manifest.json``
        next to the checkpoint step so serving replicas can compare
        live request traffic against the distribution the model
        actually trained on.  None (no manifest key at all) before the
        first sketched batch or with quality off — a serving fleet
        reads absence as "no reference", never as an empty one."""
        sk = self._quality_sketch
        if sk is None:
            return None
        payload = sk.export()
        if payload is None:
            return None
        return {"quality": {
            "examples": sk.examples, "sketches": payload,
        }}

    def save(self, stepno: int):
        data_state = {
            "epoch": self._epoch,
            "batches_done": self._batches_done,
            "fingerprint": self._data_fingerprint(),
        }
        if self.tiered is None:
            checkpoint.save(
                self.cfg.model_file,
                self._restored_step + stepno,
                self.state.params,
                self.state.opt_state,
                data_state=data_state,
                manifest_extra=self._manifest_quality(),
            )
            return
        # Tiered: the checkpoint of record is the LOGICAL table.  Small
        # logical tables merge into the ordinary dense format (dense and
        # tiered runs interchange checkpoints freely, any hot_rows);
        # larger ones save the sparse overlay (tier-layout-independent,
        # tiered-restore only).
        cfg = self.cfg
        step = self._restored_step + stepno
        host_tables = self._tier_host_tables()
        w0 = np.asarray(self.state.params.w0)
        opt_scalars = tiered_lib.get_opt_scalars(
            cfg.optimizer, self.state.opt_state
        )
        if self.tiered.dense_save_ok:
            merged = self.tiered.merged_dense(host_tables)
            params = fm.FmParams(w0=w0, table=merged[0])
            if cfg.optimizer == "sgd":
                opt_state = ()
            else:
                # The device opt pytree with its table/w0 leaves swapped
                # for the merged logical numpy arrays.
                opt_state = tiered_lib.set_opt_tables(
                    cfg.optimizer,
                    tiered_lib.set_opt_scalars(
                        cfg.optimizer, self.state.opt_state, opt_scalars,
                        np.asarray,
                    ),
                    tuple(merged[1:]),
                )
            checkpoint.save(
                cfg.model_file, step, params, opt_state,
                data_state=data_state,
                manifest_extra=self._manifest_quality(),
            )  # checkpoint.save clears any stale overlay itself
            return
        scalars = {"w0": w0, **opt_scalars}
        if self._tiering_sharded:
            # Elastic per-shard files: every rank writes its OWNED
            # shards, a fleet barrier orders the writes before rank 0
            # cleans stale formats and publishes the manifest (torn
            # saves stay detectable: restore refuses a mixed/partial
            # shard set).
            barrier = None
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                def barrier():
                    multihost_utils.sync_global_devices(
                        "tffm_tiered_shard_save"
                    )
            checkpoint.save_tiered_shards(
                cfg.model_file, step, scalars,
                self.tiered.export_shard_overlays(host_tables),
                num_shards=self.tiered.num_shards,
                data_state=data_state,
                manifest_extra=self._manifest_quality(),
                primary=jax.process_index() == 0,
                barrier=barrier,
            )
            return
        checkpoint.save_tiered(
            cfg.model_file, step, scalars,
            self.tiered.export_overlay(host_tables),
            data_state=data_state,
            manifest_extra=self._manifest_quality(),
        )


def predict(cfg: FmConfig, mesh=None) -> int:
    """Score predict_files into score_path (reference predict mode, §3.3).

    Scores are written in input order, one per line — sigmoid probabilities
    for logistic loss, raw scores for mse.

    Scoring routes through the SAME fixed-shape scorer ladder the
    online serving path uses (fast_tffm_tpu/serve/scorer.py): batches
    pad into a small set of precompiled shapes (the file's batches plus
    ``serve_batch_sizes``), so ragged shapes never retrace — every
    compile is an explicit, accounted event (``record: compile`` when
    ``metrics_file`` is set; off-ladder shapes bump
    ``serve.recompiles_unexpected``), and served scores are
    bitwise-identical to this offline path by construction.  Tiered
    sparse-overlay checkpoints (``tiered.npz``) score through the
    compact per-batch remap (serve.OverlayScorer) instead of requiring
    a dense merge.
    """
    if not cfg.predict_files:
        raise ValueError("no predict_files configured")
    if jax.process_count() > 1:
        raise NotImplementedError(
            "predict runs single-process (the reference scored on one "
            "worker too); run it without jax.distributed — the sharded "
            "checkpoint restores fine on fewer devices"
        )
    from fast_tffm_tpu.serve import scorer as serve_scorer

    mesh = mesh if mesh is not None else mesh_lib.make_mesh(cfg)
    writer = (
        obs.JsonlWriter(cfg.metrics_file) if cfg.metrics_file else None
    )
    telemetry = obs.Telemetry(enabled=cfg.telemetry)
    n = 0
    try:
        scorer = serve_scorer.make_scorer(
            cfg, mesh=mesh, telemetry=telemetry, writer=writer,
            # The pipeline delivers [batch_size] batches; making that a
            # rung means the whole offline run compiles exactly once
            # per distinct shape it actually scores.
            extra_rungs=(cfg.batch_size,),
        )
        pipeline = BatchPipeline(
            cfg.predict_files, cfg, epochs=1, shuffle=False, ordered=True
        )
        with open(cfg.score_path, "w") as out:
            for batch in pipeline:
                scores = scorer.score(batch.ids, batch.vals, batch.fields)
                for s in scores[batch.weights > 0]:
                    out.write(f"{s:.6f}\n")
                    n += 1
    finally:
        if writer is not None:
            writer.close()
    log.info(
        "wrote %d scores to %s (%d scorer compile(s), checkpoint "
        "step %d)", n, cfg.score_path, scorer.compiles, scorer.step,
    )
    return n
