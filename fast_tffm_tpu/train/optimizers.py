"""Optimizers: Adagrad and FTRL-proximal (reference sweep, SURVEY.md §2 #8).

The reference uses ``tf.train.AdagradOptimizer`` (cfg keys ``learning_rate``
and ``adagrad.initial_accumulator``) and names an Adagrad-vs-FTRL sweep.
optax ships Adagrad; FTRL-proximal (McMahan et al., the standard CTR
optimizer) is implemented here as an optax GradientTransformation since
optax has none.

Optimizer state has the same pytree structure (and hence the same sharding)
as the parameters, so a row-sharded table gets row-sharded accumulators and
optimizer updates never gather the table (SURVEY.md §7 hard-part 4).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from fast_tffm_tpu.config import FmConfig


class FtrlState(NamedTuple):
    z: optax.Params  # per-weight linear accumulator
    n: optax.Params  # per-weight squared-gradient accumulator


def ftrl(
    learning_rate: float,
    l1: float = 0.0,
    l2: float = 0.0,
    beta: float = 1.0,
    initial_accumulator: float = 0.1,
) -> optax.GradientTransformation:
    """FTRL-proximal.

    Follows the standard per-coordinate recursion:

        n_{t+1} = n_t + g^2
        sigma   = (sqrt(n_{t+1}) - sqrt(n_t)) / lr
        z_{t+1} = z_t + g - sigma * w_t
        w_{t+1} = 0                                    if |z| <= l1
                = -(z - sign(z)*l1)
                  / ((beta + sqrt(n_{t+1})) / lr + l2)  otherwise

    Returned as an update: ``u = w_{t+1} - w_t`` so it composes with
    ``optax.apply_updates``.
    """

    def init_fn(params):
        # z chosen so the closed-form w(z, n) reproduces the incoming params
        # exactly: w = -(z - sign(z)*l1)/denom  ⇒  z = -w*denom - sign(w)*l1.
        # With z=0 the first update would overwrite warm-started weights
        # (the Adagrad->FTRL sweep warm start, BASELINE config 3).
        def z_from_w(w):
            denom = (beta + jnp.sqrt(initial_accumulator)) / learning_rate + l2
            return -w * denom - jnp.sign(w) * l1

        z = jax.tree.map(z_from_w, params)
        n = jax.tree.map(
            lambda p: jnp.full_like(p, initial_accumulator), params
        )
        return FtrlState(z=z, n=n)

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("ftrl requires params (pass them to update)")
        n_new = jax.tree.map(lambda g, n: n + g * g, grads, state.n)
        z_new = jax.tree.map(
            lambda g, z, n, nn, w: z
            + g
            - (jnp.sqrt(nn) - jnp.sqrt(n)) / learning_rate * w,
            grads,
            state.z,
            state.n,
            n_new,
            params,
        )

        def solve(z, nn, w):
            denom = (beta + jnp.sqrt(nn)) / learning_rate + l2
            w_new = jnp.where(
                jnp.abs(z) <= l1,
                jnp.zeros_like(w),
                -(z - jnp.sign(z) * l1) / denom,
            )
            return w_new - w

        updates = jax.tree.map(solve, z_new, n_new, params)
        return updates, FtrlState(z=z_new, n=n_new)

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(cfg: FmConfig) -> optax.GradientTransformation:
    if cfg.optimizer == "adagrad":
        return optax.adagrad(
            learning_rate=cfg.learning_rate,
            initial_accumulator_value=cfg.adagrad_initial_accumulator,
        )
    if cfg.optimizer == "ftrl":
        return ftrl(
            learning_rate=cfg.learning_rate,
            l1=cfg.ftrl_l1,
            l2=cfg.ftrl_l2,
            beta=cfg.ftrl_beta,
            initial_accumulator=cfg.adagrad_initial_accumulator,
        )
    if cfg.optimizer == "sgd":
        return optax.sgd(cfg.learning_rate)
    if cfg.optimizer == "adam":
        return optax.adam(cfg.learning_rate)
    raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
