from fast_tffm_tpu.train.optimizers import make_optimizer  # noqa: F401
