# Lazy re-export (PEP 562): optimizers pulls in jax/optax, but this
# package also hosts train.manifest — the stdlib-only manifest reader
# the jax-free serving router polls — so the heavy import happens only
# when make_optimizer is actually touched.
__all__ = ["make_optimizer"]


def __getattr__(name: str):
    if name == "make_optimizer":
        from fast_tffm_tpu.train.optimizers import make_optimizer

        return make_optimizer
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
