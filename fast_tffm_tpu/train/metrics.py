"""Evaluation metrics: logloss and streaming AUC.

The project is judged on logloss/AUC parity (BASELINE.md).  AUC uses a
fixed-bin histogram over sigmoid scores — O(1) state per step, jit-friendly
static shapes, accumulated across batches and finalized by trapezoid rule
(equivalent to TF's streaming ``tf.metrics.auc``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

DEFAULT_AUC_BINS = 1024


class AucState(NamedTuple):
    pos: jax.Array  # [bins] weighted positive counts per score bin
    neg: jax.Array  # [bins] weighted negative counts per score bin


def auc_init(bins: int = DEFAULT_AUC_BINS) -> AucState:
    return AucState(jnp.zeros((bins,), jnp.float32), jnp.zeros((bins,), jnp.float32))


def auc_update(
    state: AucState,
    scores: jax.Array,  # [B] raw (pre-sigmoid) scores
    labels: jax.Array,  # [B] in {0,1}
    weights: jax.Array,  # [B] (0 = padded example)
) -> AucState:
    bins = state.pos.shape[0]
    p = jax.nn.sigmoid(scores)
    idx = jnp.clip((p * bins).astype(jnp.int32), 0, bins - 1)
    # Histogram via one-hot matmul, NOT `.at[idx].add`: this runs inside
    # the jitted train step, and a TPU scatter serializes per row (~ms
    # for a 16k batch — comparable to the whole step) where the
    # [B, bins] matmul is sub-0.1ms of MXU time.  HIGHEST precision: the
    # default TPU matmul rounds the f32 weights to bf16, which would
    # drift the histogram off the exact scatter-add counts (AUC parity
    # is a judged metric); the one-hot side is 0/1 and exact anyway.
    oh = jax.nn.one_hot(idx, bins, dtype=jnp.float32)
    wl = weights * labels
    dot = lambda v: jnp.matmul(v, oh, precision=jax.lax.Precision.HIGHEST)  # noqa: E731
    return AucState(state.pos + dot(wl), state.neg + dot(weights - wl))


def auc_finalize(state: AucState) -> jax.Array:
    """Trapezoidal AUC from the accumulated histogram."""
    # Sweep thresholds from high score to low: cumulative TP/FP.
    pos_rev = jnp.cumsum(state.pos[::-1])
    neg_rev = jnp.cumsum(state.neg[::-1])
    tp = jnp.concatenate([jnp.zeros((1,)), pos_rev])
    fp = jnp.concatenate([jnp.zeros((1,)), neg_rev])
    p_total = jnp.maximum(pos_rev[-1], 1e-12)
    n_total = jnp.maximum(neg_rev[-1], 1e-12)
    tpr = tp / p_total
    fpr = fp / n_total
    return jnp.sum((fpr[1:] - fpr[:-1]) * 0.5 * (tpr[1:] + tpr[:-1]))


def weighted_loss(
    scores: jax.Array,
    labels: jax.Array,
    weights: jax.Array,
    loss_type: str = "logistic",
) -> tuple[jax.Array, jax.Array]:
    """Returns (sum of weighted per-example losses, sum of weights).

    logistic -> logloss on raw scores; mse -> squared error, so the metric
    matches what training minimizes (cfg.loss_type).
    """
    if loss_type == "mse":
        d = scores - labels
        per_ex = d * d
    else:
        per_ex = jax.nn.softplus(scores) - labels * scores
    return jnp.sum(per_ex * weights), jnp.sum(weights)
