"""Checkpoint/resume via Orbax (reference: ``tf.train.Saver`` -> model_file).

The reference saves to the ``model_file`` cfg path and warm-starts from it
(SURVEY.md §5 "Checkpoint / resume").  Here ``model_file`` is a directory
with two Orbax checkpoints:

- ``<model_file>/params`` — model params + step (the "model"),
- ``<model_file>/opt``    — optimizer accumulators (Adagrad/FTRL slots).

They are split so a warm start into a *different* optimizer (the
Adagrad-vs-FTRL sweep, BASELINE config 3) restores the model and freshly
initializes the new optimizer's state.  Arrays are saved with their
shardings, so a row-sharded table checkpoints and restores shard-by-shard
without ever being gathered to one host.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from fast_tffm_tpu.ops import quant

log = logging.getLogger(__name__)


def _params_dir(model_file: str) -> str:
    return os.path.join(os.path.abspath(model_file), "params")


def _opt_dir(model_file: str) -> str:
    return os.path.join(os.path.abspath(model_file), "opt")


def _data_state_path(model_file: str) -> str:
    return os.path.join(os.path.abspath(model_file), "data_state.json")


def save(
    model_file: str,
    step: int,
    params: Any,
    opt_state: Any = None,
    data_state: Optional[dict] = None,
    manifest_extra: Optional[dict] = None,
) -> None:
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(
            _params_dir(model_file),
            {"params": params, "step": np.int64(step)},
            force=True,
        )
        if opt_state is not None:
            ckptr.save(_opt_dir(model_file), {"opt_state": opt_state}, force=True)
        elif os.path.isdir(_opt_dir(model_file)):
            # A save WITHOUT optimizer state is the whole checkpoint
            # (the convert tool's dequantized params): an opt dir left
            # over from an earlier dense save belongs to DIFFERENT
            # params, and a later warm start would silently pair the
            # stale accumulators with the new table.
            import shutil

            shutil.rmtree(_opt_dir(model_file))
    # The dense dirs are the checkpoint now; a stale tiered overlay (or
    # quantized table) left behind by an earlier table_tiering /
    # convert run must not shadow them (the restore paths check those
    # formats FIRST).
    clear_tiered(model_file)
    clear_quant(model_file)
    if data_state is not None:
        # Input-pipeline position for mid-epoch resume; written last so a
        # crash mid-save leaves the (older) params without a newer data
        # position.  Schema (written by Trainer.save): ``epoch``,
        # ``batches_done`` — batches TRAINED, advanced only by whole
        # K-step dispatches, so the position always names a super-batch
        # boundary (staged-but-untrained prefetches re-parse on resume) —
        # and ``fingerprint``, the input-stream identity that gates
        # whether the position is honored (Trainer._data_fingerprint).
        # The position feeds BatchPipeline(start_epoch, skip_batches)
        # directly ("skip to position"); with cache_epochs the resumed
        # pipeline re-parses epoch 0 once to rebuild the replay cache
        # and later epochs come from memory, so the fingerprint includes
        # the cache flag (toggling it redefines every epoch > 0).
        tmp = _data_state_path(model_file) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data_state, f)
        os.replace(tmp, _data_state_path(model_file))
    _publish_manifest(model_file, step, "dense", extra=manifest_extra)
    log.info("saved checkpoint step=%d to %s", step, model_file)


# The manifest is the hot-swap handshake with the serving path
# (serve.CheckpointWatcher and the router's canary watcher): written
# last, atomic rename, so a published step always names a complete
# checkpoint.  The helpers live in train/manifest.py (stdlib-only —
# the router process polls them without a jax import) and are
# re-exported here for this module's historical callers.
from fast_tffm_tpu.train.manifest import (  # noqa: E402,F401
    _manifest_path, _publish_manifest, read_manifest,
)


def restore_data_state(model_file: str) -> Optional[dict]:
    """The saved input-pipeline position, or None (old/absent checkpoint)."""
    try:
        with open(_data_state_path(model_file)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def exists(model_file: str) -> bool:
    d = _params_dir(model_file)
    return os.path.isdir(d) and bool(os.listdir(d))


def _restore_args_for(template):
    def args(x):
        sharding = getattr(x, "sharding", None)
        if sharding is not None:
            return ocp.ArrayRestoreArgs(sharding=sharding)
        return ocp.RestoreArgs()  # plain numpy leaf (e.g. the step counter)

    return jax.tree.map(args, template)


def restore_params(model_file: str, template: Any) -> tuple[Any, int]:
    """Restore (params, step). ``template`` is a params pytree of
    ShapeDtypeStructs carrying target shardings."""
    item = {"params": template, "step": np.int64(0)}
    with ocp.PyTreeCheckpointer() as ckptr:
        got = ckptr.restore(
            _params_dir(model_file),
            item=item,
            restore_args=_restore_args_for(item),
        )
    return got["params"], int(got["step"])


def _tiered_path(model_file: str) -> str:
    return os.path.join(os.path.abspath(model_file), "tiered.npz")


def _tiered_shard_path(model_file: str, index: int, count: int) -> str:
    return os.path.join(
        os.path.abspath(model_file), f"tiered.shard{index}of{count}.npz"
    )


def _tiered_shard_files(model_file: str) -> list:
    """[(index, count, path)] of every per-shard overlay file present."""
    import glob as _glob
    import re

    out = []
    pat = re.compile(r"tiered\.shard(\d+)of(\d+)\.npz$")
    for p in sorted(_glob.glob(
        os.path.join(os.path.abspath(model_file), "tiered.shard*.npz")
    )):
        m = pat.search(p)
        if m:
            out.append((int(m.group(1)), int(m.group(2)), p))
    return out


def exists_tiered(model_file: str) -> bool:
    return os.path.isfile(_tiered_path(model_file)) or bool(
        _tiered_shard_files(model_file)
    )


def save_tiered(
    model_file: str,
    step: int,
    scalars: dict,
    stores: dict,
    data_state: Optional[dict] = None,
    manifest_extra: Optional[dict] = None,
) -> None:
    """Sparse-overlay checkpoint for a tiered table too large to merge
    into the dense format (train.tiered): per logical store, the ids and
    values of every row that ever deviated from its deterministic init,
    plus the init descriptor that regenerates the rest.  Tier-layout-
    independent — ``hot_rows`` at restore time is free to differ.

    Layout: ``<model_file>/tiered.npz`` with keys
    ``scalar/<name>`` (w0 + optimizer w0 slots, and ``step``),
    ``<store>/ids``, ``<store>/rows``, ``<store>/descriptor`` (JSON).
    The dense ``params``/``opt`` dirs are removed — the overlay is now
    the checkpoint, and a stale dense dir must not shadow it.
    """
    path = _tiered_path(model_file)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload: dict = {
        "scalar/step": np.int64(step),
        "meta/stores": np.array(json.dumps(sorted(stores))),
    }
    for name, val in scalars.items():
        payload[f"scalar/{name}"] = np.asarray(val)
    for name, store in stores.items():
        payload[f"{name}/ids"] = store["ids"]
        payload[f"{name}/rows"] = store["rows"]
        payload[f"{name}/descriptor"] = np.array(
            json.dumps(store.get("descriptor", {}), sort_keys=True)
        )
    tmp = path + ".tmp.npz"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)
    # Remove the stale dense dirs LOUDLY: a dense checkpoint silently
    # left beside a newer overlay is an ambiguity the restore guards
    # then have to refuse (the two formats share no freshness marker).
    for stale in (_params_dir(model_file), _opt_dir(model_file)):
        if os.path.isdir(stale):
            import shutil

            shutil.rmtree(stale)
    clear_quant(model_file)
    if data_state is not None:
        dtmp = _data_state_path(model_file) + ".tmp"
        with open(dtmp, "w") as f:
            json.dump(data_state, f)
        os.replace(dtmp, _data_state_path(model_file))
    _publish_manifest(model_file, step, "tiered", extra=manifest_extra)
    log.info("saved tiered overlay checkpoint step=%d to %s", step, path)


def save_tiered_shards(
    model_file: str,
    step: int,
    scalars: dict,
    overlays_by_shard: dict,
    num_shards: int,
    data_state: Optional[dict] = None,
    manifest_extra: Optional[dict] = None,
    primary: bool = True,
    barrier=None,
) -> None:
    """Rank-sharded overlay checkpoint (train.tiered_fleet): each rank
    writes one ``tiered.shard{s}of{S}.npz`` per OWNED shard, ids in
    GLOBAL space, same per-store payload schema as ``save_tiered`` —
    the union of the S files IS the checkpoint, and because every row
    is keyed by global id the union re-partitions across any new shard
    count (elastic resume).  Every file carries step+scalars (they are
    replicated state; redundancy keeps any single file self-describing).

    Multi-rank protocol: all ranks write their files, ``barrier()``
    (if given — ``multihost_utils.sync_global_devices`` in the fleet)
    joins them, then the PRIMARY rank alone removes whatever the new
    files supersede (stale shard sets from a different S, a plain
    tiered.npz, the dense dirs, quant.npz), writes ``data_state`` and
    publishes the manifest — so a published step always names a
    complete shard set.
    """
    os.makedirs(os.path.abspath(model_file), exist_ok=True)
    wrote = set()
    for s, stores in overlays_by_shard.items():
        payload: dict = {
            "scalar/step": np.int64(step),
            "meta/stores": np.array(json.dumps(sorted(stores))),
            "meta/shard": np.array([int(s), int(num_shards)], np.int64),
        }
        for name, val in scalars.items():
            payload[f"scalar/{name}"] = np.asarray(val)
        for name, store in stores.items():
            payload[f"{name}/ids"] = store["ids"]
            payload[f"{name}/rows"] = store["rows"]
            payload[f"{name}/descriptor"] = np.array(
                json.dumps(store.get("descriptor", {}), sort_keys=True)
            )
        path = _tiered_shard_path(model_file, s, num_shards)
        tmp = path + ".tmp.npz"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
        wrote.add(path)
    if barrier is not None:
        barrier()
    if not primary:
        return
    keep = {
        _tiered_shard_path(model_file, s, num_shards)
        for s in range(num_shards)
    }
    for _, _, p in _tiered_shard_files(model_file):
        if p not in keep:
            os.remove(p)
    try:
        os.remove(_tiered_path(model_file))
    except FileNotFoundError:
        pass
    for stale in (_params_dir(model_file), _opt_dir(model_file)):
        if os.path.isdir(stale):
            import shutil

            shutil.rmtree(stale)
    clear_quant(model_file)
    if data_state is not None:
        dtmp = _data_state_path(model_file) + ".tmp"
        with open(dtmp, "w") as f:
            json.dump(data_state, f)
        os.replace(dtmp, _data_state_path(model_file))
    _publish_manifest(model_file, step, "tiered", extra=manifest_extra)
    log.info(
        "saved tiered shard checkpoint step=%d (%d/%d shards this rank) "
        "to %s", step, len(overlays_by_shard), num_shards, model_file,
    )


def _read_tiered_file(path: str) -> tuple:
    with np.load(path, allow_pickle=False) as z:
        names = json.loads(str(z["meta/stores"]))
        step = int(z["scalar/step"])
        scalars = {
            k.split("/", 1)[1]: z[k]
            for k in z.files
            if k.startswith("scalar/") and k != "scalar/step"
        }
        stores = {}
        for name in names:
            stores[name] = {
                "ids": z[f"{name}/ids"],
                "rows": z[f"{name}/rows"],
                "descriptor": json.loads(str(z[f"{name}/descriptor"])),
            }
    return step, scalars, stores


def restore_tiered(model_file: str) -> Optional[tuple]:
    """(step, scalars, stores) from a tiered overlay, or None.

    Reads BOTH formats: the single-file overlay (``tiered.npz``) and a
    rank-sharded shard set, whose per-store payloads are concatenated
    into one global-id overlay — so every consumer (host-global restore,
    elastic re-sharding at any R', the serve OverlayScorer) sees one
    format.  An INCOMPLETE or mixed shard set refuses loudly: silently
    restoring a partial table would train on re-initialized rows.
    """
    path = _tiered_path(model_file)
    if os.path.isfile(path):
        return _read_tiered_file(path)
    shard_files = _tiered_shard_files(model_file)
    if not shard_files:
        return None
    counts = {c for _, c, _ in shard_files}
    if len(counts) != 1:
        raise ValueError(
            f"tiered shard checkpoint in {model_file} mixes shard counts "
            f"{sorted(counts)}; remove the stale set"
        )
    count = counts.pop()
    have = {s for s, _, _ in shard_files}
    missing = sorted(set(range(count)) - have)
    if missing:
        raise ValueError(
            f"tiered shard checkpoint in {model_file} is missing shards "
            f"{missing} of {count}; refusing a partial-table restore"
        )
    step = scalars = None
    merged: dict = {}
    for s, _, p in sorted(shard_files):
        f_step, f_scalars, f_stores = _read_tiered_file(p)
        if step is None:
            step, scalars = f_step, f_scalars
        elif f_step != step:
            raise ValueError(
                f"tiered shard files in {model_file} disagree on step "
                f"({f_step} != {step}); the save was torn"
            )
        for name, payload in f_stores.items():
            acc = merged.setdefault(
                name, {"ids": [], "rows": [],
                       "descriptor": payload["descriptor"]}
            )
            if payload["descriptor"] != acc["descriptor"]:
                raise ValueError(
                    f"tiered shard files disagree on store {name!r} "
                    "descriptor; the save mixed configs"
                )
            acc["ids"].append(payload["ids"])
            acc["rows"].append(payload["rows"])
    stores = {
        name: {
            "ids": np.concatenate(acc["ids"]),
            "rows": np.concatenate(acc["rows"]),
            "descriptor": acc["descriptor"],
        }
        for name, acc in merged.items()
    }
    return step, scalars, stores


def clear_tiered(model_file: str) -> None:
    """Remove a stale overlay after a dense-format save (the dense dirs
    are now the checkpoint; precedence must not flip back)."""
    try:
        os.remove(_tiered_path(model_file))
    except FileNotFoundError:
        pass
    for _, _, p in _tiered_shard_files(model_file):
        try:
            os.remove(p)
        except FileNotFoundError:
            pass


# ----------------------------------------------------------------------
# Dense QUANTIZED checkpoint (quant.npz): bf16 / int8-with-scales table
# ----------------------------------------------------------------------


def _quant_path(model_file: str) -> str:
    return os.path.join(os.path.abspath(model_file), "quant.npz")


def exists_quant(model_file: str) -> bool:
    return os.path.isfile(_quant_path(model_file))


def save_quant(model_file: str, step: int, w0,
               qt: "quant.QuantTable") -> None:
    """Dense quantized checkpoint: the serving-oriented compact format
    (``tools/convert_checkpoint.py`` writes it; the serve ladder loads
    it as the device-resident table).  Layout:
    ``<model_file>/quant.npz`` with ``scalar/step``, ``scalar/w0``,
    ``quant/codes`` (int8, or bf16 as a uint16 bit view),
    ``quant/scales`` (int8 only) and ``quant/descriptor`` — the JSON
    format identity (dtype / chunk / vocab / dim) a loader must match
    or refuse.  The dense params/opt dirs and any tiered overlay are
    removed: quant.npz is now the checkpoint, and three formats with
    no shared freshness marker must never coexist.
    """
    path = _quant_path(model_file)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = {
        "scalar/step": np.int64(step),
        "scalar/w0": np.asarray(w0, np.float32),
        "quant/descriptor": np.array(
            json.dumps(qt.descriptor(), sort_keys=True)
        ),
    }
    for name, arr in quant.table_to_arrays(qt).items():
        payload[f"quant/{name}"] = arr
    tmp = path + ".tmp.npz"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)
    for stale in (_params_dir(model_file), _opt_dir(model_file)):
        if os.path.isdir(stale):
            import shutil

            shutil.rmtree(stale)
    clear_tiered(model_file)
    _publish_manifest(model_file, step, "quant")
    log.info(
        "saved %s quantized checkpoint step=%d to %s",
        qt.dtype, step, path,
    )


def restore_quant(model_file: str) -> Optional[tuple]:
    """(step, w0, QuantTable) from quant.npz, or None."""
    path = _quant_path(model_file)
    if not os.path.isfile(path):
        return None
    with np.load(path, allow_pickle=False) as z:
        step = int(z["scalar/step"])
        w0 = float(z["scalar/w0"])
        descriptor = json.loads(str(z["quant/descriptor"]))
        arrays = {
            k.split("/", 1)[1]: z[k]
            for k in z.files
            if k.startswith("quant/") and k != "quant/descriptor"
        }
    return step, w0, quant.table_from_arrays(descriptor, arrays)


def clear_quant(model_file: str) -> None:
    """Remove a stale quant.npz after a dense/tiered-format save."""
    try:
        os.remove(_quant_path(model_file))
    except FileNotFoundError:
        pass


def restore_opt(model_file: str, template: Any) -> Optional[Any]:
    """Restore optimizer state, or None if absent/incompatible (e.g. the
    checkpoint came from a different optimizer in a sweep)."""
    d = _opt_dir(model_file)
    if not (os.path.isdir(d) and os.listdir(d)):
        return None
    item = {"opt_state": template}
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            got = ckptr.restore(d, item=item, restore_args=_restore_args_for(item))
        return got["opt_state"]
    except Exception as e:
        log.warning(
            "optimizer state in %s incompatible (%s); reinitializing", d, e
        )
        return None
