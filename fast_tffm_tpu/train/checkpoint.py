"""Checkpoint/resume via Orbax (reference: ``tf.train.Saver`` -> model_file).

The reference saves to the ``model_file`` cfg path and warm-starts from it
(SURVEY.md §5 "Checkpoint / resume").  Here ``model_file`` is a directory
with two Orbax checkpoints:

- ``<model_file>/params`` — model params + step (the "model"),
- ``<model_file>/opt``    — optimizer accumulators (Adagrad/FTRL slots).

They are split so a warm start into a *different* optimizer (the
Adagrad-vs-FTRL sweep, BASELINE config 3) restores the model and freshly
initializes the new optimizer's state.  Arrays are saved with their
shardings, so a row-sharded table checkpoints and restores shard-by-shard
without ever being gathered to one host.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

log = logging.getLogger(__name__)


def _params_dir(model_file: str) -> str:
    return os.path.join(os.path.abspath(model_file), "params")


def _opt_dir(model_file: str) -> str:
    return os.path.join(os.path.abspath(model_file), "opt")


def _data_state_path(model_file: str) -> str:
    return os.path.join(os.path.abspath(model_file), "data_state.json")


def save(
    model_file: str,
    step: int,
    params: Any,
    opt_state: Any = None,
    data_state: Optional[dict] = None,
) -> None:
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(
            _params_dir(model_file),
            {"params": params, "step": np.int64(step)},
            force=True,
        )
        if opt_state is not None:
            ckptr.save(_opt_dir(model_file), {"opt_state": opt_state}, force=True)
    if data_state is not None:
        # Input-pipeline position for mid-epoch resume; written last so a
        # crash mid-save leaves the (older) params without a newer data
        # position.  Schema (written by Trainer.save): ``epoch``,
        # ``batches_done`` — batches TRAINED, advanced only by whole
        # K-step dispatches, so the position always names a super-batch
        # boundary (staged-but-untrained prefetches re-parse on resume) —
        # and ``fingerprint``, the input-stream identity that gates
        # whether the position is honored (Trainer._data_fingerprint).
        # The position feeds BatchPipeline(start_epoch, skip_batches)
        # directly ("skip to position"); with cache_epochs the resumed
        # pipeline re-parses epoch 0 once to rebuild the replay cache
        # and later epochs come from memory, so the fingerprint includes
        # the cache flag (toggling it redefines every epoch > 0).
        tmp = _data_state_path(model_file) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data_state, f)
        os.replace(tmp, _data_state_path(model_file))
    log.info("saved checkpoint step=%d to %s", step, model_file)


def restore_data_state(model_file: str) -> Optional[dict]:
    """The saved input-pipeline position, or None (old/absent checkpoint)."""
    try:
        with open(_data_state_path(model_file)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def exists(model_file: str) -> bool:
    d = _params_dir(model_file)
    return os.path.isdir(d) and bool(os.listdir(d))


def _restore_args_for(template):
    def args(x):
        sharding = getattr(x, "sharding", None)
        if sharding is not None:
            return ocp.ArrayRestoreArgs(sharding=sharding)
        return ocp.RestoreArgs()  # plain numpy leaf (e.g. the step counter)

    return jax.tree.map(args, template)


def restore_params(model_file: str, template: Any) -> tuple[Any, int]:
    """Restore (params, step). ``template`` is a params pytree of
    ShapeDtypeStructs carrying target shardings."""
    item = {"params": template, "step": np.int64(0)}
    with ocp.PyTreeCheckpointer() as ckptr:
        got = ckptr.restore(
            _params_dir(model_file),
            item=item,
            restore_args=_restore_args_for(item),
        )
    return got["params"], int(got["step"])


def restore_opt(model_file: str, template: Any) -> Optional[Any]:
    """Restore optimizer state, or None if absent/incompatible (e.g. the
    checkpoint came from a different optimizer in a sweep)."""
    d = _opt_dir(model_file)
    if not (os.path.isdir(d) and os.listdir(d)):
        return None
    item = {"opt_state": template}
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            got = ckptr.restore(d, item=item, restore_args=_restore_args_for(item))
        return got["opt_state"]
    except Exception as e:
        log.warning(
            "optimizer state in %s incompatible (%s); reinitializing", d, e
        )
        return None
