"""Two-tier embedding table: device-resident hot rows over a host cold store.

The flagship config pins ``vocabulary_size`` to what a dense ``[V, D]``
device table (plus its optimizer slots) can afford in device memory.  CTR
vocabularies want 2^28+ rows, but CTR id streams are Zipf-skewed: a small
hot set of rows absorbs almost every occurrence.  This module exploits
that: the device holds a compact HOT table of ``hot_rows`` (H) rows —
params and optimizer slots — while the full logical table lives in host
RAM as a lazily-materialized COLD store.

Division of labor (see EMBEDDING.md for the dataflow diagram):

- :class:`TieredTable` (this module, host-side) owns the logical->hot-slot
  map, occupancy-driven LRU migration planning, the cold stores, and the
  delayed write-back ledger.  ``plan()`` runs in the DevicePrefetcher's
  transfer thread: each stacked super-batch's ids are remapped to hot-slot
  indices, misses are fetched from the cold store, and the resulting
  migration plan ships to the device alongside the batch on the same
  async H2D path — migration hides behind the transfer that already
  happens.
- The fused scan step (train.sparse / ops.sparse_apply) runs UNCHANGED
  against the hot table: it already operates on touched-row streams, and
  a remapped batch is indistinguishable from a small-vocab batch.
- Eviction values come back on a one-dispatch-delayed async D2H read
  (``Trainer._apply_migration`` gathers the evicted slots right after the
  previous dispatch and hands the device arrays to
  :meth:`TieredTable.push_writeback`); the cold store absorbs them once
  the copy lands, never stalling the dispatch loop.

Consistency rules the implementation leans on:

- plans are created in emission order (single transfer thread) and applied
  in the same order (single dispatch loop), so the planning-view slot map
  may run AHEAD of the device while the applied view
  (``id_of_slot_applied``) tracks exactly what the device tables hold;
- an eviction's value is "pending" from plan creation until its D2H lands;
  a re-fetch of a pending id waits for the fill (the dispatch loop never
  waits on the planner, so this cannot deadlock);
- checkpoint/eval sync uses the APPLIED view: unapplied plans' evicted
  rows are still device-resident and are swept with everything else.

Cold-store modes:

- EXACT (small logical tables, <= :data:`EXACT_BYTES_MAX` bytes): the full
  logical array is materialized once via the same jax init the dense path
  uses, so tiered training is element-wise identical to dense training
  (pinned by tests/test_tiered_table.py) and checkpoints in the ordinary
  dense format — tier-layout-independent, interchangeable with dense runs.
- VIRTUAL (V >= 2^26-ish): rows materialize on demand — a deterministic
  per-row hash init plus a sorted sparse overlay of every row ever written
  back, so host memory scales with rows TOUCHED, not V.  Checkpoints use
  the sparse overlay format (train.checkpoint.save_tiered).
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import NamedTuple, Optional

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.ops import quant

log = logging.getLogger(__name__)

# Cold arrays at or below this byte size are materialized EXACTLY via the
# same jax init the dense path uses (bitwise parity with dense training,
# dense-format checkpoints); larger stores use the virtual row-hash init
# with a sparse written-row overlay.  Module attribute so tests can force
# the virtual path at tiny vocabularies.
EXACT_BYTES_MAX = 1 << 28

# slot_of states: >= 0 resident at that hot slot.
_NEVER = -1  # never touched this run/restore: cold value is the row init
_EVICTED = -2  # was resident; latest value lives in (or is bound for) cold


def _bucket(n: int, lo: int = 8) -> int:
    """Round up to a power of two >= lo — migration arrays are padded to
    bucketed lengths so the gather/load jits retrace O(log) times, not
    once per distinct miss count."""
    b = lo
    while b < n:
        b <<= 1
    return b


# ----------------------------------------------------------------------
# Optimizer-state plumbing: which [V, D] tables ride beside the params
# table, and how to rebuild the sparse opt-state pytree around new ones.
# ----------------------------------------------------------------------


def opt_table_names(optimizer: str) -> tuple:
    """Names of the table-shaped optimizer slots, in pytree order."""
    return {"adagrad": ("acc",), "ftrl": ("z", "n"), "sgd": ()}[optimizer]


def get_opt_tables(optimizer: str, opt_state) -> tuple:
    if optimizer == "adagrad":
        return (opt_state.acc.table,)
    if optimizer == "ftrl":
        return (opt_state.z.table, opt_state.n.table)
    return ()


def set_opt_tables(optimizer: str, opt_state, tables: tuple):
    if optimizer == "adagrad":
        return opt_state._replace(acc=opt_state.acc._replace(table=tables[0]))
    if optimizer == "ftrl":
        return opt_state._replace(
            z=opt_state.z._replace(table=tables[0]),
            n=opt_state.n._replace(table=tables[1]),
        )
    return opt_state


def get_opt_scalars(optimizer: str, opt_state) -> dict:
    """The non-table (w0) optimizer slots, as host scalars."""
    if optimizer == "adagrad":
        return {"acc_w0": np.asarray(opt_state.acc.w0)}
    if optimizer == "ftrl":
        return {
            "z_w0": np.asarray(opt_state.z.w0),
            "n_w0": np.asarray(opt_state.n.w0),
        }
    return {}


def set_opt_scalars(optimizer: str, opt_state, scalars: dict, put):
    if optimizer == "adagrad":
        return opt_state._replace(
            acc=opt_state.acc._replace(w0=put(scalars["acc_w0"]))
        )
    if optimizer == "ftrl":
        return opt_state._replace(
            z=opt_state.z._replace(w0=put(scalars["z_w0"])),
            n=opt_state.n._replace(w0=put(scalars["n_w0"])),
        )
    return opt_state


# ----------------------------------------------------------------------
# Cold store: one logical [V, D] f32 array in host RAM
# ----------------------------------------------------------------------


def _hash_uniform(ids: np.ndarray, dim: int, seed: int,
                  scale: float) -> np.ndarray:
    """Deterministic per-row uniform(-scale, scale) init, vectorized.

    splitmix64 over (id * dim + column) xor a seed constant: any row of
    the virtual table is computable without materializing any other row —
    the property the lazy cold store needs (jax.random's table draw can't
    be sliced without materializing [V, D], which is the thing a 2^28+
    vocabulary cannot do).  Not bitwise-equal to the dense jax init; the
    virtual mode only exists where a dense table cannot.
    """
    with np.errstate(over="ignore"):
        x = ids.astype(np.uint64)[:, None] * np.uint64(dim) + np.arange(
            dim, dtype=np.uint64
        )[None, :]
        x ^= np.uint64((seed * 0x9E3779B97F4A7C15 + 1) & 0xFFFFFFFFFFFFFFFF)
        x += np.uint64(0x9E3779B97F4A7C15)
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    u = (x >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))
    return ((u * 2.0 - 1.0) * scale).astype(np.float32)


class ColdStore:
    """Host-RAM backing for one logical ``[vocab, dim]`` f32 table.

    Two modes:

    - dense-backed (``from_dense`` / exact init): one real ndarray;
      gather/scatter are plain fancy indexing; ``to_dense`` is free.
    - virtual: ``init_rows(ids) -> [n, dim]`` computes any row on demand
      and a sorted (ids, rows) overlay holds every row ever written.
      Memory scales with written rows, not vocab.

    Storage format: rows live PACKED through an
    :class:`ops.quant.RowCodec` (``cold_dtype``): fp32 is the identity
    codec (bit-exact, the historical behavior), bf16/int8 store
    compact packed rows — encoded on every write (scatter /
    write-back), decoded on every read (gather / hot-load).  The
    overlay machinery never looks inside a row, so it is entirely
    dtype-agnostic; ``nbytes`` reports the real compact footprint.
    """

    def __init__(self, vocab: int, dim: int, descriptor: dict,
                 init_rows=None, dense: Optional[np.ndarray] = None,
                 codec: Optional[quant.RowCodec] = None):
        self.vocab = vocab
        self.dim = dim
        self.descriptor = dict(descriptor)
        self._init_rows = init_rows
        self._codec = codec if codec is not None else quant.RowCodec(
            "fp32", dim
        )
        self._dense = dense
        # Sorted sparse overlay (virtual mode): _ids ascending, _rows[i]
        # is the stored (packed) value of row _ids[i].  Writes land in
        # an unsorted TAIL of (sorted ids, rows) batches first and merge
        # into the main arrays only when the tail outgrows a fraction of
        # them — rebuilding the whole overlay per write-back flush would
        # be O(written_rows) per super-batch (quadratic over a run).
        self._ids = np.empty((0,), np.int64)
        self._rows = self._codec.empty(0)
        self._tail: list = []  # [(sorted unique ids, rows), ...] newest last
        self._tail_n = 0

    @property
    def cold_dtype(self) -> str:
        return self._codec.dtype

    @classmethod
    def from_dense(cls, arr: np.ndarray, descriptor: dict,
                   codec: Optional[quant.RowCodec] = None) -> "ColdStore":
        vocab, dim = arr.shape
        if codec is not None and codec.dtype != "fp32":
            return cls(vocab, dim, descriptor, dense=codec.encode(arr),
                       codec=codec)
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        if not arr.flags.writeable:  # np.asarray(jax_array) is read-only
            arr = arr.copy()
        return cls(vocab, dim, descriptor, dense=arr, codec=codec)

    @property
    def dense_backed(self) -> bool:
        return self._dense is not None

    @property
    def nbytes(self) -> int:
        if self._dense is not None:
            return self._dense.nbytes
        return (
            self._ids.nbytes + self._rows.nbytes
            + sum(i.nbytes + r.nbytes for i, r in self._tail)
        )

    @property
    def written_rows(self) -> int:
        if self._dense is not None:
            return self.vocab
        self._compact()
        return len(self._ids)

    def _overlay(self, out, ids, o_ids, o_rows) -> None:
        """out[k] = decode(o_rows[j]) wherever ids[k] == o_ids[j]
        (o_ids sorted; ``out`` is f32)."""
        if not len(o_ids):
            return
        pos = np.searchsorted(o_ids, ids)
        pos_c = np.minimum(pos, len(o_ids) - 1)
        hit = o_ids[pos_c] == ids
        if hit.any():
            out[hit] = self._codec.decode(o_rows[pos_c[hit]])

    def gather(self, ids: np.ndarray) -> np.ndarray:
        """Current f32 value of each logical row (written value, else
        init) — quantized stores dequantize on the way out (the
        hot-load path)."""
        ids = ids.astype(np.int64, copy=False)
        if self._dense is not None:
            # Fancy indexing is already a copy; fp32's decode is the
            # identity on it.
            return self._codec.decode(self._dense[ids])
        out = self._init_rows(ids)
        self._overlay(out, ids, self._ids, self._rows)
        for t_ids, t_rows in self._tail:  # newest last = newest wins
            self._overlay(out, ids, t_ids, t_rows)
        return out

    def scatter(self, ids: np.ndarray, rows: np.ndarray) -> None:
        """Write f32 rows (ids unique) into the store — quantized
        stores re-encode on the way in (the write-back path)."""
        if not len(ids):
            return
        ids = ids.astype(np.int64, copy=False)
        if self._dense is not None and self._codec.dtype == "fp32":
            self._dense[ids] = rows
            return
        self._store_packed(
            ids, self._codec.encode(np.asarray(rows, np.float32))
        )

    def _store_packed(self, ids: np.ndarray, packed: np.ndarray) -> None:
        """Write already-packed rows (the overlay-restore path — no
        decode/re-encode round trip, so a checkpointed row restores
        bit-exactly whatever the codec)."""
        if packed.shape[1:] != (self._codec.width,):
            raise ValueError(
                f"packed rows have width {packed.shape[1:]} but this "
                f"{self._codec.dtype} store expects "
                f"[{self._codec.width}]"
            )
        if self._dense is not None:
            self._dense[ids] = packed
            return
        order = np.argsort(ids, kind="stable")
        self._tail.append((
            ids[order].copy(),
            np.ascontiguousarray(packed[order]),
        ))
        self._tail_n += len(ids)
        if self._tail_n > max(4096, len(self._ids) // 2):
            self._compact()

    def _compact(self) -> None:
        """Merge the write tail into the sorted main overlay (newest
        write wins per id) — amortized O(total log total)."""
        if not self._tail:
            return
        all_ids = np.concatenate([self._ids] + [i for i, _ in self._tail])
        all_rows = np.concatenate(
            [self._rows] + [r for _, r in self._tail]
        )
        # Keep the LAST occurrence of each id: unique() keeps the first,
        # so dedupe over the reversed arrays.
        rev_ids = all_ids[::-1]
        u, first = np.unique(rev_ids, return_index=True)
        self._ids = u
        self._rows = np.ascontiguousarray(all_rows[::-1][first])
        self._tail = []
        self._tail_n = 0

    def to_dense(self) -> np.ndarray:
        """The full logical array as f32 (dense checkpoint / merged
        eval); only legal for dense-backed or small-enough virtual
        stores."""
        if self._dense is None:
            if self.vocab * self.dim * 4 > EXACT_BYTES_MAX:
                raise ValueError(
                    f"cold store [{self.vocab}, {self.dim}] is too large "
                    "to materialize densely; use the tiered overlay "
                    "checkpoint format"
                )
            self._compact()
            dense = self._init_rows(np.arange(self.vocab, dtype=np.int64))
            if len(self._ids):
                dense[self._ids] = self._codec.decode(self._rows)
            self._dense = (
                dense if self._codec.dtype == "fp32"
                else self._codec.encode(dense)
            )
            self._ids = np.empty((0,), np.int64)
            self._rows = self._codec.empty(0)
        return self._codec.decode(self._dense)

    def export(self) -> dict:
        """Sparse overlay payload for the tiered checkpoint format.
        ``rows`` is the PACKED storage array (codec-specific width) —
        the descriptor's dtype names the format, and a restore stores
        the packed rows verbatim (no decode/re-encode drift).

        Dense-backed stores export EVERY row (the overlay degenerates to
        the dense slice) — the rank-sharded checkpoint path needs this:
        no single rank can assemble the merged dense array, so each
        shard's store serializes in the overlay format regardless of
        backing.  Single-process dense-backed saves keep using the
        ordinary dense format (``dense_save_ok``)."""
        if self._dense is not None:
            return {
                "ids": np.arange(self.vocab, dtype=np.int64),
                "rows": self._dense.copy(),
            }
        self._compact()
        return {"ids": self._ids.copy(), "rows": self._rows.copy()}

    def import_overlay(self, payload: dict) -> None:
        ids = payload["ids"].astype(np.int64, copy=False)
        if len(ids):
            self._store_packed(
                ids,
                np.asarray(payload["rows"], self._codec.storage_dtype),
            )


def _virtual_descriptor(cfg: FmConfig, name: str) -> dict:
    if name == "table":
        desc = {"kind": "uniform", "seed": cfg.seed,
                "range": cfg.init_value_range}
    elif name in ("acc", "n"):
        desc = {"kind": "const", "value": cfg.adagrad_initial_accumulator}
    elif name == "z":
        denom0 = float(
            (cfg.ftrl_beta + np.sqrt(cfg.adagrad_initial_accumulator))
            / cfg.learning_rate + cfg.ftrl_l2
        )
        desc = {"kind": "ftrl_z", "seed": cfg.seed,
                "range": cfg.init_value_range, "denom0": denom0,
                "l1": cfg.ftrl_l1}
    else:
        raise ValueError(f"unknown store {name!r}")
    # Storage-format identity rides the descriptor (empty for fp32, so
    # pre-quantization checkpoints keep matching byte-for-byte): an
    # overlay written under one cold_dtype refuses to restore under
    # another — its packed rows are not the other format's bytes.
    desc.update(quant.cold_codec(cfg).descriptor())
    return desc


def _virtual_store(cfg: FmConfig, name: str, *, vocab: Optional[int] = None,
                   id_offset: int = 0) -> ColdStore:
    """Virtual cold store over ``vocab`` rows.  ``id_offset`` keys the
    hash init in GLOBAL id space: a rank-sharded store over local ids
    [0, vs) initializes row i exactly like the host-global store
    initializes row ``id_offset + i`` — the property that makes sharded
    and global tiering element-wise identical (and shard overlays
    layout-independent once ids are globalized)."""
    vocab = cfg.vocabulary_size if vocab is None else vocab
    dim = cfg.embedding_dim
    off = np.int64(id_offset)
    desc = _virtual_descriptor(cfg, name)
    if desc["kind"] == "uniform":
        seed, r = desc["seed"], desc["range"]

        def init_rows(ids):
            return _hash_uniform(ids + off, dim, seed, r)
    elif desc["kind"] == "const":
        v = desc["value"]

        def init_rows(ids):
            return np.full((len(ids), dim), v, np.float32)
    else:  # ftrl_z, derived from the params row init (see module note:
        # any params row that ever deviated from init has a written z
        # row beside it, so deriving from the INIT formula is exact).
        seed, r = desc["seed"], desc["range"]
        denom0, l1 = np.float32(desc["denom0"]), np.float32(desc["l1"])

        def init_rows(ids):
            p = _hash_uniform(ids + off, dim, seed, r)
            return -p * denom0 - np.sign(p) * l1
    return ColdStore(vocab, dim, desc, init_rows=init_rows,
                     codec=quant.cold_codec(cfg))


def _exact_stores(cfg: FmConfig, names: tuple,
                  params_table: Optional[np.ndarray],
                  row_range: Optional[tuple] = None) -> dict:
    """Dense-backed stores materialized via the SAME jax init the dense
    trainer uses — bit-identical starting point, pinned by tier-1.

    ``row_range=(lo, hi)`` slices the GLOBAL init down to a rank shard's
    id span: the full table is drawn once (exact mode only exists where
    that fits) and everything outside the shard is dropped, so a sharded
    shard's rows are bitwise the rows a host-global store holds.  A
    provided ``params_table`` is already in the caller's (possibly
    local) space — the optimizer init is elementwise, so no slicing."""
    import jax

    from fast_tffm_tpu.models import fm
    from fast_tffm_tpu.train import sparse as sparse_lib

    if params_table is None:
        params = fm.init_params(jax.random.PRNGKey(cfg.seed), cfg)
        params_table = np.asarray(params.table)
        if row_range is not None:
            params_table = params_table[row_range[0]:row_range[1]].copy()
    params = fm.FmParams(w0=np.zeros((), np.float32), table=params_table)
    codec = quant.cold_codec(cfg)
    stores = {
        "table": ColdStore.from_dense(
            params_table, {"kind": "exact", **codec.descriptor()}, codec
        )
    }
    opt_names = tuple(n for n in names if n != "table")
    if opt_names:
        opt = sparse_lib.init_sparse_opt_state(cfg, params)
        for name, tab in zip(opt_names, get_opt_tables(cfg.optimizer, opt)):
            stores[name] = ColdStore.from_dense(
                np.asarray(tab), {"kind": "exact", **codec.descriptor()},
                codec,
            )
    return stores


# ----------------------------------------------------------------------
# Migration plan + manager
# ----------------------------------------------------------------------


class ShardSpec(NamedTuple):
    """Which slice of the logical table a :class:`TieredTable` instance
    manages under rank-sharded tiering (train.tiered_fleet).

    ``index``/``count`` carve the id space into ``count`` contiguous
    ranges; the instance then operates entirely in LOCAL coordinates
    (vocab ``V/count``, hot rows ``H/count``, local ids/slots).  With
    ``rows_enabled=False`` the instance is a metadata MIRROR: it tracks
    the slot map + LRU deterministically (every rank plans every shard
    over identical global batches, so mirrors stay in lockstep with the
    owner at zero communication) but builds no cold stores, fetches no
    rows, and keeps no write-back ledger — per-rank host bytes and
    migration traffic stay ~1/R."""

    index: int = 0
    count: int = 1
    rows_enabled: bool = True


class Plan(NamedTuple):
    """Host-side migration plan for one super-batch (pre-shipping)."""

    plan_id: int
    load_slots: np.ndarray  # [Mp] i32, padded with hot_rows (scatter-drop)
    load_ids: np.ndarray  # [n_load] i64 logical ids (applied-view update)
    load_rows: tuple  # per-store [Mp, D] f32 (pad rows are zeros)
    evict_slots: np.ndarray  # [Ep] i32, padded with 0 (ignored host-side)
    n_load: int
    n_evict: int


class Shipment(NamedTuple):
    """What DevicePrefetcher hands the dispatch loop per super-batch when
    tiering is on: the remapped device batch plus the device-side halves
    of the migration plan (shipped on the same async H2D path)."""

    batch: object  # device super-batch (remapped ids)
    load_slots: object  # device [Mp] i32
    load_rows: tuple  # device per-store [Mp, D] f32
    evict_slots: object  # device [Ep] i32
    load_slots_h: np.ndarray  # host copy for the applied-view update
    load_ids: np.ndarray
    plan_id: int
    n_load: int
    n_evict: int


class TieredTable:
    """Host-side manager of the two-tier table (see module docstring).

    Thread contract: ``plan``/``flush`` run in the transfer thread;
    ``push_writeback``/``note_applied``/``sync_from_device`` run in the
    dispatch loop; ``snapshot`` may run in the heartbeat thread.  One
    condition variable guards all state; only the transfer thread ever
    WAITS on it (for a pending write-back fill), and the fill comes from
    the dispatch loop, which never blocks on the planner — so the wait
    always resolves.
    """

    # Keep this many newest write-back entries unflushed: their D2H may
    # still be in flight, and forcing them would stall the transfer
    # thread on the device.  Anything older is one-dispatch-plus stale
    # and its copy has long landed.
    FLUSH_KEEP = 2

    def __init__(self, cfg: FmConfig, telemetry=None,
                 dense_tables: Optional[dict] = None,
                 overlay: Optional[dict] = None,
                 shard: Optional[ShardSpec] = None):
        from fast_tffm_tpu import obs

        self.cfg = cfg
        self.shard = shard if shard is not None else ShardSpec()
        v_global = cfg.vocabulary_size
        h_global = min(cfg.hot_rows, cfg.vocabulary_size)
        if v_global % self.shard.count or h_global % self.shard.count:
            raise ValueError(
                f"vocabulary_size={v_global} and hot_rows={h_global} must "
                f"both divide by the tier shard count "
                f"{self.shard.count} (contiguous id-range ownership)"
            )
        self.vocab = v_global // self.shard.count
        self.hot_rows = h_global // self.shard.count
        self.id_offset = self.shard.index * self.vocab
        self.rows_enabled = bool(self.shard.rows_enabled)
        self.dim = cfg.embedding_dim
        self.codec = quant.cold_codec(cfg)
        self.names = ("table",) + opt_table_names(cfg.optimizer)
        self._cv = threading.Condition(threading.RLock())
        self.slot_of = np.full(self.vocab, _NEVER, np.int32)
        self.id_of_slot = np.full(self.hot_rows, -1, np.int64)
        # What the DEVICE tables hold right now (advanced by note_applied
        # as the dispatch loop applies plans); the planning view above
        # runs ahead by the in-flight plan depth.
        self.id_of_slot_applied = np.full(self.hot_rows, -1, np.int64)
        self.last_used = np.zeros(self.hot_rows, np.int64)
        self._free_ptr = 0
        self._tick = 0
        self._plan_seq = 0
        # Write-back ledger: plan_id -> entry; entries fill when the
        # dispatch loop hands over the gathered device rows.
        self._entries: dict = {}
        self._entry_q: deque = deque()
        self._pending: dict = {}  # logical id -> (entry, row index)
        # Set by cancel_waits() when the dispatch loop is going away: a
        # transfer thread blocked waiting for a write-back fill must be
        # released (the fill will never come) or shutdown joins forever.
        self._cancelled = False
        # Occurrence-level cache accounting (the bench's hot_hit_frac).
        self._hit_occ = 0
        self._miss_occ = 0
        self._oor_occ = 0
        self._rows_loaded = 0
        self._rows_evicted = 0
        self._rows_written_back = 0
        self._seen_rows = 0  # distinct logical ids ever resident
        # Mirrors never touch rows, so their counters must not inflate
        # this rank's tiered.* telemetry — the per-rank numbers are the
        # ~1/R claim the fleet bench asserts.
        if not self.rows_enabled:
            telemetry = None
        tel = telemetry if telemetry is not None else obs.NULL
        self._c_hit = tel.counter("tiered.hit_occurrences")
        self._c_miss = tel.counter("tiered.miss_occurrences")
        self._c_load = tel.counter("tiered.rows_loaded")
        self._c_evict = tel.counter("tiered.rows_evicted")
        self._c_wb = tel.counter("tiered.writeback_rows")
        self.stores = self._build_stores(dense_tables, overlay)

    # ------------------------------------------------------------------
    # construction / restore
    # ------------------------------------------------------------------

    def _build_stores(self, dense_tables, overlay) -> tuple:
        cfg = self.cfg
        if not self.rows_enabled:
            return ()
        codec = quant.cold_codec(cfg)
        # Exact-vs-virtual is decided on the GLOBAL table bytes, never
        # the shard slice: all shard counts of the same config must pick
        # the same mode, or an elastic resume would try to restore one
        # format into the other.
        exact = cfg.vocabulary_size * self.dim * 4 <= EXACT_BYTES_MAX
        if dense_tables is not None:
            # Warm start from a dense checkpoint (always small V).  The
            # caller hands arrays already sliced to this shard's id
            # range.  Any missing optimizer store initializes from the
            # RESTORED params — same semantics as the dense path's
            # opt_init on restored params (elementwise, so it works in
            # local coordinates).
            stores = {
                name: ColdStore.from_dense(
                    arr, {"kind": "restored"}, codec
                )
                for name, arr in dense_tables.items()
            }
            missing = [n for n in self.names if n not in stores]
            if missing:
                fresh = _exact_stores(
                    cfg, self.names, dense_tables["table"]
                )
                for n in missing:
                    stores[n] = fresh[n]
            return tuple(stores[n] for n in self.names)
        if exact:
            row_range = (
                None if self.shard.count == 1
                else (self.id_offset, self.id_offset + self.vocab)
            )
            built = _exact_stores(cfg, self.names, None, row_range)
        else:
            built = {
                n: _virtual_store(cfg, n, vocab=self.vocab,
                                  id_offset=self.id_offset)
                for n in self.names
            }
        if overlay is not None:
            for name in self.names:
                payload = overlay[name]
                want = built[name].descriptor
                got = payload.get("descriptor")
                # kind="dense" overlays carry EVERY row's value (a
                # rank-sharded save of a dense-backed store), so they
                # are init-independent and restore onto any store of
                # matching storage format.
                if got is not None and got.get("kind") == "dense":
                    fmt = {k: v for k, v in got.items() if k != "kind"}
                    want_fmt = codec.descriptor()
                    if fmt != want_fmt:
                        raise ValueError(
                            f"tiered checkpoint store {name!r} was packed "
                            f"as {fmt} but this run's cold_dtype expects "
                            f"{want_fmt}"
                        )
                elif got is not None and got != want:
                    raise ValueError(
                        f"tiered checkpoint store {name!r} was written "
                        f"under a different init ({got} != {want}); "
                        "seed/init_value_range/optimizer hyperparams must "
                        "match the run that saved it"
                    )
                built[name].import_overlay(payload)
        return tuple(built[n] for n in self.names)

    @property
    def dense_save_ok(self) -> bool:
        """Whether the merged logical table fits the ordinary dense
        checkpoint format (tier-layout-independent AND dense-run-
        interchangeable)."""
        return all(
            s.dense_backed or s.vocab * s.dim * 4 <= EXACT_BYTES_MAX
            for s in self.stores
        )

    # ------------------------------------------------------------------
    # transfer-thread side: remap + migration planning
    # ------------------------------------------------------------------

    def plan(self, ids: np.ndarray) -> tuple[np.ndarray, Plan]:
        """Remap a super-batch's logical ids to hot-slot indices,
        allocating slots for misses (LRU eviction when the never-used
        pool is exhausted).  Returns (remapped ids, migration plan).

        Runs in the transfer thread; the host work here (np.unique +
        cold gathers) overlaps the previous super-batch's dispatch.
        """
        H, V = self.hot_rows, self.vocab
        flat = ids.reshape(-1)
        oor = (flat < 0) | (flat >= V)
        any_oor = bool(oor.any())
        src = flat[~oor] if any_oor else flat
        u = np.unique(src)
        with self._cv:
            self._flush_entries()
            self._tick += 1
            t = self._tick
            self._plan_seq += 1
            pid = self._plan_seq
            slots_u = self.slot_of[u]
            miss = slots_u < 0
            miss_ids = u[miss].astype(np.int64)
            n_miss = int(miss_ids.size)
            # One fetch serves every occurrence of a missed id in this
            # super-batch, so a miss is counted ONCE per unique id per
            # super-batch; the remaining occurrences are hits.
            self._hit_occ += int(src.size) - n_miss
            self._miss_occ += n_miss
            self._oor_occ += int(flat.size - src.size)
            self._c_hit.add(int(src.size) - n_miss)
            self._c_miss.add(n_miss)
            evict_slots = np.empty((0,), np.int32)
            rows: tuple = ()
            if n_miss:
                if n_miss > H:
                    raise RuntimeError(
                        f"hot_rows={H} is smaller than one super-batch's "
                        f"unique id count ({n_miss}); raise hot_rows or "
                        "shrink steps_per_dispatch*batch_size*max_features"
                    )
                res_slots = slots_u[~miss]
                self.last_used[res_slots] = t
                n_fresh = min(n_miss, H - self._free_ptr)
                new_slots = np.empty(n_miss, np.int32)
                if n_fresh:
                    new_slots[:n_fresh] = np.arange(
                        self._free_ptr, self._free_ptr + n_fresh,
                        dtype=np.int32,
                    )
                    self._free_ptr += n_fresh
                    # Stamp fresh slots NOW: eviction selection below
                    # scans last_used, and a just-allocated slot (still
                    # at its never-used 0) must not be "least recently
                    # used" in the very plan that allocated it.
                    self.last_used[new_slots[:n_fresh]] = t
                n_evict = n_miss - n_fresh
                if n_evict:
                    cand = np.argpartition(
                        self.last_used, n_evict - 1
                    )[:n_evict].astype(np.int32)
                    if (
                        int(self.last_used[cand].max()) >= t
                        or int(self.id_of_slot[cand].min()) < 0
                    ):
                        raise RuntimeError(
                            f"hot_rows={H} cannot hold this super-batch's "
                            "working set: every eviction candidate is in "
                            "use by the current super-batch"
                        )
                    evict_ids = self.id_of_slot[cand].copy()
                    self.slot_of[evict_ids] = _EVICTED
                    if self.rows_enabled:
                        # Mirrors mark _EVICTED (slot-map bookkeeping)
                        # but keep no write-back ledger: the owner rank
                        # captures the values.
                        entry = {
                            "ids": evict_ids, "dev": None, "host": None,
                            "skip": set(),
                        }
                        self._entries[pid] = entry
                        self._entry_q.append(pid)
                        for j, i in enumerate(evict_ids):
                            self._pending[int(i)] = (entry, j)
                    new_slots[n_fresh:] = cand
                    evict_slots = cand
                    self._rows_evicted += n_evict
                    self._c_evict.add(n_evict)
                self._seen_rows += int(
                    np.count_nonzero(self.slot_of[miss_ids] == _NEVER)
                )
                self.slot_of[miss_ids] = new_slots
                self.id_of_slot[new_slots] = miss_ids
                self.last_used[new_slots] = t
                if self.rows_enabled:
                    rows = self._fetch(miss_ids)
                self._rows_loaded += n_miss
                self._c_load.add(n_miss)
            else:
                self.last_used[slots_u] = t
            # Remap: every present id is now resident; OOR occurrences
            # map to H so the device scatter drops their updates — the
            # same "silently dropped" contract the dense path has for
            # ids >= vocabulary_size.
            if any_oor:
                safe = np.where(oor, 0, flat)
                new_flat = np.where(oor, np.int32(H), self.slot_of[safe])
            else:
                new_flat = self.slot_of[flat]
            new_ids = new_flat.astype(np.int32).reshape(ids.shape)
            # Bucket-pad the migration arrays (bounded jit retraces).
            mp = _bucket(max(1, n_miss))
            load_slots = np.full(mp, H, np.int32)
            pad_rows = []
            if n_miss:
                load_slots[:n_miss] = self.slot_of[miss_ids]
                for r in rows:
                    pr = np.zeros((mp, r.shape[1]), np.float32)
                    pr[:n_miss] = r
                    pad_rows.append(pr)
            elif self.rows_enabled:
                pad_rows = [
                    np.zeros((mp, self.dim), np.float32) for _ in self.names
                ]
            ep = _bucket(max(1, len(evict_slots)))
            evict_pad = np.zeros(ep, np.int32)
            evict_pad[:len(evict_slots)] = evict_slots
            return new_ids, Plan(
                plan_id=pid,
                load_slots=load_slots,
                load_ids=miss_ids,
                load_rows=tuple(pad_rows),
                evict_slots=evict_pad,
                n_load=n_miss,
                n_evict=int(len(evict_slots)),
            )

    def _fetch(self, miss_ids: np.ndarray) -> tuple:
        """Cold-store rows for miss_ids, serving ids with an in-flight
        write-back from the pending ledger (waiting for the fill when the
        D2H has not landed yet).  Called under the lock."""
        n = len(miss_ids)
        pend_mask = None
        if self._pending:
            pids = np.fromiter(self._pending.keys(), np.int64,
                               len(self._pending))
            pend_mask = np.isin(miss_ids, pids)
            if not pend_mask.any():
                pend_mask = None
        if pend_mask is None:
            return tuple(s.gather(miss_ids) for s in self.stores)
        cold_ids = miss_ids[~pend_mask]
        outs = [
            np.empty((n, s.dim), np.float32) for s in self.stores
        ]
        if len(cold_ids):
            for out, s in zip(outs, self.stores):
                out[~pend_mask] = s.gather(cold_ids)
        for k in np.nonzero(pend_mask)[0]:
            i = int(miss_ids[k])
            pe = self._pending.pop(i, None)
            if pe is None:
                # A sync/flush from the dispatch loop absorbed this
                # entry into the cold store while we waited on another
                # fill (mid-run checkpoint); the cold value IS the
                # written-back one now.
                row_id = miss_ids[k:k + 1]
                for out, s in zip(outs, self.stores):
                    out[k] = s.gather(row_id)[0]
                continue
            entry, j = pe
            host = self._entry_host(entry)
            for out, hr in zip(outs, host):
                out[k] = hr[j]
            entry["skip"].add(j)
        return tuple(outs)

    def cancel_waits(self) -> None:
        """Release any transfer-thread wait on a write-back fill — the
        dispatch loop is exiting (exception, halt, interrupt) and the
        fill will never come.  The woken wait raises, which surfaces in
        the prefetcher's error channel and lets shutdown join cleanly.
        ``reopen()`` re-arms the manager for a later train() run."""
        with self._cv:
            self._cancelled = True
            self._cv.notify_all()

    def reopen(self) -> None:
        with self._cv:
            self._cancelled = False

    def _entry_host(self, entry) -> list:
        """Host copies of an entry's gathered rows, waiting for the
        dispatch loop's fill if needed.  Called under the lock; the wait
        releases it (Condition), so push_writeback can land."""
        while entry["dev"] is None and not self._cancelled:
            self._cv.wait()
        if entry["dev"] is None:
            raise RuntimeError(
                "tiered write-back wait cancelled: the dispatch loop "
                "exited before filling this plan's eviction rows"
            )
        if entry["host"] is None:
            n = len(entry["ids"])
            entry["host"] = [
                np.asarray(a)[:n] for a in entry["dev"]
            ]
            entry["dev"] = ()  # drop the device references
        return entry["host"]

    def _flush_entries(self, force: bool = False) -> None:
        """Absorb settled write-back entries into the cold stores.  The
        newest FLUSH_KEEP entries stay buffered unless forced (their D2H
        may still be in flight); unfilled entries (plans not yet applied)
        are always left alone — the applied-view sweep covers them."""
        keep = 0 if force else self.FLUSH_KEEP
        while len(self._entry_q) > keep:
            pid = self._entry_q[0]
            entry = self._entries[pid]
            if entry["dev"] is None and entry["host"] is None:
                break  # not yet applied by the dispatch loop
            self._entry_q.popleft()
            del self._entries[pid]
            host = self._entry_host(entry)
            ids = entry["ids"]
            live = np.array(
                [j for j in range(len(ids)) if j not in entry["skip"]],
                np.int64,
            )
            for i in ids[live]:
                pe = self._pending.get(int(i))
                if pe is not None and pe[0] is entry:
                    del self._pending[int(i)]
            if len(live):
                self._rows_written_back += len(live)
                self._c_wb.add(len(live))
                for s, hr in zip(self.stores, host):
                    s.scatter(ids[live], hr[live])

    # ------------------------------------------------------------------
    # dispatch-loop side
    # ------------------------------------------------------------------

    def push_writeback(self, plan_id: int, dev_rows: tuple) -> None:
        """Hand over the device arrays gathered at a plan's evict slots
        (called right after the gather is enqueued; non-blocking)."""
        with self._cv:
            entry = self._entries.get(plan_id)
            if entry is not None:
                entry["dev"] = dev_rows
                self._cv.notify_all()

    def note_applied(self, shipment: Shipment) -> None:
        """Advance the applied view once a plan's loads hit the device."""
        if shipment.n_load == 0:
            return
        with self._cv:
            self.id_of_slot_applied[
                shipment.load_slots_h[:shipment.n_load]
            ] = shipment.load_ids

    def sync_from_device(self, host_tables: list) -> None:
        """Write every device-resident row back into the cold stores
        (checkpoint/eval path).  ``host_tables`` are np copies of the
        CURRENT device hot tables, ordered like ``self.names``.  Uses
        the applied view, so plans still in flight (whose evicted rows
        are still on device) are swept correctly."""
        if not self.rows_enabled:
            raise RuntimeError(
                "sync_from_device on a mirror tier shard: only the owning "
                "rank holds this shard's cold stores"
            )
        with self._cv:
            self._flush_entries(force=True)
            slots = np.nonzero(self.id_of_slot_applied >= 0)[0]
            if len(slots):
                ids = self.id_of_slot_applied[slots]
                for s, t in zip(self.stores, host_tables):
                    s.scatter(ids, t[slots])

    def gather_logical(self, ids: np.ndarray) -> np.ndarray:
        """Current PARAMS rows for logical ids, from the cold store
        (callers sync the hot rows back first — the evaluate path).
        Locked against concurrent write-back flushes."""
        if not self.rows_enabled:
            raise RuntimeError(
                "gather_logical on a mirror tier shard: only the owning "
                "rank holds this shard's cold stores"
            )
        with self._cv:
            return self.stores[0].gather(ids)

    def merged_dense(self, host_tables: list) -> list:
        """Full logical arrays (params table first), cold+hot merged.

        Returns COPIES taken under the lock: the live cold backing keeps
        absorbing write-backs from the transfer thread, and a mid-run
        checkpoint serializing the shared array could capture torn rows.
        """
        self.sync_from_device(host_tables)
        with self._cv:
            return [s.to_dense().copy() for s in self.stores]

    def export_overlay(self, host_tables: list) -> dict:
        """Sparse overlay checkpoint payload.  Virtual stores export
        their written-row overlay under the init descriptor; dense-backed
        stores export EVERY row under ``kind="dense"`` (init-independent
        — the rank-sharded save path, where no rank can write the merged
        dense checkpoint)."""
        self.sync_from_device(host_tables)
        with self._cv:
            out = {}
            for name, s in zip(self.names, self.stores):
                payload = s.export()
                if s.dense_backed:
                    payload["descriptor"] = {
                        "kind": "dense", **self.codec.descriptor()
                    }
                else:
                    payload["descriptor"] = s.descriptor
                out[name] = payload
            return out

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """Host-only counters for heartbeats/results (no device access)."""
        with self._cv:
            total = self._hit_occ + self._miss_occ
            return {
                "hot_rows": self.hot_rows,
                "vocab": self.vocab,
                "resident_rows": int(self._free_ptr),
                "rows_seen": int(self._seen_rows),
                "hit_occurrences": int(self._hit_occ),
                "miss_occurrences": int(self._miss_occ),
                "hot_hit_frac": (
                    round(self._hit_occ / total, 6) if total else 0.0
                ),
                "rows_loaded": int(self._rows_loaded),
                "rows_evicted": int(self._rows_evicted),
                "writeback_rows": int(self._rows_written_back),
                "oor_occurrences": int(self._oor_occ),
                "cold_store_bytes": int(
                    sum(s.nbytes for s in self.stores)
                ),
                "cold_written_rows": int(
                    0 if not self.stores or self.stores[0].dense_backed
                    else self.stores[0].written_rows
                ),
                # Storage-format identity of the cold rows: the dtype
                # string is for report readers (non-numeric values are
                # skipped by /metrics), the bytes-per-row gauge is the
                # compaction factor the bench's quantized_table section
                # compares across dtypes (fp32 = 4 * D).
                "cold_dtype": self.codec.dtype,
                "cold_bytes_per_row": int(self.codec.bytes_per_row),
            }

    def health_view(self) -> dict:
        """Logical-row occupancy for the health record: with tiering on,
        the scan-carry row-touch mask counts HOT SLOTS; the manager sees
        every logical id host-side and reports the logical numbers."""
        with self._cv:
            return {
                "emb_rows_touched": int(self._seen_rows),
                "emb_row_occupancy": round(self._seen_rows / self.vocab, 9),
                "hot_slots_resident": int(self._free_ptr),
            }
