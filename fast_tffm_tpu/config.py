"""Typed configuration, loadable from the reference's INI ``.cfg`` surface.

The reference drives everything from an INI file with ``[General]``,
``[Train]`` and ``[Predict]`` sections (SURVEY.md §2 #12, §5 "Config").  We
accept the same sections and keys, backed by a dataclass, plus TPU-specific
keys in an optional ``[Tpu]`` section.  Unknown keys warn instead of failing
so old configs keep working.
"""

from __future__ import annotations

import configparser
import dataclasses
import glob as _glob
import logging
from typing import Optional

log = logging.getLogger(__name__)

_TRUE = {"1", "true", "yes", "on"}
_FALSE = {"0", "false", "no", "off"}


def _parse_bool(s: str) -> bool:
    v = s.strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    raise ValueError(f"not a boolean: {s!r}")


def _parse_files(s: str) -> list[str]:
    """Comma/semicolon-separated list of file patterns, glob-expanded."""
    out: list[str] = []
    for part in s.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        hits = sorted(_glob.glob(part))
        out.extend(hits if hits else [part])
    return out


@dataclasses.dataclass
class FmConfig:
    # --- [General] (reference keys, SURVEY.md §2 #12) ---
    vocabulary_size: int = 2**20
    # Kept for config compatibility: the reference used it to split the table
    # into N variables for parameter servers.  Here sharding is mesh-driven;
    # the value is accepted and ignored (mesh_model plays its role).
    vocabulary_block_num: int = 1
    hash_feature_id: bool = False
    factor_num: int = 8
    model_file: str = "./fm_model"
    log_file: str = ""
    # Field-aware FM extension: number of fields (0 = plain FM).
    field_num: int = 0

    # --- [Train] ---
    train_files: list[str] = dataclasses.field(default_factory=list)
    weight_files: list[str] = dataclasses.field(default_factory=list)
    validation_files: list[str] = dataclasses.field(default_factory=list)
    epoch_num: int = 1
    batch_size: int = 1024
    learning_rate: float = 0.01
    adagrad_initial_accumulator: float = 0.1
    optimizer: str = "adagrad"  # adagrad | ftrl | sgd | adam
    loss_type: str = "logistic"  # logistic | mse
    factor_lambda: float = 0.0
    bias_lambda: float = 0.0
    # FTRL extras
    ftrl_l1: float = 0.0
    ftrl_l2: float = 0.0
    ftrl_beta: float = 1.0
    init_value_range: float = 0.01
    # Input-pipeline knobs (reference queue knobs, SURVEY.md §2 #6).
    thread_num: int = 4
    queue_size: int = 64
    # Parse in this many spawned worker PROCESSES instead of thread_num
    # in-process threads (0 = threads).  Escapes the GIL entirely —
    # required for the pure-Python parse fallback to scale at all, and
    # frees the trainer process's interpreter on the native path too.
    # Parsed batches return over POSIX shared memory (data.procpool).
    parse_processes: int = 0
    # Multi-epoch parsed-batch cache (the tf.data .cache() pattern):
    # epoch 0 parses, epochs 1..E-1 replay the cached batches in a
    # seeded per-epoch permutation — no re-read/re-parse.  Cross-epoch
    # remixing drops to batch granularity (the documented tradeoff).
    # cache_max_bytes bounds host memory; overflowing it falls back to
    # re-parsing later epochs (cache_result = "overflow").
    cache_epochs: bool = False
    cache_max_bytes: int = 1 << 30
    # Store the epoch cache as PRE-STACKED [K, ...] super-batches
    # (K = steps_per_dispatch), stacked once at epoch-0 group boundaries:
    # replay epochs hand whole super-batches to the transfer stage, which
    # skips its per-dispatch np.stack entirely.  Cross-epoch remixing
    # drops to SUPER-batch granularity (the next step of the cache_epochs
    # tradeoff); only engages when cache_epochs is on.
    cache_prestacked: bool = False
    # Inbound shared-memory ring for parse_processes: raw windows are
    # written into one of this many fixed SHM slots and workers parse in
    # place — only slot descriptors cross the worker queue (0 = ship
    # window bytes over the queue like before).  Slot capacity is sized
    # from the shuffle window; an oversized window falls back to the
    # queue path (counted as ingest.ring_fallback_windows).
    ring_slots: int = 4
    # Kept for config compatibility: the reference ran N shuffle-queue
    # threads between its reader and parser queues.  Here shuffling is a
    # window permutation inside the (single, sequential-IO) reader thread
    # — it costs one rng permutation per window, so there is nothing to
    # parallelize; parsing parallelism is thread_num.  Accepted and
    # ignored, like vocabulary_block_num.
    shuffle_threads: int = 1
    shuffle_buffer: int = 10000
    save_steps: int = 0  # 0 = only at end of training
    log_steps: int = 100
    # Run validation every N steps during training (0 = only at the end)
    # — the reference printed periodic step/loss/validation-loss
    # (SURVEY.md §5 metrics row).
    validation_steps: int = 0
    seed: int = 0

    # --- [Predict] ---
    predict_files: list[str] = dataclasses.field(default_factory=list)
    score_path: str = "./scores.txt"
    # Online serving (run_tffm.py serve; fast_tffm_tpu/serve): an HTTP
    # scoring endpoint (POST /score, libsvm lines in, one score per
    # line out) over a compiled fixed-shape scorer.  0 with the serve
    # mode = an OS-assigned port (logged, and printed as
    # "serving on host:port").
    serve_port: int = 0
    # Bind address for the scoring endpoint.  Loopback by default for
    # the same reason as status_host: the endpoint is unauthenticated.
    serve_host: str = "127.0.0.1"
    # The fixed microbatch shape ladder: requests pad/coalesce into the
    # smallest of these example counts that holds them, and every rung
    # is AOT-precompiled at startup — steady-state serving never
    # compiles.  Comma-separated, ascending after parse.
    serve_batch_sizes: str = "64,256,1024"
    # Request-coalescing deadline: a microbatch dispatches when the
    # largest rung fills OR this many ms pass since its first request —
    # the latency/throughput dial.  0 = dispatch immediately (lowest
    # latency, worst fill).
    max_batch_wait_ms: float = 2.0
    # Warm checkpoint hot-swap: poll the trainer-published
    # serve_manifest.json every this-many seconds and swap new params
    # in between dispatches (zero recompiles, no dropped requests).
    # 0 = serve the startup checkpoint forever.
    serve_poll_secs: float = 2.0
    # Scale-out serving (serve/router.py): run this many shared-nothing
    # replica serve processes (each the full scorer/batcher/server
    # stack on its own port) behind a power-of-two-choices router on
    # serve_port.  0 or 1 = the classic single-process server, no
    # router.  See SERVING.md "Scale-out".
    serve_replicas: int = 0
    # Router admission control: a request is shed with a fast 429 (+
    # Retry-After) when the fleet's projected queue delay — in-flight
    # requests over the measured completion rate — exceeds this budget,
    # so admitted-request p99 stays bounded instead of collapsing under
    # a traffic spike.  0 = admit everything (latency grows unboundedly
    # under overload).
    serve_shed_deadline_ms: float = 50.0
    # Rolling manifest promotion: instead of every replica self-swapping
    # on the manifest poll, the ROUTER canaries one replica on the new
    # checkpoint, shadow-scores a recent traffic sample against a
    # baseline replica, compares the score distributions via
    # `tools/report.py --compare`, and only then promotes the fleet
    # (or rolls the canary back).  Requires serve_replicas >= 2.
    serve_canary: bool = False
    # Which request transports the scoring endpoints accept: "text"
    # (POST /score, libsvm lines), "bin" (POST /score_bin,
    # length-prefixed little-endian id/value/field arrays — skips text
    # parsing on the hot path entirely), or "both" (default).
    serve_transport: str = "both"
    # Per-request distributed tracing sample rate for the serving path
    # (0 = off, 1 = every request).  A sampled request gets a request
    # id (client-supplied X-Request-Id or minted), the id propagates
    # router -> replica (HTTP header for /score, the flags-gated frame
    # trailer for /score_bin) and is echoed in the response header,
    # and a connected span chain (admit -> proxy -> queue -> coalesce
    # -> dispatch -> respond) lands in the trace files.  Requires
    # trace_file (the spans need somewhere to go); the unsampled path
    # is byte-identical to sampling off.  See OBSERVABILITY.md.
    serve_trace_sample: float = 0.0
    # Serving SLO: the latency objective in ms.  A completed request
    # slower than this counts against the error budget (alongside
    # sheds and 5xx responses).  0 = latency does not enter the SLO.
    serve_slo_p99_ms: float = 0.0
    # Serving SLO: the availability objective (e.g. 0.999).  Defines
    # the error budget 1 - availability; the serving path computes the
    # rolling burn rate bad_frac / budget over a sliding window and
    # exposes it as the `serve.burn_rate` gauge + serve-block key (an
    # alert signal: "burn_rate > 10 : warn").  0 = no burn-rate
    # accounting (slo_bad_frac still reports when serve_slo_p99_ms is
    # set).  See OBSERVABILITY.md "Serving SLO & burn rate".
    serve_slo_availability: float = 0.0
    # Text-parse engine for POST /score: "vec" (default) runs the
    # batch parser (serve/textparse.py — one regex validation pass +
    # strided/vectorized conversion over the whole body, with
    # automatic per-line fallback on out-of-grammar input), "legacy"
    # forces the per-line libsvm.parse_line loop.  Both are pinned
    # bitwise-identical (arrays AND error text) by test; the knob
    # exists for bisection and as the fallback's direct spelling.
    serve_parse_mode: str = "vec"
    # HTTP front-end worker pool for the scoring endpoints (server AND
    # router): this many persistent handler threads serve accepted
    # connections from a bounded hand-off queue instead of spawning a
    # thread per connection.  Size it >= the expected concurrent
    # kept-alive connections (a kept-alive peer holds a worker until
    # it closes or the 60 s socket timeout fires).  0 = the r14
    # thread-per-connection mode, byte-identical serving behavior.
    serve_http_threads: int = 8
    # Accept-loop count for the pooled front end: N > 1 adds N-1 extra
    # accept loops, each on its own SO_REUSEPORT listener when the
    # kernel supports it (feature-probed; portable fallback shares the
    # primary socket).  Only meaningful with serve_http_threads > 0.
    serve_http_acceptors: int = 1

    # --- observability (SURVEY.md §5: tracing/metrics rebuild) ---
    # Directory for a jax.profiler trace of steps
    # [profile_start_step, profile_start_step + profile_steps). Empty = off.
    profile_dir: str = ""
    profile_start_step: int = 10
    profile_steps: int = 5
    # JSONL stream of per-interval training metrics (step, examples,
    # loss, auc, examples_per_sec, elapsed). Empty = off.  Every record
    # carries a "record" type ("run_header" | "train" | "validation" |
    # "heartbeat" | "final") so one file is self-describing.
    metrics_file: str = ""
    # Run-wide telemetry (obs.Telemetry): per-stage counters/gauges/
    # timing histograms across reader, parse workers, the transfer
    # thread, and the dispatch loop.  Near-zero hot-path overhead (one
    # perf_counter + one uncontended lock per BATCH event); disabling it
    # swaps in no-op instruments — zero behavior change either way.
    telemetry: bool = True
    # Heartbeat cadence in seconds: a background thread periodically
    # writes one structured JSONL record (into metrics_file when set)
    # with the telemetry snapshot + ingest_wait_frac, and logs a
    # one-line summary — any run self-reports its bottleneck.  0 = off.
    heartbeat_secs: float = 0.0
    # Causal batch tracing: write a Chrome-trace-format (Perfetto-
    # loadable) span file here — per-window read, SHM ring slot
    # acquire/release, per-batch parse (thread AND process workers),
    # prefetcher stack / staging-wait / H2D, and train-loop wait/
    # dispatch, all correlated by batch/super-batch id so one super-
    # batch's life is a connected chain from file read to fused-scan
    # dispatch.  Empty = off (no-op tracer; bit-identical training).
    # Multi-host ranks > 0 suffix the path with .rankN; merge with
    # `python tools/report.py --trace <files>`.
    trace_file: str = ""
    # What to do when a dispatch produces a non-finite (NaN/inf)
    # gradient (detected on-device by the scan-carry health monitors,
    # checked one dispatch delayed so detection costs no pipeline
    # bubble): "warn" logs once and keeps counting (the final record
    # carries the totals); "halt" raises NonFiniteGradError without
    # overwriting the checkpoint with poisoned params.
    nan_policy: str = "warn"
    # Live status endpoint (obs.StatusServer): serve /metrics
    # (Prometheus text exposition of every telemetry snapshot + the
    # health/tiered blocks) and /status (the heartbeat JSON record, on
    # demand) from an in-process stdlib HTTP server on this port.
    # 0 = off (no server exists; training is bit-identical).  The
    # endpoint is read-only and never touches the hot path — requests
    # read the same thread-safe snapshots a heartbeat does.
    status_port: int = 0
    # Bind address for the status endpoint.  Loopback by default: the
    # endpoint is unauthenticated, so serving other hosts (a real
    # Prometheus scrape) is an explicit opt-in ("0.0.0.0").
    status_host: str = "127.0.0.1"
    # Declarative alert watchdog riding the heartbeat thread (needs
    # heartbeat_secs > 0): ';'-separated rules of the form
    # "signal > threshold [for N] : warn|halt" evaluated against every
    # heartbeat record (signals: any record path like ingest_wait_frac
    # / health.grad_norm / tiered.hot_hit_frac, plus derived
    # grad_norm_drift, beat_gap_s, prefetch_out_empty_frac — see
    # OBSERVABILITY.md).  Breaches emit `record: alert` JSONL entries;
    # action halt raises AlertHaltError at the next dispatch boundary
    # without overwriting the checkpoint.  "" = off.
    alert_rules: str = ""
    # Resource & compile observability (obs/resource.py): a `resource`
    # block in every heartbeat/status/final record — process RSS +
    # peak-RSS, the component host-memory ledger (SHM ring, staging
    # pool, epoch cache, tiered cold store, trace buffer byte gauges),
    # device memory (backend memory_stats where supported, a
    # shape-derived table+optimizer estimate elsewhere), and the
    # compile sentinel: the train-step compile path runs through an
    # AOT (.lower().compile()) cache that counts compilations, records
    # wall time + XLA cost analysis per compile (`record: compile`
    # JSONL entries), and flags any recompile beyond the documented
    # epoch-tail K'=leftover as `recompiles_unexpected` (warn by
    # default; alert signal of the same name).  Off = no sentinel, no
    # resource block, the historical jit dispatch path — bit-identical
    # training, same contract as every other obs knob.
    resource_metrics: bool = True
    # Model-quality & data-drift observability (obs/quality.py): the
    # plane that watches the MODEL where telemetry/resource watch the
    # system.  On (default): parse workers maintain fixed-memory
    # distribution sketches over feature values / example lengths /
    # id occupancy (obs/sketch.py; process workers ship deltas back
    # like parse timings), the trainer computes windowed online eval
    # (rolling logloss / AUC / calibration ratio from its own
    # scores+labels, consumed one-dispatch-delayed like the health
    # monitors) and adjacent-window PSI drift signals — all riding
    # heartbeat/final/train-results as a `quality` block resolvable by
    # alert_rules (e.g. "quality.psi_values > 0.2 for 3 : warn") —
    # and every save publishes the cumulative sketches into
    # serve_manifest.json so the serving fleet can detect
    # training->serving skew (the serve block's `skew_*` keys /
    # tffm_serve_skew_* series).  Off: no sketches, no scores readback,
    # no quality block, no manifest payload — bitwise-identical
    # training and byte-identical serving (pinned by test, same
    # contract as telemetry/trace/resource).
    quality: bool = True
    # Examples per quality window: the rotation cadence of the drift
    # sketches (PSI compares adjacent windows) AND the size of the
    # online-eval ring (windowed logloss/AUC describe the most recent
    # this-many examples).  Smaller = faster drift detection, noisier
    # statistics.
    quality_window: int = 65536
    # Live training-fleet aggregation plane (obs/fleet.py): comma-
    # separated host:port status endpoints, one per rank in rank order
    # (each rank's own --status_port surface).  When set, rank 0
    # scrapes every target's /status on the heartbeat cadence, merges
    # the per-rank records into a `fleet` block on its heartbeat/
    # status/final records (summed examples, weighted wait fractions,
    # MAX-merged tails, scrape staleness) with live straggler
    # attribution (straggler_ratio, slowest_rank + share,
    # rank_step_skew, exchange_frac — all alertable), appends per-rank
    # tffm_train_rank_* labeled series to its /metrics, and the
    # multi-device dispatch loop times the cross-rank collective
    # barrier (train.exchange, one-dispatch-delayed — no pipeline
    # bubble).  Requires heartbeat_secs > 0 (the scrape cadence).
    # "" = off: no scrape thread, no probe, bitwise-identical
    # training — same contract as every other obs knob.
    train_fleet_scrape: str = ""
    # Windowed trace rotation: when the tracer's buffer reaches this
    # many events it dumps and resets, producing trace.0.json,
    # trace.1.json, ... (merge with tools/report.py --trace) — removes
    # the in-memory event cap for multi-hour traced runs.  0 = off
    # (single trace_file, 1M-event cap).  Requires trace_file.
    trace_rotate_events: int = 0

    # --- [Tpu] (new; not in reference) ---
    # Max features per example; batches are padded to this static shape.
    max_features: int = 64
    # Mesh axes: data-parallel x model-parallel (table row-sharding).
    mesh_data: int = 1
    mesh_model: int = 1
    # Sharded-lookup strategy: "auto" (GSPMD decides from shardings) or
    # "shardmap" (explicit mod-sharded lookup + psum, SURVEY.md §7 step 4).
    lookup: str = "auto"
    # Compute dtype for the interaction term ("float32" | "bfloat16").
    compute_dtype: str = "float32"
    # Use the Pallas kernel for the scorer when on TPU.
    use_pallas: bool = True
    # Interaction implementation: '' derives from use_pallas (True ->
    # 'pallas', False -> 'jnp'); 'flat' selects the pure-XLA flat-layout
    # one-hot-matmul variant (same math as the Pallas kernels, fused by
    # XLA instead).  Applies to plain FM; field-aware FM (field_num > 0)
    # always uses its closed-form op (ops.interaction.ffm_interaction;
    # FAST_TFFM_FFM_AUTODIFF=1 forces the autodiff einsum oracle).
    interaction: str = ""
    # Kernel autotuner surface (ops/autotune.py): "auto" benchmarks the
    # candidate interaction implementations at the run's actual shapes,
    # parity-gates them against reference, and promotes the fastest
    # (persisted per backend/shape in autotune_cache.json so later runs
    # and the serve fleet skip measurement); "reference" | "pallas" |
    # "packed" pin an impl with zero measurement ("packed" is the flat
    # one-hot-matmul layout, see EMBEDDING.md).  "" keeps the legacy
    # interaction/use_pallas derivation, bit-identical to before the
    # autotuner existed.  Routes training (the fused scan step) AND the
    # compiled serving rungs; FFM (field_num > 0) always uses its
    # closed-form op regardless.
    interaction_impl: str = ""
    # Persistent XLA compilation cache directory (jax's
    # jax_compilation_cache_dir): restarts and replica spawns reuse
    # compiled executables from disk instead of paying warmup compiles
    # again.  "" = off.  platform.enable_compile_cache() is the one
    # wiring point; platform.compile_cache_stats() counts hits/misses.
    compile_cache_dir: str = ""
    # Sparse row updates (IndexedSlices-style): optimizer touches only the
    # rows in the batch. Falls back to dense when the optimizer/l2_mode
    # combination requires it (see train.sparse.supports_sparse).
    sparse_update: bool = True
    # How sparse updates hit the table: "scatter" uses XLA row scatter
    # (general but slow on TPU), "tile" the Pallas sort+tile-scan kernels
    # (ops.sparse_apply), "auto" picks tile when supported.
    sparse_apply: str = "auto"
    # Fast ingest: read files as raw binary chunks, C++ line scan + parse,
    # no Python string per line. Shuffling permutes lines within
    # shuffle_buffer-line windows (same mixing window as the line path's
    # reservoir). Line path is used for weight_files or when the native
    # parser is unavailable.
    fast_ingest: bool = True
    # Host-side sparse-apply prep: pipeline threads sort each batch's ids
    # and precompute the tile-apply metadata in C++ (saves ~11 ms/step of
    # on-device XLA sort at Criteo shapes).  Only engages on the
    # single-process tile path with the native lib available.
    host_sort: bool = True
    # L2 mode: "batch" regularizes only the rows touched by the batch
    # (sparse-friendly); "full" regularizes the whole table (dense grads,
    # only sane for small vocabularies).
    l2_mode: str = "batch"
    # Device-resident multi-step training: one dispatch trains this many
    # batches via jax.lax.scan over a stacked super-batch — no Python or
    # host round-trip between the K steps.  1 = the classic one dispatch
    # per batch.  Logging / validation / save / profiler cadences and the
    # checkpointed mid-epoch position all move to super-batch granularity
    # (a resume always lands on a super-batch boundary).
    steps_per_dispatch: int = 1
    # How many stacked super-batches the transfer stage keeps in flight:
    # super-batch n+1 is stacked and shipped (shard_batch/device_put) on a
    # background thread while n trains.  Bounds host+device memory for
    # staged input at prefetch_super_batches * steps_per_dispatch batches.
    prefetch_super_batches: int = 2
    # Two-tier embedding table (train.tiered): "on" keeps only the
    # hottest rows device-resident (params + optimizer slots for
    # hot_rows rows) over a host-RAM cold store holding the full
    # logical vocabulary_size table, with occupancy-driven LRU
    # migration planned per super-batch in the prefetch stage.  Unlocks
    # V >= 2^28 vocabularies that cannot exist as a dense device table;
    # requires the sparse update path (adagrad/ftrl/sgd, batch L2) and
    # a single process.  "off" = the classic dense device table.
    table_tiering: str = "off"  # off | on
    # Device-resident hot rows when table_tiering=on.  Must hold every
    # unique id of one super-batch (steps_per_dispatch * batch_size *
    # max_features is a safe upper bound); clamped to vocabulary_size.
    hot_rows: int = 1 << 22
    # Storage dtype of the tiered COLD store's rows (table_tiering=on):
    # "fp32" (default; bit-exact, the pre-quantization behavior),
    # "bf16" (half the host bytes per cold row), or "int8" (symmetric
    # codes + one fp32 scale per row — rows migrate hot<->cold
    # individually, so scales are per-row here; see ops/quant.py and
    # EMBEDDING.md).  Cold rows are stored compact, dequantized on
    # hot-load, re-quantized on write-back; the device hot table (and
    # training math) stays float32.  Non-fp32 training is parity-
    # within-tolerance vs fp32, not bitwise (pinned by
    # tests/test_quant.py).
    cold_dtype: str = "fp32"
    # Storage dtype of the device-resident SERVING table (serve mode +
    # offline predict through the ladder): "fp32" | "bf16" | "int8".
    # Quantized tables hold 2-4x more rows per byte of device memory —
    # replica density — with dequant fused into the compiled rungs
    # (served scores stay within a pinned tolerance of fp32; the
    # steady-state zero-compile contract is unchanged).  See
    # SERVING.md.
    serve_table_dtype: str = "fp32"
    # int8 scale granularity for DENSE quantized tables (the serving
    # table and the quant.npz checkpoint): this many consecutive rows
    # share one fp32 scale (0 = one scale per row).  64 amortizes the
    # scale to ~0.06 B/row (the ~4x point at D=9) while bounding an
    # outlier row's precision blast radius to its own chunk.  The
    # tiered cold store always uses per-row scales regardless.
    quant_chunk: int = 64
    # How multi-device sparse updates are exchanged over the data axis
    # (both the shardmap step and the GSPMD sharded tile apply; the
    # reference's IndexedSlices push, SURVEY.md §3.2): "dense" psums
    # a [vocab_local, 2D] delta (O(vocab), simple, best at small vocab /
    # large batch); "entries" all-gathers only the deduped touched-row
    # entry streams (batch-proportional, vocab-independent — the scaling
    # property the reference's PS push had); "auto" picks whichever moves
    # fewer bytes for the static shapes.
    sparse_exchange: str = "auto"
    # Double-buffer the entries exchange's ID PLANE one super-batch
    # step ahead (ops/sparse_apply.entries_prefetch): the deduped
    # touched-row streams for scan step k+1 are computed and
    # all-gathered while step k's local apply runs, so only the
    # payload gather stays on the critical path — compute-overlapped
    # cross-rank merge, bitwise-identical parameters (the id plane is a
    # pure function of the batch ids; pinned by test).  "auto" (default)
    # overlaps whenever the GSPMD sharded entries exchange is actually
    # active (multi-shard data axis, entries mode, fused scan); "on"
    # REQUIRES that path and refuses loudly otherwise (the
    # silently-inert-knob discipline); "off" never overlaps — the
    # diagnostic A/B mode, under which the train.exchange probe blocks
    # synchronously and so measures the UN-overlapped exchange window
    # (see OBSERVABILITY.md).
    sparse_exchange_overlap: str = "auto"  # auto | on | off
    # How tiered-table ownership is partitioned across the mesh
    # (train.tiered_fleet): "global" is the classic single-process
    # host-global hot-slot map; "shards" splits id range + hot slots +
    # cold stores + write-back ledger by MODEL column, each rank
    # planning/migrating/checkpointing ONLY the shards whose columns it
    # owns (~1/R host bytes and migration traffic per rank — the
    # multi-process tiering mode).  "auto" picks shards when
    # process_count > 1, else global.  Sharded tiering requires every
    # model column to live on one process (canonically mesh_data=1,
    # mesh_model=R), identical global batches on every rank, and
    # vocabulary/hot_rows divisible by mesh_model.
    tiered_partition: str = "auto"  # auto | global | shards
    # Incident flight recorder (obs/blackbox.py; OBSERVABILITY.md
    # "Incidents & capture"): every long-running process (trainer rank,
    # serve replica, router) keeps fixed-memory rings of recent
    # heartbeat records / alerts / trace tail, and dumps an
    # incidents/<ts>_<reason>/ forensic bundle on any alert breach,
    # crash-truthful final, or manual POST /incident.  Rings are a few
    # hundred KB and touch no disk until an incident fires, so the
    # recorder is on by default; off = no rings, no bundles, the
    # /incident route answers 503 — bitwise-identical training and
    # byte-identical serving (pinned by test).
    blackbox: bool = True
    # Where incident bundles land; "" derives <model_file>/incidents
    # (training) or the serving checkpoint dir's incidents/ (serve).
    # Setting it with blackbox off is refused (inert-knob discipline).
    incident_dir: str = ""
    # Serve traffic capture (serve/wire.py CaptureWriter): fraction of
    # scored requests whose canonical request+response frames are
    # appended to serve_capture_file in the TFC1 container (SERVING.md
    # "Capture & replay") — replayable bit-for-bit by tools/replay.py
    # against a live endpoint.  0 = off (byte-identical serving).
    serve_capture_sample: float = 0.0
    # TFC1 capture output path; rotates to <path>.1 at 64 MiB.  With
    # --replicas N the router gives each managed replica its own
    # <path>.replicaI.  Requires serve_capture_sample > 0 and vice
    # versa (a capture file nothing samples into, or a sample rate with
    # nowhere to land, is the silently-inert-knob bug).
    serve_capture_file: str = ""

    def __post_init__(self) -> None:
        if self.vocabulary_size <= 0:
            raise ValueError("vocabulary_size must be positive")
        if self.factor_num <= 0:
            raise ValueError("factor_num must be positive")
        if self.optimizer not in ("adagrad", "ftrl", "sgd", "adam"):
            raise ValueError(f"unknown optimizer {self.optimizer!r}")
        if self.loss_type not in ("logistic", "mse"):
            raise ValueError(f"unknown loss_type {self.loss_type!r}")
        if self.lookup not in ("auto", "shardmap"):
            raise ValueError(f"unknown lookup {self.lookup!r}")
        if self.l2_mode not in ("batch", "full"):
            raise ValueError(f"unknown l2_mode {self.l2_mode!r}")
        if self.sparse_apply not in ("auto", "tile", "scatter"):
            raise ValueError(f"unknown sparse_apply {self.sparse_apply!r}")
        if self.sparse_exchange not in ("auto", "dense", "entries"):
            raise ValueError(
                f"unknown sparse_exchange {self.sparse_exchange!r}"
            )
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"unknown compute_dtype {self.compute_dtype!r}")
        if self.interaction not in ("", "pallas", "jnp", "flat"):
            raise ValueError(f"unknown interaction {self.interaction!r}")
        if self.interaction_impl not in (
            "", "auto", "reference", "pallas", "packed"
        ):
            raise ValueError(
                f"unknown interaction_impl {self.interaction_impl!r} "
                "(want auto | reference | pallas | packed, or '' for "
                "the legacy interaction/use_pallas surface)"
            )
        if self.interaction_impl and self.interaction:
            # Inert-knob discipline: interaction_impl supersedes the
            # legacy knob, so a run setting both would silently ignore
            # one of them — refuse at startup instead.
            raise ValueError(
                f"interaction_impl={self.interaction_impl!r} and the "
                f"legacy interaction={self.interaction!r} are both set; "
                "interaction_impl would silently win — drop one"
            )
        if self.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got {self.steps_per_dispatch}"
            )
        if self.prefetch_super_batches < 1:
            raise ValueError(
                "prefetch_super_batches must be >= 1, got "
                f"{self.prefetch_super_batches}"
            )
        if self.parse_processes < 0:
            raise ValueError(
                f"parse_processes must be >= 0, got {self.parse_processes}"
            )
        if self.heartbeat_secs < 0:
            raise ValueError(
                f"heartbeat_secs must be >= 0, got {self.heartbeat_secs}"
            )
        if self.nan_policy not in ("warn", "halt"):
            raise ValueError(f"unknown nan_policy {self.nan_policy!r}")
        if not 0 <= self.status_port < 65536:
            raise ValueError(
                f"status_port must be in [0, 65535], got {self.status_port}"
            )
        if self.quality_window < 32:
            # 32 == obs.quality._MIN_PSI_EXAMPLES (pinned equal by
            # test): below it no window ever reaches judgeable mass,
            # so the PSI drift signals would silently never appear —
            # the inert-knob hazard, failed loudly at startup instead.
            raise ValueError(
                "quality_window must be >= 32 (windows below the "
                "minimum judgeable mass would silently disable the "
                f"PSI drift signals), got {self.quality_window}"
            )
        if self.trace_rotate_events < 0:
            raise ValueError(
                "trace_rotate_events must be >= 0, got "
                f"{self.trace_rotate_events}"
            )
        if self.trace_rotate_events and not self.trace_file:
            raise ValueError(
                "trace_rotate_events requires trace_file (it is a "
                "storage policy of the trace output)"
            )
        if self.train_fleet_scrape:
            # The aggregator scrapes on the heartbeat cadence and its
            # `fleet` block rides the heartbeat-shaped records; with
            # no heartbeat the plane would be configured but silently
            # dead — same inertness rule as alert_rules below.
            if self.heartbeat_secs <= 0:
                raise ValueError(
                    "train_fleet_scrape requires heartbeat_secs > 0 "
                    "(rank 0 scrapes the fleet on the heartbeat "
                    "cadence; without one the plane would never run)"
                )
            for target in self.train_fleet_scrape.split(","):
                target = target.strip()
                if not target:
                    continue
                host, sep, port = target.rpartition(":")
                if not sep or not host or not port.isdigit() \
                        or not 0 < int(port) < 65536:
                    raise ValueError(
                        "train_fleet_scrape targets must be host:port "
                        f"pairs, got {target!r}"
                    )
        if self.alert_rules:
            # Parse at construction so a typo'd rule fails the run at
            # startup, not silently at the first heartbeat.  The obs
            # module is stdlib-only, so this import is cheap and safe
            # here.
            from fast_tffm_tpu.obs.alerts import (
                parse_rules, resolved_signal,
            )

            rules = parse_rules(self.alert_rules)
            # The watchdog rides the heartbeat thread: rules without a
            # heartbeat would NEVER evaluate — for a halt rule that is
            # a safety mechanism silently inert, the one config bug
            # the alert module must never allow.  Fail at startup.
            if self.heartbeat_secs <= 0:
                raise ValueError(
                    "alert_rules requires heartbeat_secs > 0 (the "
                    "watchdog evaluates rules on the heartbeat "
                    "thread; without one no rule would ever fire)"
                )
            # Same inertness hazard one plane over: a rule watching the
            # heartbeat's `resource` block (recompiles_unexpected,
            # rss_mb, ...) is non-evaluable on every beat when the
            # resource plane is off.
            if not self.resource_metrics:
                inert = [
                    r.signal for r in rules
                    if resolved_signal(r.signal).startswith("resource.")
                ]
                if inert:
                    raise ValueError(
                        f"alert_rules watch resource-plane signals "
                        f"{inert} but resource_metrics is off — the "
                        "heartbeat would carry no resource block and "
                        "these rules could never fire; enable "
                        "resource_metrics or drop the rules"
                    )
            # And again for the model-quality plane: a drift rule
            # (quality.psi_values, logloss_drift, calib_ratio) — or a
            # serving skew rule (serve.skew_*), whose keys only exist
            # when the skew monitor does — is non-evaluable on every
            # beat when quality=off.
            if not self.quality:
                inert = [
                    r.signal for r in rules
                    if resolved_signal(r.signal).startswith("quality.")
                    or resolved_signal(r.signal).startswith(
                        "serve.skew_"
                    )
                ]
                if inert:
                    raise ValueError(
                        f"alert_rules watch quality-plane signals "
                        f"{inert} but quality is off — the records "
                        "would carry no quality block / skew keys and "
                        "these rules could never fire; enable quality "
                        "or drop the rules"
                    )
            # And for the training-fleet plane: straggler_ratio /
            # rank_step_skew / exchange_frac (and any explicit
            # fleet.* path) only exist in the `fleet` block rank 0
            # builds when train_fleet_scrape names the targets.
            if not self.train_fleet_scrape:
                inert = [
                    r.signal for r in rules
                    if resolved_signal(r.signal).startswith("fleet.")
                ]
                if inert:
                    raise ValueError(
                        f"alert_rules watch training-fleet signals "
                        f"{inert} but train_fleet_scrape is unset — "
                        "no record would carry a fleet block and "
                        "these rules could never fire; set "
                        "train_fleet_scrape or drop the rules"
                    )
        if not 0 <= self.serve_port < 65536:
            raise ValueError(
                f"serve_port must be in [0, 65535], got {self.serve_port}"
            )
        if self.max_batch_wait_ms < 0:
            raise ValueError(
                "max_batch_wait_ms must be >= 0, got "
                f"{self.max_batch_wait_ms}"
            )
        if self.serve_poll_secs < 0:
            raise ValueError(
                f"serve_poll_secs must be >= 0, got {self.serve_poll_secs}"
            )
        if self.serve_replicas < 0:
            raise ValueError(
                f"serve_replicas must be >= 0, got {self.serve_replicas}"
            )
        if self.serve_shed_deadline_ms < 0:
            raise ValueError(
                "serve_shed_deadline_ms must be >= 0, got "
                f"{self.serve_shed_deadline_ms}"
            )
        if self.serve_transport not in ("text", "bin", "both"):
            raise ValueError(
                f"unknown serve_transport {self.serve_transport!r}"
            )
        if self.serve_canary and self.serve_replicas < 2:
            # The silently-inert-knob discipline (same as cold_dtype /
            # alert_rules): canary promotion shadow-compares one
            # replica against another, so without a >= 2-replica fleet
            # the knob could never do anything.
            raise ValueError(
                "serve_canary requires serve_replicas >= 2 (promotion "
                "shadow-scores the canary against a baseline replica)"
            )
        if not 0.0 <= self.serve_trace_sample <= 1.0:
            raise ValueError(
                "serve_trace_sample must be in [0, 1], got "
                f"{self.serve_trace_sample}"
            )
        if self.serve_trace_sample > 0 and not self.trace_file:
            # The silently-inert-knob discipline: a sampled request's
            # span chain needs a trace file to land in; without one the
            # knob could never do anything.
            raise ValueError(
                "serve_trace_sample > 0 requires trace_file (sampled "
                "request chains are written to the trace output)"
            )
        if not 0.0 <= self.serve_capture_sample <= 1.0:
            raise ValueError(
                "serve_capture_sample must be in [0, 1], got "
                f"{self.serve_capture_sample}"
            )
        if self.serve_capture_sample > 0 and not self.serve_capture_file:
            # The silently-inert-knob discipline: sampled captures need
            # a file to land in.
            raise ValueError(
                "serve_capture_sample > 0 requires serve_capture_file "
                "(captured request/response frames are appended there)"
            )
        if self.serve_capture_file and self.serve_capture_sample <= 0:
            raise ValueError(
                "serve_capture_file is set but serve_capture_sample is "
                "0 — nothing would ever be captured; set a sample rate "
                "or drop the file"
            )
        if self.incident_dir and not self.blackbox:
            raise ValueError(
                "incident_dir is set but blackbox is off — no incident "
                "bundle could ever land there; enable blackbox or drop "
                "incident_dir"
            )
        if self.serve_slo_p99_ms < 0:
            raise ValueError(
                "serve_slo_p99_ms must be >= 0, got "
                f"{self.serve_slo_p99_ms}"
            )
        if not 0.0 <= self.serve_slo_availability < 1.0:
            raise ValueError(
                "serve_slo_availability must be in [0, 1) — it is the "
                "fraction of requests the SLO promises (0 = off), got "
                f"{self.serve_slo_availability}"
            )
        if self.serve_canary and self.serve_poll_secs <= 0:
            # Same hazard one knob over: the router's canary watcher
            # polls the manifest at serve_poll_secs, so 0 means no
            # promotion could ever start.
            raise ValueError(
                "serve_canary requires serve_poll_secs > 0 (the "
                "router's promotion watcher polls the manifest at "
                "that cadence)"
            )
        if self.serve_parse_mode not in ("vec", "legacy"):
            raise ValueError(
                f"unknown serve_parse_mode {self.serve_parse_mode!r} "
                "(expected 'vec' or 'legacy')"
            )
        if self.serve_http_threads < 0:
            raise ValueError(
                "serve_http_threads must be >= 0 (0 = thread-per-"
                f"connection), got {self.serve_http_threads}"
            )
        if self.serve_http_acceptors < 1:
            raise ValueError(
                "serve_http_acceptors must be >= 1, got "
                f"{self.serve_http_acceptors}"
            )
        if self.serve_http_acceptors > 1 and self.serve_http_threads == 0:
            # The silently-inert-knob discipline: extra accept loops
            # only exist in the pooled front end; with the pool off the
            # knob could never do anything.
            raise ValueError(
                "serve_http_acceptors > 1 requires serve_http_threads "
                "> 0 (extra accept loops feed the pooled front end)"
            )
        self.serve_ladder  # parse/validate serve_batch_sizes at startup
        if self.cache_max_bytes <= 0:
            raise ValueError(
                f"cache_max_bytes must be positive, got {self.cache_max_bytes}"
            )
        if self.ring_slots < 0:
            raise ValueError(
                f"ring_slots must be >= 0, got {self.ring_slots}"
            )
        if self.table_tiering not in ("off", "on"):
            raise ValueError(
                f"unknown table_tiering {self.table_tiering!r}"
            )
        if self.hot_rows < 1:
            raise ValueError(f"hot_rows must be >= 1, got {self.hot_rows}")
        if self.cold_dtype not in ("fp32", "bf16", "int8"):
            raise ValueError(f"unknown cold_dtype {self.cold_dtype!r}")
        if self.serve_table_dtype not in ("fp32", "bf16", "int8"):
            raise ValueError(
                f"unknown serve_table_dtype {self.serve_table_dtype!r}"
            )
        if self.quant_chunk < 0:
            raise ValueError(
                f"quant_chunk must be >= 0, got {self.quant_chunk}"
            )
        if self.sparse_exchange_overlap not in ("auto", "on", "off"):
            raise ValueError(
                "unknown sparse_exchange_overlap "
                f"{self.sparse_exchange_overlap!r}"
            )
        if self.sparse_exchange_overlap == "on" \
                and self.sparse_exchange == "dense":
            # Inert-knob discipline: the overlap double-buffers the
            # ENTRIES exchange's id plane; under the dense psum there
            # is no id plane to prefetch.  (The remaining "on"
            # requirements — sharded apply, multi-shard data axis —
            # need the mesh and are enforced at Trainer build.)
            raise ValueError(
                "sparse_exchange_overlap=on requires the entries "
                "exchange; sparse_exchange=dense has no id plane to "
                "overlap"
            )
        if self.tiered_partition not in ("auto", "global", "shards"):
            raise ValueError(
                f"unknown tiered_partition {self.tiered_partition!r}"
            )
        if self.tiered_partition != "auto" and self.table_tiering != "on":
            # tiered_partition names how the tiered table's ownership
            # splits across ranks; without tiering there is nothing to
            # partition (silently-inert-knob discipline).
            raise ValueError(
                "tiered_partition requires table_tiering=on (it "
                "partitions the tiered table's hot-slot ownership)"
            )
        if self.cold_dtype != "fp32" and self.table_tiering != "on":
            # The silently-inert-knob hazard (same discipline as
            # alert_rules-without-heartbeat): cold_dtype names the
            # tiered cold store's storage format, and without tiering
            # there is no cold store for it to apply to.
            raise ValueError(
                "cold_dtype != fp32 requires table_tiering=on (it is "
                "the storage dtype of the tiered cold store)"
            )
        if self.cache_prestacked and not self.cache_epochs:
            raise ValueError(
                "cache_prestacked requires cache_epochs (it is a storage "
                "format of the epoch cache)"
            )
        if self.weight_files and len(self.weight_files) != len(self.train_files):
            raise ValueError(
                "weight_files must parallel train_files "
                f"({len(self.weight_files)} vs {len(self.train_files)})"
            )

    @property
    def serve_ladder(self) -> tuple:
        """``serve_batch_sizes`` parsed into an ascending tuple of
        unique positive ints (the serving microbatch shape ladder)."""
        try:
            sizes = tuple(sorted({
                int(p) for p in self.serve_batch_sizes.split(",")
                if p.strip()
            }))
        except ValueError:
            raise ValueError(
                "serve_batch_sizes must be comma-separated ints, got "
                f"{self.serve_batch_sizes!r}"
            ) from None
        if not sizes or any(s <= 0 for s in sizes):
            raise ValueError(
                "serve_batch_sizes needs at least one positive size, "
                f"got {self.serve_batch_sizes!r}"
            )
        return sizes

    @property
    def embedding_dim(self) -> int:
        """Width of one table row: 1 linear weight + factor vector(s)."""
        k = self.factor_num
        return 1 + (k * self.field_num if self.field_num else k)

    @property
    def interaction_resolved(self) -> str:
        """The ops.interaction dispatch name ("jnp" | "pallas" | "flat")
        the step math should use — or "auto", which callers resolve
        through ops.autotune.resolve() before building the step.
        ``interaction_impl`` (the autotuner surface) supersedes the
        legacy ``interaction``/``use_pallas`` derivation."""
        if self.interaction_impl:  # validated in __post_init__
            if self.interaction_impl == "auto":
                return "auto"
            return {
                "reference": "jnp", "pallas": "pallas", "packed": "flat",
            }[self.interaction_impl]
        if self.interaction:  # validated in __post_init__
            return self.interaction
        return "pallas" if self.use_pallas else "jnp"

    @property
    def compute_jnp_dtype(self):
        """The interaction compute dtype as a jnp dtype.  bfloat16 halves
        the gathered-rows HBM traffic (the sparse step's dominant cost);
        parameters, optimizer state, loss and metrics stay float32."""
        import jax.numpy as jnp

        return jnp.bfloat16 if self.compute_dtype == "bfloat16" else jnp.float32


# INI key -> (dataclass field, parser).  Keys match the reference cfg surface
# (SURVEY.md §2 #12); dotted keys like ``adagrad.initial_accumulator`` are the
# reference spelling.
_KEYMAP = {
    "vocabulary_size": ("vocabulary_size", int),
    "vocabulary_block_num": ("vocabulary_block_num", int),
    "hash_feature_id": ("hash_feature_id", _parse_bool),
    "factor_num": ("factor_num", int),
    "field_num": ("field_num", int),
    "model_file": ("model_file", str),
    "log_file": ("log_file", str),
    "train_files": ("train_files", _parse_files),
    "weight_files": ("weight_files", _parse_files),
    "validation_files": ("validation_files", _parse_files),
    "epoch_num": ("epoch_num", int),
    "batch_size": ("batch_size", int),
    "learning_rate": ("learning_rate", float),
    "adagrad.initial_accumulator": ("adagrad_initial_accumulator", float),
    "adagrad_initial_accumulator": ("adagrad_initial_accumulator", float),
    "optimizer": ("optimizer", str),
    "loss_type": ("loss_type", str),
    "factor_lambda": ("factor_lambda", float),
    "bias_lambda": ("bias_lambda", float),
    "ftrl.l1": ("ftrl_l1", float),
    "ftrl.l2": ("ftrl_l2", float),
    "ftrl.beta": ("ftrl_beta", float),
    "ftrl_l1": ("ftrl_l1", float),
    "ftrl_l2": ("ftrl_l2", float),
    "ftrl_beta": ("ftrl_beta", float),
    "init_value_range": ("init_value_range", float),
    "thread_num": ("thread_num", int),
    "queue_size": ("queue_size", int),
    "shuffle_threads": ("shuffle_threads", int),
    "shuffle_buffer": ("shuffle_buffer", int),
    "save_steps": ("save_steps", int),
    "log_steps": ("log_steps", int),
    "validation_steps": ("validation_steps", int),
    "seed": ("seed", int),
    "predict_files": ("predict_files", _parse_files),
    "score_path": ("score_path", str),
    "serve_port": ("serve_port", int),
    "serve_host": ("serve_host", str),
    "serve_batch_sizes": ("serve_batch_sizes", str),
    "max_batch_wait_ms": ("max_batch_wait_ms", float),
    "serve_poll_secs": ("serve_poll_secs", float),
    "serve_replicas": ("serve_replicas", int),
    "serve_shed_deadline_ms": ("serve_shed_deadline_ms", float),
    "serve_canary": ("serve_canary", _parse_bool),
    "serve_transport": ("serve_transport", str),
    "serve_trace_sample": ("serve_trace_sample", float),
    "serve_slo_p99_ms": ("serve_slo_p99_ms", float),
    "serve_slo_availability": ("serve_slo_availability", float),
    "serve_parse_mode": ("serve_parse_mode", str),
    "serve_http_threads": ("serve_http_threads", int),
    "serve_http_acceptors": ("serve_http_acceptors", int),
    "profile_dir": ("profile_dir", str),
    "profile_start_step": ("profile_start_step", int),
    "profile_steps": ("profile_steps", int),
    "metrics_file": ("metrics_file", str),
    "telemetry": ("telemetry", _parse_bool),
    "heartbeat_secs": ("heartbeat_secs", float),
    "trace_file": ("trace_file", str),
    "nan_policy": ("nan_policy", str),
    "status_port": ("status_port", int),
    "status_host": ("status_host", str),
    "alert_rules": ("alert_rules", str),
    "resource_metrics": ("resource_metrics", _parse_bool),
    "quality": ("quality", _parse_bool),
    "quality_window": ("quality_window", int),
    "trace_rotate_events": ("trace_rotate_events", int),
    "train_fleet_scrape": ("train_fleet_scrape", str),
    "max_features": ("max_features", int),
    "mesh_data": ("mesh_data", int),
    "mesh_model": ("mesh_model", int),
    "lookup": ("lookup", str),
    "compute_dtype": ("compute_dtype", str),
    "use_pallas": ("use_pallas", _parse_bool),
    "interaction": ("interaction", str),
    "interaction_impl": ("interaction_impl", str),
    "compile_cache_dir": ("compile_cache_dir", str),
    "sparse_update": ("sparse_update", _parse_bool),
    "sparse_apply": ("sparse_apply", str),
    "fast_ingest": ("fast_ingest", _parse_bool),
    "host_sort": ("host_sort", _parse_bool),
    "l2_mode": ("l2_mode", str),
    "sparse_exchange": ("sparse_exchange", str),
    "sparse_exchange_overlap": ("sparse_exchange_overlap", str),
    "tiered_partition": ("tiered_partition", str),
    "steps_per_dispatch": ("steps_per_dispatch", int),
    "prefetch_super_batches": ("prefetch_super_batches", int),
    "parse_processes": ("parse_processes", int),
    "cache_epochs": ("cache_epochs", _parse_bool),
    "cache_max_bytes": ("cache_max_bytes", int),
    "cache_prestacked": ("cache_prestacked", _parse_bool),
    "ring_slots": ("ring_slots", int),
    "table_tiering": ("table_tiering", str),
    "hot_rows": ("hot_rows", int),
    "cold_dtype": ("cold_dtype", str),
    "serve_table_dtype": ("serve_table_dtype", str),
    "quant_chunk": ("quant_chunk", int),
    "blackbox": ("blackbox", _parse_bool),
    "incident_dir": ("incident_dir", str),
    "serve_capture_sample": ("serve_capture_sample", float),
    "serve_capture_file": ("serve_capture_file", str),
}


def load_config(path: str, overrides: Optional[dict] = None) -> FmConfig:
    """Load an INI ``.cfg`` file (reference-compatible) into an FmConfig."""
    parser = configparser.ConfigParser()
    read = parser.read(path)
    if not read:
        raise FileNotFoundError(path)
    values: dict = {}
    for section in parser.sections():
        for key, raw in parser.items(section):
            key = key.strip().lower()
            if key not in _KEYMAP:
                log.warning("ignoring unknown config key [%s] %s", section, key)
                continue
            field, fn = _KEYMAP[key]
            values[field] = fn(raw)
    if overrides:
        values.update(overrides)
    return FmConfig(**values)
