"""Input pipeline: files -> shuffled, parsed, padded device batches.

Replaces the reference's TF queue-runner pipeline (``TextLineReader`` +
shuffle batch queues, SURVEY.md §2 #6) with a thread-based producer/consumer
design driven by the same config knobs (``thread_num``, ``queue_size``,
``shuffle_buffer``, ``epoch_num``), feeding numpy batches that the train
loop ships to the device while the next batch parses — host-side pipelining
in place of TF queues.

Parsing uses the C++ extension when available (multi-threaded tokenizer +
murmur hashing, like the reference's ``FmParser``) and falls back to the
pure-Python oracle.  ``parse_processes`` moves parsing into a spawned
worker-process pool (``data.procpool``) that ships parsed batches back over
POSIX shared memory — the GIL-free analogue of the reference's free-running
C++ parser threads, and the only way the pure-Python parse path scales.

One pipeline spans ALL epochs of a run (``epochs``/``start_epoch``): the
reader reseeds per epoch, emits :class:`EpochEnd` markers in-band
(``epoch_marks=True``), and — with ``cache_epochs`` — retains epoch 0's
parsed batches so later epochs replay from memory instead of re-parsing.
"""

from __future__ import annotations

import logging
import pickle
import random
import threading
import time
from collections import deque
from typing import Iterator, NamedTuple, Optional, Sequence

import numpy as np

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data import libsvm

log = logging.getLogger(__name__)

# Raw-chunk read size for the fast ingest path.  Groups reference their
# window buffer (~shuffle_buffer lines when shuffling), so resident memory
# is bounded by the in-flight group count (work queue + parser threads)
# times the window byte size — a few windows in practice.
_CHUNK_BYTES = 4 << 20

_SENTINEL = object()
_CANCELLED = object()
_TIMEOUT = object()  # _ClosableQueue.get(timeout=...) expired empty


class EpochEnd(NamedTuple):
    """In-band epoch-boundary marker (``epoch_marks=True``).

    Yielded by BatchPipeline after the last batch of ``epoch``; the
    DevicePrefetcher flushes its pending super-batch group at a marker
    and forwards it, so super-batches never span epochs and the trainer
    can advance its checkpointed (epoch, batches_done) position without
    owning the epoch loop.
    """

    epoch: int


class SuperBatch(NamedTuple):
    """A pre-stacked ``[K, ...]`` group delivered in-band.

    With ``prestack_k > 0`` the pipeline stacks dispatch groups ONCE at
    epoch-0 group boundaries and delivers (and caches) them in this
    wrapper; :class:`DevicePrefetcher` recognizes it and ships the
    stacked batch straight to the device, skipping its own per-dispatch
    ``stack_batches`` — the replay epochs' host work drops to the
    permutation loop plus the H2D put.
    """

    batch: libsvm.Batch  # every leaf carries a leading K axis
    n: int  # batches stacked (K, or an epoch tail's K' < K)


class _Error:
    """Carries a worker/reader exception to the consuming thread."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class _ClosableQueue:
    """Bounded queue whose ``cancel()`` wakes every blocked producer and
    consumer immediately — deterministic shutdown with no timed polling
    (the previous design's 0.1 s put/get polls could leave workers
    lingering a poll period after close).

    ``put`` returns False (instead of blocking) once cancelled; ``get``
    returns the module-level ``_CANCELLED`` sentinel.

    ``hist`` (an obs.DepthHist) records the depth every put/get saw —
    the full occupancy distribution, not a heartbeat-time point sample,
    so a queue flapping full↔empty between beats still shows up.
    """

    def __init__(self, maxsize: int, hist=None):
        self._items: deque = deque()
        self._max = max(1, maxsize)
        self._cv = threading.Condition()
        self._cancelled = False
        self._hist = hist if hist is not None else obs.NULL.depth_hist("")

    def put(self, item) -> bool:
        with self._cv:
            while len(self._items) >= self._max and not self._cancelled:
                self._cv.wait()
            if self._cancelled:
                return False
            self._items.append(item)
            self._hist.observe(len(self._items))
            self._cv.notify_all()
            return True

    def get(self, timeout: Optional[float] = None):
        """Next item; blocks until one arrives, the queue is cancelled
        (``_CANCELLED``), or — with ``timeout`` — the deadline passes
        with the queue still empty (``_TIMEOUT``).  The timed form is
        the serve batcher's coalescing wait: collect requests until the
        microbatch deadline, then dispatch whatever arrived."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._cv:
            while not self._items and not self._cancelled:
                if deadline is None:
                    self._cv.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return _TIMEOUT
                self._cv.wait(remaining)
            if not self._items:
                return _CANCELLED
            self._hist.observe(len(self._items))
            item = self._items.popleft()
            self._cv.notify_all()
            return item

    def cancel(self):
        with self._cv:
            self._cancelled = True
            self._items.clear()
            self._cv.notify_all()

    def qsize(self) -> int:
        """Instantaneous depth (snapshot-time telemetry sample; a racy
        read of a deque length is exact enough for a gauge)."""
        return len(self._items)


def _read_weight_file(path: str) -> list[str]:
    # Keep EVERY line (even blanks) so weight line i pairs with data line i;
    # parsing to float happens only for lines actually used.
    with open(path) as f:
        return [line.strip() for line in f]


def iter_lines(
    files: Sequence[str],
    weight_files: Optional[Sequence[str]] = None,
) -> Iterator[tuple[str, float]]:
    """Yield (line, weight) over all files; weights default to 1.0.

    ``weight_files`` parallels ``files`` line-for-line (reference
    ``weight_files`` cfg key, SURVEY.md §2 #6): weight-file line i belongs
    to data-file line i; blank/comment data lines are skipped along with
    their weight lines.
    """
    for i, path in enumerate(files):
        weights = None
        if weight_files:
            weights = _read_weight_file(weight_files[i])
        with open(path) as f:
            for lineno, line in enumerate(f):
                if not line.strip() or line.lstrip().startswith("#"):
                    continue
                if weights is None:
                    w = 1.0
                else:
                    try:
                        w = float(weights[lineno])
                    except (IndexError, ValueError) as e:
                        raise ValueError(
                            f"weight file {weight_files[i]} line {lineno + 1} "
                            f"does not pair with data file {path}: {e}"
                        ) from e
                yield line, w


def _shuffled(
    it: Iterator[tuple[str, float]], buffer_size: int, rng: random.Random
) -> Iterator[tuple[str, float]]:
    """Reservoir-style streaming shuffle (like TF's shuffle queue)."""
    buf: list[tuple[str, float]] = []
    for item in it:
        if len(buf) < buffer_size:
            buf.append(item)
            continue
        j = rng.randrange(buffer_size)
        yield buf[j]
        buf[j] = item
    rng.shuffle(buf)
    yield from buf


def _raw_chunk_stream(files: Sequence[str], chunk_bytes: int):
    """Binary chunks of all files as ONE stream; a '\\n' is injected at a
    file boundary when the file lacks a trailing newline, so lines never
    merge across files and batches pack across files like the line path."""
    for path in files:
        last = b"\n"
        with open(path, "rb") as f:
            while True:
                chunk = f.read(chunk_bytes)
                if not chunk:
                    break
                last = chunk[-1:]
                yield chunk
        if last != b"\n":
            yield b"\n"


def _iter_raw_windows(
    files: Sequence[str],
    batch_size: int,
    window_lines: int,
    chunk_bytes: int = _CHUNK_BYTES,
):
    """Yield (buf, starts[n], ends[n]) windows of complete raw text lines.

    The fast ingest path: files are read in binary chunks, accumulated to
    a byte target predicted from a running bytes-per-line estimate, and
    scanned ONCE by the C++ line scanner — the previous design counted
    newlines with bytes.count() first and then re-scanned with memchr,
    paying two passes over every byte.  Windows reference the joined
    buffer directly; no Python string is ever created per line.

    Mid-stream windows hold a multiple of ``batch_size`` lines so the
    caller can slice exact groups; leftover lines (plus any incomplete
    tail) are carried into the next buffer as bytes, including across
    file boundaries.  The final window flushes everything.
    """
    from fast_tffm_tpu.data import native

    window_lines = max(window_lines, batch_size)
    stream = _raw_chunk_stream(files, chunk_bytes)
    pending = b""
    est_bpl = 80.0  # running bytes-per-line estimate
    guess = 0  # line-count guess for the scanner (stable density)
    at_eof = False
    while not at_eof:
        target = int(window_lines * est_bpl) + 1
        parts = [pending]
        size = len(parts[0])
        first = True
        # Read at least one chunk per round (guarantees progress when the
        # carried-over pending bytes alone held < one batch of lines).
        while size < target or first:
            first = False
            chunk = next(stream, None)
            if chunk is None:
                at_eof = True
                break
            parts.append(chunk)
            size += len(chunk)
        buf = b"".join(parts)
        pending = b""
        if not buf:
            continue  # at_eof: the while condition ends the loop
        buf_end = len(buf) if at_eof else buf.rfind(b"\n") + 1
        if buf_end == 0:  # not a single complete line yet; need more bytes
            pending = buf
            est_bpl *= 2.0
            continue
        starts = native.find_line_offsets(buf, buf_end, guess=guess or None)
        n = len(starts)
        if n == 0:
            if at_eof:
                return
            pending = buf
            continue
        est_bpl = buf_end / n
        guess = n + 2
        ends = np.append(starts[1:], buf_end)
        if at_eof:
            n_keep = n  # flush everything, partial group included
        else:
            n_keep = (n // batch_size) * batch_size
            if n_keep == 0:  # window bytes held < one batch of lines
                pending = buf
                continue
            if n_keep < n:
                pending = buf[int(starts[n_keep]):]
            elif buf_end < len(buf):
                pending = buf[buf_end:]
        yield buf, starts[:n_keep], ends[:n_keep]


def _iter_raw_groups(
    files: Sequence[str], batch_size: int, chunk_bytes: int = _CHUNK_BYTES
):
    """Yield (buf, starts, ends) groups of <= batch_size raw lines, in
    file order (no shuffle) — the unshuffled convenience used by bench
    and tests; BatchPipeline slices windows itself to shuffle lines."""
    for buf, starts, ends in _iter_raw_windows(
        files, batch_size, batch_size, chunk_bytes
    ):
        for i in range(0, len(starts), batch_size):
            yield buf, starts[i:i + batch_size], ends[i:i + batch_size]


def _item_len(item) -> int:
    """Number of lines in a work item (line chunk or raw group)."""
    if isinstance(item, tuple):
        return len(item[1])
    return len(item)


def _batch_nbytes(batch: libsvm.Batch) -> int:
    arrays = [batch.labels, batch.ids, batch.vals, batch.fields,
              batch.weights]
    if batch.sort_meta is not None:
        arrays.extend(batch.sort_meta)  # ~doubles a batch
    return sum(a.nbytes for a in arrays)


def _msg_bytes(msg) -> int:
    """Serialized size of a work message for the ``ingest.work_msg_bytes``
    counter.  Descriptor messages (rawslot/mark) are measured exactly —
    they are ~200 B and their smallness is the claim a tier-1 test pins;
    payload-bearing fallbacks (raw windows, line chunks) are ESTIMATED
    from their content lengths instead of pickled a second time — with
    them, mp.Queue's feeder already pays the full serialization once,
    and doubling that cost to count it would re-add the parent-side tax
    the ring exists to remove."""
    kind = msg[0]
    if kind in ("rawslot", "mark"):
        return len(pickle.dumps(msg, pickle.HIGHEST_PROTOCOL))
    if kind == "raw":
        _, _, buf, starts_list, ends_list = msg
        return len(buf) + sum(
            a.nbytes for a in starts_list
        ) + sum(a.nbytes for a in ends_list)
    if kind == "lines":
        _, _, lines, weights = msg
        return sum(len(s) for s in lines) + 8 * len(weights)
    return 0  # pragma: no cover - shutdown sentinel


def _strided_rounds(it, shard_id: int, num_shards: int):
    """Yield every num_shards-th item, but only from COMPLETE rounds.

    Multi-host input sharding: shard s takes items s, n+s, 2n+s, ... of the
    (identically seeded, hence identical) global stream.  Every shard must
    emit the SAME number of items — a host running one extra step would
    deadlock the others in the step's collectives — so an item is held back
    until its round is known complete (an item of the next round arrives)
    and the tail round is dropped at EOF if partial.
    """
    pending = None  # (round, item) candidate from this shard's slot
    last_idx = -1
    for idx, item in enumerate(it):
        last_idx = idx
        r = idx // num_shards
        if pending is not None and r > pending[0]:
            yield pending[1]
            pending = None
        if idx % num_shards == shard_id:
            pending = (r, item)
    if pending is not None and last_idx >= pending[0] * num_shards + num_shards - 1:
        yield pending[1]


class BatchPipeline:
    """Background parse/batch pipeline spanning a whole training run.

    One reader thread streams work items into a queue; ``thread_num``
    parser threads (or, with ``parse_processes > 0``, that many spawned
    worker PROCESSES — see :mod:`fast_tffm_tpu.data.procpool`) turn them
    into padded :class:`Batch` objects pushed to a bounded output queue
    (``queue_size``).  Batch order is nondeterministic across parser
    workers (like the reference's async queues) unless ``ordered=True``,
    which keeps the parallel parse but reorders delivery by sequence
    number (deterministic given the seed).

    The pipeline owns the EPOCH loop: ``epochs`` is the run's total epoch
    count, epoch e reseeds with ``seed + e``, and ``start_epoch`` /
    ``skip_batches`` name a resume position ("skip to (epoch, batch)").
    With ``epoch_marks=True`` an :class:`EpochEnd` marker is yielded
    in-band after each epoch's last batch (exact under ``ordered=True``;
    with free-running workers it can arrive up to the in-flight batch
    count early).
    """

    def __init__(
        self,
        files: Sequence[str],
        cfg: FmConfig,
        *,
        weight_files: Optional[Sequence[str]] = None,
        epochs: int = 1,
        shuffle: bool = True,
        drop_remainder: bool = False,
        seed: Optional[int] = None,
        ordered: bool = False,
        start_epoch: int = 0,
        skip_batches: int = 0,
        shard: tuple[int, int] = (0, 1),
        sort_meta_spec=None,
        cache_epochs: bool = False,
        cache_max_bytes: int = 1 << 30,
        prestack_k: int = 0,
        epoch_marks: bool = False,
        telemetry: Optional[obs.Telemetry] = None,
        tracer: Optional[obs.Tracer] = None,
        quality: Optional["obs.StreamSketch"] = None,
    ):
        self.files = list(files)
        # Telemetry instruments (obs.NULL when not passed: every call
        # below is a no-op, so instrumentation never branches).  Stage
        # naming: ingest.* covers reader + parse workers + delivery.
        self.telemetry = telemetry if telemetry is not None else obs.NULL
        tel = self.telemetry
        # Causal batch tracing (obs.NULL_TRACER = no-op): spans per read
        # window / ring-slot acquire / parse, plus an ``ingest.deliver``
        # point at the single delivery exit that bridges the reader's
        # work-item ``seq`` to the delivered ``batch`` index — the join
        # key the prefetcher's super-batch grouping continues from.
        self.tracer = tracer if tracer is not None else obs.NULL_TRACER
        # Model-quality drift sketches (obs.StreamSketch, None = off):
        # maintained ON the parse path — thread workers fold each
        # parsed batch in directly (the accumulator locks internally),
        # process workers keep a local SketchSet and ship serialized
        # deltas back on their result messages exactly like parse
        # timings.  Cached replay epochs re-deliver epoch-0 batches and
        # are deliberately NOT re-sketched: a replay's distribution is
        # epoch 0's by construction, so re-adding it would only inflate
        # counts without moving any distribution.
        self._quality = quality
        # seq of the batch most recently yielded by the streaming core
        # (generator chains are synchronous, so at the __iter__ exit this
        # names exactly the item that just bubbled up); None for cached
        # replays, which have no fresh parse to correlate with.
        self._last_seq: Optional[int] = None
        self._deliver_idx = 0
        self._c_batches = tel.counter("ingest.batches")
        self._c_examples = tel.counter("ingest.examples")
        self._c_cache_replays = tel.counter("ingest.cache_replay_batches")
        self._t_parse = tel.timer("ingest.parse")
        self._t_reader_block = tel.timer("ingest.reader_block")
        self._t_out_block = tel.timer("ingest.out_block")
        # Prestacked-cache + inbound-ring instruments: how many windows
        # went through the SHM ring vs fell back to the pickled queue
        # path, the descriptor bytes that DID cross the queue, and the
        # once-per-group stack time of the prestacked cache.
        self._t_prestack = tel.timer("ingest.prestack")
        self._c_ring_windows = tel.counter("ingest.ring_windows")
        self._c_ring_fallback = tel.counter("ingest.ring_fallback_windows")
        self._c_ring_bytes = tel.counter("ingest.ring_window_bytes")
        self._c_q_msg_bytes = tel.counter("ingest.work_msg_bytes")
        # Component memory ledger (resource plane): the bytes this
        # pipeline is RESPONSIBLE for right now — the epoch cache's
        # retained batches (raw or prestacked; drops to 0 on overflow)
        # and the SHM ring's fixed slot allocation (0 once torn down).
        self._g_cache_bytes = tel.gauge("ingest.cache_bytes")
        self._g_ring_bytes = tel.gauge("ingest.ring_bytes")
        # Always-real counter (not gated on telemetry): out-of-range-id
        # batches are a data/vocabulary integrity signal the trainer
        # surfaces in its RESULTS, not just in logs or optional stages.
        self._oor_counter = obs.Counter()
        tel.sample("ingest.oor_batches", lambda: self._oor_counter.value)
        tel.sample(
            "ingest.truncated_features", lambda: self.truncated_features
        )
        self.cfg = cfg
        self.weight_files = list(weight_files) if weight_files else None
        self.epochs = epochs
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self.seed = cfg.seed if seed is None else seed
        # Resume position: deliver epochs [start_epoch, epochs), skipping
        # the first skip_batches of epoch start_epoch WITHOUT parsing them
        # (the cached path re-parses epoch 0 to rebuild the replay cache —
        # see __iter__).  Skipping happens after shuffling, so the stream
        # continues exactly where a run with the same seed left off.
        if not 0 <= start_epoch < max(1, epochs):
            raise ValueError(
                f"start_epoch {start_epoch} outside [0, {epochs})"
            )
        self.start_epoch = start_epoch
        self.skip_batches = skip_batches
        # Multi-host input sharding (shard_id, num_shards): this pipeline
        # emits only its strided share of the global stream, round-complete
        # (see _strided_rounds).  Skip counts apply AFTER sharding.
        if not (0 <= shard[0] < shard[1]):
            raise ValueError(f"bad shard {shard}")
        self.shard = shard
        # ordered=True delivers batches in input order (the predict path
        # needs score/line alignment; model-axis-spanning hosts need
        # identical order).  Parsing still runs on thread_num workers —
        # items carry sequence numbers and the consumer reorders.
        self.ordered = ordered
        self.epoch_marks = epoch_marks
        self._native, self._parser = _make_parser(cfg)
        # (vocab, chunk, tile) or None: when set, workers attach host-
        # computed sparse-apply prep (native.sort_meta) to each batch,
        # moving the device step's id sort onto these threads.  Needs the
        # native lib; silently skipped if it failed to build (the device
        # fallback path sorts on-chip).
        self._sort_meta_spec = (
            sort_meta_spec if self._native is not None else None
        )
        self._sort_meta_warned = False
        # Truncation counted OUTSIDE the in-process native parser: process
        # workers ship their per-batch drop counts back with each batch,
        # and cached-epoch replays re-add epoch 0's total per replay (the
        # same features a re-parse would have dropped again), so the
        # trainer's periodic warning stays truthful in every ingest mode.
        self._trunc_extra = 0
        # Fast ingest: raw binary chunks + C++ line scan, no Python string
        # per line. Requires the native parser; weight_files need per-line
        # pairing so they stay on the line path. Shuffling permutes LINES
        # within shuffle_buffer-line windows (matching the line path's
        # reservoir window).
        self._raw = (
            cfg.fast_ingest and self._native is not None
            and not self.weight_files
        )
        # Multi-epoch parsed-batch cache (the tf.data ``.cache()``
        # pattern): epoch 0 parses normally while retaining every
        # delivered Batch; epochs 1..E-1 replay the cached batches in a
        # seeded per-epoch permutation instead of re-reading and
        # re-parsing the same text.  Batch contents are preserved exactly
        # (so attached sort_meta stays valid); cross-epoch remixing drops
        # to batch granularity — the documented tradeoff, opt-in only.
        # A byte budget guards host memory (overflow falls back to
        # re-parsing); resume positions are honored (cache-aware: epoch 0
        # re-parses once to rebuild the cache, later epochs replay).
        self._cache_epochs = (
            cache_epochs and epochs > 1 and shard == (0, 1)
        )
        self._cache_max_bytes = cache_max_bytes
        # Prestacked cache storage (cache_prestacked): dispatch groups of
        # prestack_k batches are stacked ONCE at epoch-0 group boundaries
        # and delivered/cached as SuperBatch items; replay epochs permute
        # at super-batch granularity and the transfer stage skips its
        # per-dispatch stack.  Only meaningful when the cache engages.
        self._prestack_k = prestack_k if self._cache_epochs else 0
        # Outcome of the cache for observability: "off" | "cached" |
        # "overflow" (budget blown during epoch 0; later epochs re-parsed).
        self.cache_result = "off"

    @property
    def truncated_features(self) -> int:
        """Feature occurrences dropped by max_features so far (reference
        FmParser warned about truncation, SURVEY.md §2 #1); the trainer
        surfaces this periodically.  Includes process-worker drops and
        cached-epoch replays (each replay re-adds epoch 0's total)."""
        base = self._native.truncated_features if self._native else 0
        return base + self._trunc_extra

    @property
    def oor_batches(self) -> int:
        """Batches whose host sort prep hit out-of-range feature ids — a
        data/vocabulary_size integrity bug (the device-sort path silently
        drops those updates).  Counted across thread AND process workers;
        the trainer surfaces it in train results and the final record."""
        return self._oor_counter.value

    def stats(self) -> dict:
        """Point-in-time data-integrity snapshot: the counters every
        self-report (heartbeat, final record, /status endpoint) carries.
        Thread-safe and callable at any time, including after shutdown —
        the live status endpoint reads it from HTTP handler threads
        while the pipeline runs."""
        return {
            "truncated_features": int(self.truncated_features),
            "out_of_range_batches": int(self.oor_batches),
            "ingest_cache": self.cache_result,
        }

    def __iter__(self) -> Iterator:
        E, e0 = self.epochs, self.start_epoch
        if not self._cache_epochs:
            inner = self._emit_stream(E - e0, e0, self.skip_batches)
        else:
            inner = self._iter_cached(E, e0)
        # Delivery accounting happens at the single exit point so every
        # path (threads, procpool, cached replay) counts identically.
        # The O(batch) example count only runs when telemetry is live —
        # "disabled" must mean no per-batch work at all, or the bench's
        # on/off overhead probe compares against a lie.
        counting = self.telemetry.enabled
        tracing = self.tracer.enabled
        for item in inner:
            if isinstance(item, SuperBatch):
                self._c_batches.add(item.n)
                if counting:
                    self._c_examples.add(
                        int(np.count_nonzero(item.batch.weights > 0))
                    )
                if tracing:
                    self.tracer.point("ingest.deliver", args={
                        "batch": self._deliver_idx, "n": item.n,
                        "seq": self._last_seq,
                    })
                self._deliver_idx += item.n
            elif not isinstance(item, EpochEnd):
                self._c_batches.add(1)
                if counting:
                    self._c_examples.add(
                        int(np.count_nonzero(item.weights > 0))
                    )
                if tracing:
                    self.tracer.point("ingest.deliver", args={
                        "batch": self._deliver_idx, "n": 1,
                        "seq": self._last_seq,
                    })
                self._deliver_idx += 1
            yield item

    def _emit_stream(self, n_epochs: int, first_epoch: int, skip: int):
        """_iter_stream with EpochEnd markers filtered per epoch_marks."""
        for item in self._iter_stream(n_epochs, first_epoch, skip):
            if isinstance(item, EpochEnd) and not self.epoch_marks:
                continue
            yield item

    def _iter_cached(self, E: int, e0: int):
        """cache_epochs delivery: parse epoch 0 once (caching every
        batch), then replay epochs 1..E-1 as seeded permutations of the
        cache.  A resume past the start of epoch 0 re-parses epoch 0 to
        REBUILD the cache (delivering nothing for already-trained
        batches), then replays from the resume position — later epochs
        come from memory instead of a per-epoch re-parse."""
        if self._prestack_k > 0:
            yield from self._iter_cached_prestacked(E, e0)
            return
        cache: Optional[list] = []
        size = 0
        self.cache_result = "cached"
        deliver = e0 == 0
        skip = self.skip_batches
        trunc_start = self.truncated_features
        n_seen = 0
        stream = self._iter_stream(1, 0, 0)
        try:
            for item in stream:
                if isinstance(item, EpochEnd):
                    if deliver and self.epoch_marks:
                        yield item
                    continue
                if cache is not None:
                    size += _batch_nbytes(item)
                    if size > self._cache_max_bytes:
                        log.info(
                            "ingest cache over budget (%d > %d bytes); "
                            "re-parsing later epochs", size,
                            self._cache_max_bytes,
                        )
                        cache = None
                        self.cache_result = "overflow"
                        self._g_cache_bytes.set(0)  # retained nothing
                        if not deliver:
                            break  # rebuild-only parse: stop early
                    else:
                        cache.append(item)
                        self._g_cache_bytes.set(size)
                n_seen += 1
                if deliver and n_seen > skip:
                    yield item
        finally:
            stream.close()
        if cache is None:  # budget blown: stream the remaining epochs
            if deliver:
                if E > 1:
                    yield from self._emit_stream(E - 1, 1, 0)
            else:
                # The resumed epoch streams from ITS seed with the skip —
                # identical to what the uninterrupted overflow run
                # delivered for that epoch.
                yield from self._emit_stream(E - e0, e0, skip)
            return
        epoch0_trunc = self.truncated_features - trunc_start
        self._last_seq = None  # replays have no fresh parse to trace
        for epoch in range(max(1, e0), E):
            order = list(range(len(cache)))
            if self.shuffle:
                random.Random(self.seed + epoch).shuffle(order)
            start = skip if epoch == e0 else 0
            for i in order[start:]:
                self._c_cache_replays.add(1)
                yield cache[i]
            # A re-parse of this epoch would have dropped the same
            # features again; keep the running counter truthful.
            self._trunc_extra += epoch0_trunc
            if self.epoch_marks:
                yield EpochEnd(epoch)

    @staticmethod
    def _slice_super(sb: SuperBatch, start: int) -> SuperBatch:
        """Leading-axis tail slice of a stacked group (views, no copy):
        a resume position that lands inside a group delivers only the
        group's untrained suffix."""
        b = sb.batch
        meta = b.sort_meta
        if meta is not None:
            meta = type(meta)(*(x[start:] for x in meta))
        return SuperBatch(
            libsvm.Batch(
                b.labels[start:], b.ids[start:], b.vals[start:],
                b.fields[start:], b.weights[start:], sort_meta=meta,
            ),
            sb.n - start,
        )

    def _iter_cached_prestacked(self, E: int, e0: int):
        """cache_prestacked delivery: epoch 0 streams as usual but every
        ``prestack_k`` delivered batches are stacked ONCE into a [K, ...]
        SuperBatch (the epoch tail stacks at K' = leftover) which is
        both delivered and cached; epochs 1..E-1 replay the cached
        super-batches in a seeded per-epoch permutation.  The batches
        inside every group are byte-identical to the plain cached path —
        only the replay permutation granularity changes (super-batch
        instead of batch, the documented tradeoff).  Resume mirrors
        ``_iter_cached``: epoch 0 re-parses to rebuild, the skip count
        is consumed in whole groups (a trainer position always lands on
        a group boundary; a foreign mid-group skip delivers the group's
        sliced tail)."""
        k = self._prestack_k
        cache: Optional[list] = []
        size = 0
        self.cache_result = "cached"
        deliver = e0 == 0
        skip = self.skip_batches
        trunc_start = self.truncated_features
        n_seen = 0  # batches consumed from the epoch-0 stream
        group: list = []
        stream = self._iter_stream(1, 0, 0)

        def flush_group():
            """Stack the pending group once; cache + deliver decisions."""
            nonlocal size, cache, group
            if not group:
                return None
            with self._t_prestack.time():
                sb = SuperBatch(stack_batches(group), len(group))
            start_idx = n_seen - len(group)
            group = []
            if cache is not None:
                size += _batch_nbytes(sb.batch)
                if size > self._cache_max_bytes:
                    log.info(
                        "ingest cache over budget (%d > %d bytes); "
                        "re-parsing later epochs", size,
                        self._cache_max_bytes,
                    )
                    cache = None
                    self.cache_result = "overflow"
                    self._g_cache_bytes.set(0)  # retained nothing
                else:
                    cache.append(sb)
                    self._g_cache_bytes.set(size)
            if not deliver:
                return None
            if start_idx >= skip:
                return sb
            if n_seen > skip:  # mid-group resume: deliver the tail
                return self._slice_super(sb, skip - start_idx)
            return None

        try:
            for item in stream:
                if isinstance(item, EpochEnd):
                    out = flush_group()  # epoch tail: K' = leftover
                    if out is not None:
                        yield out
                    if deliver and self.epoch_marks:
                        yield item
                    if cache is None and not deliver:
                        break  # rebuild-only parse overflowed: stop early
                    continue
                group.append(item)
                n_seen += 1
                if len(group) == k:
                    out = flush_group()
                    if out is not None:
                        yield out
                    if cache is None and not deliver:
                        break
        finally:
            stream.close()
        if cache is None:  # budget blown: stream the remaining epochs
            if deliver:
                if E > 1:
                    yield from self._emit_stream(E - 1, 1, 0)
            else:
                yield from self._emit_stream(E - e0, e0, skip)
            return
        epoch0_trunc = self.truncated_features - trunc_start
        self._last_seq = None  # replays have no fresh parse to trace
        for epoch in range(max(1, e0), E):
            order = list(range(len(cache)))
            if self.shuffle:
                random.Random(self.seed + epoch).shuffle(order)
            rem = skip if epoch == e0 else 0
            for gi in order:
                sb = cache[gi]
                if rem >= sb.n:
                    rem -= sb.n
                    continue
                self._c_cache_replays.add(sb.n - rem)
                yield self._slice_super(sb, rem) if rem else sb
                rem = 0
            self._trunc_extra += epoch0_trunc
            if self.epoch_marks:
                yield EpochEnd(epoch)

    # ------------------------------------------------------------------
    # Streaming core: reader -> parse workers (threads or processes)
    # ------------------------------------------------------------------

    def _line_chunks(self, rng):
        """Line path: line-level shuffle, then fixed-size chunks."""
        cfg = self.cfg
        it = iter_lines(self.files, self.weight_files)
        if self.shuffle:
            it = _shuffled(it, max(1, cfg.shuffle_buffer), rng)
        chunk: list[tuple[str, float]] = []
        for item in it:
            chunk.append(item)
            if len(chunk) == cfg.batch_size:
                yield chunk
                chunk = []
        if chunk:
            yield chunk

    def _raw_groups(self, rng):
        """Fast path: scan-once windows -> line-level shuffle ->
        groups.  The shuffle window is ``shuffle_buffer`` LINES (like
        the line path's reservoir), permuted with numpy — each group
        then references a shuffled, non-contiguous view of the window
        buffer, which parse_raw gathers zero-copy."""
        cfg = self.cfg
        window = (
            max(cfg.shuffle_buffer, cfg.batch_size)
            if self.shuffle else cfg.batch_size
        )
        for buf, starts, ends in _iter_raw_windows(
            self.files, cfg.batch_size, window
        ):
            n = len(starts)
            if self.shuffle and n > 1:
                perm = np.random.default_rng(
                    rng.getrandbits(63)
                ).permutation(n)
                starts, ends = starts[perm], ends[perm]
            for i in range(0, n, cfg.batch_size):
                yield buf, starts[i:i + cfg.batch_size], ends[
                    i:i + cfg.batch_size
                ]

    def _epoch_items(self, n_epochs: int, first_epoch: int, skip: int):
        """(seq, work-item-or-EpochEnd) across epochs — the reader-side
        epoch loop: per-epoch reseeding (``seed + epoch``, matching what
        a fresh per-epoch pipeline would draw), drop_remainder filtering
        BEFORE sharding (all shards must see the same global item
        indexing), strided multi-host sharding, and the resume skip
        (first epoch only, post-shard)."""
        cfg = self.cfg
        seq = 0
        for epoch in range(first_epoch, first_epoch + n_epochs):
            rng = random.Random(self.seed + epoch)
            to_skip = skip if epoch == first_epoch else 0
            if self._raw:
                # Line-level shuffle happens inside _raw_groups over
                # shuffle_buffer-line windows — the same mixing window as
                # the line path's reservoir, so no group-order reservoir
                # on top (stacking one would pin many window buffers).
                it = self._raw_groups(rng)
            else:
                it = self._line_chunks(rng)
            if self.drop_remainder:
                it = (x for x in it if _item_len(x) >= cfg.batch_size)
            if self.shard[1] > 1:
                it = _strided_rounds(it, *self.shard)
            for item in it:
                if to_skip > 0:
                    to_skip -= 1
                    continue
                yield seq, item
                seq += 1
            yield seq, EpochEnd(epoch)
            seq += 1

    def _traced_items(self, it):
        """Wrap the reader's work-item stream with ``read.item`` spans:
        each span covers the time to PRODUCE one item (file read, window
        scan, shuffle) — generator chains run synchronously, so nothing
        else can hide inside it.  No-op (plain passthrough) when tracing
        is off."""
        tracer = self.tracer
        if not tracer.enabled:
            yield from it
            return
        tracer.name_thread("ingest-reader")
        while True:
            t0 = time.perf_counter()
            nxt = next(it, None)
            if nxt is None:
                return
            seq, item = nxt
            if not isinstance(item, EpochEnd):
                tracer.emit("read.item", t0, time.perf_counter() - t0,
                            args={"seq": seq})
            yield seq, item

    def _iter_stream(
        self, n_epochs: int, first_epoch: int = 0, skip: int = 0
    ) -> Iterator:
        if n_epochs <= 0:
            return
        if self.cfg.parse_processes > 0:
            yield from self._iter_stream_procs(n_epochs, first_epoch, skip)
        else:
            yield from self._iter_stream_threads(n_epochs, first_epoch, skip)

    def _attach_meta(self, batch: libsvm.Batch) -> libsvm.Batch:
        """Host sort prep for one batch (thread-mode workers)."""
        from fast_tffm_tpu.data import native as _native

        # Metadata is an optimization, not a correctness requirement:
        # the device-sort path handles sort_meta=None.  A native failure
        # here must degrade, not kill the epoch — same contract as
        # Trainer._put's fallback.  But the two failure classes degrade
        # differently (ADVICE r5): out-of-range ids are a
        # data/vocabulary_size integrity bug whose updates the device
        # path SILENTLY drops, so that warning repeats per bad batch;
        # any other native failure disables the spec once and goes quiet.
        try:
            return batch._replace(
                sort_meta=_native.sort_meta(
                    batch.ids, *self._sort_meta_spec
                )
            )
        except _native.OutOfRangeIdsError as e:
            self._oor_counter.add(1)
            log.warning(
                "host sort_meta rejected a batch (%s); the input data or "
                "vocabulary_size is wrong — the device-sort path will "
                "silently drop updates for ids >= vocabulary_size", e,
            )
        except Exception as e:
            self._sort_meta_spec = None
            if not self._sort_meta_warned:
                self._sort_meta_warned = True
                log.warning(
                    "host sort_meta failed (%s: %s); falling back to "
                    "device sort for the rest of the run",
                    type(e).__name__, e,
                )
        return batch

    def _iter_stream_threads(
        self, n_epochs: int, first_epoch: int, skip: int
    ) -> Iterator:
        cfg = self.cfg
        # Per-put/get depth histograms (not heartbeat-time point samples:
        # a flapping queue shows its full occupancy distribution).  work
        # deep + out shallow = parse-bound; work shallow + out deep = the
        # consumer (training) is the bottleneck.
        work = _ClosableQueue(
            max(2, cfg.queue_size),
            hist=self.telemetry.depth_hist("ingest.work_q_depth"),
        )
        out = _ClosableQueue(
            max(2, cfg.queue_size),
            hist=self.telemetry.depth_hist("ingest.out_q_depth"),
        )
        n_workers = max(1, cfg.thread_num)

        tracer = self.tracer
        tracing = tracer.enabled
        timed = self.telemetry.enabled or tracing

        def reader():
            try:
                for seq, item in self._traced_items(self._epoch_items(
                    n_epochs, first_epoch, skip
                )):
                    # Producer-block time: how long the reader waits for
                    # a work-queue slot.  Large totals mean parsing (not
                    # reading) limits ingest.
                    t0 = time.perf_counter()
                    ok = work.put((seq, item))
                    self._t_reader_block.observe(time.perf_counter() - t0)
                    if not ok:
                        return
            except BaseException as e:  # surfaces in the consumer
                out.put(_Error(e))
            finally:
                for _ in range(n_workers):
                    if not work.put(_SENTINEL):
                        break

        def parse_worker():
            if tracing:
                tracer.name_thread("parse-worker")
            while True:
                got = work.get()
                if got is _CANCELLED:
                    return
                if got is _SENTINEL:
                    out.put(_SENTINEL)
                    return
                seq, chunk = got
                if isinstance(chunk, EpochEnd):
                    out.put((seq, chunk))
                    continue
                try:
                    # Per-batch timing only when someone consumes it:
                    # "disabled" must mean no per-batch work at all, or
                    # the bench's on/off overhead probes compare
                    # against a lie (same invariant as delivery
                    # counting above).
                    t0p = time.perf_counter() if timed else 0.0
                    if isinstance(chunk, tuple):  # raw (buf,starts,ends)
                        batch = self._native.parse_raw(
                            chunk[0], chunk[1], chunk[2], cfg.batch_size
                        )
                    else:
                        lines = [c[0] for c in chunk]
                        weights = [c[1] for c in chunk]
                        batch = self._parser(lines, weights)
                    if self._sort_meta_spec is not None:
                        batch = self._attach_meta(batch)
                    if timed:
                        dtp = time.perf_counter() - t0p
                        self._t_parse.observe(dtp)
                        if tracing:
                            tracer.emit("parse.batch", t0p, dtp,
                                        args={"seq": seq})
                    if self._quality is not None:
                        # Drift sketches ride the parse threads (batch
                        # cadence, lock inside the accumulator) so the
                        # delivery path pays nothing.  Guarded: a
                        # sketching failure is an OBSERVER failure —
                        # it degrades the quality plane, it must never
                        # surface through the worker's fatal error
                        # path and kill the training it observes.
                        try:
                            self._quality.update_batch(
                                batch.ids, batch.vals, batch.weights
                            )
                        except Exception as e:  # noqa: BLE001
                            self._quality = None  # degrade for good
                            log.warning(
                                "quality sketching disabled: "
                                "update_batch failed (%s: %s); "
                                "training continues without ingest "
                                "drift sketches",
                                type(e).__name__, e,
                            )
                except BaseException as e:
                    out.put(_Error(e))
                    continue
                # Worker-block time on delivery: the consumer (transfer
                # stage / training) isn't draining fast enough.
                t0 = time.perf_counter()
                out.put((seq, batch))
                self._t_out_block.observe(time.perf_counter() - t0)

        threads = [threading.Thread(target=reader, daemon=True)]
        threads += [
            threading.Thread(target=parse_worker, daemon=True)
            for _ in range(n_workers)
        ]
        for t in threads:
            t.start()
        finished = 0
        next_seq = 0
        held: dict = {}  # ordered mode: out-of-order batches by seq
        try:
            while finished < n_workers:
                item = out.get()
                if item is _CANCELLED:
                    return  # torn down externally
                if item is _SENTINEL:
                    finished += 1
                    continue
                if isinstance(item, _Error):
                    raise item.exc
                seq, obj = item
                if not self.ordered:
                    self._last_seq = seq
                    yield obj
                    continue
                # Reorder by sequence number: parsing is parallel but
                # delivery follows reader order (bounded by in-flight
                # items: work queue + workers + out queue).
                held[seq] = obj
                while next_seq in held:
                    self._last_seq = next_seq
                    yield held.pop(next_seq)
                    next_seq += 1
            # Workers exited; whatever is held is contiguous from
            # next_seq (an error would have raised above).
            for seq in sorted(held):
                self._last_seq = seq
                yield held[seq]
        finally:
            # Deterministic shutdown: cancel wakes every blocked put/get
            # at once, so joins complete without timed polling.
            work.cancel()
            out.cancel()
            for t in threads:
                t.join()

    def _ring_slot_bytes(self) -> int:
        """Ring slot capacity for this config's raw windows: text bytes
        (window lines at a generous 1 KB/line, plus one read chunk of
        accumulation overshoot) + the 16 B/line offset arrays.  A window
        that still outgrows this falls back to the pickled queue path —
        counted, never wrong — so the estimate only has to be right for
        the common case."""
        cfg = self.cfg
        window_lines = (
            max(cfg.shuffle_buffer, cfg.batch_size)
            if self.shuffle else cfg.batch_size
        )
        want = window_lines * (1024 + 16) + 2 * _CHUNK_BYTES
        return min(max(want, 1 << 20), 64 << 20)

    def _iter_stream_procs(
        self, n_epochs: int, first_epoch: int, skip: int
    ) -> Iterator:
        """Multiprocess parse: the reader thread coalesces work by raw
        window and a spawned worker pool parses + preps batches, shipping
        them back as shared memory segments (data.procpool) — parsing
        never touches this process's GIL, which is what makes
        ``thread_num`` useless on the pure-Python parse path.

        With ``ring_slots > 0`` the raw direction is zero-copy too: the
        reader writes each window (text + offsets) into a slot of an
        inbound SHM ring and only slot DESCRIPTORS cross the work queue;
        workers parse in place and recycle slots over a free queue.
        Windows larger than a slot (and the line path) fall back to
        pickling through the queue, exactly as before."""
        import multiprocessing as mp
        import queue as _q

        from fast_tffm_tpu.data import procpool

        cfg = self.cfg
        ctx = mp.get_context("spawn")
        n_workers = max(1, cfg.parse_processes)
        # Raw work items are whole windows (many batches each); a couple
        # per worker bounds resident window bytes without starving.
        work = ctx.Queue(maxsize=max(2, min(cfg.queue_size, 2 * n_workers)))
        out = ctx.Queue(maxsize=max(2, cfg.queue_size))
        stop = ctx.Event()
        shm_tag = procpool.make_shm_tag()
        ring = None
        ring_free = None
        if self._raw and cfg.ring_slots > 0:
            ring = procpool.ShmRing.create(
                shm_tag, cfg.ring_slots, self._ring_slot_bytes()
            )
            # Ledger: the ring is a fixed allocation for its lifetime.
            self._g_ring_bytes.set(cfg.ring_slots * ring.slot_bytes)
            ring_free = ctx.Queue(maxsize=cfg.ring_slots + 1)
            for i in range(cfg.ring_slots):
                ring_free.put(i)
        spec = procpool.WorkerSpec(
            vocabulary_size=cfg.vocabulary_size,
            max_features=cfg.max_features,
            hash_feature_id=cfg.hash_feature_id,
            field_num=cfg.field_num,
            batch_size=cfg.batch_size,
            use_native=self._native is not None,
            sort_meta_spec=self._sort_meta_spec,
            shm_tag=shm_tag,
            ring_name=ring.name if ring is not None else None,
            ring_slots=cfg.ring_slots,
            ring_slot_bytes=ring.slot_bytes if ring is not None else 0,
            trace=self.tracer.enabled,
            sketch_every=(
                procpool.SKETCH_SHIP_EVERY
                if self._quality is not None else 0
            ),
        )
        procs = [
            ctx.Process(
                target=procpool.parse_worker_main,
                args=(spec, work, out, stop, ring_free), daemon=True,
            )
            for _ in range(n_workers)
        ]
        for p in procs:
            p.start()
        # Depth histograms around the parent-side queue ends (mp.Queue
        # qsize is approximate, and can raise on exotic platforms — the
        # helper degrades to not observing).
        h_work = self.telemetry.depth_hist("ingest.work_q_depth")
        h_out = self.telemetry.depth_hist("ingest.out_q_depth")
        h_ring = self.telemetry.depth_hist("ingest.ring_free_slots")

        def observe_depth(hist, q):
            try:
                hist.observe(q.qsize())
            except (NotImplementedError, OSError):  # pragma: no cover
                pass

        def put_mp(q, item) -> bool:
            return procpool.put_with_stop(q, item, stop)

        # Descriptor-size accounting only when telemetry is live: the
        # whole point of the ring is that work messages shrink to slot
        # descriptors, and the counter is what proves it (tier-1 test).
        counting = self.telemetry.enabled
        tracer = self.tracer

        reader_err: list = []

        def reader():
            pend = None  # (buf, seq0, [starts...], [ends...])

            def put_work(msg) -> bool:
                # Same producer-block accounting as the thread path: time
                # waiting for a work-queue slot (parse-bound signal).
                if counting:
                    self._c_q_msg_bytes.add(_msg_bytes(msg))
                observe_depth(h_work, work)
                t0 = time.perf_counter()
                ok = put_mp(work, msg)
                self._t_reader_block.observe(time.perf_counter() - t0)
                return ok

            def flush() -> bool:
                nonlocal pend
                if pend is None:
                    return True
                buf, seq0, starts_list, ends_list = (
                    pend[0], pend[1], pend[2], pend[3]
                )
                pend = None
                n_lines = sum(len(s) for s in starts_list)
                if (
                    ring is not None
                    and procpool.ShmRing.need_bytes(len(buf), n_lines)
                    <= ring.slot_bytes
                ):
                    observe_depth(h_ring, ring_free)
                    # Slot-acquire wait: all slots in flight = the ring's
                    # backpressure; a long span here means parse workers
                    # (not the reader) limit ingest.
                    t0s = time.perf_counter()
                    slot = procpool.get_with_stop(ring_free, stop)
                    if slot is None:
                        return False
                    if tracer.enabled:
                        tracer.emit(
                            "ring.slot_acquire", t0s,
                            time.perf_counter() - t0s,
                            args={"slot": slot, "seq": seq0},
                        )
                    ring.write(
                        slot, buf,
                        np.concatenate(starts_list),
                        np.concatenate(ends_list),
                    )
                    self._c_ring_windows.add(1)
                    self._c_ring_bytes.add(len(buf))
                    return put_work((
                        "rawslot", seq0, slot, len(buf),
                        [len(s) for s in starts_list],
                    ))
                # Oversized window (or ring off): the window's bytes
                # cross the queue pickled, exactly the old contract.
                self._c_ring_fallback.add(1)
                return put_work(
                    ("raw", seq0, bytes(buf), starts_list, ends_list)
                )

            try:
                for seq, item in self._traced_items(self._epoch_items(
                    n_epochs, first_epoch, skip
                )):
                    if isinstance(item, EpochEnd):
                        if not flush():
                            return
                        if not put_work(("mark", seq, item.epoch)):
                            return
                    elif isinstance(item, tuple):  # raw group
                        buf, s, e = item
                        if pend is not None and pend[0] is not buf:
                            if not flush():
                                return
                        if pend is None:
                            pend = (buf, seq, [s], [e])
                        else:
                            pend[2].append(s)
                            pend[3].append(e)
                    else:  # line chunk
                        if not flush():
                            return
                        lines = [c[0] for c in item]
                        weights = [c[1] for c in item]
                        if not put_work(("lines", seq, lines, weights)):
                            return
                if not flush():
                    return
            except BaseException as e:
                reader_err.append(e)
            finally:
                for _ in range(n_workers):
                    if not put_mp(work, None):
                        break

        rt = threading.Thread(target=reader, daemon=True)
        rt.start()
        expect_done = n_workers
        next_seq = 0
        held: dict = {}
        try:
            while expect_done > 0:
                if reader_err:
                    raise reader_err.pop()
                observe_depth(h_out, out)
                try:
                    msg = out.get(timeout=0.1)
                except _q.Empty:
                    dead = [p for p in procs
                            if p.exitcode not in (None, 0)]
                    if dead:
                        raise RuntimeError(
                            f"parse worker died (exitcode "
                            f"{dead[0].exitcode})"
                        )
                    continue
                kind = msg[0]
                if kind == "done":
                    expect_done -= 1
                    # Trailing span shipment: worker events that ended
                    # after its last batch (e.g. the final window span)
                    # — and the worker's final quality-sketch delta
                    # (batches sketched since its last periodic ship).
                    if len(msg) > 1:
                        tracer.add_raw(msg[1])
                    if (
                        len(msg) > 2 and msg[2] is not None
                        and self._quality is not None
                    ):
                        self._quality.absorb(msg[2])
                    continue
                if kind == "err":
                    raise msg[1]
                if kind == "mark":
                    seq, obj = msg[1], EpochEnd(msg[2])
                else:  # ("batch", seq, shm, meta, trunc, note, t,
                    #    spans, sketch_delta)
                    seq = msg[1]
                    obj = procpool.attach_batch(spec, msg[2], msg[3])
                    self._trunc_extra += msg[4]
                    self._log_worker_note(msg[5])
                    # Workers can't reach this process's registry; they
                    # ship their parse wall time with each batch instead
                    # — and their trace spans and quality-sketch deltas
                    # the same way (deltas every SKETCH_SHIP_EVERY
                    # batches; None in between).
                    self._t_parse.observe(msg[6])
                    tracer.add_raw(msg[7])
                    if (
                        len(msg) > 8 and msg[8] is not None
                        and self._quality is not None
                    ):
                        self._quality.absorb(msg[8])
                if not self.ordered:
                    self._last_seq = seq
                    yield obj
                    continue
                held[seq] = obj
                while next_seq in held:
                    self._last_seq = next_seq
                    yield held.pop(next_seq)
                    next_seq += 1
            if reader_err:
                raise reader_err.pop()
            for seq in sorted(held):
                self._last_seq = seq
                yield held[seq]
        finally:
            stop.set()
            # Reap the pool first (workers give up their blocked puts
            # within one poll period; their queue feeders flush on
            # exit), THEN drain: every shipped-but-unconsumed segment is
            # guaranteed visible by the time the workers are gone, so
            # none outlives the run in /dev/shm.  A terminated straggler
            # can still lose in-flight messages — the worker-side emit()
            # fallback covers its own unsent segment.
            rt.join()
            for p in procs:
                p.join(timeout=5)
            for p in procs:
                if p.is_alive():  # pragma: no cover - stuck worker
                    p.terminate()
                    p.join(timeout=5)
            try:
                while True:
                    msg = out.get_nowait()
                    if msg and msg[0] == "batch":
                        procpool.discard_segment(msg[2])
            except _q.Empty:
                pass
            if ring is not None:
                ring.destroy()
                self._g_ring_bytes.set(0)  # allocation gone
            qs = (work, out) if ring_free is None else (
                work, out, ring_free
            )
            for q in qs:
                q.close()
                q.cancel_join_thread()
            # Backstop for segments a crashed worker created but never
            # shipped: everything this pipeline tagged is garbage now.
            leaked = procpool.sweep_segments(shm_tag)
            if leaked:
                log.warning(
                    "swept %d orphaned /dev/shm segment(s) tagged %s "
                    "(a parse worker died mid-ship)", leaked, shm_tag,
                )

    def _log_worker_note(self, note) -> None:
        """Mirror thread-mode sort_meta degradation logging for notes a
        process worker shipped back with a batch."""
        if note is None:
            return
        kind, msg = note
        if kind == "oor":
            self._oor_counter.add(1)
            log.warning(
                "host sort_meta rejected a batch (%s); the input data or "
                "vocabulary_size is wrong — the device-sort path will "
                "silently drop updates for ids >= vocabulary_size", msg,
            )
        elif kind == "sketch_failed":
            if not getattr(self, "_sketch_warned", False):
                self._sketch_warned = True
                log.warning(
                    "quality sketching failed in a parse worker (%s); "
                    "that worker's drift feed is disabled, training "
                    "continues", msg,
                )
        elif not self._sort_meta_warned:
            self._sort_meta_warned = True
            log.warning(
                "host sort_meta failed in a parse worker (%s); those "
                "workers fall back to device sort", msg,
            )


def stack_batches(
    batches: Sequence[libsvm.Batch], out: Optional[libsvm.Batch] = None
) -> libsvm.Batch:
    """Stack K parsed batches into one [K, batch, ...] super-batch.

    The stacked Batch feeds the K-step scan train step (train.loop.
    make_scan_train_step), which consumes the leading axis one step at a
    time.  Host-computed ``sort_meta`` rides along leaf-wise when EVERY
    batch carries it (shapes agree by construction: all meta derives from
    the same (batch_size * max_features, CHUNK, TILE, vocab)); a group
    with any meta-less batch drops it entirely — the device-sort path
    handles meta-less batches, and a per-step mix would change the scan
    xs pytree mid-run.

    ``out`` (a Batch of preallocated [K, ...] arrays, sort_meta arrays
    included iff this group stacks meta) receives the stacked data in
    place and is returned — the transfer stage's staging-buffer pool
    recycles these so steady-state stacking allocates nothing.  Callers
    passing ``out`` must not reuse the buffers until the consumer is
    done with the returned Batch.
    """
    if not batches:
        raise ValueError("stack_batches needs at least one batch")
    if len(batches) == 1:  # K=1 (or an epoch tail of 1): zero-copy views
        b = batches[0]
        meta = b.sort_meta
        if meta is not None:
            meta = type(meta)(*(x[None] for x in meta))
        return libsvm.Batch(
            b.labels[None], b.ids[None], b.vals[None], b.fields[None],
            b.weights[None], sort_meta=meta,
        )
    metas = [b.sort_meta for b in batches]
    has_meta = all(m is not None for m in metas)
    if out is None:
        core = (
            np.stack([b.labels for b in batches]),
            np.stack([b.ids for b in batches]),
            np.stack([b.vals for b in batches]),
            np.stack([b.fields for b in batches]),
            np.stack([b.weights for b in batches]),
        )
        meta = None
        if has_meta:
            meta = type(metas[0])(
                *(np.stack(cols) for cols in zip(*metas))
            )
        return libsvm.Batch(*core, sort_meta=meta)
    for name in ("labels", "ids", "vals", "fields", "weights"):
        np.stack(
            [getattr(b, name) for b in batches], out=getattr(out, name)
        )
    if has_meta:
        if out.sort_meta is None:
            raise ValueError("out has no sort_meta arrays for this group")
        for cols, dst in zip(zip(*metas), out.sort_meta):
            np.stack(cols, out=dst)
        return out
    return out._replace(sort_meta=None)


class _StagingPool:
    """Reusable pre-allocated host staging buffers for super-batch
    stacking (single-threaded: only the transfer thread touches it).

    Steady-state stacking writes into recycled [K, ...] arrays instead
    of allocating ~super-batch bytes per dispatch.  A buffer is only
    recycled after the device transfer that read from it is COMPLETE:
    retired buffers queue behind their device super-batch and the pool
    blocks on the oldest transfer (``jax.block_until_ready``, resolved
    lazily so the data layer stays importable without jax) before
    handing its buffers out again.  By the time super-batch n + depth
    stacks, transfer n has long finished, so the wait is ~0 in steady
    state.  Keyed by (K, batch shape, has-meta) — epoch tails at
    K' < K get their own small slot.
    """

    def __init__(self, limit: int, reuse_counter=None, tracer=None,
                 bytes_gauge=None):
        self._free: dict = {}  # key -> [Batch bufset, ...]
        self._inflight: deque = deque()  # (dev, key, bufset)
        self._limit = max(1, limit)
        self._c_reuse = (
            reuse_counter if reuse_counter is not None
            else obs.NULL.counter("")
        )
        self._tracer = tracer if tracer is not None else obs.NULL_TRACER
        # Ledger: bytes of staging buffers this pool OWNS (free +
        # in-flight).  Alias mode hands ownership to the zero-copy
        # device array, so those bytes leave the ledger at retire.
        self._bytes = 0
        self._g_bytes = (
            bytes_gauge if bytes_gauge is not None
            else obs.NULL.gauge("")
        )
        # Whether put_fn's device arrays ALIAS the host staging buffers
        # (None = not yet probed).  jax.device_put on a single-device
        # CPU mesh is zero-copy: the "device" array shares memory with
        # the numpy buffer, so recycling the buffer would rewrite
        # super-batches still queued for dispatch.  The first retire()
        # probes once; aliasing permanently disables reuse (fresh
        # allocations per group — correct, just not recycled).
        self._alias_mode: Optional[bool] = None

    @staticmethod
    def _key(group):
        b = group[0]
        has_meta = all(x.sort_meta is not None for x in group)
        return (len(group), b.ids.shape, has_meta)

    @staticmethod
    def _alloc(group, has_meta):
        k = len(group)
        b = group[0]

        def empty(x):
            return np.empty((k,) + x.shape, x.dtype)

        meta = None
        if has_meta:
            meta = type(b.sort_meta)(*(empty(x) for x in b.sort_meta))
        return libsvm.Batch(
            empty(b.labels), empty(b.ids), empty(b.vals),
            empty(b.fields), empty(b.weights), sort_meta=meta,
        )

    @staticmethod
    def _wait(dev) -> None:
        """Block until a shipped super-batch's H2D transfers finished —
        only then are its staging buffers safe to overwrite.  jax is
        resolved lazily (and only if already imported): a numpy-only
        put_fn has nothing to wait for."""
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return
        try:
            jax.block_until_ready(dev)
        except Exception:  # pragma: no cover - non-array put results
            pass

    def acquire(self, group) -> libsvm.Batch:
        key = self._key(group)
        if len(self._inflight) >= self._limit:
            # Block-on-oldest-transfer before recycling: the span makes
            # the ROADMAP question "is the prefetcher thread blocked on
            # staging reuse?" directly visible in a trace.
            with self._tracer.span(
                "prefetch.staging_wait",
                args={"inflight": len(self._inflight)},
            ):
                while len(self._inflight) >= self._limit:
                    dev, k2, bufs = self._inflight.popleft()
                    self._wait(dev)
                    self._free.setdefault(k2, []).append(bufs)
        free = self._free.get(key)
        if free:
            self._c_reuse.add(1)
            return free.pop()
        bufs = self._alloc(group, key[2])
        self._bytes += _batch_nbytes(bufs)
        self._g_bytes.set(self._bytes)
        return bufs

    @staticmethod
    def _probe_alias(dev, bufs: libsvm.Batch) -> bool:
        """True when any leaf of ``dev`` may share memory with a staging
        buffer — the zero-copy device_put case where reuse would corrupt
        in-flight data.  Only probed on the CPU backend (accelerator puts
        always copy across the host/device boundary); errs toward True
        (no reuse) on any surprise.

        Multi-device leaves are unconditionally treated as aliasing: the
        CPU client may zero-copy individual shards at the PJRT-buffer
        level, but ``np.asarray`` on a sharded array assembles a fresh
        copy, so ``np.shares_memory`` cannot observe the alias from
        Python.  Recycling under a (1, N) mesh provably rewrites queued
        super-batches (rare bimodal loss flips under host load), so the
        probe refuses reuse rather than trusting an unverifiable copy."""
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return False
        try:
            if jax.default_backend() != "cpu":
                return False
            host = [x for x in bufs[:5]]
            if bufs.sort_meta is not None:
                host.extend(bufs.sort_meta)
            for leaf in jax.tree_util.tree_leaves(dev):
                if isinstance(leaf, np.ndarray):
                    # A shipped object retaining host numpy (e.g. host
                    # sort_meta) references the staging buffers directly.
                    if any(np.shares_memory(leaf, h) for h in host):
                        return True
                elif isinstance(leaf, jax.Array):
                    if len(leaf.sharding.device_set) > 1:
                        return True
                    a = np.asarray(leaf)
                    if any(np.shares_memory(a, h) for h in host):
                        return True
        except Exception:  # pragma: no cover - be safe, not fast
            return True
        return False

    def retire(self, dev, group, bufs: libsvm.Batch) -> None:
        """Queue the buffers behind their device transfer for reuse."""
        if self._alias_mode is None:
            self._alias_mode = self._probe_alias(dev, bufs)
            if self._alias_mode:
                log.info(
                    "staging-buffer reuse disabled: device_put may alias "
                    "host memory on this backend (CPU zero-copy; "
                    "unverifiable for sharded arrays), so recycling "
                    "would corrupt in-flight super-batches; stacking "
                    "allocates fresh buffers"
                )
        if self._alias_mode:
            # The device array owns this memory now — it left the pool.
            self._bytes = max(0, self._bytes - _batch_nbytes(bufs))
            self._g_bytes.set(self._bytes)
            return
        self._inflight.append((dev, self._key(group), bufs))


class DevicePrefetcher:
    """Double-buffered transfer stage between BatchPipeline and the loop.

    A background thread pulls parsed batches from ``source``, stacks
    ``steps_per_dispatch`` of them into a [K, ...] super-batch
    (:func:`stack_batches`, carrying host ``sort_meta``), and ships it to
    the device with ``put_fn`` (shard + device_put; the dispatch is
    async, so super-batch n+1's H2D copies overlap super-batch n's
    training).  At most ``depth`` shipped super-batches wait in the
    bounded output queue — host/device memory for staged input stays
    capped at ~(depth + 1) super-batches.  The source's tail yields a
    short super-batch at K' = leftover.

    Iterating yields ``(device_super_batch, n_batches)``.  An
    :class:`EpochEnd` marker from the source flushes the pending group
    (so super-batches never span epochs — the epoch tail dispatches at
    K' = leftover, exactly like before) and is forwarded verbatim.
    A :class:`SuperBatch` from the source (the pre-stacked epoch cache)
    skips ``stack_batches`` entirely and ships as-is; with
    ``staging=True`` the stacking path writes into a small pool of
    recycled pre-allocated host buffers (safe only when ``put_fn``
    copies out of host memory, as device_put does).
    Exceptions from the source or the transfer re-raise in the consumer;
    ``close()`` cancels the output queue (waking a blocked producer
    immediately — no poll latency) and joins the thread; it is
    idempotent (iteration calls it on exit).
    """

    def __init__(self, source, steps_per_dispatch: int, put_fn,
                 depth: int = 2, telemetry: Optional[obs.Telemetry] = None,
                 staging: bool = False,
                 tracer: Optional[obs.Tracer] = None,
                 ship_fn=None):
        self._k = max(1, steps_per_dispatch)
        self._put_fn = put_fn
        # Optional fused stack+H2D: ship_fn takes the raw batch group
        # and returns the device super-batch in ONE transfer (parallel.
        # mesh.FusedShipper), or None to decline — then the classic
        # stack_batches + put_fn path below runs unchanged.
        self._ship_fn = ship_fn
        # Transfer-stage instruments: stack vs H2D vs output-block time.
        # out_block large = the device is the bottleneck (healthy);
        # out_q_depth pinned low with the trainer starving = ingest-bound.
        tel = telemetry if telemetry is not None else obs.NULL
        self._out = _ClosableQueue(
            max(1, depth), hist=tel.depth_hist("prefetch.out_q_depth")
        )
        self._t_stack = tel.timer("prefetch.stack")
        self._t_put = tel.timer("prefetch.device_put")
        self._t_out_block = tel.timer("prefetch.out_block")
        self._c_super = tel.counter("prefetch.super_batches")
        self._c_prestack = tel.counter("prefetch.prestack_hits")
        self._c_fused = tel.counter("prefetch.fused_ships")
        # Trace correlation: this stage ASSIGNS the super-batch id (sb
        # = emission order, which the bounded FIFO output queue carries
        # unchanged to the consumer, so the train loop's own dispatch
        # counter names the same super-batch) and carries the delivered
        # batch index forward (counted here in source order — the same
        # order the pipeline's ``ingest.deliver`` points counted).
        self._tracer = tracer if tracer is not None else obs.NULL_TRACER
        self._sb_id = 0
        self._batch_idx = 0
        # Staging-buffer reuse is opt-in: it requires put_fn to COPY out
        # of the host arrays (device_put does; an identity put_fn used
        # by tests/bench drains hands the arrays downstream, where a
        # recycled buffer would be overwritten under the consumer).
        self._pool = (
            _StagingPool(
                max(1, depth) + 1,
                reuse_counter=tel.counter("prefetch.staging_reuse"),
                tracer=self._tracer,
                bytes_gauge=tel.gauge("prefetch.staging_bytes"),
            )
            if staging else None
        )
        self._thread = threading.Thread(
            target=self._run, args=(iter(source),), daemon=True
        )
        self._thread.start()

    def _run(self, it):
        try:
            self._tracer.name_thread("prefetch")
            group: list = []
            while True:
                batch = next(it, _SENTINEL)
                if batch is _SENTINEL:
                    break
                if isinstance(batch, EpochEnd):
                    if group:
                        if not self._emit(group):
                            return
                        group = []
                    if not self._out.put(batch):
                        return
                    continue
                if isinstance(batch, SuperBatch):
                    # Pre-stacked fast path (cache_prestacked replay —
                    # and epoch 0, which the pipeline stacks once at
                    # group boundaries): no stack here, straight to the
                    # device.  A pending partial group (mid-group
                    # resume tail) flushes first to keep order.
                    if group:
                        if not self._emit(group):
                            return
                        group = []
                    if not self._emit_prestacked(batch):
                        return
                    continue
                group.append(batch)
                if len(group) == self._k:
                    if not self._emit(group):
                        return
                    group = []
            if group:
                self._emit(group)  # epoch tail: K' = leftover
        except BaseException as e:  # surfaces in the consumer
            self._out.put(_Error(e))
        finally:
            self._out.put(_SENTINEL)
            # Deterministically release the source's own resources (a
            # BatchPipeline generator holds parser threads + queues).
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # pragma: no cover - best effort
                    pass

    def _emit(self, group) -> bool:
        sb_id, batch0 = self._sb_id, self._batch_idx
        self._sb_id += 1
        self._batch_idx += len(group)
        if self._ship_fn is not None:
            # Fused stack+H2D: one staging copy, ONE device transfer,
            # on-device carve.  Timed under the H2D instrument (it IS
            # the transfer; there is no separate stack phase to time).
            with self._t_put.time(), obs.trace_span("tffm:h2d"), \
                    self._tracer.span(
                        "prefetch.fused_ship",
                        args={"sb": sb_id, "batch0": batch0,
                              "n": len(group)},
                        flow=("s", f"sb{sb_id}"),
                    ):
                dev = self._ship_fn(group)
            if dev is not None:
                self._c_fused.add(1)
                self._c_super.add(1)
                t0 = time.perf_counter()
                ok = self._out.put((dev, len(group)))
                self._t_out_block.observe(time.perf_counter() - t0)
                return ok
        bufs = None
        with self._t_stack.time(), obs.trace_span("tffm:stack"), \
                self._tracer.span(
                    "prefetch.stack",
                    args={"sb": sb_id, "batch0": batch0, "n": len(group)},
                    flow=("s", f"sb{sb_id}"),
                ):
            if self._pool is not None and len(group) > 1:
                bufs = self._pool.acquire(group)
                stacked = stack_batches(group, out=bufs)
            else:
                stacked = stack_batches(group)
        with self._t_put.time(), obs.trace_span("tffm:h2d"), \
                self._tracer.span(
                    "prefetch.h2d", args={"sb": sb_id},
                    flow=("t", f"sb{sb_id}"),
                ):
            dev = self._put_fn(stacked)
        if bufs is not None:
            self._pool.retire(dev, group, bufs)
        self._c_super.add(1)
        t0 = time.perf_counter()
        ok = self._out.put((dev, len(group)))
        self._t_out_block.observe(time.perf_counter() - t0)
        return ok

    def _emit_prestacked(self, sb: SuperBatch) -> bool:
        """Ship an already-stacked group: zero stacking work, one put."""
        sb_id, batch0 = self._sb_id, self._batch_idx
        self._sb_id += 1
        self._batch_idx += sb.n
        with self._t_put.time(), obs.trace_span("tffm:h2d"), \
                self._tracer.span(
                    "prefetch.h2d",
                    args={"sb": sb_id, "batch0": batch0, "n": sb.n,
                          "prestacked": True},
                    flow=("s", f"sb{sb_id}"),
                ):
            dev = self._put_fn(sb.batch)
        self._c_super.add(1)
        self._c_prestack.add(1)
        t0 = time.perf_counter()
        ok = self._out.put((dev, sb.n))
        self._t_out_block.observe(time.perf_counter() - t0)
        return ok

    def __iter__(self):
        try:
            while True:
                item = self._out.get()
                if item is _SENTINEL or item is _CANCELLED:
                    return
                if isinstance(item, _Error):
                    raise item.exc
                yield item
        finally:
            self.close()

    def close(self):
        """Stop the transfer thread and reap it (idempotent)."""
        self._out.cancel()
        self._thread.join()


def _make_parser(cfg: FmConfig):
    """Returns (native_parser_or_None, (lines, weights) -> Batch)."""
    native = None
    try:
        from fast_tffm_tpu.data import native as _native

        # Parallelism comes from the pipeline's thread_num WORKERS (each
        # parses a different group with the GIL released); internal C++
        # threads on top would oversubscribe cores (thread_num^2) and a
        # per-group fork/join barrier pipelines worse than independent
        # groups anyway.
        native = _native.NativeParser(
            vocabulary_size=cfg.vocabulary_size,
            max_features=cfg.max_features,
            hash_feature_id=cfg.hash_feature_id,
            field_num=cfg.field_num,
            num_threads=1,
        )
    except Exception as e:  # pragma: no cover - env-dependent
        log.info("native parser unavailable (%s); using Python parser", e)

    if native is not None:

        def parse(lines, weights):
            return native.parse_batch(lines, cfg.batch_size, weights)

        return native, parse

    def parse_py(lines, weights):
        examples = libsvm.parse_lines(
            lines, cfg.vocabulary_size, cfg.hash_feature_id, cfg.field_num
        )
        return libsvm.make_batch(
            examples, cfg.batch_size, cfg.max_features, weights
        )

    return None, parse_py
