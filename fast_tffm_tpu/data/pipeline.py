"""Input pipeline: files -> shuffled, parsed, padded device batches.

Replaces the reference's TF queue-runner pipeline (``TextLineReader`` +
shuffle batch queues, SURVEY.md §2 #6) with a thread-based producer/consumer
design driven by the same config knobs (``thread_num``, ``queue_size``,
``shuffle_buffer``, ``epoch_num``), feeding numpy batches that the train
loop ships to the device while the next batch parses — host-side pipelining
in place of TF queues.

Parsing uses the C++ extension when available (multi-threaded tokenizer +
murmur hashing, like the reference's ``FmParser``) and falls back to the
pure-Python oracle.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
from typing import Iterator, Optional, Sequence

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data import libsvm

log = logging.getLogger(__name__)

# Raw-chunk read size for the fast ingest path. Each shuffled group keeps
# its source chunk alive, so this also bounds shuffle-buffer memory.
_CHUNK_BYTES = 4 << 20

_SENTINEL = object()


class _Error:
    """Carries a worker/reader exception to the consuming thread."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def _read_weight_file(path: str) -> list[str]:
    # Keep EVERY line (even blanks) so weight line i pairs with data line i;
    # parsing to float happens only for lines actually used.
    with open(path) as f:
        return [line.strip() for line in f]


def iter_lines(
    files: Sequence[str],
    weight_files: Optional[Sequence[str]] = None,
) -> Iterator[tuple[str, float]]:
    """Yield (line, weight) over all files; weights default to 1.0.

    ``weight_files`` parallels ``files`` line-for-line (reference
    ``weight_files`` cfg key, SURVEY.md §2 #6): weight-file line i belongs
    to data-file line i; blank/comment data lines are skipped along with
    their weight lines.
    """
    for i, path in enumerate(files):
        weights = None
        if weight_files:
            weights = _read_weight_file(weight_files[i])
        with open(path) as f:
            for lineno, line in enumerate(f):
                if not line.strip() or line.lstrip().startswith("#"):
                    continue
                if weights is None:
                    w = 1.0
                else:
                    try:
                        w = float(weights[lineno])
                    except (IndexError, ValueError) as e:
                        raise ValueError(
                            f"weight file {weight_files[i]} line {lineno + 1} "
                            f"does not pair with data file {path}: {e}"
                        ) from e
                yield line, w


def _shuffled(
    it: Iterator[tuple[str, float]], buffer_size: int, rng: random.Random
) -> Iterator[tuple[str, float]]:
    """Reservoir-style streaming shuffle (like TF's shuffle queue)."""
    buf: list[tuple[str, float]] = []
    for item in it:
        if len(buf) < buffer_size:
            buf.append(item)
            continue
        j = rng.randrange(buffer_size)
        yield buf[j]
        buf[j] = item
    rng.shuffle(buf)
    yield from buf


def _raw_chunk_stream(files: Sequence[str], chunk_bytes: int):
    """Binary chunks of all files as ONE stream; a '\\n' is injected at a
    file boundary when the file lacks a trailing newline, so lines never
    merge across files and batches pack across files like the line path."""
    for path in files:
        last = b"\n"
        with open(path, "rb") as f:
            while True:
                chunk = f.read(chunk_bytes)
                if not chunk:
                    break
                last = chunk[-1:]
                yield chunk
        if last != b"\n":
            yield b"\n"


def _iter_raw_groups(
    files: Sequence[str], batch_size: int, chunk_bytes: int = _CHUNK_BYTES
):
    """Yield (buf, offsets[n+1]) groups of <= batch_size raw text lines.

    The fast ingest path: files are read in binary chunks, line starts
    found by the C++ scanner, and groups reference the chunk buffer
    directly — no Python string is ever created per line.  Chunks are
    accumulated (newline counts are cheap) and joined ONCE per buffer so
    oversized batches don't cause quadratic re-copies; leftover lines are
    carried into the next buffer, including across file boundaries.
    """
    from fast_tffm_tpu.data import native

    stream = _raw_chunk_stream(files, chunk_bytes)
    pending = b""
    at_eof = False
    guess = 0  # line-count guess carried between buffers (stable density)
    while not at_eof:
        parts = [pending]
        nls = pending.count(b"\n")
        # Gather at least one full group's worth of complete lines.
        while nls < batch_size:
            chunk = next(stream, None)
            if chunk is None:
                at_eof = True
                break
            parts.append(chunk)
            nls += chunk.count(b"\n")
        buf = b"".join(parts)
        pending = b""
        if at_eof:
            buf_end = len(buf)
        else:
            buf_end = buf.rfind(b"\n") + 1  # >=1: nls >= batch_size >= 1
        starts = native.find_line_offsets(buf, buf_end, guess=guess or None)
        n_lines = len(starts)
        guess = n_lines + 2
        if n_lines == 0:
            if at_eof:
                return
            pending = buf
            continue
        ends = np.append(starts[1:], buf_end)
        if at_eof:
            n_keep = n_lines  # flush everything, partial group included
        else:
            n_keep = (n_lines // batch_size) * batch_size
            leftover_start = (
                int(starts[n_keep]) if n_keep < n_lines else buf_end
            )
            pending = buf[leftover_start:]
        for i in range(0, n_keep, batch_size):
            j = min(i + batch_size, n_keep)
            offsets = np.empty((j - i + 1,), np.int64)
            offsets[:-1] = starts[i:j]
            offsets[-1] = ends[j - 1]
            yield (buf, offsets)


def _item_len(item) -> int:
    """Number of lines in a work item (line chunk or raw group)."""
    if isinstance(item, tuple):
        return len(item[1]) - 1
    return len(item)


def _strided_rounds(it, shard_id: int, num_shards: int):
    """Yield every num_shards-th item, but only from COMPLETE rounds.

    Multi-host input sharding: shard s takes items s, n+s, 2n+s, ... of the
    (identically seeded, hence identical) global stream.  Every shard must
    emit the SAME number of items — a host running one extra step would
    deadlock the others in the step's collectives — so an item is held back
    until its round is known complete (an item of the next round arrives)
    and the tail round is dropped at EOF if partial.
    """
    pending = None  # (round, item) candidate from this shard's slot
    last_idx = -1
    for idx, item in enumerate(it):
        last_idx = idx
        r = idx // num_shards
        if pending is not None and r > pending[0]:
            yield pending[1]
            pending = None
        if idx % num_shards == shard_id:
            pending = (r, item)
    if pending is not None and last_idx >= pending[0] * num_shards + num_shards - 1:
        yield pending[1]


class BatchPipeline:
    """Background-threaded parse/batch pipeline.

    One reader thread streams (line, weight) pairs into a work queue in
    chunks; ``thread_num`` parser threads turn chunks into padded
    :class:`Batch` objects pushed to a bounded output queue
    (``queue_size``).  Batch order is nondeterministic across parser
    threads (like the reference's async queues); set ``thread_num=1`` for
    determinism.
    """

    def __init__(
        self,
        files: Sequence[str],
        cfg: FmConfig,
        *,
        weight_files: Optional[Sequence[str]] = None,
        epochs: int = 1,
        shuffle: bool = True,
        drop_remainder: bool = False,
        seed: Optional[int] = None,
        ordered: bool = False,
        skip_batches: int = 0,
        shard: tuple[int, int] = (0, 1),
    ):
        self.files = list(files)
        self.cfg = cfg
        self.weight_files = list(weight_files) if weight_files else None
        self.epochs = epochs
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self.seed = cfg.seed if seed is None else seed
        # Mid-epoch resume: skip the first N batches of epoch 0 WITHOUT
        # parsing them.  Skipping happens after shuffling, so the stream
        # continues exactly where a run with the same seed left off (batch
        # delivery order across >1 parser threads remains nondeterministic,
        # like the reference's async queues).
        self.skip_batches = skip_batches
        # Multi-host input sharding (shard_id, num_shards): this pipeline
        # emits only its strided share of the global stream, round-complete
        # (see _strided_rounds).  Skip counts apply AFTER sharding.
        if not (0 <= shard[0] < shard[1]):
            raise ValueError(f"bad shard {shard}")
        self.shard = shard
        # ordered=True forces one parser thread so batches come out in
        # input order (the predict path needs score/line alignment).
        self.ordered = ordered
        self._native, self._parser = _make_parser(cfg)
        # Fast ingest: raw binary chunks + C++ line scan, no Python string
        # per line. Requires the native parser; weight_files need per-line
        # pairing so they stay on the line path. Shuffling happens at
        # batch-group granularity here (the line path shuffles lines).
        self._raw = (
            cfg.fast_ingest and self._native is not None
            and not self.weight_files
        )

    def __iter__(self) -> Iterator[libsvm.Batch]:
        cfg = self.cfg
        work: queue.Queue = queue.Queue(maxsize=max(2, cfg.queue_size))
        out: queue.Queue = queue.Queue(maxsize=max(2, cfg.queue_size))
        n_workers = 1 if self.ordered else max(1, cfg.thread_num)
        stop = threading.Event()

        def put_checked(q: queue.Queue, item) -> bool:
            """Bounded put that gives up once the consumer is gone."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def _line_chunks(rng):
            """Line path: line-level shuffle, then fixed-size chunks."""
            it = iter_lines(self.files, self.weight_files)
            if self.shuffle:
                it = _shuffled(it, max(1, cfg.shuffle_buffer), rng)
            chunk: list[tuple[str, float]] = []
            for item in it:
                chunk.append(item)
                if len(chunk) == cfg.batch_size:
                    yield chunk
                    chunk = []
            if chunk:
                yield chunk

        def reader():
            try:
                for epoch in range(self.epochs):
                    rng = random.Random(self.seed + epoch)
                    to_skip = self.skip_batches if epoch == 0 else 0
                    if self._raw:
                        it = _iter_raw_groups(self.files, cfg.batch_size)
                        if self.shuffle:  # group-granularity shuffle
                            buffer = max(
                                1, cfg.shuffle_buffer // cfg.batch_size
                            )
                            it = _shuffled(it, buffer, rng)
                    else:
                        it = _line_chunks(rng)
                    if self.drop_remainder:
                        # Filter BEFORE sharding so all shards see the same
                        # global item indexing (a partial group dropped by
                        # one host only would desync step counts).
                        it = (
                            x for x in it
                            if _item_len(x) >= cfg.batch_size
                        )
                    if self.shard[1] > 1:
                        it = _strided_rounds(it, *self.shard)
                    for item in it:
                        if stop.is_set():
                            return
                        if to_skip > 0:
                            to_skip -= 1
                            continue
                        if not put_checked(work, item):
                            return
            except BaseException as e:  # surfaces in the consumer
                put_checked(out, _Error(e))
            finally:
                for _ in range(n_workers):
                    put_checked(work, _SENTINEL)

        def parse_worker():
            while not stop.is_set():
                try:
                    chunk = work.get(timeout=0.1)
                except queue.Empty:
                    continue
                if chunk is _SENTINEL:
                    put_checked(out, _SENTINEL)
                    return
                try:
                    if isinstance(chunk, tuple):  # raw (buf, offsets) group
                        batch = self._native.parse_raw(
                            chunk[0], chunk[1], cfg.batch_size
                        )
                    else:
                        lines = [c[0] for c in chunk]
                        weights = [c[1] for c in chunk]
                        batch = self._parser(lines, weights)
                except BaseException as e:
                    put_checked(out, _Error(e))
                    continue
                put_checked(out, batch)

        threads = [threading.Thread(target=reader, daemon=True)]
        threads += [
            threading.Thread(target=parse_worker, daemon=True)
            for _ in range(n_workers)
        ]
        for t in threads:
            t.start()
        finished = 0
        try:
            while finished < n_workers:
                item = out.get()
                if item is _SENTINEL:
                    finished += 1
                    continue
                if isinstance(item, _Error):
                    raise item.exc
                yield item
        finally:
            # Unblock and reap every thread: stop flag + drain both queues.
            stop.set()
            for t in threads:
                while t.is_alive():
                    for q in (work, out):
                        try:
                            while True:
                                q.get_nowait()
                        except queue.Empty:
                            pass
                    t.join(timeout=0.05)


def _make_parser(cfg: FmConfig):
    """Returns (native_parser_or_None, (lines, weights) -> Batch)."""
    native = None
    try:
        from fast_tffm_tpu.data import native as _native

        native = _native.NativeParser(
            vocabulary_size=cfg.vocabulary_size,
            max_features=cfg.max_features,
            hash_feature_id=cfg.hash_feature_id,
            field_num=cfg.field_num,
            num_threads=max(1, cfg.thread_num),
        )
    except Exception as e:  # pragma: no cover - env-dependent
        log.info("native parser unavailable (%s); using Python parser", e)

    if native is not None:

        def parse(lines, weights):
            return native.parse_batch(lines, cfg.batch_size, weights)

        return native, parse

    def parse_py(lines, weights):
        examples = libsvm.parse_lines(
            lines, cfg.vocabulary_size, cfg.hash_feature_id, cfg.field_num
        )
        return libsvm.make_batch(
            examples, cfg.batch_size, cfg.max_features, weights
        )

    return None, parse_py
